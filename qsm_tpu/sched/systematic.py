"""Systematic schedule exploration — bounded-exhaustive model checking.

The property layer samples k SEEDED schedules per program (core/property.py,
the reference's QuickCheck approach).  This module replaces sampling with
ENUMERATION for small programs: every delivery-order decision the scheduler
can make is explored depth-first, every distinct history is collected, and
the whole set is decided in ONE batched checker call — turning "k random
schedules found nothing" into "all N interleavings explored, none violate",
a certainty the reference family cannot produce.

How it composes with the scheduler: delivery choice is the scheduler's only
nondeterminism (process step order is fixed — sched/scheduler.py), and
``Scheduler(choices=...)`` replays a scripted prefix then defaults to
choice 0, logging the branching factor at every delivery.  Determinism
makes tree search stateless: running prefix ``p`` reveals the branching
factors along ``p``'s leftmost completion, and lexicographic backtracking
over the logged factors enumerates the full tree without ever storing it.

Fault injection composes when — and only when — the plan is
DETERMINISTIC: crash schedules (``crash_at`` fires on delivery counts)
and partitions (a pure (src, dst) predicate) never consult the seeded
RNG's outcome, so scripted replay explores them exactly — enumerating
every delivery order UNDER a crash plan turns "the failover survived k
random crash trials" into "the failover survives EVERY interleaving of
this crash schedule" (the verified claim, extended to fault tolerance).
Probabilistic faults (drop/duplicate/delay rates) draw from the RNG that
scripted replay bypasses and are refused, as before; sampling
(prop_concurrent with a FaultPlan) remains the way to explore those.

The batching story is the TPU story: enumeration yields hundreds-to-
thousands of small histories per program, exactly the shape the device
kernel's vmap batch wants (SURVEY.md §2b trial/batch parallelism).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import types

from ..core.history import History
from ..ops.backend import LineariseBackend, Verdict
from .runner import HistoryRecorder, _client, prepare_run
from .scheduler import FaultPlan, Message, PruneRun, Scheduler


@dataclasses.dataclass
class ExploreResult:
    """Outcome of exploring one program's interleaving tree."""

    schedules_run: int
    distinct_histories: int
    exhausted: bool         # True: the WHOLE tree fit under max_schedules
    violations: int
    undecided: int
    seconds: float
    violating: Optional[History] = None  # first violating history, if any
    # schedules cut short by the state-fingerprint prune (subset of
    # schedules_run) — the mechanism's observability counter
    pruned_schedules: int = 0

    @property
    def ok(self) -> bool:
        return self.violations == 0

    @property
    def verified(self) -> bool:
        """Every interleaving explored AND decided, none violating — the
        certainty claim.  False whenever the tree was truncated or any
        history came back undecided."""
        return self.exhausted and self.violations == 0 and self.undecided == 0


def schedule_key(choices: Sequence[int]) -> str:
    """Stamp a delivery-choice script into a history seed string.  The
    ONE encode site; :func:`parse_schedule_key` is its inverse (replay
    regressions store this string, so the pair must never drift)."""
    return "explore:" + ",".join(map(str, choices))


def parse_schedule_key(seed_key) -> Optional[List[int]]:
    """The choice script from a :func:`schedule_key` stamp, or None when
    ``seed_key`` is not an exploration stamp (an ordinary seeded run)."""
    if not (isinstance(seed_key, str) and seed_key.startswith("explore:")):
        return None
    body = seed_key[len("explore:"):]
    return [int(x) for x in body.split(",") if x != ""]


def _next_prefix(choices: List[int], factors: List[int]
                 ) -> Optional[List[int]]:
    """Lexicographic successor: the deepest position that still has an
    untried sibling, bumped; None when the tree is exhausted."""
    for i in range(len(factors) - 1, -1, -1):
        c = choices[i] if i < len(choices) else 0
        if c + 1 < factors[i]:
            return (choices[:i] if i < len(choices)
                    else choices + [0] * (i - len(choices))) + [c + 1]
    return None


def _summarize(hists: List[History], verdicts
               ) -> Tuple[int, int, Optional[History]]:
    """(violations, undecided, first violating history) — the ONE verdict
    accounting site for explore_program and explore_many."""
    violations = int((verdicts == int(Verdict.VIOLATION)).sum())
    undecided = int((verdicts == int(Verdict.BUDGET_EXCEEDED)).sum())
    violating = None
    for h, v in zip(hists, verdicts):
        if int(v) == int(Verdict.VIOLATION):
            violating = h
            break
    return violations, undecided, violating


# ---------------------------------------------------------------------------
# State-fingerprint pruning (VERDICT.md round 3, "Next round" #7)
#
# The scheduler is deterministic, so identical quiescent state ⇒ identical
# subtree of reachable histories.  Different delivery orders of independent
# messages routinely CONVERGE to the same state (round 3 measured ~300×
# redundancy: 10,000 schedules → 35 distinct histories on set/racy 3×5), so
# the enumeration fingerprints the full scheduler state at every delivery
# point and prunes any node whose state was first reached under an earlier
# (lexicographically smaller) schedule.  DFS lex order guarantees the first
# encounter's subtree is fully enumerated before any second encounter, so
# pruning drops only duplicate work — it cannot lose a history (the
# cross-checked guarantee: tests/test_systematic.py compares pruned vs
# unpruned history sets on every model family).
#
# "Full state" means everything behavior- or history-relevant: events
# recorded so far, the in-flight pool (as a multiset — children are
# explored exhaustively either way, so pool ORDER does not matter), and
# per-process mailboxes, liveness flags, and generator continuations
# (bytecode position + locals, recursing through yield-from and object
# state by VALUE so two runs' distinct-but-equal SUT instances match).
# Anything unfingerprintable makes the hook answer "don't prune" — an
# exotic SUT degrades to the exact unpruned enumeration, never to an
# unsound skip.  The one documented soundness boundary: SUT state must be
# reachable from the SUT object or its process generators (true for
# in-tree models; module-level globals would be invisible).
# ---------------------------------------------------------------------------

class _Unfingerprintable(Exception):
    pass


_FP_MAX_DEPTH = 24


def _fp_val(v, depth: int = 0):
    """Hashable VALUE fingerprint of state reachable from process frames.
    Raises _Unfingerprintable rather than guessing: a truncated or lossy
    fingerprint could equate distinct states, which would be an unsound
    prune."""
    if depth > _FP_MAX_DEPTH:
        raise _Unfingerprintable("nesting too deep")
    if v is None or isinstance(v, (int, str, bool, float, bytes)):
        return v
    if isinstance(v, Message):
        # uid is a global send counter: behaviorally inert (trace/debug
        # only), and including it would defeat every match
        return ("M", v.src, v.dst, _fp_val(v.payload, depth + 1))
    if isinstance(v, dict):
        return ("D",) + tuple(sorted(
            ((_fp_val(k, depth + 1), _fp_val(x, depth + 1))
             for k, x in v.items()), key=repr))
    if isinstance(v, (list, tuple)) or type(v).__name__ == "deque":
        return ("L",) + tuple(_fp_val(x, depth + 1) for x in v)
    if isinstance(v, (set, frozenset)):
        return ("S",) + tuple(sorted((_fp_val(x, depth + 1) for x in v),
                                     key=repr))
    if isinstance(v, (HistoryRecorder, Scheduler)):
        # the recorder's events and the scheduler's pool/procs are
        # fingerprinted at the top level; recursing here would cycle
        return type(v).__name__
    if isinstance(v, types.GeneratorType):
        return _fp_gen(v, depth + 1)
    d = getattr(v, "__dict__", None)
    if d is not None:  # SUT instances: compare by value, not identity
        return ("O", type(v).__name__, _fp_val(d, depth + 1))
    raise _Unfingerprintable(f"opaque value {type(v).__name__}")


# Locals of EXACTLY the runner's client frame that are constant across
# every run of one enumeration (`ops` is the whole per-pid program,
# sched/runner.py::_client) — fingerprinting them at every delivery
# point is pure cost.  Sound because `seen` never outlives one
# program's enumeration AND the skip is scoped by code object: a SUT
# process generator with a *mutating* local that happens to share the
# name must still be fingerprinted (a name-based skip there would
# conflate distinct states — an unsound prune).
_CLIENT_CODE = _client.__code__
_CLIENT_SKIP_LOCALS = frozenset({"ops"})


def _fp_gen(g, depth: int = 0):
    """Continuation fingerprint: code identity + bytecode position +
    locals, following the yield-from chain (clients delegate to
    ``sut.perform``)."""
    fr = g.gi_frame
    if fr is None:
        return ("G", g.gi_code.co_name, "done")
    sub = g.gi_yieldfrom
    loc = fr.f_locals
    if fr.f_code is _CLIENT_CODE:
        loc = {k: v for k, v in loc.items()
               if k not in _CLIENT_SKIP_LOCALS}
    return ("G", g.gi_code.co_name, fr.f_lasti,
            _fp_val(loc, depth + 1),
            _fp_gen(sub, depth + 1)
            if isinstance(sub, types.GeneratorType) else None)


def _state_fingerprint(sched: Scheduler, rec: HistoryRecorder):
    """Full quiescent-state identity (see block comment above)."""
    events = tuple((r.pid, r.cmd, r.arg, r.resp, r.invoke_time,
                    r.response_time) for r in rec.recs)
    pool = tuple(sorted(
        (_fp_val((f.msg.src, f.msg.dst, f.msg.payload)) for f in sched.pool),
        key=repr))
    procs = tuple(
        (name, p.done, p.blocked, p.crashed,
         tuple(_fp_val((m.src, m.payload)) for m in p.mailbox),
         _fp_val(p.send_value),
         "done" if p.done else _fp_gen(p.gen))
        for name, p in sorted(sched.procs.items()))
    monitors = tuple(sorted((t, tuple(ws))
                            for t, ws in sched.monitors.items()))
    return (events, pool, procs, monitors)


def deterministic_faults(faults: Optional[FaultPlan]) -> bool:
    """True when ``faults`` never consults the RNG's outcome — the class
    of plans systematic exploration can enumerate exactly.  The answer
    lives on FaultPlan itself so a future seeded knob is decided next to
    the fields it is defined by."""
    return faults is None or faults.is_deterministic()


def _enumerate(sut_factory, program, max_schedules: int, max_steps: int,
               prune: bool = True, faults: Optional[FaultPlan] = None
               ) -> Tuple[List[History], int, bool, int]:
    """Walk one program's delivery-choice tree depth-first: (distinct
    histories, schedules run, whole tree fit under max_schedules,
    schedules cut short by the prune).  ``prune`` enables
    state-fingerprint subtree skipping (see above); pruned partial runs
    still count as schedules run.  ``faults`` must be a deterministic
    plan (callers validate)."""
    histories: Dict[Tuple, History] = {}
    seen: Dict[tuple, tuple] = {}  # state fp -> first-visit choice path
    prefix: Optional[List[int]] = []
    schedules = 0
    pruned_n = 0
    exhausted = True
    while prefix is not None:
        if schedules >= max_schedules:
            exhausted = False
            break
        sched, rec = prepare_run(sut_factory(), program, seed=0,
                                 max_steps=max_steps, choices=prefix,
                                 faults=faults)
        if prune:
            script = prefix

            # only CRASH plans depend on the delivery count; a
            # partitions-only plan is depth-independent and keeps the
            # full pruning power (incl. loop cutting)
            def hook(s, _script=script, _rec=rec,
                     _faulty=bool(faults and faults.crash_at)):
                log = s.choice_log
                try:
                    fp = _state_fingerprint(s, _rec)
                except _Unfingerprintable:
                    return False  # can't identify ⇒ never skip
                if _faulty:
                    # pending crash points fire on the DELIVERY COUNT, so
                    # under a fault plan two same-state nodes at different
                    # depths have different futures — the count joins the
                    # identity (costs loop-cutting, keeps soundness)
                    fp = (fp, s.n_delivered)
                # the EFFECTIVE path taken so far (scripted choices are
                # clamped to the live branching factor, 0 past the script)
                path = tuple(
                    min(_script[j] if j < len(_script) else 0, log[j] - 1)
                    for j in range(len(log)))
                return seen.setdefault(fp, path) != path

            sched.prune_hook = hook
        try:
            sched.run()
            pruned = False
        except PruneRun:
            pruned = True
            pruned_n += 1
        schedules += 1
        if not pruned:
            h = rec.history(seed=schedule_key(prefix))
            histories.setdefault(h.fingerprint(), h)
        prefix = _next_prefix(prefix, sched.choice_log)
    return list(histories.values()), schedules, exhausted, pruned_n


def explore_program(
    sut_factory: Callable[[], object],
    program,
    spec,
    backend: Optional[LineariseBackend] = None,
    max_schedules: int = 10_000,
    max_steps: int = 100_000,
    faults: Optional[FaultPlan] = None,
    check: bool = True,
    prune: bool = True,
) -> ExploreResult:
    """Enumerate every delivery schedule of ``program`` (up to
    ``max_schedules``), then decide all distinct histories in one batched
    checker call.

    ``backend`` picks the checker (default: the framework's fastest host
    oracle via ``core.property._default_oracle``); a fresh SUT is built
    per schedule from ``sut_factory`` (state must not leak between
    runs — same contract as the property layer's executions).

    ``check=False`` enumerates only (for coverage ground truth): every
    history reports as undecided, so ``verified`` can never be claimed
    from an unchecked run.
    """
    if not deterministic_faults(faults):
        raise ValueError(
            "systematic exploration is incompatible with PROBABILISTIC "
            "fault injection (drop/duplicate/delay rates are seeded "
            "draws, which scripted replay bypasses); crash schedules and "
            "partitions are deterministic and explore fine — use "
            "prop_concurrent sampling for the probabilistic plans")
    t0 = time.perf_counter()
    hists, schedules, exhausted, pruned_n = _enumerate(
        sut_factory, program, max_schedules, max_steps,
        prune=prune, faults=faults)
    if not check:
        return ExploreResult(
            schedules_run=schedules, distinct_histories=len(hists),
            exhausted=exhausted, violations=0, undecided=len(hists),
            seconds=round(time.perf_counter() - t0, 3),
            pruned_schedules=pruned_n)
    if backend is None:
        from ..core.property import _default_oracle

        backend = _default_oracle(spec)
    verdicts = (backend.check_histories(spec, hists) if hists
                else np.empty(0, np.int8))
    violations, undecided, violating = _summarize(hists, verdicts)
    return ExploreResult(
        schedules_run=schedules, distinct_histories=len(hists),
        exhausted=exhausted, violations=violations, undecided=undecided,
        seconds=round(time.perf_counter() - t0, 3), violating=violating,
        pruned_schedules=pruned_n)


def explore_many(
    sut_factory: Callable[[], object],
    programs: Sequence,
    spec,
    backend: Optional[LineariseBackend] = None,
    max_schedules: int = 10_000,
    max_steps: int = 100_000,
    prune: bool = True,
    faults: Optional[FaultPlan] = None,
    workers: int = 0,
) -> List[ExploreResult]:
    """Explore MANY programs, deciding the union of all their distinct
    histories in ONE batched checker call — the vmap-shaped workload the
    device kernel exists for (BASELINE.json:9: ≥1024 histories per
    batch): N small interleaving trees enumerate host-side, the
    exponential decisions all ride one dispatch.  Returns one
    :class:`ExploreResult` per program.

    Enumeration is identical to :func:`explore_program` per program
    (same default ``max_schedules``); DECIDED verdicts are identical
    too, but a budget-bounded device backend may defer differently —
    its memo-cache size depends on the batch bucket (JaxTPU
    ``MAX_SLOTS_FOR_BATCH``), so a history decided in a small
    per-program batch can come back BUDGET_EXCEEDED in the larger
    union batch (never the reverse direction of a wrong verdict; the
    per-program ``undecided`` count reports it).
    """
    if not deterministic_faults(faults):
        raise ValueError(
            "systematic exploration is incompatible with PROBABILISTIC "
            "fault injection; see explore_program")
    if backend is None:
        from ..core.property import _default_oracle

        backend = _default_oracle(spec)
    per_prog = []
    flat: List[History] = []
    if workers > 0:
        # fan whole-tree enumerations over worker processes (each tree
        # is milliseconds-to-seconds of pure-Python replay walking — the
        # regime the pool exists for); sut_factory must be picklable
        # (models.registry.SutFactory).  Deterministic ⇒ bit-identical
        # to the serial walk; the union batch below still decides in
        # ONE caller-side backend call.
        from .pool import ExplorePool

        pool = ExplorePool(sut_factory, n_workers=workers)
        try:
            walked = pool.explore_many(programs, max_schedules,
                                       max_steps, prune, faults)
        finally:
            pool.close()
        for hists, schedules, exhausted, pruned_n, enum_dt in walked:
            per_prog.append((slice(len(flat), len(flat) + len(hists)),
                             schedules, exhausted, pruned_n, enum_dt))
            flat.extend(hists)
    else:
        for prog in programs:
            t0 = time.perf_counter()
            hists, schedules, exhausted, pruned_n = _enumerate(
                sut_factory, prog, max_schedules, max_steps,
                prune=prune, faults=faults)
            per_prog.append((slice(len(flat), len(flat) + len(hists)),
                             schedules, exhausted, pruned_n,
                             time.perf_counter() - t0))
            flat.extend(hists)
    t0 = time.perf_counter()
    verdicts = (backend.check_histories(spec, flat) if flat
                else np.empty(0, np.int8))
    check_dt = time.perf_counter() - t0
    out = []
    for sl, schedules, exhausted, pruned_n, enum_dt in per_prog:
        hs = flat[sl]
        violations, undecided, violating = _summarize(hs, verdicts[sl])
        # per-program seconds like explore_program's: own enumeration
        # time plus this program's share of the one batched check call
        # (apportioned by history count — the batch cost driver)
        share = check_dt * (len(hs) / len(flat)) if flat else 0.0
        out.append(ExploreResult(
            schedules_run=schedules, distinct_histories=len(hs),
            exhausted=exhausted, violations=violations,
            undecided=undecided, seconds=round(enum_dt + share, 3),
            violating=violating, pruned_schedules=pruned_n))
    return out


def shrink_explored(
    sut_factory: Callable[[], object],
    program,
    spec,
    backend: Optional[LineariseBackend] = None,
    max_schedules: int = 2_000,
    max_rounds: int = 50,
    initial: Optional[ExploreResult] = None,
    faults: Optional[FaultPlan] = None,
):
    """Minimize a program whose exploration found a violation.

    QuickCheck-style greedy shrink, but the predicate is EXPLORATION:
    a candidate program survives iff exhaustively exploring it (bounded
    per candidate by ``max_schedules``) still finds a violating
    interleaving.  The result is therefore stronger than the property
    layer's shrink — the minimal program is violating under SOME
    schedule, found by search rather than by replaying one seed's
    schedule, so shrinking cannot lose the race by schedule drift.

    Returns ``(program, ExploreResult, shrink_steps)`` for the smallest
    still-violating program (the input's own result if nothing smaller
    violates).  Pass the program's already-computed result as
    ``initial`` to skip re-exploring it (exploration is deterministic,
    so the caller's result is exactly what a fresh run would produce).
    """
    from ..core.generator import dedupe, shrink_candidates

    best_prog = program
    best_res = (initial if initial is not None
                else explore_program(sut_factory, program, spec,
                                     backend=backend,
                                     max_schedules=max_schedules,
                                     faults=faults))
    if best_res.violations == 0:
        return best_prog, best_res, 0
    steps = 0
    for _ in range(max_rounds):
        improved = False
        for cand in dedupe(shrink_candidates(spec, best_prog), limit=256):
            if len(cand) >= len(best_prog):
                continue
            res = explore_program(sut_factory, cand, spec, backend=backend,
                                  max_schedules=max_schedules,
                                  faults=faults)
            if res.violations > 0:
                best_prog, best_res = cand, res
                steps += 1
                improved = True
                break  # greedy: restart candidate stream from the smaller
        if not improved:
            return best_prog, best_res, steps
    return best_prog, best_res, steps
