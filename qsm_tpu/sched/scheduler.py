"""Deterministic PULSE-style scheduler over an in-memory actor transport.

The reference builds on ``distributed-process`` (Cloud Haskell): a scheduler
process intercepts instrumented sends into a pending-message pool and, at
quiescence, picks the next message to deliver using QuickCheck-seeded
randomness — producing deterministic, replayable interleavings (SURVEY.md §0
item 2, §3.3; PULSE design after Claessen et al. ICFP'09).

TPU-first redesign: there is no reason to run real OS concurrency to *study*
concurrency.  Processes here are Python generators stepped by the scheduler —
a user-level cooperative runtime, which is exactly what PULSE instruments
Erlang/Haskell processes down to.  All nondeterminism flows from one seeded
RNG choosing message-delivery order; process step order is fixed, so
(seed, program) → identical interleaving → identical history — the
determinism contract that makes shrinking sound (SURVEY.md §7 hard-parts #4).

Processes yield *effects*:

* ``Send(to, payload)`` — asynchronous send, captured into the pool; the
  sender keeps running (actor-mailbox semantics).
* ``Recv()`` — pop the oldest mailbox message, or block until one arrives.

Everything between two yields is atomic, like a Cloud Haskell process between
two ``expect`` calls.

Fault injection (SURVEY.md §5): the scheduler mediates ALL delivery, so
message drop/duplication/partition and process crash are implemented here, as
seeded decisions of a :class:`FaultPlan`.
"""

from __future__ import annotations

import dataclasses
import random
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional


# ---------------------------------------------------------------------------
# Effects & messages
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Send:
    to: str
    payload: Any


class Recv:
    """Block until a message is in this process's mailbox; the yield
    evaluates to the delivered :class:`Message`."""


@dataclasses.dataclass(frozen=True)
class Monitor:
    """Watch another process: when it terminates the watcher's mailbox
    receives ``("DOWN", target, reason)`` with reason "crashed", "done",
    or — for a name that was never spawned — "noproc" (the
    distributed-process monitor/link primitive, including its
    DiedUnknownId case — SURVEY.md §5 failure-detection row:
    "distributed-process has monitors/links").  Non-blocking; a monitor
    on an already-dead or unknown target fires immediately.
    The notification rides the ordinary delivery pool (its arrival order
    interleaves under the same seeded choice as every other message) but
    is exempt from fault injection, like dist-process's local reliable
    notifications."""

    target: str


@dataclasses.dataclass(frozen=True)
class Message:
    src: str
    dst: str
    payload: Any
    uid: int  # global send sequence number (trace/debug)


# ---------------------------------------------------------------------------
# Fault injection
# ---------------------------------------------------------------------------

class FaultPlan:
    """Seeded fault decisions, consulted by the scheduler at delivery time.

    The reference's scheduler position makes these natural (SURVEY.md §5:
    "message drop/delay/duplication and crash injection are possible at the
    scheduler").  Probabilities are applied with the scheduler's own RNG so
    runs stay replayable from the seed.
    """

    DELIVER, DROP, DUPLICATE, DELAY = "deliver", "drop", "duplicate", "delay"

    def __init__(self, p_drop: float = 0.0, p_duplicate: float = 0.0,
                 p_delay: float = 0.0, delay_steps: int = 3,
                 partitions: Optional[List[set]] = None,
                 crash_at: Optional[Dict[str, int]] = None,
                 protected: Optional[set] = None):
        self.p_drop = p_drop
        self.p_duplicate = p_duplicate
        # Explicit delay (SURVEY.md §5 drop/DELAY/duplicate/partition): a
        # delayed message is held out of the deliverable set for the next
        # ``delay_steps`` delivery choices, forcing interleavings where it
        # arrives strictly later than everything sent meanwhile — strictly
        # more than the implicit reordering the pool already allows (which
        # can never push a message past one sent AFTER its competitors).
        self.p_delay = p_delay
        self.delay_steps = delay_steps
        self.partitions = partitions or []
        self.crash_at = dict(crash_at or {})
        # processes whose messages are never dropped (e.g. history plumbing)
        self.protected = protected or set()

    def is_deterministic(self) -> bool:
        """True when this plan never consults the RNG's OUTCOME: crash
        schedules (delivery-count triggers) and partitions (a pure
        (src, dst) predicate) only.  Systematic exploration
        (sched/systematic.py) relies on this to enumerate faulty
        executions exactly — when adding a new seeded fault knob, it
        must make this answer False."""
        return (self.p_drop == 0 and self.p_duplicate == 0
                and self.p_delay == 0)

    def decide(self, msg: Message, rng: random.Random) -> str:
        if msg.src in self.protected or msg.dst in self.protected:
            return self.DELIVER
        for group in self.partitions:
            # a partition blocks traffic crossing the group boundary
            if (msg.src in group) != (msg.dst in group):
                return self.DROP
        r = rng.random()
        if r < self.p_drop:
            return self.DROP
        if r < self.p_drop + self.p_duplicate:
            return self.DUPLICATE
        if r < self.p_drop + self.p_duplicate + self.p_delay:
            return self.DELAY
        return self.DELIVER


# ---------------------------------------------------------------------------
# Scheduler
# ---------------------------------------------------------------------------

class DeadlockError(RuntimeError):
    pass


class PruneRun(Exception):
    """Raised out of :meth:`Scheduler.run` when a ``prune_hook`` recognizes
    the current quiescent state as already explored (sched/systematic.py
    state-fingerprint pruning).  Control flow, not an error."""


@dataclasses.dataclass
class _InFlight:
    """A pooled message plus its fault bookkeeping.

    ``ready_at`` is the delivery-choice count before which a delayed
    message is ineligible; ``decided`` marks that the fault plan already
    ruled on it (a delayed message is delivered when its hold expires — it
    is not re-rolled, which could delay forever)."""

    msg: Message
    ready_at: int = 0
    decided: bool = False


@dataclasses.dataclass
class _Proc:
    name: str
    gen: Iterator
    daemon: bool
    mailbox: deque = dataclasses.field(default_factory=deque)
    blocked: bool = False  # waiting in Recv with empty mailbox
    done: bool = False
    crashed: bool = False
    send_value: Any = None  # value to send into the generator on next step


class Scheduler:
    """Single-threaded deterministic actor scheduler."""

    def __init__(self, seed: int, faults: Optional[FaultPlan] = None,
                 max_steps: int = 100_000, transport=None,
                 choices: Optional[List[int]] = None):
        self.rng = random.Random(seed)
        self.faults = faults
        self.max_steps = max_steps
        # Scripted delivery choices (sched/systematic.py): when set, the
        # k-th delivery takes eligible[choices[k]] instead of a seeded
        # draw, choices past the script's end take eligible[0], and
        # ``choice_log`` records the branching factor at every delivery —
        # the protocol systematic exploration enumerates the full
        # interleaving tree with.  Delivery choice is the ONLY
        # nondeterminism in a faultless run (process step order is fixed),
        # so the script captures the whole schedule.
        self.choices = choices
        self.choice_log: List[int] = []
        self._choice_pos = 0
        # A scripted choice >= the live branching factor means the script
        # was recorded against a different tree (model/faults drifted
        # since capture); the pick is clamped so the run still completes,
        # but the drift must be reportable (utils/cli.py cmd_replay).
        self.choice_clamped = False
        # Called at every quiescent delivery point (before the branching
        # factor is logged); returning True aborts the run via PruneRun.
        # Systematic exploration uses it to skip subtrees whose scheduler
        # state was already explored under an earlier schedule.
        self.prune_hook: Optional[Callable[["Scheduler"], bool]] = None
        # transport carries the bytes; the scheduler keeps every ordering
        # decision (sched/transport.py — None = in-memory, zero overhead).
        # owns_transport: set by prepare_run when the transport was created
        # from a string spec and should be closed with the run
        self.transport = transport
        self.owns_transport = False
        self.procs: Dict[str, _Proc] = {}
        self.monitors: Dict[str, List[str]] = {}  # target -> watchers
        self.pool: List[_InFlight] = []  # in-flight messages
        self.clock = 0  # logical event clock (history timestamps)
        self.trace: List[int] = []  # delivered message uids, in order
        self.n_delivered = 0  # delivery choices made (delays count too)
        self._uid = 0
        self._steps = 0

    # -- process management ------------------------------------------------
    def spawn(self, name: str, gen: Iterator, daemon: bool = False) -> None:
        assert name not in self.procs, f"duplicate process {name}"
        self.procs[name] = _Proc(name=name, gen=gen, daemon=daemon)

    def crash(self, name: str) -> None:
        """Kill a process: it stops running; messages to it are dropped."""
        p = self.procs.get(name)
        if p and not p.done:
            p.crashed = True
            p.done = True
            p.gen.close()
            self._notify_down(name, "crashed")

    def _pool_down(self, target: str, watcher: str, reason: str) -> None:
        """ONE construction site for the DOWN notification: pooled like
        any send (arrival order is the scheduler's seeded choice) but
        pre-decided so the fault plan never drops/delays it —
        dist-process's reliable local monitor semantics."""
        self._uid += 1
        self.pool.append(_InFlight(
            Message(src=target, dst=watcher,
                    payload=("DOWN", target, reason), uid=self._uid),
            decided=True))

    def _notify_down(self, target: str, reason: str) -> None:
        """Enqueue DOWN notifications for every watcher of ``target``."""
        for watcher in self.monitors.pop(target, []):
            self._pool_down(target, watcher, reason)

    def tick(self) -> int:
        """Advance the logical clock (history event timestamps)."""
        self.clock += 1
        return self.clock

    # -- main loop ---------------------------------------------------------
    def _bump_steps(self) -> None:
        """Count one unit of scheduler work against ``max_steps``."""
        self._steps += 1
        if self._steps > self.max_steps:
            raise DeadlockError(
                f"scheduler exceeded max_steps={self.max_steps}")

    def _runnable(self) -> List[_Proc]:
        return [p for p in self.procs.values()
                if not p.done and not p.blocked]

    def _step(self, p: _Proc) -> None:
        """Run one process until it blocks, sends+continues, or finishes.
        Sends captured here go to the pool, NOT straight to mailboxes —
        delivery order is the scheduler's seeded choice."""
        while True:
            self._bump_steps()
            try:
                eff = p.gen.send(p.send_value)
            except StopIteration:
                p.done = True
                self._notify_down(p.name, "done")
                return
            p.send_value = None
            if isinstance(eff, Send):
                self._uid += 1
                msg = Message(src=p.name, dst=eff.to,
                              payload=eff.payload, uid=self._uid)
                if self.transport is not None:
                    msg = self.transport.uplink(msg)
                self.pool.append(_InFlight(msg))
                continue  # async send: sender keeps running
            if isinstance(eff, Recv):
                if p.mailbox:
                    p.send_value = p.mailbox.popleft()
                    continue
                p.blocked = True
                return
            if isinstance(eff, Monitor):
                tgt = self.procs.get(eff.target)
                if tgt is None or tgt.done:
                    # already dead: fire immediately (dist-process
                    # notifies monitors of dead/unknown pids at once)
                    reason = ("crashed" if tgt and tgt.crashed
                              else "done" if tgt else "noproc")
                    self._pool_down(eff.target, p.name, reason)
                else:
                    self.monitors.setdefault(eff.target,
                                             []).append(p.name)
                continue  # non-blocking: watcher keeps running
            raise TypeError(f"process {p.name} yielded {eff!r}")

    def _deliver_one(self) -> None:
        """Quiescence point: seeded choice of the next in-flight message."""
        if self.prune_hook is not None and self.prune_hook(self):
            raise PruneRun
        # Deliveries count against max_steps too: duplication faults can
        # otherwise spin the pool forever with no process ever runnable.
        self._bump_steps()
        eligible = [i for i, f in enumerate(self.pool)
                    if f.ready_at <= self.n_delivered]
        if not eligible:
            # every message is held: nothing else can interleave before the
            # holds expire, so delivering early is history-equivalent —
            # and avoids wedging the run on a pure bookkeeping state
            eligible = list(range(len(self.pool)))
        if self.choices is not None:
            self.choice_log.append(len(eligible))
            k = (self.choices[self._choice_pos]
                 if self._choice_pos < len(self.choices) else 0)
            self._choice_pos += 1
            if k >= len(eligible):
                self.choice_clamped = True
            pick = eligible[min(k, len(eligible) - 1)]
        else:
            pick = eligible[self.rng.randrange(len(eligible))]
        inf = self.pool.pop(pick)
        msg = inf.msg
        action = (self.faults.decide(msg, self.rng)
                  if self.faults and not inf.decided else FaultPlan.DELIVER)
        if action == FaultPlan.DROP:
            return
        if action == FaultPlan.DELAY:
            inf.decided = True  # one ruling per message: no re-rolls
            inf.ready_at = self.n_delivered + self.faults.delay_steps
            self.pool.append(inf)
            return
        if action == FaultPlan.DUPLICATE:
            self._uid += 1
            self.pool.append(
                _InFlight(dataclasses.replace(msg, uid=self._uid)))
        dst = self.procs.get(msg.dst)
        if dst is None or dst.done:
            return  # message to dead/unknown process: dropped
        if self.transport is not None:
            msg = self.transport.downlink(msg)
        self.trace.append(msg.uid)
        dst.mailbox.append(msg)
        if dst.blocked:
            dst.blocked = False
            dst.send_value = dst.mailbox.popleft()

    def run(self) -> None:
        """Run to completion: all non-daemon processes finished.

        Crash schedule (fault plan) is applied on delivery counts.  If the
        system wedges (clients blocked, nothing in flight) the run simply
        ends — unresponded operations surface as *pending* ops in the
        history, which the lineariser complete/prunes (SURVEY.md §3.2)."""
        fired_crashes = set()  # scheduler-local: never mutate the shared plan
        # Per-run state: a Scheduler reused for a second run() must not
        # inherit the previous run's bookkeeping (ADVICE.md round 2 —
        # crash_at would fire against a stale delivery count, and a
        # half-reset is worse than none: stale _steps would turn a long
        # second run into a phantom DeadlockError, and a wedged first run's
        # undelivered pool would leak into the second).  Only the RNG
        # persists — reuse continues the seeded stream, which stays
        # deterministic for (seed, spawn-sequence) pairs.
        self.n_delivered = 0
        self._steps = 0
        self.clock = 0
        self.pool.clear()
        self.trace.clear()
        self.monitors.clear()
        self.choice_log.clear()
        self._choice_pos = 0
        self.choice_clamped = False
        while True:
            runnable = self._runnable()
            if runnable:
                # Fixed order: interleavings come from delivery choice only.
                self._step(runnable[0])
                continue
            if self.faults:
                for name, at in self.faults.crash_at.items():
                    if self.n_delivered >= at and name not in fired_crashes:
                        self.crash(name)
                        fired_crashes.add(name)
            clients_left = [p for p in self.procs.values()
                            if not p.daemon and not p.done]
            if not clients_left:
                return
            if not self.pool:
                return  # wedged: pending ops recorded by the runner
            self._deliver_one()
            self.n_delivered += 1
