"""Concurrent runner — the reference's ``runCommands`` (BASELINE.json:5).

Drives a generated :class:`~qsm_tpu.core.generator.Program` through the
deterministic scheduler against a concurrent SUT, recording per-pid
invocation/response events into a :class:`~qsm_tpu.core.history.History`
(SURVEY.md §3.1: everything between runCommands and the collected History
crosses actor mailboxes).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Protocol

from ..core.generator import Program
from ..core.history import NO_RESP, History, Op
from .scheduler import FaultPlan, Recv, Scheduler, Send

# Response time sentinel for pending ops: later than any real timestamp.
PENDING_T = 1 << 30


class ConcurrentSUT(Protocol):
    """A system under test living inside the scheduler world.

    ``setup(sched)`` spawns server/daemon processes; ``perform`` is a
    *generator* (it may yield Send/Recv effects) executing one operation on
    behalf of a client pid and returning the response value.
    """

    def setup(self, sched: Scheduler) -> None: ...
    def perform(self, pid: int, cmd: int, arg: int): ...


@dataclasses.dataclass
class _Rec:
    pid: int
    cmd: int
    arg: int
    invoke_time: int
    resp: int = NO_RESP
    response_time: int = PENDING_T


class HistoryRecorder:
    """Collects invoke/response events with scheduler-clock timestamps."""

    def __init__(self, sched: Scheduler):
        self.sched = sched
        self.recs: List[_Rec] = []

    def invoke(self, pid: int, cmd: int, arg: int) -> int:
        self.recs.append(_Rec(pid=pid, cmd=cmd, arg=arg,
                              invoke_time=self.sched.tick()))
        return len(self.recs) - 1

    def respond(self, op_id: int, resp: int) -> None:
        r = self.recs[op_id]
        r.resp = int(resp)
        r.response_time = self.sched.tick()

    def history(self, seed: Optional[int] = None,
                program_id: Optional[int] = None) -> History:
        ops = [Op(pid=r.pid, cmd=r.cmd, arg=r.arg, resp=r.resp,
                  invoke_time=r.invoke_time, response_time=r.response_time)
               for r in self.recs]
        ops.sort(key=lambda o: o.invoke_time)
        return History(ops, seed=seed, program_id=program_id)


def _client(rec: HistoryRecorder, sut: ConcurrentSUT, pid: int, ops):
    for op in ops:
        op_id = rec.invoke(pid, op.cmd, op.arg)
        resp = yield from sut.perform(pid, op.cmd, op.arg)
        rec.respond(op_id, resp)


def prepare_run(
    sut: ConcurrentSUT,
    program: Program,
    seed,  # int or str; any random.Random seed value
    faults: Optional[FaultPlan] = None,
    max_steps: int = 100_000,
    transport=None,  # None/"memory"/"tcp" or a Transport instance
    choices=None,  # scripted delivery choices (sched/systematic.py)
) -> tuple:
    """(scheduler, recorder) wired up and ready to ``sched.run()``.

    Split out of :func:`run_concurrent` so callers that need scheduler
    internals afterwards (e.g. the delivery trace, for coverage stats) share
    the exact same run protocol."""
    # ownership: a transport created HERE (from a string) is closed by
    # run_concurrent's finally; a caller-passed instance stays the
    # caller's — the property layer reuses ONE TCP transport across every
    # execution of a run (per-endpoint connections persist, so a 50k-
    # candidate shrink doesn't burn 50k ephemeral ports into TIME_WAIT)
    owns = isinstance(transport, str)
    if owns:
        from .transport import make_transport

        transport = make_transport(transport)
    try:
        sched = Scheduler(seed=seed, faults=faults, max_steps=max_steps,
                          transport=transport, choices=choices)
        rec = HistoryRecorder(sched)
        sut.setup(sched)
        for pid, ops in enumerate(program.per_pid()):
            if ops:
                sched.spawn(f"client:{pid}", _client(rec, sut, pid, ops))
    except BaseException:
        if owns and transport is not None:
            transport.close()
        raise
    sched.owns_transport = owns
    return sched, rec


def run_concurrent(
    sut: ConcurrentSUT,
    program: Program,
    seed,
    faults: Optional[FaultPlan] = None,
    max_steps: int = 100_000,
    transport=None,
    choices=None,  # scripted delivery choices (sched/systematic.py)
    sched_info: Optional[dict] = None,  # out-param: run diagnostics
) -> History:
    """Execute ``program`` concurrently; return its history.

    Determinism contract: identical (sut, program, seed, faults) → identical
    History, bit for bit — on EVERY transport (sched/transport.py); the
    transport carries bytes, never ordering.  Unresponded ops
    (faults/wedges) come back as pending ops for the lineariser to
    complete/prune.
    """
    sched, rec = prepare_run(sut, program, seed, faults, max_steps,
                             transport=transport, choices=choices)
    try:
        sched.run()
    finally:
        if sched.transport is not None and sched.owns_transport:
            sched.transport.close()
        if sched_info is not None:
            # choice_clamped: a scripted choice exceeded the live branching
            # factor — the replayed script no longer matches the tree
            sched_info["choice_clamped"] = sched.choice_clamped
    return rec.history(seed=seed)
