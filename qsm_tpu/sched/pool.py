"""Parallel execution plane — seed-deterministic schedules over worker
processes.

The reference executes one schedule at a time inside one OS process; its
distribution story is actors over transports (SURVEY.md §5).  Here the
checker plane already batches on the device, which makes host-side
*execution* the dominant end-to-end cost once checking is fast
(BENCH_E2E_r03.json: execute ≈ check for the memoised host checker).
Schedules are embarrassingly parallel BY CONSTRUCTION: every history is a
pure function of (SUT factory, program, seed, faults), so fanning
executions over worker processes changes wall-clock, never histories —
the property layer requires bit-identical results to the serial path
(tests/test_pool.py pins this).

Workers are spawned (not forked: JAX-initialized parents must not fork)
and live for the whole property run; each builds its SUT once from a
picklable factory (``qsm_tpu.models.registry.SutFactory`` or any
module-level callable) and reuses it — ``setup`` resets SUT state per
run, exactly as the serial loop relies on.

WHEN IT PAYS (measured, this image): worker warmup is ~4 s/worker (the
image's sitecustomize imports run in every interpreter) and steady-state
dispatch ~0.7 ms/job, so fan-out wins only when a single execution costs
≳2 ms — real transports, heavy SUT step functions, large programs.  The
in-tree toy SUTs execute in ~0.3 ms and are FASTER serial; that is why
``executor_workers`` defaults to 0.  This mirrors the reference's
reality: its SUTs are real distributed systems where execution is
network-bound, and that is the regime this plane exists for.
"""

from __future__ import annotations

import multiprocessing
import os
from typing import List, Optional, Sequence, Tuple

from ..core.history import History

_STATE: dict = {}


def _init_worker(sut_factory, transport_spec: str) -> None:
    from .transport import make_transport

    _STATE["sut"] = sut_factory()
    _STATE["transport"] = (None if transport_spec == "memory"
                           else make_transport(transport_spec))


def _probe_ok() -> bool:
    """Initialization probe: only returns once _init_worker succeeded."""
    return "sut" in _STATE


def _run_one(job) -> History:
    from .runner import run_concurrent

    prog, seed, faults, max_steps = job
    return run_concurrent(_STATE["sut"], prog, seed, faults=faults,
                          max_steps=max_steps,
                          transport=_STATE["transport"])


class PoolExecutor:
    """Executes (program, seed) jobs over a persistent process pool,
    preserving input order (and therefore every downstream decision)."""

    # generous ceiling for ONE job: spawn warmup is ~4 s/worker on this
    # image; an in-tree job is sub-millisecond.  Exists to turn a
    # worker-init crash into an error — multiprocessing.Pool silently
    # respawns crashing workers forever, so a sut_factory that fails in
    # the fresh interpreter (unpicklable closure, missing import) would
    # otherwise wedge run_many with no diagnostic at all.
    PROBE_TIMEOUT_S = 60.0

    def __init__(self, sut_factory, n_workers: Optional[int] = None,
                 transport: str = "memory"):
        self.n_workers = n_workers or min(8, os.cpu_count() or 2)
        ctx = multiprocessing.get_context("spawn")
        self._pool = ctx.Pool(self.n_workers, initializer=_init_worker,
                              initargs=(sut_factory, transport))
        self.jobs_run = 0
        self._probed = False

    def _probe(self) -> None:
        """Fail fast if workers cannot initialize (see PROBE_TIMEOUT_S)."""
        if self._probed:
            return
        try:
            self._pool.apply_async(_probe_ok).get(self.PROBE_TIMEOUT_S)
        except multiprocessing.TimeoutError:
            self.close()
            raise RuntimeError(
                "worker pool failed to initialize within "
                f"{self.PROBE_TIMEOUT_S:.0f}s — the sut_factory probably "
                "crashes in a fresh interpreter (it must be picklable and "
                "importable under the spawn start method; use "
                "models.registry.SutFactory)") from None
        self._probed = True

    def run_many(self, jobs: Sequence[Tuple], faults, max_steps: int
                 ) -> List[History]:
        """Execute jobs = [(program, seed), ...]; returns histories in job
        order, bit-identical to serial execution."""
        self._probe()
        payload = [(p, s, faults, max_steps) for p, s in jobs]
        # one chunk per worker: each run_many is a barrier anyway (its
        # verdicts gate the next step), so finer chunks only add IPC
        chunk = max(1, -(-len(payload) // self.n_workers))
        out = self._pool.map(_run_one, payload, chunksize=chunk)
        self.jobs_run += len(out)
        return out

    def close(self) -> None:
        self._pool.terminate()
        self._pool.join()
