"""Parallel execution plane — seed-deterministic schedules over worker
processes.

The reference executes one schedule at a time inside one OS process; its
distribution story is actors over transports (SURVEY.md §5).  Here the
checker plane already batches on the device, which makes host-side
*execution* the dominant end-to-end cost once checking is fast
(BENCH_E2E_r03.json: execute ≈ check for the memoised host checker).
Schedules are embarrassingly parallel BY CONSTRUCTION: every history is a
pure function of (SUT factory, program, seed, faults), so fanning
executions over worker processes changes wall-clock, never histories —
the property layer requires bit-identical results to the serial path
(tests/test_pool.py pins this).

Workers are spawned (not forked: JAX-initialized parents must not fork)
and live for the whole property run; each builds its SUT once from a
picklable factory (``qsm_tpu.models.registry.SutFactory`` or any
module-level callable) and reuses it — ``setup`` resets SUT state per
run, exactly as the serial loop relies on.

WHEN IT PAYS (measured, this image): worker warmup is ~4 s/worker (the
image's sitecustomize imports run in every interpreter) and steady-state
dispatch ~0.7 ms/job, so fan-out wins only when a single execution costs
≳2 ms — real transports, heavy SUT step functions, large programs.  The
in-tree toy SUTs execute in ~0.3 ms and are FASTER serial; that is why
``executor_workers`` defaults to 0.  This mirrors the reference's
reality: its SUTs are real distributed systems where execution is
network-bound, and that is the regime this plane exists for.
"""

from __future__ import annotations

import multiprocessing
import os
from typing import List, Optional, Sequence, Tuple

from ..core.history import History

_STATE: dict = {}


def _init_worker(sut_factory, transport_spec: str) -> None:
    from .transport import make_transport

    _STATE["sut"] = sut_factory()
    _STATE["transport"] = (None if transport_spec == "memory"
                           else make_transport(transport_spec))


def _probe_ok() -> bool:
    """Initialization probe: only returns once _init_worker succeeded."""
    return "sut" in _STATE


def _run_one(job) -> History:
    from .runner import run_concurrent

    prog, seed, faults, max_steps = job
    return run_concurrent(_STATE["sut"], prog, seed, faults=faults,
                          max_steps=max_steps,
                          transport=_STATE["transport"])


class _SpawnPool:
    """Shared lifecycle for the two worker pools: spawn context (JAX-
    initialized parents must not fork), a bounded initialization probe —
    multiprocessing.Pool silently respawns crashing workers forever, so
    a sut_factory that fails in the fresh interpreter (unpicklable
    closure, missing import) would otherwise wedge the first map with no
    diagnostic — and terminate-on-close."""

    PROBE_TIMEOUT_S = 60.0

    def __init__(self, initializer, initargs,
                 n_workers: Optional[int] = None):
        self.n_workers = n_workers or min(8, os.cpu_count() or 2)
        ctx = multiprocessing.get_context("spawn")
        self._pool = ctx.Pool(self.n_workers, initializer=initializer,
                              initargs=initargs)
        self._probed = False

    def _probe_fn(self):  # -> picklable callable returning True when init ran
        raise NotImplementedError

    def _probe(self) -> None:
        if self._probed:
            return
        try:
            self._pool.apply_async(self._probe_fn()).get(
                self.PROBE_TIMEOUT_S)
        except multiprocessing.TimeoutError:
            self.close()
            raise RuntimeError(
                "worker pool failed to initialize within "
                f"{self.PROBE_TIMEOUT_S:.0f}s — the sut_factory probably "
                "crashes in a fresh interpreter (it must be picklable "
                "and importable under the spawn start method; use "
                "models.registry.SutFactory)") from None
        self._probed = True

    def close(self) -> None:
        self._pool.terminate()
        self._pool.join()


def _init_explore_worker(sut_factory) -> None:
    _STATE["sut_factory"] = sut_factory


def _explore_probe_ok() -> bool:
    return "sut_factory" in _STATE


def _explore_one(job):
    """Enumerate ONE program's delivery tree in this worker (the checker
    batch stays in the parent).  Returns (histories, schedules,
    exhausted, pruned, seconds)."""
    import time

    from .systematic import _enumerate

    prog, max_schedules, max_steps, prune, faults = job
    t0 = time.perf_counter()
    hists, schedules, exhausted, pruned_n = _enumerate(
        _STATE["sut_factory"], prog, max_schedules, max_steps,
        prune=prune, faults=faults)
    return hists, schedules, exhausted, pruned_n, time.perf_counter() - t0


class ExplorePool(_SpawnPool):
    """Fans whole-tree enumerations over a persistent spawn pool.  ONE
    tree is milliseconds-to-seconds of pure-Python replay walking —
    coarse enough that per-job dispatch (~0.7 ms) is noise, unlike the
    execution pool's sub-millisecond jobs.  Enumeration is deterministic
    per program, so results are bit-identical to the serial walk
    (tests/test_pool.py); the device-shaped union batch is still decided
    by the CALLER in one backend call — workers never touch JAX.

    MEASURED ON THIS IMAGE (honest caveat): the build host has ONE CPU
    core (`os.cpu_count() == 1`), so fan-out cannot beat serial here —
    8 workers on 8 big trees (77 s serial) measured 0.75× from pure
    contention + spawn warmup.  The default stays 0 (serial); the
    feature exists for multi-core hosts, where wall-clock ≈ warmup +
    the largest tree instead of the sum."""

    def __init__(self, sut_factory, n_workers: Optional[int] = None):
        super().__init__(_init_explore_worker, (sut_factory,),
                         n_workers=n_workers)

    def _probe_fn(self):
        return _explore_probe_ok

    def explore_many(self, programs: Sequence, max_schedules: int,
                     max_steps: int, prune: bool, faults) -> List[Tuple]:
        """[(histories, schedules, exhausted, pruned, seconds)] in
        program order."""
        self._probe()
        payload = [(p, max_schedules, max_steps, prune, faults)
                   for p in programs]
        # chunksize 1: trees are coarse and wildly uneven (12 to 100k+
        # schedules); per-worker pre-chunking would serialize the big
        # tree behind small ones
        return self._pool.map(_explore_one, payload, chunksize=1)


class PoolExecutor(_SpawnPool):
    """Executes (program, seed) jobs over a persistent process pool,
    preserving input order (and therefore every downstream decision)."""

    def __init__(self, sut_factory, n_workers: Optional[int] = None,
                 transport: str = "memory"):
        super().__init__(_init_worker, (sut_factory, transport),
                         n_workers=n_workers)
        self.jobs_run = 0

    def _probe_fn(self):
        return _probe_ok

    def run_many(self, jobs: Sequence[Tuple], faults, max_steps: int
                 ) -> List[History]:
        """Execute jobs = [(program, seed), ...]; returns histories in job
        order, bit-identical to serial execution."""
        self._probe()
        payload = [(p, s, faults, max_steps) for p, s in jobs]
        # one chunk per worker: each run_many is a barrier anyway (its
        # verdicts gate the next step), so finer chunks only add IPC
        chunk = max(1, -(-len(payload) // self.n_workers))
        out = self._pool.map(_run_one, payload, chunksize=chunk)
        self.jobs_run += len(out)
        return out

    def close(self) -> None:
        self._pool.terminate()
        self._pool.join()
