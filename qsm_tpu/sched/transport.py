"""Pluggable message transports for the scheduler plane.

The reference runs its actors over ``network-transport-tcp`` or
``network-transport-inmemory`` (SURVEY.md §5 comm backend row); the
deterministic scheduler sits above either.  Same split here:

* :class:`InMemoryTransport` — the default; messages move as Python
  objects, zero overhead (the reference's in-memory transport).
* :class:`TcpLoopbackTransport` — every captured send and every delivery
  physically traverses a real localhost TCP connection (length-prefixed
  pickle frames), one persistent connection per process endpoint, exactly
  as ``network-transport-tcp`` carries Cloud Haskell actor mail.

Determinism is untouched by construction: the scheduler still makes every
ordering decision from its seeded RNG; the transport only carries bytes,
synchronously, over per-connection FIFO streams.  What the TCP variant
adds is the real-transport guarantees the in-memory path can't exercise:
payloads must survive serialization, and messages really cross the OS
socket layer (tests/test_transport.py pins history bit-equality between
the two transports).
"""

from __future__ import annotations

import pickle
import select
import socket
import struct
from typing import Dict, Tuple

from .scheduler import Message

_LEN = struct.Struct(">I")


class InMemoryTransport:
    """Messages move as Python objects — the reference's in-memory path."""

    name = "memory"

    def uplink(self, msg: Message) -> Message:
        """Carry a captured send from its source process to the pool."""
        return msg

    def downlink(self, msg: Message) -> Message:
        """Carry a chosen delivery from the pool to its destination."""
        return msg

    def close(self) -> None:
        pass


def _roundtrip(send_sock: socket.socket, recv_sock: socket.socket,
               msg: Message) -> Message:
    """Write one frame on ``send_sock`` and read it back off ``recv_sock``,
    interleaved via select: both ends are driven by the ONE scheduler
    thread, so a blocking ``sendall`` of a frame larger than the loopback
    buffers would deadlock (nobody would ever drain the peer).  Payload
    size is therefore unbounded, not silently capped."""
    blob = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    out = memoryview(_LEN.pack(len(blob)) + blob)
    chunks = []
    got = 0
    need = None  # total inbound frame size, once the header is in
    while out or need is None or got < _LEN.size + need:
        ws = [send_sock] if out else []
        rs, wr, _ = select.select([recv_sock], ws, [], 60.0)
        if not rs and not wr:
            raise ConnectionError("transport round-trip stalled (60s)")
        if wr:
            sent = send_sock.send(out[:65536])
            out = out[sent:]
        if rs:
            chunk = recv_sock.recv(65536)
            if not chunk:
                raise ConnectionError("transport peer closed mid-frame")
            chunks.append(chunk)
            got += len(chunk)
            if need is None and got >= _LEN.size:
                head = b"".join(chunks)
                (need,) = _LEN.unpack(head[:_LEN.size])
                chunks = [head]
    return pickle.loads(b"".join(chunks)[_LEN.size:])


class TcpLoopbackTransport:
    """Real localhost TCP transport under the deterministic scheduler.

    One listener plays the broker side (the scheduler's end); each named
    process endpoint gets ONE persistent loopback connection, created
    lazily at its first send or delivery.  ``uplink`` writes the captured
    send on the source's connection and the broker reads it back off the
    accepted peer; ``downlink`` writes the chosen delivery on the broker's
    peer for the destination and reads it off the destination's
    connection.  Both are synchronous round-trips on FIFO streams driven
    by the single scheduler thread, so the seeded interleaving decisions
    are exactly as replayable as in memory — histories are required to be
    bit-identical across transports.
    """

    name = "tcp"

    def __init__(self):
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(128)
        self.address = self._listener.getsockname()
        # endpoint name -> (process-side socket, broker-side socket)
        self._conns: Dict[str, Tuple[socket.socket, socket.socket]] = {}
        self.frames = 0  # frames actually carried over TCP (tests/stats)

    def _conn(self, name: str) -> Tuple[socket.socket, socket.socket]:
        pair = self._conns.get(name)
        if pair is None:
            client = socket.create_connection(self.address)
            client.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            broker, _ = self._listener.accept()
            broker.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            pair = (client, broker)
            self._conns[name] = pair
        return pair

    def uplink(self, msg: Message) -> Message:
        client, broker = self._conn(msg.src)
        self.frames += 1
        return _roundtrip(client, broker, msg)

    def downlink(self, msg: Message) -> Message:
        client, broker = self._conn(msg.dst)
        self.frames += 1
        return _roundtrip(broker, client, msg)

    def close(self) -> None:
        for client, broker in self._conns.values():
            for s in (client, broker):
                try:
                    s.close()
                except OSError:
                    pass
        self._conns.clear()
        try:
            self._listener.close()
        except OSError:
            pass


def make_transport(kind: str):
    if kind == "memory":
        return InMemoryTransport()
    if kind == "tcp":
        return TcpLoopbackTransport()
    raise ValueError(f"unknown transport {kind!r}")
