"""Graceful degradation: any device engine, host-backed on device loss.

``FailoverBackend`` wraps a device lineariser (``JaxTPU``,
``PallasTPU``, ``HybridDevice``, the router — anything with
``check_histories``) and guarantees the PROPERTY RUN SURVIVES the device
not surviving: a dispatch that times out, raises the XLA runtime error,
or hits an injected fault (resilience/faults.py) degrades the backend to
the configured host fallback — the cpp → memo oracle ladder by default —
and the run continues with exact verdicts.

Semantics that make this sound rather than hopeful:

* **Undecided lanes only.**  Dispatch happens in slices of
  ``dispatch_lanes``; verdicts banked from completed slices are
  PRESERVED and only the not-yet-decided remainder re-dispatches to the
  fallback.  A window that closes after slice 2 of 6 loses the in-flight
  slice, nothing more.
* **One-way degradation.**  A lost device stays lost for this backend
  instance: later batches go straight to the fallback instead of paying
  the timeout again per call.
* **Exact fallbacks only.**  The fallback ladder is the property
  layer's own resolution oracle family (native C++ when the toolchain
  is present, else the memoised Wing–Gong oracle), so degraded verdicts
  and witnesses are bit-identical to a clean host run — pinned by
  tests/test_resilience.py across model families.

Counters (``degradations``, ``retries``, ``fallback_engine``,
``device_histories``, ``fallback_histories``) ride ``search_stats()``
and :func:`collect_resilience` into PropertyResult.timings, bench rows,
and ``qsm-tpu stats`` — BENCH artifacts are self-describing about fault
handling.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..core.history import History
from ..core.spec import Spec
from ..ops.backend import Verdict, device_error_types
from .policy import RetryPolicy, preset, watchdog


def host_fallback(spec: Spec):
    """The host checker ladder every degradation lands on: the native
    C++ oracle when the toolchain builds it (itself falling back to the
    Python oracle for anything outside native coverage), else the
    memoised Wing–Gong oracle.  The SAME ladder the property layer's
    resolution oracle and the hybrid backend's tail use — one
    definition (ops/hybrid.py imports this one)."""
    from ..native import CppOracle, native_available
    from ..ops.wing_gong_cpu import WingGongCPU

    if native_available():
        return CppOracle(spec)
    return WingGongCPU(memo=True)


class FailoverBackend:
    """Device majority while the device lives; host ladder the moment it
    does not.  See the module docstring for the guarantees."""

    # Lanes per guarded dispatch: the unit of bankable progress.  Small
    # enough that a mid-run loss forfeits little, large enough that the
    # device still sees batched work (the kernel re-buckets internally).
    DISPATCH_LANES = 1024

    def __init__(self, spec: Spec, device, fallback=None,
                 policy: Optional[RetryPolicy] = None,
                 dispatch_lanes: Optional[int] = None):
        self.spec = spec
        self.device = device
        self.fallback = fallback if fallback is not None \
            else host_fallback(spec)
        self.policy = policy or preset("dispatch")
        self.dispatch_lanes = dispatch_lanes or self.DISPATCH_LANES
        self.name = f"failover({getattr(device, 'name', type(device).__name__)})"
        self.degraded = False
        self.degradations = 0       # device-loss events (0 or 1 per instance)
        self.retries = 0            # extra dispatch attempts before degrading
        self.fallback_engine = ""   # set on first degradation
        self.last_error = ""
        self.device_histories = 0   # lanes the device decided
        self.fallback_histories = 0  # lanes the host ladder decided

    # ------------------------------------------------------------------
    def check_histories(self, spec: Spec,
                        histories: Sequence[History]) -> np.ndarray:
        out = np.full(len(histories), int(Verdict.BUDGET_EXCEEDED), np.int8)
        pending = list(range(len(histories)))
        while pending and not self.degraded:
            idx = pending[:self.dispatch_lanes]
            try:
                sub = self._guarded_dispatch(spec,
                                             [histories[i] for i in idx])
            except device_error_types() as e:
                self._degrade(e)
                break  # the in-flight slice stays pending
            out[np.asarray(idx)] = np.asarray(sub, np.int8)
            self.device_histories += len(idx)
            pending = pending[self.dispatch_lanes:]
        if pending:
            # undecided lanes ONLY: verdicts banked above are preserved
            sub = self.fallback.check_histories(
                spec, [histories[i] for i in pending])
            out[np.asarray(pending)] = np.asarray(sub, np.int8)
            self.fallback_histories += len(pending)
        return out

    def check_witness(self, spec: Spec, history: History):
        """Witness from whichever side is alive; fallback witnesses are
        the host oracle's own — bit-identical to a clean host run."""
        if not self.degraded and hasattr(self.device, "check_witness"):
            try:
                return watchdog(
                    lambda: self.device.check_witness(spec, history),
                    self.policy.timeout_s, label=f"{self.name}.witness")
            except device_error_types() as e:
                self._degrade(e)
        return self.fallback.check_witness(spec, history)

    # ------------------------------------------------------------------
    def _guarded_dispatch(self, spec, hists):
        """One slice through the policy: each attempt bounded by the
        watchdog, retries spaced/bounded by the policy's ladder.  Raises
        a device-loss error once the ladder is exhausted."""
        state = {"attempt": 0}

        def attempt():
            state["attempt"] += 1
            if state["attempt"] > 1:
                self.retries += 1
            return watchdog(
                lambda: self.device.check_histories(spec, hists),
                self.policy.timeout_s, label=f"{self.name}.dispatch")

        return self.policy.run(attempt, retriable=device_error_types())

    def _degrade(self, err: BaseException) -> None:
        self.degraded = True
        self.degradations += 1
        self.fallback_engine = getattr(self.fallback, "name",
                                       type(self.fallback).__name__)
        self.last_error = f"{type(err).__name__}: {err}"[:200]
        # trace plane (qsm_tpu/obs): a degradation is a component event
        # in the serving stack's flight ring — no obs handle plumbed
        # through engine constructors, the global sink (set by the
        # check server) receives it; a no-sink process pays one read
        from ..obs import emit_global

        emit_global("failover.degrade", engine=self.name,
                    fallback=self.fallback_engine,
                    error=self.last_error)

    # ------------------------------------------------------------------
    def resilience(self) -> dict:
        """The self-describing counter block bench rows/CLI stats embed
        (:func:`collect_resilience` finds this by convention)."""
        return {
            "degradations": self.degradations,
            "retries": self.retries,
            "fallback_engine": self.fallback_engine or None,
            "device_histories": self.device_histories,
            "fallback_histories": self.fallback_histories,
            "policy": self.policy.name,
            **({"last_error": self.last_error} if self.last_error else {}),
        }

    def search_stats(self):
        """The wrapped engine's cost record with the resilience counters
        folded in; fallback nodes are absorbed ALONGSIDE device iters —
        the hybrid backend's honesty rule (work moved to the host is
        only a saving when the host's nodes are shown too)."""
        from ..search.stats import SearchStats, collect_search_stats

        st = collect_search_stats(self.device) or SearchStats()
        st.engine = self.name
        st.degradations += self.degradations
        st.retries += self.retries
        if self.fallback_engine:
            st.fallback_engine = self.fallback_engine
        if self.fallback_histories:
            st.tail_histories += self.fallback_histories
            st.absorb(collect_search_stats(self.fallback))
        return st


def collect_resilience(backend) -> dict:
    """Resilience counters for ANY backend — zeros when it exposes none,
    so every bench row can stamp the block unconditionally (an artifact
    that says ``degradations: 0`` is a claim; a missing key is a
    shrug).  Probes the conventional wrapper attributes like
    ``collect_search_stats`` does."""
    for obj in (backend, getattr(backend, "device", None),
                getattr(backend, "plain", None),
                getattr(backend, "inner", None)):
        fn = getattr(obj, "resilience", None)
        if callable(fn):
            return fn()
    return {"degradations": 0, "retries": 0, "fallback_engine": None}
