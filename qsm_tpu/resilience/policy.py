"""ONE retry/deadline policy for the whole stack.

Every prior round grew its own ad-hoc bounded-call constants — bench.py's
``--retries 2 --retry-interval 30``, the watcher's ``--probe-timeout 60
--retries 4 --retry-interval 10`` seize literals, ``utils/device.py``'s
bare 45 s — and the round-5 verdict (717 probes, 9 device hits) showed
what scattered knobs cost: nobody can say what the stack actually does
when the chip wedges, because six call sites answer differently.

This module is the single source of truth: a :class:`RetryPolicy` value
type (bounded attempts, exponential backoff + jitter, a wall-clock
deadline across the whole retry ladder) plus the :data:`PRESETS` table of
named policies every probe/dispatch/seize site refers to BY NAME.  A
timeout that needs retuning is edited here once; artifacts that stamp a
policy name stay self-describing across the retune.

The second export is :func:`watchdog`: a bounded in-process call.  A
wedged chip tunnel makes in-process device dispatch uninterruptible
(VERDICT.md round 1 — the first ``jax.devices()`` blocks forever), so the
watchdog runs the dispatch on an abandonable daemon thread: on timeout
the CALL is lost but the PROCESS survives to degrade to a host backend
(resilience/failover.py).  Abandonment, not cancellation — cancellation
does not exist for a hung XLA call.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable, Iterator, Optional, Sequence


class WatchdogTimeout(RuntimeError):
    """A watchdogged call exceeded its bound and was abandoned (the
    worker thread may still be wedged on the device; the caller is free
    to degrade)."""


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retries + backoff + deadline as one immutable value.

    ``attempts``   total tries (1 = no retry).
    ``timeout_s``  per-attempt wall-clock bound (None = unbounded; only
                   sensible for host-side work that cannot wedge).
    ``backoff_s``  sleep before the first retry; multiplied by
                   ``backoff_factor`` per further retry.
    ``jitter_frac`` ± fraction of each backoff randomized (decorrelates
                   fleet retries; 0 keeps the historical fixed spacing).
    ``deadline_s`` wall-clock across the WHOLE ladder: no retry starts
                   past it, whatever ``attempts`` says.
    """

    name: str = "custom"
    attempts: int = 1
    timeout_s: Optional[float] = 45.0
    backoff_s: float = 0.0
    backoff_factor: float = 2.0
    jitter_frac: float = 0.0
    deadline_s: Optional[float] = None

    def with_(self, **overrides) -> "RetryPolicy":
        """A derived policy; the name records the provenance so stamped
        artifacts still say which preset the override started from."""
        if "name" not in overrides:
            overrides["name"] = f"{self.name}*"
        return dataclasses.replace(self, **overrides)

    def delays(self, rng=None) -> Iterator[float]:
        """The ``attempts - 1`` sleeps between tries."""
        d = self.backoff_s
        for _ in range(max(0, self.attempts - 1)):
            j = d * self.jitter_frac
            yield max(0.0, d + (rng.uniform(-j, j) if rng is not None and j
                                else 0.0))
            d *= self.backoff_factor

    def run(self, fn: Callable, *, retriable: Sequence[type] = (Exception,),
            should_retry: Optional[Callable] = None,
            sleep: Callable = time.sleep, rng=None):
        """``fn()`` under the policy: retry on ``retriable`` exceptions
        and on return values ``should_retry`` rejects, spaced by
        :meth:`delays`, never past ``deadline_s``.

        Returns the first accepted value (or the last rejected one when
        the ladder is exhausted — the caller sees the final state, e.g.
        the last failed probe).  Raises the last exception when every
        attempt raised.  ``sleep``/``rng`` are injectable so tests pin
        the ladder without wall-clock.
        """
        retriable = tuple(retriable)
        if rng is None and self.jitter_frac:
            # jitter must actually happen when the policy asks for it:
            # an entropy-seeded rng is correct here (decorrelating fleet
            # retries is the point, and retry SPACING is deliberately
            # outside the determinism contract — no verdict depends on
            # it); tests pass a seeded rng to pin the ladder
            rng = random.Random()
        t0 = time.monotonic()
        plan = list(self.delays(rng))
        last_err: Optional[BaseException] = None
        last_val = None
        for i in range(max(1, self.attempts)):
            try:
                val = fn()
            except retriable as e:  # noqa: PERF203 — the ladder IS the loop
                last_err, last_val = e, None
            else:
                if should_retry is None or not should_retry(val):
                    return val
                last_err, last_val = None, val
            if i >= len(plan):
                break
            d = plan[i]
            if (self.deadline_s is not None
                    and time.monotonic() - t0 + d > self.deadline_s):
                break  # no retry starts past the deadline
            sleep(d)
        if last_err is not None:
            raise last_err
        return last_val


def watchdog(fn: Callable, timeout_s: Optional[float],
             label: str = "dispatch"):
    """Run ``fn()`` bounded by ``timeout_s`` wall-clock seconds.

    ``None``/``<= 0`` runs inline (no thread).  Otherwise the call runs
    on a daemon worker thread; on timeout the thread is ABANDONED (it may
    be wedged on an uninterruptible device call — that is the scenario
    this exists for) and :class:`WatchdogTimeout` raises so the caller
    can degrade.  Exceptions from ``fn`` re-raise unchanged.
    """
    if not timeout_s or timeout_s <= 0:
        return fn()
    box: dict = {}

    def _run():
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — relayed to the caller
            box["error"] = e

    t = threading.Thread(target=_run, daemon=True, name=f"watchdog:{label}")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise WatchdogTimeout(
            f"{label} exceeded {timeout_s:.1f}s and was abandoned "
            "(device wedged mid-dispatch?)")
    if "error" in box:
        raise box["error"]
    return box.get("value")


# ---------------------------------------------------------------------------
# The named presets — the ONLY place probe/dispatch timing constants live.
# Callers refer to these by name (bench.py --probe-policy, the watcher's
# seize pipeline, utils/device.py defaults); artifacts stamp the name.
# ---------------------------------------------------------------------------

PRESETS = {
    # one bounded probe of the default backend (utils/device.py default;
    # also the CLI device-gate bound, env-overridable there)
    "probe": RetryPolicy(name="probe", attempts=1, timeout_s=45.0),
    # the watcher's steady probe loop (tools/probe_watcher.py --interval
    # cadence supplies the spacing; each probe itself is one attempt)
    "watcher-probe": RetryPolicy(name="watcher-probe", attempts=1,
                                 timeout_s=45.0),
    # cheap is-the-window-still-open re-probe before each seize-stage tool
    "window-reprobe": RetryPolicy(name="window-reprobe", attempts=1,
                                  timeout_s=30.0),
    # bench.py headline: the tunnel has healed mid-round before, so a few
    # spaced re-probes are cheap relative to forfeiting the round's only
    # real-chip window (was --retries 2 --retry-interval 30)
    "bench-probe": RetryPolicy(name="bench-probe", attempts=3,
                               timeout_s=60.0, backoff_s=30.0,
                               backoff_factor=1.0),
    # the watcher's window-seize bench invocation: the window is OPEN, so
    # retries come fast (was --retries 4 --retry-interval 10)
    "seize-probe": RetryPolicy(name="seize-probe", attempts=5,
                               timeout_s=60.0, backoff_s=10.0,
                               backoff_factor=1.0),
    # guarded device dispatch (resilience/failover.py): one quick retry
    # with a short jittered backoff, everything inside a 10-minute
    # deadline.  timeout_s is BOUNDED by default: the flagship failure
    # mode is a dispatch that HANGS (round-1 wedged tunnel), which only
    # the abandoning watchdog can catch — a failover wrapper that cannot
    # catch hangs defeats its purpose.  300 s per slice is generous for
    # legitimate work (a 1024-lane slice on the slow CPU fallback
    # finishes in ~15 s); callers with slower legitimate dispatch
    # override via with_(timeout_s=...).
    "dispatch": RetryPolicy(name="dispatch", attempts=2, timeout_s=300.0,
                            backoff_s=0.5, backoff_factor=2.0,
                            jitter_frac=0.1, deadline_s=600.0),
    # the serving plane (qsm_tpu/serve): timeout_s bounds ONE micro-batch
    # dispatch on the warm engine (server.py watchdogs it — a hang at the
    # `serve` fault site degrades the batch to the exact host ladder, not
    # the server); deadline_s is the default per-request deadline the
    # admission layer enforces — a request past it is answered SHED,
    # never late and never wrong (docs/SERVING.md).
    "serve": RetryPolicy(name="serve", attempts=1, timeout_s=60.0,
                         deadline_s=30.0),
    # one micro-batch dispatched to a pool WORKER PROCESS
    # (serve/pool.py): timeout_s bounds the round-trip over the worker
    # pipe — past it the worker is presumed wedged and is shed exactly
    # like a wedged chip (killed, lanes re-dispatched to a healthy
    # worker or the supervisor's own host ladder); attempts bounds how
    # many workers one batch may burn before the in-process last
    # resort; deadline_s caps the whole shed/re-dispatch ladder so a
    # request's lanes resolve inside its serve deadline.  backoff_s
    # stays 0: the re-dispatch target is a DIFFERENT process, so there
    # is nothing to wait out.
    "worker-dispatch": RetryPolicy(name="worker-dispatch", attempts=3,
                                   timeout_s=30.0, deadline_s=60.0),
    # one sub-request routed to a fleet NODE (qsm_tpu/fleet/router.py):
    # timeout_s bounds the router→node round-trip over the socket —
    # past it the node is presumed wedged/partitioned and the lanes
    # re-dispatch to a DIFFERENT node (the failed one is excluded, so
    # backoff_s stays 0 like worker-dispatch: there is nothing to wait
    # out); attempts bounds how many nodes one sub-request may burn
    # before the router's own in-process host ladder is the last rung;
    # deadline_s caps the whole route/re-dispatch ladder inside the
    # request's serve deadline.
    "fleet-route": RetryPolicy(name="fleet-route", attempts=3,
                               timeout_s=20.0, deadline_s=45.0),
    # the membership health probe (qsm_tpu/fleet/membership.py): one
    # cheap bounded stats round-trip per node per beat — a node that
    # misses it repeatedly is quarantined one-way (routing stops) and
    # re-admitted only on sustained health.  backoff_s here is the
    # probe's RE-PROBE spacing while a node is down (the membership
    # loop multiplies it per consecutive failure, capped), not a retry
    # of one probe.
    "fleet-probe": RetryPolicy(name="fleet-probe", attempts=1,
                               timeout_s=5.0, backoff_s=1.0,
                               backoff_factor=2.0),
    # the router's anti-entropy beat (qsm_tpu/fleet/replog.py digest
    # exchange): timeout_s bounds one digest/pull/push round-trip —
    # catch-up traffic must never hold a connection past it (a wedged
    # node's catch-up is abandoned and retried next beat); deadline_s
    # caps one whole reconciliation sweep across the fleet so a big
    # backlog ships over several beats instead of one unbounded one.
    "anti-entropy": RetryPolicy(name="anti-entropy", attempts=1,
                                timeout_s=15.0, deadline_s=60.0),
    # one node-to-node gossip exchange (qsm_tpu/fleet/gossip.py):
    # timeout_s bounds a single digests/covers/pull/push round-trip
    # with ONE random peer; deadline_s caps a whole beat's fan-out so
    # a slow peer costs this node one bounded slice of its beat, not
    # the beat — convergence rides the NEXT beat's fresh random picks.
    # attempts stays 1: gossip is retried by cadence, never in-line (a
    # peer that just failed is excluded for the rest of the sweep).
    "gossip": RetryPolicy(name="gossip", attempts=1, timeout_s=10.0,
                          deadline_s=20.0),
}


def preset(name: str) -> RetryPolicy:
    """The named policy, or a clean error listing what exists."""
    try:
        return PRESETS[name]
    except KeyError:
        raise KeyError(f"unknown RetryPolicy preset {name!r}; "
                       f"one of {sorted(PRESETS)}") from None
