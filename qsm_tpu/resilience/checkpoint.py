"""Checkpoint/resume for bench scans — partial progress is bankable.

Round 5's healed windows were minutes long and every artifact writer in
the repo treated its output as all-or-nothing within a cell: a scan
killed after cell 2 of 6 left cells 1-2 on disk only if the tool
happened to write incrementally, and a *re-run* started from cell 1
again, spending the next window on work already banked.  The
decompose-and-continue idea the segmentation checker applies to
histories (PAPERS.md: decrease-and-conquer monitoring) applies to the
run lifecycle too: each cell is a unit of progress that, once measured,
must never be re-paid.

Two exports:

* :func:`atomic_write_json` / :func:`atomic_write_text` — THE artifact
  write primitive (tmp in the same directory + fsync + ``os.replace``):
  a reader never sees a half-written document, a killed writer never
  destroys the previous version.  Every JSON artifact writer in
  ``tools/`` and ``bench.py`` routes through these.
* :class:`CellJournal` — a resumable JSONL artifact (one header line +
  one row per completed cell, rewritten atomically on every emit).
  ``resume=True`` preloads completed rows from a compatible prior file
  so the caller skips their cells; ``skipped`` markers are NOT
  completions (a time-boxed cut must be re-attempted, matching the
  probe watcher's ``_tool_rows`` accounting).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple


def atomic_write_text(path: str, text: str) -> None:
    """Write-whole-then-rename: crash-safe at every instant.  The tmp
    lives in the target's directory so the rename never crosses a
    filesystem; fsync before rename so the rename can't land before the
    data (the power-loss ordering bug tmpfile writers usually keep)."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def atomic_write_json(path: str, obj, indent: Optional[int] = None) -> None:
    atomic_write_text(path, json.dumps(obj, indent=indent) + "\n")


class CellJournal:
    """One header + one JSON line per completed cell, atomic per emit.

    ``header`` identifies the scan; on ``resume=True`` a prior file at
    ``path`` is adopted iff its header matches on ``match_keys``
    (default: artifact name and device/CPU-fallback provenance — a
    CPU-fallback scan must never pre-satisfy a device scan's cells).
    A mismatched prior file is moved aside to ``<path>.pre-resume``
    rather than overwritten: the mismatch guard exists to protect
    banked measurements, so it must never itself destroy them.
    The header gains a ``resumed_cells`` count so the artifact is
    self-describing about what this run re-measured vs inherited.

    A truncated trailing line (the writer was killed mid-write under a
    non-atomic scheme, or the file predates this journal) is dropped,
    not fatal — the cell it described simply re-runs.
    """

    def __init__(self, path: str, header: Dict, resume: bool = False,
                 match_keys: Sequence[str] = ("artifact",
                                              "device_fallback")):
        self.path = path
        self._rows: List[Dict] = []
        self._done: Dict[str, Dict] = {}
        self.resumed_cells = 0
        if resume:
            prev_header, prev_rows = self._load(path)
            if prev_header is not None and all(
                    prev_header.get(k) == header.get(k)
                    for k in match_keys):
                for r in prev_rows:
                    key = r.get("cell")
                    if key and "skipped" not in r:
                        self._done[key] = r
                        self._rows.append(r)
                self.resumed_cells = len(self._done)
            elif prev_header is not None:
                # incompatible prior artifact (e.g. --resume pointed at a
                # banked DEVICE scan from a CPU-fallback run): adopt
                # nothing, but PRESERVE it — the constructor's first
                # flush below would otherwise atomically destroy the one
                # copy of measurements this run cannot reproduce
                try:
                    os.replace(path, f"{path}.pre-resume")
                except OSError:
                    pass  # unpreservable (permissions): proceed as before
        self.header = {**header, "resumed_cells": self.resumed_cells}
        self._flush()

    @staticmethod
    def _load(path: str) -> Tuple[Optional[Dict], List[Dict]]:
        try:
            with open(path) as f:
                raw = f.read().splitlines()
        except OSError:
            return None, []
        docs = []
        for ln in raw:
            if not ln.strip():
                continue
            try:
                docs.append(json.loads(ln))
            except ValueError:
                break  # truncated/garbled: trust nothing at or past it
        if not docs:
            return None, []
        return docs[0], docs[1:]

    # ------------------------------------------------------------------
    def complete(self, key: str) -> Optional[Dict]:
        """The banked row for ``key`` (this run or a resumed prior one),
        or None when the cell still needs running."""
        return self._done.get(key)

    def emit(self, key: str, row: Dict) -> Dict:
        """Bank one cell row (stamped with its key) atomically."""
        row = {"cell": key, **row}
        self._rows.append(row)
        if "skipped" not in row:
            self._done[key] = row
        self._flush()
        return row

    def rows(self) -> List[Dict]:
        """Header + every row, in file order."""
        return [self.header] + list(self._rows)

    def _flush(self) -> None:
        atomic_write_text(
            self.path,
            "\n".join(json.dumps(x) for x in self.rows()) + "\n")
