"""Env/config-driven fault plane — every degradation path tier-1 testable.

The failure modes that matter here (wedged tunnel, hung dispatch,
mid-scan device loss) only occur on hardware the CI never has, so the
degradation code they exercise would otherwise ship untested — exactly
the code that must not be wrong when the round's one healed window
opens.  This module simulates them ON THE CPU PLATFORM, at named fault
sites the production code calls through :func:`inject`.

Syntax (the ``QSM_TPU_FAULTS`` env var, comma-separated rules)::

    QSM_TPU_FAULTS="hang:dispatch:0.3,raise:seize,wedge:probe"
    QSM_TPU_FAULTS="raise:dispatch@2"        # fire on the 2nd+ hit

    rule    := action ":" site [":" probability] ["@" nth]
    action  := "hang"   (sleep QSM_TPU_FAULT_HANG_S, default 3600 s,
                         then raise — a real hang never returns; the
                         bounded sleep keeps un-watchdogged tests alive)
             | "raise"  (raise InjectedFault at the site)
             | "wedge"  (returned to the caller: site-specific
                         unavailability — a probe reports the tunnel
                         wedged instead of raising)
             | "kill"   (SIGKILL the CURRENT PROCESS at the site — no
                         cleanup, no atexit, exactly an OOM-kill or a
                         segfaulting engine.  Meant for the ``worker``
                         site: the rule rides the env into every pool
                         worker process, so a worker's own dispatch
                         kills that worker and the SUPERVISOR's
                         shed/re-dispatch path is what gets tested)
             | "partition" (returned to the caller: the frames of this
                         exchange are dropped BOTH directions — the
                         request never arrives, the response never
                         comes back.  Meant for the ``node`` site
                         (fleet router→node I/O): the router treats it
                         as the link being out, not the node having
                         answered, so its re-dispatch/quarantine path
                         is what gets tested.  ``@nth`` = the link
                         STAYS partitioned from that hit on)
    nth     := fire on the nth hit of the site AND every later one
               (a lost device stays lost — "mid-scan crash" semantics;
               for kill:worker the count is PER PROCESS, so a respawned
               worker dies again at the same dispatch ordinal — the
               crash-loop the quarantine path exists for; for
               partition:node the link stays down — "switch died
               mid-soak" semantics)

Probability draws come from ONE ``random.Random`` seeded by
``QSM_TPU_FAULTS_SEED`` (default 0), so a fault schedule is replayable —
the same discipline the scheduler plane's ``FaultPlan`` follows for
message-level faults.  The two planes are deliberately separate: a
``FaultPlan`` perturbs the SYSTEM UNDER TEST (dropped messages, crashed
processes) and is part of the property being checked; this plane
perturbs the CHECKER'S OWN infrastructure (device dispatch, probes,
window seizes) and must never change a verdict — only where it is
computed.

Fault sites instrumented today: ``probe`` (utils/device.py),
``dispatch`` (ops/jax_kernel.py, ops/pallas_kernel.py — i.e. every
device engine entry), ``seize`` (tools/probe_watcher.py), ``serve``
(serve/server.py micro-batch dispatch — a hang/raise there exercises
the check server's degrade-to-host-ladder path on the CPU platform,
tests/test_serve.py), ``worker`` (serve/worker.py pool-worker dispatch
— hang/raise/kill INSIDE a worker process exercises the supervisor's
shed → re-dispatch → respawn/quarantine ladder, tests/test_serve_pool.py),
``node`` (fleet/router.py router→node round-trips — partition/hang/raise
there exercises the fleet tier's exclude-and-re-dispatch ladder down to
the router's own in-process host ladder, tests/test_fleet.py), ``router``
(serve/client.py client→router round-trips — partition/hang/raise there
exercises the client's bounded multi-address failover onto the standby
router, tests/test_fleet_ha.py; same hang/raise/kill/partition grammar,
one level further out), ``lease`` (fleet/lease.py lease-store
acquire/renew transactions — partition/hang/raise there makes the HA
plane lose beats: an active that cannot renew demotes (one-way per
term), a standby that cannot acquire waits, and NOBODY serves a stale
term; the router counts the losses as ``lease_faults``,
tests/test_fleet_ha.py).
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from typing import List, Optional

ENV_VAR = "QSM_TPU_FAULTS"
SEED_VAR = "QSM_TPU_FAULTS_SEED"
HANG_VAR = "QSM_TPU_FAULT_HANG_S"

ACTIONS = ("hang", "raise", "wedge", "kill", "partition")


class InjectedFault(RuntimeError):
    """A fault-plane-injected failure (never raised in production: the
    plane is off unless ``QSM_TPU_FAULTS`` is set)."""

    def __init__(self, site: str, action: str):
        super().__init__(f"injected {action} at fault site {site!r} "
                         f"({ENV_VAR}={os.environ.get(ENV_VAR, '')!r})")
        self.site = site
        self.action = action


@dataclasses.dataclass(frozen=True)
class FaultRule:
    action: str
    site: str
    p: float = 1.0
    nth: Optional[int] = None  # fire on the nth hit and every later one


class FaultPlane:
    """Parsed fault rules + the per-site hit/fired accounting."""

    def __init__(self, rules: List[FaultRule], seed: str = "0",
                 spec_str: str = ""):
        self.rules = rules
        self.spec_str = spec_str
        self.seed_str = seed
        self.rng = random.Random(f"qsm_tpu_faults:{seed}")
        self.hits: dict = {}
        self.fired: dict = {}

    @classmethod
    def parse(cls, spec_str: str, seed: str = "0") -> "FaultPlane":
        rules: List[FaultRule] = []
        for part in spec_str.split(","):
            part = part.strip()
            if not part:
                continue
            nth = None
            if "@" in part:
                part, n = part.rsplit("@", 1)
                if not n.isdigit() or int(n) < 1:
                    raise ValueError(
                        f"bad fault rule {part!r}@{n!r}: @nth wants a "
                        "positive integer")
                nth = int(n)
            fields = part.split(":")
            if len(fields) not in (2, 3):
                raise ValueError(
                    f"bad fault rule {part!r}: want action:site[:p][@n]")
            action, site = fields[0], fields[1]
            if action not in ACTIONS:
                raise ValueError(f"bad fault action {action!r}: "
                                 f"one of {ACTIONS}")
            if not site:
                raise ValueError(f"bad fault rule {part!r}: empty site")
            p = float(fields[2]) if len(fields) == 3 else 1.0
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"bad fault probability {p!r} "
                                 f"in {part!r}")
            rules.append(FaultRule(action, site, p, nth))
        return cls(rules, seed=seed, spec_str=spec_str)

    def action_for(self, site: str) -> Optional[str]:
        """The action to perform at this hit of ``site`` (None = none).
        Counts the hit either way; first matching rule wins."""
        n = self.hits[site] = self.hits.get(site, 0) + 1
        for r in self.rules:
            if r.site != site:
                continue
            if r.nth is not None and n < r.nth:
                continue
            if r.p < 1.0 and self.rng.random() >= r.p:
                continue
            self.fired[site] = self.fired.get(site, 0) + 1
            return r.action
        return None


_plane: Optional[FaultPlane] = None


def active_plane() -> FaultPlane:
    """The process-wide plane, re-parsed whenever the env changes (tests
    flip ``QSM_TPU_FAULTS`` between cases via monkeypatch)."""
    global _plane
    spec = os.environ.get(ENV_VAR, "")
    seed = os.environ.get(SEED_VAR, "0")
    if (_plane is None or _plane.spec_str != spec
            or _plane.seed_str != seed):
        _plane = FaultPlane.parse(spec, seed=seed)
    return _plane


def inject(site: str) -> Optional[str]:
    """THE fault hook.  Production cost when the plane is off: one env
    read.  With a matching rule: ``raise`` raises :class:`InjectedFault`;
    ``hang`` sleeps ``QSM_TPU_FAULT_HANG_S`` (default 3600 — long enough
    that any watchdog fires first) then raises; ``kill`` SIGKILLs the
    current process (a crash leaves no traceback and runs no cleanup —
    the supervisor side is what survives to be tested); ``wedge`` and
    ``partition`` are RETURNED so the site applies its own
    unavailability semantics (a partitioned fleet link drops the frames
    without the wait a real timeout would cost the test)."""
    if not os.environ.get(ENV_VAR):
        return None
    act = active_plane().action_for(site)
    if act is not None:
        # a fired rule is a flight-recorder trigger (qsm_tpu/obs): a
        # fault drill against a live server leaves an artifact.  BEFORE
        # acting — hang/kill would otherwise erase the evidence.  The
        # emit is a no-op without a global obs sink, and never blocks
        # the action itself.
        from ..obs import emit_global

        emit_global("fault.hit", site=site, action=act)
    if act == "raise":
        raise InjectedFault(site, act)
    if act == "hang":
        time.sleep(float(os.environ.get(HANG_VAR, "3600")))
        raise InjectedFault(site, act)
    if act == "kill":
        import signal

        os.kill(os.getpid(), signal.SIGKILL)
    return act


def fired_snapshot() -> dict:
    """Per-site fired counts of this process' fault plane (``{}`` when
    the plane is off) — the `stats()` / metrics "fault-site hits" feed."""
    if not os.environ.get(ENV_VAR):
        return {}
    return dict(active_plane().fired)
