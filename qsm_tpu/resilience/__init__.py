"""``qsm_tpu.resilience`` — the plane that keeps runs alive when the
hardware is not.

Round 5's verdict: 717 probes, 9 device hits, and any hung
``pallas_call`` or killed bench subprocess loses the whole run.  This
package is the systematic answer, four pieces that compose:

* ``policy``     — ONE :class:`RetryPolicy` (bounded retries, backoff +
  jitter, wall-clock deadline) + the named :data:`~policy.PRESETS` every
  probe/dispatch/seize site uses, and the :func:`~policy.watchdog`
  bounded-call wrapper for uninterruptible device dispatch.
* ``failover``   — :class:`~failover.FailoverBackend`: device majority
  while the device lives, exact host ladder (cpp → memo) the moment it
  does not; verdicts/witnesses bit-identical to a clean host run,
  undecided lanes only re-dispatched.
* ``faults``     — the ``QSM_TPU_FAULTS`` env fault plane (hang / raise
  / wedge at named sites) so every degradation path above is tier-1
  testable on the CPU platform, no hardware required.
* ``checkpoint`` — :func:`~checkpoint.atomic_write_json` (tmp+rename+
  fsync, THE artifact write primitive) and
  :class:`~checkpoint.CellJournal` (resumable per-cell scan journals:
  ``--resume`` re-runs zero completed cells).

Documented in docs/RESILIENCE.md; gated by tests/test_resilience.py and
the ``QSM-RES-*`` qsmlint rules (analysis/resilience_passes.py).
"""

from .checkpoint import CellJournal, atomic_write_json, atomic_write_text
from .failover import FailoverBackend, collect_resilience, host_fallback
from .faults import FaultPlane, InjectedFault, active_plane, inject
from .policy import (PRESETS, RetryPolicy, WatchdogTimeout, preset,
                     watchdog)

__all__ = [
    "RetryPolicy", "PRESETS", "preset", "watchdog", "WatchdogTimeout",
    "FailoverBackend", "collect_resilience",
    "host_fallback", "FaultPlane", "InjectedFault", "active_plane",
    "inject", "CellJournal", "atomic_write_json", "atomic_write_text",
]
