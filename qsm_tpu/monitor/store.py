"""Durable session substrate — snapshot + bounded journal per session.

ISSUE 18's durability contract (docs/MONITOR.md "Durability"): a
monitor session's resumable state splits into two planes with two
different owners —

* the DECIDED PREFIX lives in the serve verdict cache as banked prefix
  rows (monitor/frontier.py ``_bank_put``), which ride the replog's
  segments and therefore its anti-entropy / gossip / subsumption
  machinery for free.  This file never duplicates them;
* the SESSION-LOCAL remainder — open window, frontier state set, hash
  chain state, reorder buffer, seq counter — is one JSON doc
  (``MonitorSession.to_doc``) small in the WINDOW, not the stream.
  That doc plus a bounded tail journal of raw event batches is what
  :class:`SessionStore` keeps, one file per session.

File format (``<root>/<sid>.jsonl``):

* exactly one ``{"kind": "snap", "doc": {...}}`` line (the file is
  atomically REWRITTEN at snapshot time — resilience/checkpoint.py
  ``atomic_write_text`` — so a crash mid-compaction leaves the old
  file, never a torn one);
* zero or more ``{"kind": "ev", "seq": n, "events": [...]}`` lines
  appended after it (append + flush per batch; a torn TRAILING line —
  the only kind a crash can produce — is dropped at load, exactly the
  CellJournal discipline).

**Resume = deserialize, not replay.**  ``load`` hands back the
snapshot doc (``MonitorSession.from_doc`` rebuilds the session in
O(doc) with ZERO engine folds) plus the tail batches, which re-apply
through the normal seq-idempotent ``append`` path; any cut they commit
lands on the already-banked prefix rows (``prefix_hits``), so a node
restart or ring move costs bank lookups, never ``_end_states`` folds
(tests/test_monitor.py durable-resume pin monkeypatches the fold to
prove it).

**Compaction.**  When a session's tail grows past ``snap_every``
batches, the owner rewrites the snapshot from the live session and the
tail resets — the file stays O(window + snap_every · batch), never
O(stream).

Thread-safety: one session's appends are serialized by its own
``session.lock`` (the caller holds it); the store's own lock only
guards the cross-session tail-length map and directory scans.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from ..resilience.checkpoint import atomic_write_text

# journal tail batches a session may accumulate before its owner
# should re-snapshot (MonitorSession.append does this automatically)
DEFAULT_SNAP_EVERY = 64

_SUFFIX = ".session.jsonl"


class SessionStore:
    """One directory of per-session snapshot+journal files (module
    docstring).  sids are sanitized into filenames; anything a sid
    could contain that the filesystem couldn't is hex-escaped, so no
    sid ever escapes ``root`` or collides with another."""

    def __init__(self, root: str, *, snap_every: int = DEFAULT_SNAP_EVERY):
        self.root = str(root)
        self.snap_every = max(1, int(snap_every))
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        self._tail: Dict[str, int] = {}   # sid -> ev lines since snap

    # -- naming --------------------------------------------------------
    @staticmethod
    def _fname(sid: str) -> str:
        safe = "".join(
            c if c.isalnum() or c in "-_" else f"%{ord(c):02x}"
            for c in str(sid))
        return safe + _SUFFIX

    def path_of(self, sid: str) -> str:
        return os.path.join(self.root, self._fname(sid))

    # -- writes --------------------------------------------------------
    def snapshot(self, sid: str, doc: dict) -> None:
        """(Re)write ``sid``'s file as one fresh snapshot line — the
        compaction step; every journaled batch so far is now inside
        the doc, so the tail resets."""
        atomic_write_text(
            self.path_of(sid),
            json.dumps({"kind": "snap", "doc": doc},
                       separators=(",", ":")) + "\n")
        with self._lock:
            self._tail[sid] = 0

    def append_events(self, sid: str, seq: int, events: list) -> None:
        """Journal one applied batch (its first event's stream index is
        ``seq`` — replay re-appends with it, so overlap is idempotent).
        Append + flush: a crash tears at most the trailing line, which
        ``load`` drops."""
        line = json.dumps({"kind": "ev", "seq": int(seq),
                           "events": events},
                          separators=(",", ":")) + "\n"
        with open(self.path_of(sid), "a") as f:
            f.write(line)
            f.flush()
        with self._lock:
            self._tail[sid] = self._tail.get(sid, 0) + 1

    def drop(self, sid: str) -> None:
        """Forget a session (closed sessions answer their verdict and
        leave nothing to resume)."""
        try:
            os.remove(self.path_of(sid))
        except OSError:
            pass
        with self._lock:
            self._tail.pop(sid, None)

    # -- reads ---------------------------------------------------------
    def tail_len(self, sid: str) -> int:
        with self._lock:
            return self._tail.get(sid, 0)

    def load(self, sid: str) -> Optional[Tuple[dict, List[dict]]]:
        """``(snapshot_doc, tail_batches)`` for ``sid``, or None when
        nothing durable exists.  Garbled files (foreign bytes, no snap
        line) read as absent — durability must never wedge an open; a
        torn trailing line is dropped silently (crash mid-append)."""
        try:
            with open(self.path_of(sid)) as f:
                lines = f.read().splitlines()
        except OSError:
            return None
        doc: Optional[dict] = None
        tail: List[dict] = []
        for i, line in enumerate(lines):
            try:
                rec = json.loads(line)
            except ValueError:
                if i == len(lines) - 1:
                    continue              # torn trailing line: expected
                return None               # mid-file garble: foreign file
            if not isinstance(rec, dict):
                return None
            if rec.get("kind") == "snap":
                doc = rec.get("doc")
                tail = []                 # batches before a snap are in it
            elif rec.get("kind") == "ev":
                tail.append(rec)
            else:
                return None
        if not isinstance(doc, dict):
            return None
        with self._lock:
            self._tail[sid] = len(tail)
        return doc, tail

    def list_sids(self) -> List[str]:
        """Every sid with a durable file (restart recovery sweeps)."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for name in sorted(names):
            if not name.endswith(_SUFFIX):
                continue
            stem = name[:-len(_SUFFIX)]
            # invert the filename escaping
            sid, i = [], 0
            while i < len(stem):
                if stem[i] == "%" and i + 2 < len(stem) + 1:
                    try:
                        sid.append(chr(int(stem[i + 1:i + 3], 16)))
                        i += 3
                        continue
                    except ValueError:
                        pass
                sid.append(stem[i])
                i += 1
            out.append("".join(sid))
        return out

    def stats(self) -> dict:
        with self._lock:
            tails = dict(self._tail)
        try:
            files = [n for n in os.listdir(self.root)
                     if n.endswith(_SUFFIX)]
        except OSError:
            files = []
        return {"root": self.root, "files": len(files),
                "snap_every": self.snap_every,
                "tail_batches": sum(tails.values())}
