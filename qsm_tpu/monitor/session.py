"""Monitor sessions — a live system's event stream as a first-class
serving object (docs/MONITOR.md).

A session is opened against one spec, fed invocation/response events as
they happen, and decided INCREMENTALLY by the frontier layer
(monitor/frontier.py): decided prefixes bank under rolling prefix
fingerprints and leave memory, the open window re-checks from the
frontier states only, and the verdict is exact at every step — the
session's answer after event k equals the whole-history ``check``
verdict of the first k events, bit-for-bit.

**Event forms** (``MonitorSession.append``):

* ``{"type": "invoke", "pid": p, "cmd": c, "arg": a, "t": t?}`` /
  ``{"type": "respond", "pid": p, "resp": r, "t": t?}`` — the live
  stream.  Arrival order IS time order (monotonic ``t`` enforced;
  omitted ``t`` is assigned from the arrival counter), so decisions
  fire the moment they are decidable.
* a raw ``[pid, cmd, arg, resp, invoke_time, response_time]`` row — a
  completed op (recorded corpora, ingest adapters).  Rows must arrive
  sorted by invoke time; their response events are held in a small
  reorder buffer until the invoke horizon passes them (a response is
  not *final* until no future op can invoke before it), which keeps
  mid-stream verdicts exact for overlapping recorded traces too.

**Per-key composition.**  A spec with a VALIDATED per-key projection
(core/spec.py ``projection_report``; the exact gate PComp trusts) gets
one frontier per key over the PROJECTED spec: an event touches only its
key's frontier, so a one-key event re-checks one key's window
(``pcomp`` per suffix — the ISSUE's o(n) shape), and per-key prefixes
bank under the projected spec's own fingerprint domain.  Verdicts
recombine by the PComp aggregation rule (VIOLATION beats
BUDGET_EXCEEDED beats LINEARIZABLE).

**Bounds** (the QSM-MON-UNBOUNDED contract, analysis/monitor_passes.py):
the event log is capped (``max_events`` — appends past it are refused
loudly), frontier state sets are capped (frontier.py ``max_states``),
and committed-prefix ops are EVICTED from frontier windows; the
:class:`SessionManager` caps live sessions and refuses opens past the
cap (the server answers SHED).
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..core.history import History
from ..core.spec import Spec
from ..ops.backend import Verdict
from ..sched.runner import PENDING_T
from .frontier import (DEFAULT_MAX_STATES, DEFAULT_NODE_BUDGET,
                       IncrementalFrontier)

DEFAULT_MAX_EVENTS = 65_536
DEFAULT_MAX_SESSIONS = 256

# reorder-buffer entry kinds: responses drain before invokes at equal
# times (cuts are strict — resp < inv — so the order is deterministic
# and never manufactures a cut)
_K_RESPOND, _K_INVOKE = 0, 1


class SessionError(ValueError):
    """A malformed event/stream — answered as an error, never applied."""


class SessionLimit(RuntimeError):
    """A bound refused the work (session cap, event cap) — the serve
    layer answers SHED, exactly like admission pressure."""


class MonitorSession:
    """One live session (module docstring).  Not thread-safe by itself
    — callers hold :attr:`lock` (one serve connection usually drives a
    session, but router replay and stats can race it)."""

    def __init__(self, sid: str, spec: Spec, *,
                 proj_spec: Optional[Spec] = None,
                 bank=None,
                 node_budget: int = DEFAULT_NODE_BUDGET,
                 max_states: int = DEFAULT_MAX_STATES,
                 max_events: int = DEFAULT_MAX_EVENTS,
                 trace: str = ""):
        self.sid = sid
        self.spec = spec
        self.proj = proj_spec
        self.bank = bank
        self.trace = trace           # the session's trace id (obs plane)
        # reentrant: the serve handler holds it across an append/close
        # whose manager bookkeeping re-reads counters under it
        self.lock = threading.RLock()
        self.node_budget = node_budget
        self.max_states = max_states
        self.max_events = max_events
        self.rows: List[list] = []   # canonical 6-rows, invoke order
        self.seq = 0                 # raw events accepted (idempotent replay)
        self.closed = False
        self.flipped = False
        self.last_used = time.monotonic()  # idle-eviction clock
        self.flip_pushed = False     # the flip payload left for a client
        self.flip_rows: Optional[List[list]] = None  # stream at flip time
        # the serve layer stamps the registry identity here at open so
        # failover replay and the flip->shrink path can rebuild engines
        self.model: Optional[str] = None
        self.spec_kwargs: dict = {}
        # durable substrate (monitor/store.py), bound by the manager;
        # None = in-memory only (the PR 14 behavior, unchanged)
        self.store = None
        self._row_of_pid: Dict[int, int] = {}   # outstanding invocation row
        self._key_of_pid: Dict[int, Optional[int]] = {}
        self._frontiers: Dict[Optional[int], IncrementalFrontier] = {}
        self._dirty: set = set()
        self._heap: List[tuple] = []  # reorder buffer (rows' responses)
        self._heap_seq = 0
        self._horizon = -1            # latest final time (see module doc)
        self._last_t = -1
        self._auto_t = 0

    # -- event intake ---------------------------------------------------
    def append(self, events, seq: Optional[int] = None) -> int:
        """Apply a batch of events; returns how many were NEW (replays
        carry ``seq`` — the stream index of their first event — so a
        re-sent append after a failover/restart is idempotent)."""
        if self.closed:
            raise SessionError(f"session {self.sid} is closed")
        events = list(events)
        skip = 0
        if seq is not None:
            seq = int(seq)
            if seq > self.seq:
                raise SessionError(
                    f"session {self.sid}: append seq {seq} leaves a gap "
                    f"(next expected {self.seq})")
            skip = min(len(events), self.seq - seq)
        fresh = events[skip:]
        if not fresh:
            return 0
        if len(self.rows) + len(fresh) > self.max_events:
            raise SessionLimit(
                f"session {self.sid}: event cap {self.max_events} "
                "reached — close the session or raise max_events")
        for ev in fresh:
            self._apply(ev)
            self.seq += 1
        self._drain(final=False)
        if self.store is not None:
            # journal the fresh slice under its stream index; replay
            # re-appends with the same seq, so overlap is idempotent.
            # Past the tail cap, compact: rewrite the snapshot from the
            # live session (the journaled batches are now inside it)
            self.store.append_events(self.sid, self.seq - len(fresh),
                                     fresh)
            if self.store.tail_len(self.sid) >= self.store.snap_every:
                self.store.snapshot(self.sid, self.to_doc())
        return len(fresh)

    def _apply(self, ev) -> None:
        if isinstance(ev, dict):
            self._apply_dict(ev)
        elif isinstance(ev, (list, tuple)) and len(ev) == 6:
            self._apply_row([int(v) for v in ev])
        else:
            raise SessionError(
                f"session {self.sid}: event must be an invoke/respond "
                f"dict or a 6-row, got {ev!r}")

    def _next_t(self, t) -> int:
        if t is None:
            t = self._last_t + 1 if self._last_t >= self._auto_t \
                else self._auto_t
        t = int(t)
        if t < self._last_t:
            raise SessionError(
                f"session {self.sid}: event time {t} runs backwards "
                f"(last {self._last_t}) — the monitor's exactness "
                "contract needs real-time-ordered arrival")
        self._last_t = t
        self._auto_t = t + 1
        return t

    def _apply_dict(self, ev: dict) -> None:
        kind = ev.get("type")
        if kind in ("invoke", "call"):
            t = self._next_t(ev.get("t"))
            pid = int(ev["pid"])
            cmd, arg = int(ev["cmd"]), int(ev.get("arg", 0))
            self._push(_K_INVOKE, t, (pid, cmd, arg))
            self._horizon = max(self._horizon, t)
        elif kind in ("respond", "ok", "return"):
            t = self._next_t(ev.get("t"))
            pid = int(ev["pid"])
            self._push(_K_RESPOND, t, (pid, int(ev.get("resp", 0))))
            self._horizon = max(self._horizon, t)
        else:
            raise SessionError(
                f"session {self.sid}: unknown event type {kind!r} "
                "(one of invoke/respond, or a 6-row)")

    def _apply_row(self, row: List[int]) -> None:
        pid, cmd, arg, resp, inv, ret = row
        if inv < self._horizon:
            # rows must arrive in invoke order: an earlier invoke after
            # the horizon advanced would have been decided without it
            raise SessionError(
                f"session {self.sid}: row invokes at {inv} behind the "
                f"stream horizon {self._horizon} — stream rows sorted "
                "by invoke_time")
        pending = resp is None or resp < 0 or ret >= PENDING_T
        if not pending and ret < inv:
            raise SessionError(
                f"session {self.sid}: row responds at {ret} before its "
                f"invocation at {inv}")
        self._push(_K_INVOKE, inv, (pid, cmd, arg))
        if not pending:
            self._push(_K_RESPOND, ret, (pid, resp))
        # only the INVOKE is final-ordered for rows; the response waits
        # in the buffer until the invoke horizon passes it
        self._horizon = max(self._horizon, inv)
        self._last_t = max(self._last_t, inv)
        self._auto_t = max(self._auto_t, inv + 1)

    def _push(self, kind: int, t: int, payload: tuple) -> None:
        heapq.heappush(self._heap, (t, kind, self._heap_seq, payload))
        self._heap_seq += 1

    def _drain(self, final: bool) -> None:
        """Release buffered events up to the horizon (everything, on
        close) into the rows log and the frontiers."""
        while self._heap and (final or self._heap[0][0] <= self._horizon):
            t, kind, _, payload = heapq.heappop(self._heap)
            if kind == _K_INVOKE:
                pid, cmd, arg = payload
                if pid in self._row_of_pid:
                    raise SessionError(
                        f"session {self.sid}: pid {pid} invokes with "
                        "an outstanding op (one op per pid at a time)")
                key = self._key_for(cmd, arg)
                self._row_of_pid[pid] = len(self.rows)
                self._key_of_pid[pid] = key
                self.rows.append([pid, cmd, arg, -1, t, PENDING_T])
                f = self._frontier(key)
                if key is None:
                    f.invoke(pid, cmd, arg, t)
                else:
                    pcmd, parg, _ = self.spec.project_op(cmd, arg, 0)
                    f.invoke(pid, pcmd, parg, t)
                self._dirty.add(key)
            else:
                pid, resp = payload
                i = self._row_of_pid.pop(pid, None)
                if i is None:
                    raise SessionError(
                        f"session {self.sid}: pid {pid} responds with "
                        "no outstanding invocation")
                key = self._key_of_pid.pop(pid, None)
                self.rows[i][3] = resp
                self.rows[i][5] = t
                f = self._frontier(key)
                if key is None:
                    f.respond(pid, resp, t)
                else:
                    cmd, arg = self.rows[i][1], self.rows[i][2]
                    _, _, presp = self.spec.project_op(cmd, arg, resp)
                    f.respond(pid, presp, t)
                self._dirty.add(key)

    # -- per-key plumbing ----------------------------------------------
    def _key_for(self, cmd: int, arg: int) -> Optional[int]:
        if self.proj is None:
            return None
        key = self.spec.partition_key(cmd, arg)
        if key is None:
            raise SessionError(
                f"session {self.sid}: command {cmd} has no partition "
                "key (non-total projection reached a per-key session)")
        return int(key)

    def _frontier(self, key: Optional[int]) -> IncrementalFrontier:
        f = self._frontiers.get(key)
        if f is None:
            f = self._frontiers[key] = IncrementalFrontier(
                self.proj if key is not None else self.spec,
                bank=self.bank, node_budget=self.node_budget,
                max_states=self.max_states)
        return f

    # -- deciding -------------------------------------------------------
    def decide(self) -> int:
        """Advance + re-check every frontier an event touched since the
        last decide; returns the session verdict (worst across
        frontiers — the PComp aggregation rule).  Sets :attr:`flipped`
        (and snapshots the stream) the first time the verdict becomes
        VIOLATION; a flip is terminal (docs/MONITOR.md)."""
        if self.flipped:
            return int(Verdict.VIOLATION)
        for key in sorted(self._dirty, key=lambda k: (k is None, k)):
            f = self._frontiers[key]
            if f.advance() != int(Verdict.VIOLATION):
                f.check_window()
        self._dirty.clear()
        verdict = int(Verdict.LINEARIZABLE)
        for f in self._frontiers.values():
            v = f.verdict
            if v == int(Verdict.VIOLATION):
                verdict = v
                break
            if v == int(Verdict.BUDGET_EXCEEDED):
                verdict = v
        if verdict == int(Verdict.VIOLATION) and not self.flipped:
            self.flipped = True
            self.flip_rows = [list(r) for r in self.rows]
            self._bank_recheck()
        return verdict

    def close(self) -> int:
        """Flush the reorder buffer, decide one last time, seal."""
        if not self.closed:
            self._drain(final=True)
            v = self.decide()
            self.closed = True
            return v
        return self.decide()

    # -- durability (ISSUE 18) ------------------------------------------
    def to_doc(self) -> dict:
        """The session's COMPLETE resumable state as one JSON-safe doc
        (caller holds :attr:`lock`).  O(window + reorder buffer), not
        O(stream): the rows log rides along for flip repros, but the
        decided prefix itself is only its hash-chain state + frontier
        states — the banked prefix rows stay in the replog where they
        already live."""
        return {
            "sid": self.sid,
            "spec_name": self.spec.name,
            "spec_kwargs": self.spec.spec_kwargs(),
            "model": self.model,
            "model_kwargs": dict(self.spec_kwargs),
            "per_key": self.proj is not None,
            "rows": [list(r) for r in self.rows],
            "seq": self.seq,
            "closed": self.closed,
            "flipped": self.flipped,
            "flip_pushed": self.flip_pushed,
            "flip_rows": self.flip_rows,
            "row_of_pid": {str(p): i
                           for p, i in self._row_of_pid.items()},
            "key_of_pid": {str(p): k
                           for p, k in self._key_of_pid.items()},
            "heap": [[t, k, n, list(payload)]
                     for t, k, n, payload in self._heap],
            "heap_seq": self._heap_seq,
            "horizon": self._horizon,
            "last_t": self._last_t,
            "auto_t": self._auto_t,
            "frontiers": {("" if key is None else str(key)): f.to_doc()
                          for key, f in self._frontiers.items()},
        }

    @classmethod
    def from_doc(cls, doc: dict, spec: Spec, *,
                 proj_spec: Optional[Spec] = None,
                 bank=None,
                 node_budget: int = DEFAULT_NODE_BUDGET,
                 max_states: int = DEFAULT_MAX_STATES,
                 max_events: int = DEFAULT_MAX_EVENTS,
                 trace: str = "") -> "MonitorSession":
        """Inverse of :meth:`to_doc` — O(doc) deserialization, zero
        engine folds.  The caller passes the REBUILT spec (and
        projected spec) and must have checked identity against the
        doc's ``spec_name``/``spec_kwargs`` first; bank/oracle are
        re-bound process-locally."""
        s = cls(doc["sid"], spec, proj_spec=proj_spec, bank=bank,
                node_budget=node_budget, max_states=max_states,
                max_events=max_events, trace=trace)
        s.model = doc.get("model")
        s.spec_kwargs = dict(doc.get("model_kwargs") or {})
        s.rows = [list(r) for r in doc["rows"]]
        s.seq = int(doc["seq"])
        s.closed = bool(doc["closed"])
        s.flipped = bool(doc["flipped"])
        # the one attribute whose post-publication writes are
        # lock-guarded (the flip-push latch): restore it under the
        # same guard, keeping one discipline across every write site
        with s.lock:
            s.flip_pushed = bool(doc.get("flip_pushed", False))
        s.flip_rows = doc.get("flip_rows")
        s._row_of_pid = {int(p): int(i)
                         for p, i in doc["row_of_pid"].items()}
        s._key_of_pid = {int(p): (None if k is None else int(k))
                         for p, k in doc["key_of_pid"].items()}
        s._heap = [(int(t), int(k), int(n), tuple(payload))
                   for t, k, n, payload in doc["heap"]]
        heapq.heapify(s._heap)
        s._heap_seq = int(doc["heap_seq"])
        s._horizon = int(doc["horizon"])
        s._last_t = int(doc["last_t"])
        s._auto_t = int(doc["auto_t"])
        for kstr, fdoc in doc["frontiers"].items():
            key = None if kstr == "" else int(kstr)
            fspec = proj_spec if key is not None else spec
            s._frontiers[key] = IncrementalFrontier.from_doc(
                fdoc, fspec, bank=bank, node_budget=node_budget,
                max_states=max_states)
        return s

    # -- the devq seam --------------------------------------------------
    def _bank_recheck(self) -> None:
        """Monitor seam (qsm_tpu/devq): a flip is terminal and snapshot-
        backed, but it was decided incrementally — bank the flipped
        stream so the next seized window re-proves it as ONE whole-
        history check (the strongest cross-examination the incremental
        frontier can get).  Free (one global read) without a queue."""
        from ..devq.queue import bank_histories, global_devq

        if global_devq() is None or not self.rows:
            return
        bank_histories(self.spec, [self.history()], plane="monitor")

    # -- introspection --------------------------------------------------
    def history(self) -> History:
        """The stream so far as a canonical History (the ONE decoder,
        utils/report.py — identical rows to what a whole-history
        ``check`` of this stream would decode)."""
        from ..utils.report import history_from_rows

        return history_from_rows([list(r) for r in self.rows])

    def counters(self) -> Dict[str, int]:
        c = {"events": self.seq, "ops": len(self.rows),
             "frontiers": len(self._frontiers),
             "advances": 0, "prefix_hits": 0, "window_checks": 0,
             "committed_ops": 0, "window_ops": 0}
        for f in self._frontiers.values():
            c["advances"] += f.counters.advances
            c["prefix_hits"] += f.counters.prefix_hits
            c["window_checks"] += f.counters.window_checks
            c["committed_ops"] += f.counters.committed_ops
            c["window_ops"] += len(f.window)
        return c

    def snapshot(self) -> dict:
        from ..serve.protocol import VERDICT_NAMES

        v = (int(Verdict.VIOLATION) if self.flipped
             else self._worst_cached())
        return {"session": self.sid, "spec": self.spec.name,
                "per_key": self.proj is not None,
                "verdict": VERDICT_NAMES[v],
                "flipped": self.flipped, "closed": self.closed,
                **self.counters()}

    def _worst_cached(self) -> int:
        worst = int(Verdict.LINEARIZABLE)
        for f in self._frontiers.values():
            if f.verdict == int(Verdict.VIOLATION):
                return int(Verdict.VIOLATION)
            if f.verdict == int(Verdict.BUDGET_EXCEEDED):
                worst = int(Verdict.BUDGET_EXCEEDED)
        return worst


class SessionManager:
    """Bounded registry of live sessions + the monitor plane's running
    totals (the ``stats()`` session block and the SearchStats session
    counters both read here — one source, so metrics reconcile with
    stats by construction)."""

    def __init__(self, *, bank=None,
                 max_sessions: int = DEFAULT_MAX_SESSIONS,
                 max_events: int = DEFAULT_MAX_EVENTS,
                 node_budget: int = DEFAULT_NODE_BUDGET,
                 max_states: int = DEFAULT_MAX_STATES,
                 idle_s: float = 3600.0,
                 store=None):
        self.bank = bank
        # durable substrate (monitor/store.py SessionStore-shaped);
        # None = in-memory sessions only, the pre-ISSUE-18 behavior
        self.store = store
        self.max_sessions = max(1, int(max_sessions))
        self.max_events = int(max_events)
        self.node_budget = int(node_budget)
        self.max_states = int(max_states)
        # abandoned-session reclamation: a client that crashed without
        # closing must not pin a slot forever — when the cap is hit,
        # sessions idle past this are evicted LRU-first (their events
        # are replayable by seq and their prefixes stay banked, so an
        # evicted-then-returning client resumes by re-open + replay)
        self.idle_s = float(idle_s)
        self._lock = threading.Lock()
        self._sessions: "OrderedDict[str, MonitorSession]" = OrderedDict()
        self._n = 0
        # running totals (live sessions' counters are folded in at
        # close so the totals never go backwards)
        self.opened = 0
        self.closed = 0
        self.resumed = 0             # open() calls that found a live sid
        self.restored = 0            # opens resumed from the durable store
        self.evicted = 0             # idle sessions reclaimed at cap
        self.flips_pushed = 0        # flip payloads handed to clients
        self._closed_events = 0
        self._closed_advances = 0
        self._closed_prefix_hits = 0

    # ------------------------------------------------------------------
    def open(self, sid: Optional[str], spec: Spec,
             proj_spec: Optional[Spec], *, trace: str = ""
             ) -> Tuple[MonitorSession, bool]:
        """Open (or resume) a session; ``(session, resumed)``.  A live
        sid re-opened against the SAME spec identity resumes (the
        router's failover replay and client reconnects depend on it);
        a different spec under the same sid is refused loudly."""
        with self._lock:
            if sid is not None and sid in self._sessions:
                s = self._sessions[sid]
                if (s.spec.name, s.spec.spec_kwargs()) != \
                        (spec.name, spec.spec_kwargs()):
                    raise SessionError(
                        f"session {sid} is open against "
                        f"{s.spec.name!r}; close it first")
                self._sessions.move_to_end(sid)
                s.last_used = time.monotonic()
                self.resumed += 1
                return s, True
            stale = self._pop_idle_locked() \
                if len(self._sessions) >= self.max_sessions else []
            full = len(self._sessions) >= self.max_sessions
        # fold evicted counters OUTSIDE the manager lock: the one
        # global order is session.lock BEFORE manager._lock (note_flip
        # runs under a handler-held session lock), so the manager must
        # never reach for a session lock while holding its own
        for s_old in stale:
            self._fold(s_old, evicted=True)
        # durable resume (ISSUE 18): a sid evicted at cap or lost to a
        # process restart comes back from the store in O(doc) — zero
        # engine folds; the journal tail replays seq-idempotently onto
        # banked prefixes.  Outside both locks: deserialization touches
        # no shared state until the session is registered below
        restored: Optional[MonitorSession] = None
        if sid is not None and self.store is not None:
            restored = self._restore(sid, spec, proj_spec, trace)
        with self._lock:
            if sid is not None and sid in self._sessions:
                # a racing open of the same sid won between our lock
                # holds: resume it (same-identity check as above)
                s = self._sessions[sid]
                if (s.spec.name, s.spec.spec_kwargs()) != \
                        (spec.name, spec.spec_kwargs()):
                    raise SessionError(
                        f"session {sid} is open against "
                        f"{s.spec.name!r}; close it first")
                self.resumed += 1
                return s, True
            if len(self._sessions) >= self.max_sessions:
                raise SessionLimit(
                    f"session cap {self.max_sessions} reached "
                    f"({len(self._sessions)} live) — close sessions "
                    "or raise max_sessions")
            if restored is not None:
                self._sessions[sid] = restored
                self.restored += 1
                return restored, True
            if sid is None:
                self._n += 1
                sid = f"s{self._n:06d}"
                while sid in self._sessions:  # caller-named collision
                    self._n += 1
                    sid = f"s{self._n:06d}"
            s = MonitorSession(sid, spec, proj_spec=proj_spec,
                               bank=self.bank,
                               node_budget=self.node_budget,
                               max_states=self.max_states,
                               max_events=self.max_events, trace=trace)
            s.store = self.store
            self._sessions[sid] = s
            self.opened += 1
        if self.store is not None:
            # seed the durable file before any batch journals against
            # it (an atomic rewrite, so it also resets a file whose
            # restore came back unreplayable).  Session lock, never
            # under the manager's — the one global order
            with s.lock:
                self.store.snapshot(sid, s.to_doc())
        return s, False

    def _restore(self, sid: str, spec: Spec,
                 proj_spec: Optional[Spec], trace: str
                 ) -> Optional[MonitorSession]:
        """Rebuild ``sid`` from the durable store; None on a miss or an
        unreplayable file (the open proceeds fresh and re-seeds it).
        Same identity gate as the live-resume path — a durable doc for
        a DIFFERENT spec is refused loudly, never silently shadowed.
        The store binds only AFTER the tail replays, so the replay
        never re-journals its own batches."""
        loaded = self.store.load(sid)
        if loaded is None:
            return None
        doc, tail = loaded
        if (doc.get("spec_name"), doc.get("spec_kwargs")) != \
                (spec.name, spec.spec_kwargs()):
            raise SessionError(
                f"session {sid} is durable against "
                f"{doc.get('spec_name')!r}; close it first")
        try:
            s = MonitorSession.from_doc(
                doc, spec, proj_spec=proj_spec, bank=self.bank,
                node_budget=self.node_budget,
                max_states=self.max_states,
                max_events=self.max_events, trace=trace)
            for batch in tail:
                s.append(batch["events"], seq=batch["seq"])
        except (KeyError, TypeError, ValueError, SessionLimit):
            return None
        s.store = self.store
        return s

    def _pop_idle_locked(self) -> List[MonitorSession]:
        """Pop sessions idle past ``idle_s``, LRU-first (caller holds
        ``_lock``; no session locks touched here — the fold happens
        outside, in the one global lock order).  An evicted client
        resumes by re-open + seq replay with its banked prefixes
        intact.

        With a durable store the cap bounds MEMORY, not open sessions:
        if nothing is idle, the LRU session is evicted to the store
        anyway (its file is already current — every append journals —
        so a returning client restores in O(doc) with zero folds).
        Without a store the cap stays hard and the open raises
        SessionLimit, the pre-ISSUE-18 behavior."""
        now = time.monotonic()
        victims = [k for k, s in self._sessions.items()
                   if now - s.last_used >= self.idle_s]
        if not victims and self.store is not None and self._sessions:
            victims = [next(iter(self._sessions))]  # LRU: oldest entry
        return [self._sessions.pop(sid) for sid in victims]

    def _fold(self, s: MonitorSession, evicted: bool = False) -> None:
        """Fold a departing session's counters into the running totals
        (session lock taken BEFORE the manager lock — the one order)."""
        with s.lock:
            c = s.counters()
        with self._lock:
            if evicted:
                self.evicted += 1
            else:
                self.closed += 1
            self._closed_events += c["events"]
            self._closed_advances += c["advances"]
            self._closed_prefix_hits += c["prefix_hits"]

    def get(self, sid: str) -> Optional[MonitorSession]:
        with self._lock:
            s = self._sessions.get(sid)
            if s is not None:
                self._sessions.move_to_end(sid)
                s.last_used = time.monotonic()
            return s

    def close(self, sid: str) -> Optional[MonitorSession]:
        with self._lock:
            s = self._sessions.pop(sid, None)
        if s is None:
            return None
        self._fold(s)
        if self.store is not None:
            # a closed session answered its verdict; nothing resumes it
            self.store.drop(sid)
        return s

    def note_flip(self) -> None:
        with self._lock:
            self.flips_pushed += 1

    # -- accounting ----------------------------------------------------
    def totals(self) -> Dict[str, int]:
        with self._lock:
            live = list(self._sessions.values())
            out = {"sessions_live": len(live),
                   "opened": self.opened, "closed": self.closed,
                   "resumed": self.resumed, "restored": self.restored,
                   "evicted": self.evicted,
                   "session_events": self._closed_events,
                   "frontier_advances": self._closed_advances,
                   "prefix_hits": self._closed_prefix_hits,
                   "flips_pushed": self.flips_pushed}
        # per-session locks taken OUTSIDE the manager lock (and never
        # the other way around here): note_flip holds a session lock
        # while taking the manager's, so nesting them here would be
        # the lock-order cycle family (g) exists to catch
        for s in live:
            with s.lock:
                c = s.counters()
            out["session_events"] += c["events"]
            out["frontier_advances"] += c["advances"]
            out["prefix_hits"] += c["prefix_hits"]
        return out

    def search_stats(self):
        """The monitor plane's SearchStats record (search/stats.py):
        the four session counters under their compact keys, so bench
        rows and ``qsm-tpu stats`` carry the same numbers the session
        block reports."""
        from ..search.stats import SearchStats

        t = self.totals()
        return SearchStats(engine="monitor",
                           session_events=t["session_events"],
                           frontier_advances=t["frontier_advances"],
                           flips_pushed=t["flips_pushed"],
                           prefix_hits=t["prefix_hits"])

    def snapshot(self) -> dict:
        with self._lock:
            sessions = list(self._sessions.values())
        live = []
        for s in sessions:  # session locks outside the manager lock
            with s.lock:
                live.append(s.snapshot())
        return {**self.totals(), "max_sessions": self.max_sessions,
                "max_events": self.max_events, "live": live}
