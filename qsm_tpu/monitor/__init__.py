"""qsm_tpu.monitor — linearizability monitoring as a live service.

The streaming plane (docs/MONITOR.md): sessions accumulate a running
system's invocation/response events, an incremental frontier
(``frontier.py`` — the forward-running twin of ops/segdc.py's
quiescent-cut algebra) decides each prefix the moment it is decidable,
decided prefixes bank in the serve verdict cache under rolling prefix
fingerprints, and a verdict flip is pushed with a shrink-plane-minimized
repro.  ``session.py`` owns event validation/ordering and the bounded
session registry; serve/server.py speaks the ``session.*`` protocol ops
over it and fleet/router.py routes + replays sessions across nodes.
"""

from .frontier import (IncrementalFrontier, PrefixHasher,
                       decode_frontier_states, encode_frontier_states)
from .session import (DEFAULT_MAX_EVENTS, DEFAULT_MAX_SESSIONS,
                      MonitorSession, SessionError, SessionLimit,
                      SessionManager)
from .store import SessionStore

__all__ = [
    "IncrementalFrontier", "PrefixHasher", "MonitorSession",
    "SessionManager", "SessionError", "SessionLimit", "SessionStore",
    "encode_frontier_states", "decode_frontier_states",
    "DEFAULT_MAX_EVENTS", "DEFAULT_MAX_SESSIONS",
]
