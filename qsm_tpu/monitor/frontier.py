"""Incremental per-session frontier — linearizability decided as events arrive.

The decrease-and-conquer monitoring shape (PAPERS.md: arXiv:2410.04581;
the same quiescent-cut algebra ``ops/segdc.py`` uses for batch work,
run FORWARD over a growing event stream instead of backward over a
finished history):

* events append in real-time order (the session layer enforces
  monotonic timestamps), so a **committed quiescent cut stays a cut
  forever** — every later op invokes after every earlier response;
* whenever the open window grows a new quiescent cut, the completed
  segment behind it is folded into the frontier: ``F' = end_states(seg,
  F)`` (ops/segdc.py ``_end_states`` — ALL model states reachable by
  some complete valid linearization).  ``F' = ∅`` is an exact
  VIOLATION of the whole stream so far (segdc's block-decomposition
  iff); a non-empty ``F'`` banks in the verdict cache under the
  prefix's **incremental fingerprint** (:class:`PrefixHasher` — a
  rolling sha256, so a growing prefix never re-hashes from scratch)
  with the frontier states riding the row, and the committed ops leave
  the window (the decided-prefix eviction that keeps a long-lived
  session's memory O(window), not O(stream));
* the open window (pending ops allowed; no internal cuts) is re-checked
  for satisfiability from the frontier states only —
  ``oracle.check_from`` per state, exactly segdc's final-segment rule —
  so re-deciding after k appended events costs o(n) engine work on an
  n-event stream.

**Verdict exactness.**  LINEARIZABLE ⟺ some frontier chain reaches a
satisfiable window (segdc's iff).  A VIOLATION is *stable under
extension*: every new op invokes after every already-completed op's
response, so any linearization of the extended stream, truncated at its
first new op, is a linearization of the old stream with trailing
pendings pruned — if none existed before, none exists after
(docs/MONITOR.md "Why a flip is final").  The monitor therefore treats
VIOLATION as terminal, and a session's final verdict equals the
whole-history ``check`` verdict bit-for-bit (tests/test_monitor.py
parity pins).

**Prefix banking / resume.**  A committed prefix banks
``verdict=LINEARIZABLE`` with the frontier state set encoded in the
row's witness slot (:func:`encode_frontier_states` — prefix keys live
in their own fingerprint domain, disjoint from check rows, so no
``verify_witness`` consumer ever sees one).  Re-feeding the same event
stream — a client resuming after a node restart, a router replaying a
session onto a respawned node — advances cut-by-cut on bank hits with
ZERO engine work (``prefix_hits`` counts them).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..core.history import History, Op
from ..core.spec import Spec
from ..ops.backend import Verdict
from ..ops.segdc import _Budget, _end_states, default_middle_oracle
from ..sched.runner import PENDING_T

# the prefix rows' own fingerprint domain: a prefix key can never
# collide with serve.cache.fingerprint_key's (spec, whole-history) doc.
# v2 = the hash-CHAIN form (ISSUE 18): the rolling digest became a
# serializable chain state, which changes every non-empty prefix key —
# the domain bump retires v1 rows wholesale instead of letting the two
# schemes alias
_PREFIX_DOMAIN = "qsm_tpu_monitor_prefix_v2"
# witness-slot header tag for encoded frontier state sets
_FRONTIER_TAG = -7741

# bounded by contract (QSM-MON-UNBOUNDED, analysis/monitor_passes.py):
# a frontier state set past this cap stops cut-committing (the window
# keeps growing toward the session's own event cap instead — honest
# degradation, never an unbounded set)
DEFAULT_MAX_STATES = 64
DEFAULT_NODE_BUDGET = 2_000_000


class PrefixHasher:
    """Rolling sha256 HASH CHAIN over a spec identity header plus each
    committed op: ``state_{k+1} = sha256(state_k || op_k)``.  ``key()``
    is O(1) (the state IS the key), so the n-th prefix fingerprint
    never re-hashes the n-1 ops before it — and unlike a streaming
    digest object, the chain state is one hex string that round-trips
    through JSON, which is what lets a DURABLE session (ISSUE 18,
    monitor/store.py) resume its fingerprint mid-stream after a
    process restart without replaying the prefix."""

    def __init__(self, spec: Spec):
        self.state = hashlib.sha256(json.dumps(
            [_PREFIX_DOMAIN, spec.name, spec.spec_kwargs()],
            sort_keys=True).encode()).hexdigest()
        self.ops_hashed = 0

    def push(self, op: Op) -> None:
        self.state = hashlib.sha256(
            (self.state + json.dumps(
                [op.pid, op.cmd, op.arg, op.resp, op.invoke_time,
                 op.response_time])).encode()).hexdigest()
        self.ops_hashed += 1

    def key(self) -> str:
        return self.state

    def copy(self) -> "PrefixHasher":
        return PrefixHasher.from_state(self.state, self.ops_hashed)

    @classmethod
    def from_state(cls, state: str, ops_hashed: int) -> "PrefixHasher":
        """Rebuild a hasher mid-chain (durable-session resume)."""
        h = cls.__new__(cls)
        h.state = str(state)
        h.ops_hashed = int(ops_hashed)
        return h


def encode_frontier_states(states: Sequence[Tuple[int, ...]]) -> List[list]:
    """Frontier state set → the bank row's witness-slot encoding:
    ``[[_FRONTIER_TAG, state_dim], *states]`` (sorted — deterministic
    rows compact/replicate byte-stably)."""
    states = sorted(tuple(int(v) for v in s) for s in states)
    dim = len(states[0]) if states else 0
    return [[_FRONTIER_TAG, dim]] + [list(s) for s in states]


def decode_frontier_states(witness) -> Optional[Set[Tuple[int, ...]]]:
    """Inverse of :func:`encode_frontier_states`; None when the slot
    holds anything else (an alien row must never masquerade as a
    frontier)."""
    if not witness:
        return None
    head = list(witness[0])
    if len(head) != 2 or head[0] != _FRONTIER_TAG:
        return None
    dim = int(head[1])
    out: Set[Tuple[int, ...]] = set()
    for row in list(witness)[1:]:
        s = tuple(int(v) for v in row)
        if len(s) != dim:
            return None
        out.add(s)
    return out if out else None


@dataclasses.dataclass
class FrontierCounters:
    """One frontier's cost/shape record (session + SearchStats feed)."""

    events: int = 0            # ops applied (invokes; responses update)
    advances: int = 0          # quiescent cuts committed
    prefix_hits: int = 0       # cuts committed from the bank, zero engine
    window_checks: int = 0     # satisfiability re-checks of the window
    committed_ops: int = 0     # ops evicted behind the committed cut
    states: int = 0            # current frontier state-set size


class IncrementalFrontier:
    """One spec's incremental frontier (module docstring).  The session
    layer owns event validation and ordering; this class owns the cut
    algebra, the bank hand-off and the window re-check."""

    def __init__(self, spec: Spec, *, bank=None, oracle=None,
                 node_budget: int = DEFAULT_NODE_BUDGET,
                 max_states: int = DEFAULT_MAX_STATES):
        self.spec = spec
        self.bank = bank                     # VerdictCache-shaped get/put
        # the planner's host from-state engine: the native C++ checker
        # when the toolchain is present (its end_states/check_from walk
        # middles 3-10x faster), else the memoised Python oracle —
        # exactly SegDC's ladder (ops/segdc.py default_middle_oracle)
        self.oracle = oracle or default_middle_oracle(spec)
        self.node_budget = int(node_budget)
        self.max_states = max(1, int(max_states))
        self.window: List[Op] = []           # open ops, invoke order
        self.states: Set[Tuple[int, ...]] = {
            tuple(int(v) for v in spec.initial_state())}
        self.hasher = PrefixHasher(spec)
        self.counters = FrontierCounters(states=1)
        self.verdict = int(Verdict.LINEARIZABLE)  # empty stream: vacuous
        self._saturated = False  # state-set cap hit: stop committing cuts
        # pid -> window index of its one outstanding op.  Pendings block
        # every later cut, so they never commit out of the window; their
        # indices only SHIFT on eviction (adjusted in advance()).
        self._pending: Dict[int, int] = {}

    # -- event application --------------------------------------------
    def invoke(self, pid: int, cmd: int, arg: int, t: int) -> None:
        """One invocation whose time is >= every event already applied
        (the session layer enforces the order and the one-outstanding-
        op-per-pid history model)."""
        self._pending[pid] = len(self.window)
        self.window.append(make_pending_op(pid, cmd, arg, t))
        self.counters.events += 1

    def respond(self, pid: int, resp: int, t: int) -> bool:
        """Complete ``pid``'s outstanding op (Op is frozen — replaced
        in place); False when the pid has none here."""
        i = self._pending.pop(pid, None)
        if i is None:
            return False
        self.window[i] = dataclasses.replace(
            self.window[i], resp=resp, response_time=t)
        return True

    def append_completed(self, op: Op) -> None:
        """One already-completed op (ingested rows, replayed streams)
        whose invoke_time respects the arrival order."""
        self.window.append(op)
        self.counters.events += 1

    # -- the frontier step --------------------------------------------
    def advance(self) -> int:
        """Commit every quiescent cut the window now contains (bank
        hits first, engine folds otherwise); returns the frontier
        verdict — VIOLATION the moment some committed fold empties the
        state set.  Cheap no-op when no new cut exists."""
        if self.verdict == int(Verdict.VIOLATION):
            return self.verdict
        while not self._saturated:
            cut = self._first_cut()
            if cut is None:
                break
            seg = self.window[:cut]
            # key of the prefix INCLUDING this segment, via a peek copy
            # (the live hasher only advances on a successful commit)
            peek = self._peek_hasher(seg)
            key = peek.key()
            nxt = self._bank_get(key)
            if nxt is not None:
                self.counters.prefix_hits += 1
            else:
                budget = _Budget(self.node_budget)
                nxt = _end_states(self.spec, seg, self.states, budget)
                if nxt is None:
                    # budget blown mid-fold: leave the cut uncommitted
                    # (the window re-check still answers, just from an
                    # older frontier) — never guess, never wedge
                    break
                if not nxt:
                    self.verdict = int(Verdict.VIOLATION)
                    return self.verdict
                if len(nxt) > self.max_states:
                    # bounded by contract: an exploding frontier stops
                    # cut-committing instead of growing without cap
                    self._saturated = True
                    break
                self._bank_put(key, nxt)
            self.hasher = peek
            self.window = self.window[cut:]
            # every pending op sits at index >= cut (a pending blocks
            # all later cuts), so the shift can never go negative
            self._pending = {p: i - cut for p, i in self._pending.items()}
            self.states = nxt
            self.counters.advances += 1
            self.counters.committed_ops += cut
            self.counters.states = len(nxt)
        return self.verdict

    def _peek_hasher(self, seg: Sequence[Op]) -> PrefixHasher:
        peek = self.hasher.copy()
        for op in seg:
            peek.push(op)
        return peek

    def _first_cut(self) -> Optional[int]:
        """Smallest i>0 such that every window op before i responded
        before window op i invoked (pending = sentinel, blocks all
        later cuts)."""
        max_resp = -1
        for i, op in enumerate(self.window):
            if i and max_resp < op.invoke_time:
                return i
            max_resp = max(max_resp, op.response_time)
        return None

    # -- the window re-check ------------------------------------------
    def check_window(self) -> int:
        """Satisfiability of the open window from the frontier states
        (segdc's final-segment rule): LINEARIZABLE from ANY state wins;
        all states VIOLATION is an exact stream violation; any budget
        blow-up stays honestly undecided."""
        if self.verdict == int(Verdict.VIOLATION):
            return self.verdict
        if not self.window:
            self.verdict = int(Verdict.LINEARIZABLE)
            return self.verdict
        self.counters.window_checks += 1
        last = History(list(self.window))
        saw_budget = False
        # sorted: deterministic state try-order (replayable cost records)
        for state in sorted(self.states):
            v = self.oracle.check_from(self.spec, last,
                                       np.asarray(state, np.int32))
            if v == Verdict.LINEARIZABLE:
                self.verdict = int(Verdict.LINEARIZABLE)
                return self.verdict
            if v == Verdict.BUDGET_EXCEEDED:
                saw_budget = True
        self.verdict = int(Verdict.BUDGET_EXCEEDED if saw_budget
                           else Verdict.VIOLATION)
        return self.verdict

    # -- bank plumbing -------------------------------------------------
    def _bank_get(self, key: str) -> Optional[Set[Tuple[int, ...]]]:
        if self.bank is None:
            return None
        e = self.bank.get(key)
        if e is None or e.verdict != int(Verdict.LINEARIZABLE):
            return None
        return decode_frontier_states(e.witness)

    def _bank_put(self, key: str, states: Set[Tuple[int, ...]]) -> None:
        if self.bank is None:
            return
        self.bank.put(key, int(Verdict.LINEARIZABLE),
                      encode_frontier_states(states))

    # -- durability (ISSUE 18) -----------------------------------------
    def to_doc(self) -> dict:
        """The frontier's COMPLETE resumable state as one JSON-safe doc:
        window ops as 6-rows, frontier states, the hash-chain state,
        pending indices, counters, verdict, saturation.  The bank and
        oracle are NOT in the doc — they are process-local substrate the
        restorer re-binds (the banked prefix rows themselves already
        ride the replog)."""
        return {
            "window": [[op.pid, op.cmd, op.arg, op.resp,
                        op.invoke_time, op.response_time]
                       for op in self.window],
            "states": sorted([int(v) for v in s] for s in self.states),
            "hash_state": self.hasher.state,
            "ops_hashed": self.hasher.ops_hashed,
            "pending": {str(p): i for p, i in self._pending.items()},
            "verdict": self.verdict,
            "saturated": self._saturated,
            "counters": dataclasses.asdict(self.counters),
        }

    @classmethod
    def from_doc(cls, doc: dict, spec: Spec, *, bank=None, oracle=None,
                 node_budget: int = DEFAULT_NODE_BUDGET,
                 max_states: int = DEFAULT_MAX_STATES
                 ) -> "IncrementalFrontier":
        """Inverse of :meth:`to_doc`: O(doc) deserialization, ZERO
        engine folds — the committed prefix resumes as its hash-chain
        state plus the frontier state set, never as a replay."""
        f = cls(spec, bank=bank, oracle=oracle,
                node_budget=node_budget, max_states=max_states)
        f.window = [
            Op(pid=int(r[0]), cmd=int(r[1]), arg=int(r[2]),
               resp=int(r[3]), invoke_time=int(r[4]),
               response_time=int(r[5]))
            for r in doc["window"]]
        f.states = {tuple(int(v) for v in s) for s in doc["states"]}
        f.hasher = PrefixHasher.from_state(doc["hash_state"],
                                           doc["ops_hashed"])
        f._pending = {int(p): int(i)
                      for p, i in doc.get("pending", {}).items()}
        f.verdict = int(doc["verdict"])
        f._saturated = bool(doc.get("saturated", False))
        f.counters = FrontierCounters(
            **{k: int(v) for k, v in doc.get("counters", {}).items()})
        return f

    # -- introspection -------------------------------------------------
    def snapshot(self) -> dict:
        c = self.counters
        return {"window_ops": len(self.window),
                "committed_ops": c.committed_ops,
                "advances": c.advances,
                "prefix_hits": c.prefix_hits,
                "window_checks": c.window_checks,
                "states": len(self.states),
                "saturated": self._saturated,
                "verdict": self.verdict}


def make_pending_op(pid: int, cmd: int, arg: int, invoke_time: int) -> Op:
    """A just-invoked op (no response yet) — the monitor's unit of
    arrival; :meth:`IncrementalFrontier.complete_op` fills it in."""
    return Op(pid=pid, cmd=cmd, arg=arg, resp=-1,
              invoke_time=invoke_time, response_time=PENDING_T)
