"""Observability: counterexample printing, structured logs, regression files.

The reference leans on QuickCheck's counterexample printer and ``collect``
stats, and "checkpointing" is seed replay (SURVEY.md §5).  Here: a per-pid
timeline printer for minimal counterexamples, one-JSON-line-per-trial
structured logging, and persisted regression files carrying everything needed
to replay a failure — (model, impl, seed key, config, program, history).
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import IO, Optional

from ..core.generator import ProgOp, Program
from ..core.history import History, Op
from ..core.property import Counterexample, PropertyConfig
from ..core.spec import Spec


# ---------------------------------------------------------------------------
# Counterexample pretty-printer
# ---------------------------------------------------------------------------

def _op_label(spec: Spec, op: Op) -> str:
    sig = spec.CMDS[op.cmd]
    arg = f"({op.arg})" if sig.n_args > 1 else "()"
    resp = "?" if op.is_pending else str(op.resp)
    return f"{sig.name}{arg} -> {resp}"


def format_history(spec: Spec, history: History, width: int = 60) -> str:
    """Per-pid timeline: one row per operation, bars showing the real-time
    interval — overlap between rows is exactly the concurrency the
    lineariser had to untangle."""
    if not history.ops:
        return "(empty history)"
    t_max = max(o.response_time for o in history.ops
                if not o.is_pending) if history.n_pending < len(
                    history.ops) else max(o.invoke_time for o in history.ops)
    t_max = max(t_max, 1)
    scale = max(1.0, t_max / width)
    lines = []
    for o in history.ops:
        lo = int(o.invoke_time / scale)
        hi = (int(min(o.response_time, t_max) / scale)
              if not o.is_pending else width)
        hi = max(hi, lo + 1)
        bar = " " * lo + "[" + "=" * (hi - lo - 1) + ("]" if not o.is_pending
                                                      else ">")
        lines.append(f"  pid {o.pid}  {_op_label(spec, o):24s} |{bar}")
    return "\n".join(lines)


def format_counterexample(spec: Spec, cx: Counterexample) -> str:
    head = (f"counterexample after trial {cx.trial} "
            f"(seed {cx.trial_seed!r}, {cx.shrink_steps} shrink steps, "
            f"{len(cx.program)} ops):")
    return head + "\n" + format_history(spec, cx.history)


# ---------------------------------------------------------------------------
# Structured logging — one JSON line per event
# ---------------------------------------------------------------------------

class JsonlLogger:
    """Minimal structured logger: ``log.emit("trial", trial=3, ok=True)``
    writes one self-contained JSON line (SURVEY.md §5 metrics/logging)."""

    def __init__(self, stream: Optional[IO] = None, path: Optional[str] = None):
        assert not (stream and path)
        self._own = open(path, "a") if path else None
        self.stream = self._own or stream

    def emit(self, event: str, **fields) -> None:
        if self.stream is None:
            return
        rec = {"ts": round(time.time(), 3), "event": event, **fields}
        self.stream.write(json.dumps(rec) + "\n")
        self.stream.flush()

    def close(self) -> None:
        if self._own:
            self._own.close()


# ---------------------------------------------------------------------------
# Regression files — persisted failing triples
# ---------------------------------------------------------------------------

def faults_to_doc(fp) -> Optional[dict]:
    """JSON-safe FaultPlan serialization (sets become sorted lists)."""
    if fp is None:
        return None
    return {"p_drop": fp.p_drop, "p_duplicate": fp.p_duplicate,
            "p_delay": fp.p_delay, "delay_steps": fp.delay_steps,
            "partitions": [sorted(g) for g in fp.partitions],
            "crash_at": dict(fp.crash_at),
            "protected": sorted(fp.protected)}


def faults_from_doc(doc: Optional[dict]):
    from ..sched.scheduler import FaultPlan

    if doc is None:
        return None
    return FaultPlan(p_drop=doc["p_drop"], p_duplicate=doc["p_duplicate"],
                     p_delay=doc.get("p_delay", 0.0),
                     delay_steps=doc.get("delay_steps", 3),
                     partitions=[set(g) for g in doc["partitions"]],
                     crash_at=doc["crash_at"],
                     protected=set(doc["protected"]))


def save_regression(path: str, model: str, impl: str, spec: Spec,
                    cfg: PropertyConfig, cx: Counterexample) -> None:
    """Persist a failure as a self-contained replayable JSON file."""
    doc = {
        "model": model,
        "impl": impl,
        "spec": spec.name,
        "spec_kwargs": spec.spec_kwargs(),
        "config": {
            **{k: v for k, v in dataclasses.asdict(cfg).items()
               if k != "faults"},
            "faults": faults_to_doc(cfg.faults)},
        "trial": cx.trial,
        "trial_seed": cx.trial_seed,
        "shrink_steps": cx.shrink_steps,
        "program": {"n_pids": cx.program.n_pids,
                    "ops": [[o.pid, o.cmd, o.arg] for o in cx.program.ops]},
        "history": [[o.pid, o.cmd, o.arg, o.resp, o.invoke_time,
                     o.response_time] for o in cx.history.ops],
    }
    # tmp+rename (resilience/checkpoint.py): a regression file is the
    # only copy of a found bug — a crash mid-write must not corrupt it
    from ..resilience.checkpoint import atomic_write_json

    atomic_write_json(path, doc, indent=1)


def history_from_rows(rows) -> History:
    """The ONE decoder for the ``[pid, cmd, arg, resp, invoke_time,
    response_time]`` history encoding (regression files, external trace
    files — the `check` CLI, the ingest adapters).  Normalizes pending
    markers: a null/negative resp or a response_time at/past the
    sentinel both mean pending, canonicalized to ``resp=-1,
    response_time=PENDING_T``.

    Op order is CANONICAL, not insertion-luck: rows sort under the
    deterministic total order ``(invoke_time, response_time, pid, cmd,
    arg, resp)``, so any permutation of the same rows decodes to the
    same History (same fingerprint, same cache row, same witness
    indices).  Rows already in invocation order — every in-tree writer
    — are unchanged.  Witness op indices refer to this canonical
    order.

    A completed row whose response precedes its invocation is not a
    history at all (no schedule produces one; an adapter that builds
    one mis-paired its events) — refused loudly, never silently
    reordered into something checkable."""
    from ..sched.runner import PENDING_T

    ops = []
    for i, (pid, cmd, arg, resp, inv, ret) in enumerate(rows):
        pending = resp is None or resp < 0 or ret is None or ret >= PENDING_T
        if not pending and ret < inv:
            raise ValueError(
                f"history row {i} ({[pid, cmd, arg, resp, inv, ret]}): "
                f"response_time {ret} precedes invoke_time {inv} — "
                "responses cannot precede their invocations (mis-paired "
                "events in the producer?)")
        ops.append(Op(pid=pid, cmd=cmd, arg=arg,
                      resp=-1 if pending else resp,
                      invoke_time=inv,
                      response_time=PENDING_T if pending else ret))
    ops.sort(key=lambda o: (o.invoke_time, o.response_time, o.pid,
                            o.cmd, o.arg, o.resp))
    return History(ops)


def load_regression(path: str):
    """(model, impl, trial_seed, program, history, faults, spec_kwargs)
    from a regression file; ``faults`` is the FaultPlan the failure was
    found under (replay must reuse it or the schedule diverges), and
    ``spec_kwargs`` rebuilds the exact spec the failure was captured
    against (missing in pre-round-2 files: empty dict = registry
    defaults, which is what those files were in fact captured with)."""
    with open(path) as f:
        doc = json.load(f)
    prog = Program(tuple(ProgOp(p, c, a) for p, c, a in doc["program"]["ops"]),
                   n_pids=doc["program"]["n_pids"])
    hist = history_from_rows(doc["history"])
    faults = faults_from_doc(doc["config"].get("faults"))
    return (doc["model"], doc["impl"], doc["trial_seed"], prog, hist, faults,
            doc.get("spec_kwargs", {}))
