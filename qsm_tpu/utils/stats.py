"""Schedule-coverage statistics.

The reference has no coverage tooling; SURVEY.md §5 prescribes "distinct
interleavings explored" as the quality metric for the scheduler's exploration
— if many seeds collapse onto few schedules, the race detector is weaker than
its trial count suggests.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

from ..core.generator import Program
from ..sched.runner import prepare_run
from ..sched.scheduler import FaultPlan


@dataclasses.dataclass
class CoverageStats:
    seeds: int
    distinct_schedules: int  # distinct delivery-order traces
    distinct_histories: int  # distinct (op, resp, interval) sequences

    @property
    def schedule_diversity(self) -> float:
        return self.distinct_schedules / max(self.seeds, 1)


def schedule_coverage(sut_factory, program: Program, seeds: Iterable,
                      faults: Optional[FaultPlan] = None,
                      max_steps: int = 100_000) -> CoverageStats:
    """Run ``program`` under each seed; count distinct schedules/histories.

    ``sut_factory`` must build a FRESH SUT per run (state is per-run).  The
    schedule signature is the scheduler's delivered-uid trace — exactly the
    nondeterminism the seed controls (SURVEY.md §3.3).
    """
    schedules, histories = set(), set()
    n = 0
    for seed in seeds:
        n += 1
        sched, rec = prepare_run(sut_factory(), program, seed,
                                 faults=faults, max_steps=max_steps)
        sched.run()
        schedules.add(tuple(sched.trace))
        h = rec.history()
        histories.add(h.fingerprint())
    return CoverageStats(seeds=n, distinct_schedules=len(schedules),
                         distinct_histories=len(histories))
