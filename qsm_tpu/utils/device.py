"""Bounded TPU-availability probing and platform forcing.

The image registers the single-chip axon TPU plugin in every interpreter via
``sitecustomize``; when the chip tunnel is wedged, the *first* ``jax.devices()``
call blocks forever — it cannot be interrupted or timed out in-process once
backend initialization has started.  Everything that wants the real chip must
therefore probe from a **subprocess with a wall-clock timeout** first, and only
initialize the in-process backend after the probe succeeds (VERDICT.md round 1:
"Wedged-backend hangs in the CLI", "bench.py must fail fast").

Two entry points:

* :func:`probe_default_backend` — subprocess probe of whatever backend the
  default environment provides (the axon TPU plugin, normally), bounded by a
  timeout.  Never hangs the caller.
* :func:`force_cpu_platform` — the conftest dance: make THIS process use the
  CPU platform (optionally with a virtual multi-device mesh) even though the
  plugin is registered.  A plain ``JAX_PLATFORMS=cpu`` env var is ignored once
  the plugin registered; ``jax.config.update`` after import wins as long as no
  backend has initialized yet.
"""

from __future__ import annotations

import dataclasses
import os
import re
import subprocess
import sys
from typing import Optional

_PROBE_SNIPPET = (
    "import jax\n"
    "ds = jax.devices()\n"
    "import jax.numpy as jnp\n"
    "x = int(jnp.arange(8).sum())\n"
    "assert x == 28, x\n"
    "print(ds[0].platform, len(ds), ds[0])\n"
)


def _strip_host_platform_flag(flags: str) -> str:
    """Remove only the virtual-CPU-mesh forcing flag from an XLA_FLAGS
    string; every other (operator chip-tuning) flag must survive, or probe
    and real init would validate different XLA configurations."""
    return re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                  flags).strip()


def forced_host_device_env(n_devices: int,
                           base_env: Optional[dict] = None) -> dict:
    """Subprocess environment pinning the JAX CPU platform with
    ``n_devices`` virtual devices — the one derivation every
    forced-device-count lane uses (tests/_mesh_worker.py parity
    subprocesses, tools/bench_mesh.py scaling cells, the fleet gate
    re-run).  The flag must reach the child BEFORE its first backend
    init, which is exactly why this is an env builder and not an
    in-process setter: :func:`force_cpu_platform` covers the in-process
    case, subprocesses get their mesh width from here.  Existing
    operator ``XLA_FLAGS`` survive (only a previous forcing flag is
    replaced — same contract as :func:`_strip_host_platform_flag`)."""
    env = dict(os.environ if base_env is None else base_env)
    flags = _strip_host_platform_flag(env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (f"{flags} --xla_force_host_platform_device_count="
                        f"{int(n_devices)}").strip()
    env["JAX_PLATFORMS"] = "cpu"
    return env


@dataclasses.dataclass(frozen=True)
class Probe:
    """Result of a bounded backend probe."""

    ok: bool          # backend initialized AND ran a tiny computation
    platform: str     # e.g. "tpu", "cpu"; "none" if init failed
    detail: str       # device string on success, diagnostic on failure

    @property
    def is_device(self) -> bool:
        """True when a real accelerator (not host CPU) answered."""
        return self.ok and self.platform not in ("cpu", "none")


def probe_default_backend(timeout_s: Optional[float] = None,
                          policy=None, on_attempt=None) -> Probe:
    """Probe the environment's default JAX backend from a subprocess.

    The subprocess inherits the default platform selection (axon plugin) —
    explicit CPU overrides a caller may have exported are stripped so the
    probe answers "is the real chip reachable", not "is anything reachable".
    Only the host-platform forcing flag is stripped from ``XLA_FLAGS``
    (ADVICE.md round 2): operator chip-tuning flags must stay, or the probe
    would validate a different XLA configuration than the in-process
    backend actually initializes with.

    Bounding and retries come from ONE place — a
    :class:`~qsm_tpu.resilience.policy.RetryPolicy` (default: the
    ``probe`` preset; bench.py passes ``bench-probe``, the watcher its
    seize presets).  A wedged tunnel yields ``ok=False`` after the
    policy's per-attempt timeout instead of hanging forever; multi-attempt
    policies re-probe on a non-device answer, spaced by the policy's
    backoff, and return the LAST probe.  ``timeout_s`` (back-compat)
    overrides the policy's per-attempt bound.  ``on_attempt`` is called
    with every individual :class:`Probe` (bench.py's probe log).
    """
    from ..resilience.policy import preset

    if policy is None:
        policy = preset("probe")
    if timeout_s is not None:
        policy = policy.with_(timeout_s=float(timeout_s))

    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    flags = _strip_host_platform_flag(env.get("XLA_FLAGS", ""))
    if flags:
        env["XLA_FLAGS"] = flags
    else:
        env.pop("XLA_FLAGS", None)

    def one_probe() -> Probe:
        # fault site (resilience/faults.py): a "wedge" simulates the
        # tunnel down without hardware; hang/raise are caught below so
        # the probe keeps its never-raises contract
        from ..resilience.faults import InjectedFault, inject

        try:
            if inject("probe") == "wedge":
                return Probe(False, "none",
                             f"backend init exceeded "
                             f"{policy.timeout_s:.0f}s "
                             "(fault-injected wedge)")
        except InjectedFault as e:
            return Probe(False, "none", f"fault-injected: {e}")
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_SNIPPET],
                capture_output=True, text=True, timeout=policy.timeout_s,
                env=env)
        except subprocess.TimeoutExpired:
            return Probe(False, "none",
                         f"backend init exceeded {policy.timeout_s:.0f}s "
                         "(chip tunnel wedged?)")
        except OSError as e:  # e.g. fork failure
            return Probe(False, "none", f"probe subprocess failed: {e!r}")
        if r.returncode != 0:
            tail = (r.stderr or r.stdout).strip().splitlines()[-5:]
            return Probe(False, "none", " | ".join(tail)[-400:])
        parts = r.stdout.split(maxsplit=2)
        if len(parts) < 3:
            return Probe(False, "none",
                         f"unexpected probe output {r.stdout!r}")
        return Probe(True, parts[0], r.stdout.strip())

    def attempt() -> Probe:
        p = one_probe()
        if on_attempt is not None:
            on_attempt(p)
        return p

    return policy.run(attempt, should_retry=lambda p: not p.is_device)


def probe_or_force_cpu(force_cpu: bool = False,
                       probe_timeout_s: Optional[float] = None,
                       policy=None):
    """The artifact-tool preamble, in ONE place (bench.py,
    tools/bench_configs.py, tools/bench_e2e.py all need the identical
    sequence — diverging copies would label fallbacks differently):
    bounded-probe the real chip unless ``force_cpu``; pin this process to
    the CPU platform when the chip is absent.  Probe bounding/retries ride
    the shared :class:`RetryPolicy` plumbing of
    :func:`probe_default_backend` (``policy``/``probe_timeout_s``).
    Returns ``(on_tpu, probe_detail, header)`` where ``header`` is the
    provenance dict artifacts embed (device / device_fallback /
    tpu_probe / iso).
    """
    import datetime

    if force_cpu:
        on_tpu, detail = False, "skipped (--force-cpu)"
    else:
        p = probe_default_backend(probe_timeout_s, policy=policy)
        on_tpu, detail = p.is_device, p.detail
    if not on_tpu:
        force_cpu_platform()
    else:
        # device runs only: on XLA:CPU the AOT cache loader warns about
        # machine-feature mismatches ("could lead to SIGILL") — the
        # fallback path that guards the round's headline must not gamble
        # on that, and CPU compiles are cheap anyway.  On the chip the
        # cache is the window-economics win (seize subprocesses share
        # first-compiles).
        enable_compile_cache()
    import jax

    header = {
        "iso": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "device": str(jax.devices()[0]),
        "device_fallback": None if on_tpu else "cpu",
        "tpu_probe": detail,
    }
    return on_tpu, detail, header


def _compile_cache_dir(dirpath: Optional[str] = None) -> str:
    """THE default-directory derivation for the persistent compile
    cache: env override, else ``<repo>/.jax_cache``.  One definition
    shared by :func:`enable_compile_cache` and
    :func:`compile_cache_entries` (ADVICE.md round 5, finding 4: the
    duplicated env-var + three-dirname-hops derivation could silently
    desynchronize, and the before/after cache stamps benchmarks embed
    would then count the wrong directory)."""
    if dirpath is not None:
        return dirpath
    return os.environ.get(
        "QSM_TPU_COMPILE_CACHE",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), ".jax_cache"))


def enable_compile_cache(dirpath: Optional[str] = None) -> None:
    """Turn on JAX's persistent (on-disk, cross-process) compilation cache.

    Why here: healing-window economics.  The unrolled kernel bodies cost
    ~2.4× to compile, the seize pipeline runs bench/scale/e2e as SEPARATE
    bounded subprocesses, and first-compiles are 20-40 s on the chip — a
    shared on-disk cache means only the window's first process pays them.
    Safe to call any time before (or after) backend init; never raises —
    an old jax without the knobs just skips it."""
    dirpath = _compile_cache_dir(dirpath)
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", dirpath)
        # default thresholds skip small/fast compiles; the kernel's many
        # (bucket, slots, chunk, unroll) executables are individually
        # cheap-ish but numerous — cache them all
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # noqa: BLE001 — a cache is an optimization, never
        pass           # a reason to fail a bench or a test


def compile_cache_entries(dirpath: Optional[str] = None) -> Optional[int]:
    """Entry count of the persistent compile cache directory, or None when
    it does not exist.  Benchmarks stamp this before/after their compile
    phase so an artifact records whether its warm-up paid real
    first-compiles or hit the cross-process cache (VERDICT.md round 4,
    "What's weak" #3: nothing in the banked windows records compile-cache
    state, so compile-cost-inside-the-window could not be ruled out)."""
    try:
        return sum(1 for e in os.scandir(_compile_cache_dir(dirpath))
                   if e.is_file())
    except OSError:
        return None


def force_cpu_platform(n_devices: Optional[int] = None) -> None:
    """Force THIS process onto the JAX CPU platform (before any device use).

    Must run before the first ``jax.devices()`` / first traced computation;
    afterwards the backend is already bound.  ``n_devices`` materializes a
    virtual multi-device CPU mesh (sharding tests / dryruns).
    """
    flags = _strip_host_platform_flag(os.environ.get("XLA_FLAGS", ""))
    if n_devices is not None:
        flags = (f"{flags} --xla_force_host_platform_device_count="
                 f"{n_devices}").strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
