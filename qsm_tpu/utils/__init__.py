"""Config, CLI, structured logging, counterexample printing, coverage stats
(SURVEY.md §5 auxiliary subsystems)."""

from .report import (JsonlLogger, format_counterexample, format_history,
                     load_regression, save_regression)
from .stats import CoverageStats, schedule_coverage
