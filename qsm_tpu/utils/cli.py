"""Command-line interface — ``python -m qsm_tpu <cmd>``.

The reference's knobs are QuickCheck ``Args`` (maxSuccess, replay seed, size)
(SURVEY.md §5 config): here that's a plain argparse CLI over the registry —
``run`` (property check), ``replay`` (reproduce a persisted failure),
``bench`` (checker throughput), ``stats`` (search-cost accounting —
qsm_tpu/search), ``coverage`` (schedule diversity), ``lint`` (the
qsmlint static analyzer — docs/ANALYSIS.md), ``serve``/``submit`` (the
long-lived check server and its client — qsm_tpu/serve,
docs/SERVING.md).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Optional

from ..core.property import PropertyConfig, prop_concurrent, replay
from ..models.registry import MODELS, SutFactory, make
from ..ops.wing_gong_cpu import WingGongCPU
from ..sched.runner import run_concurrent
from ..sched.scheduler import FaultPlan
from .report import (JsonlLogger, format_counterexample, format_history,
                     load_regression, save_regression)
from .stats import schedule_coverage


# one list for every subcommand: a backend added to only one
# parser would silently be unselectable from the other.  "auto" = the
# fastest exact host checker (native C++ when the toolchain builds it,
# else the memoised oracle) — the default for `run`, where a user just
# wants verdicts (kv-64 under the raw memo oracle costs ~17s per 60
# trials; the native path ~1s, identical verdicts)
_BACKENDS = ("auto", "auto-tpu", "cpu", "cpp", "tpu", "hybrid-tpu",
             "pallas-tpu", "pcomp", "pcomp-cpp", "pcomp-tpu", "segdc",
             "segdc-cpp", "segdc-tpu", "rootsplit", "rootsplit-tpu")

# index == Verdict value (ops/backend.py); ONE site for the rendering —
# the serving plane's wire protocol owns it (serve/protocol.py) so the
# server, the client, and every subcommand render identically
from ..serve.protocol import VERDICT_NAMES as _VERDICT_NAMES


def _ensure_device_reachable(timeout_s: Optional[float] = None) -> None:
    """Fail fast (never hang) before initializing a device backend.

    A wedged chip tunnel blocks the first in-process ``jax.devices()``
    forever (VERDICT.md round 1, "What's weak" #5), so probe from a bounded
    subprocess first.  A process pinned to the CPU platform is REFUSED, not
    waved through (ADVICE.md round 2): a device backend on a cpu-pinned
    process would run the lockstep kernel pathologically slowly on host
    while looking like a TPU result.  ``jax.config.jax_platforms`` is the
    pinning mechanism that actually wins on this image (the env var is
    ignored once the plugin has registered), but an exported
    ``JAX_PLATFORMS=cpu`` is refused too — where it IS effective the same
    silent-host-run hazard applies, and where it isn't the caller's intent
    was still a CPU run.
    """
    import os
    import sys as _sys

    _refuse = SystemExit(
        "this process is pinned to the CPU platform; a device backend here "
        "would run the lockstep kernel on host CPU.\n"
        "use --backend cpu/pcomp/segdc, or clear JAX_PLATFORMS")

    def _cpu_first(platforms: str) -> bool:
        # "cpu,tpu" selects cpu first too — a plain == "cpu" would wave the
        # comma form through into the same silent-host-run hazard
        return (platforms or "").strip().split(",")[0].strip() == "cpu"

    if "jax" in _sys.modules:
        import jax

        if _cpu_first(jax.config.jax_platforms or ""):
            raise _refuse
    if _cpu_first(os.environ.get("JAX_PLATFORMS", "")):
        raise _refuse
    from ..resilience.policy import preset
    from .device import probe_default_backend

    # bound from the shared probe preset (resilience/policy.py), env- or
    # caller-overridable — the CLI gate keeps no timeout literal of its own
    if timeout_s is None:
        timeout_s = preset("probe").timeout_s
    timeout_s = float(os.environ.get("QSM_TPU_PROBE_TIMEOUT", timeout_s))
    p = probe_default_backend(timeout_s=timeout_s)
    if not p.is_device:
        # a cpu-only answer is also a refusal: --backend tpu on a host
        # platform would run the lockstep kernel pathologically slowly
        # while looking like a TPU result
        raise SystemExit(
            f"no accelerator backend reachable ({p.detail})\n"
            "use --backend cpu/pcomp, or repair the chip tunnel")


def _make_backend(name: str, spec):
    from ..ops.pcomp import NotDecomposableError

    try:
        return _make_backend_inner(name, spec)
    except NotDecomposableError as e:
        # exactly this misconfiguration exits cleanly; unrelated
        # ValueErrors from backend construction still traceback
        raise SystemExit(str(e)) from e


def _make_backend_inner(name: str, spec):
    if name == "auto":
        from ..core.property import _default_oracle

        return _default_oracle(spec)
    if name == "cpu":
        return WingGongCPU(memo=True)
    if name == "cpp":
        from ..native import CppOracle, native_available, native_error

        if not native_available():
            raise SystemExit(f"native backend unavailable: {native_error()}\n"
                             "use --backend cpu")
        return CppOracle(spec)
    if name == "pcomp-cpp":
        from ..native import CppOracle, native_available, native_error
        from ..ops.pcomp import PComp

        if not native_available():
            raise SystemExit(f"native backend unavailable: {native_error()}\n"
                             "use --backend pcomp")
        return PComp(spec, lambda pspec: CppOracle(pspec))
    if name == "segdc-cpp":
        from ..native import CppOracle, native_available, native_error
        from ..ops.segdc import SegDC

        if not native_available():
            raise SystemExit(f"native backend unavailable: {native_error()}\n"
                             "use --backend segdc")
        cpp = CppOracle(spec)
        return SegDC(spec, make_inner=lambda s: cpp, oracle=cpp)
    if name == "tpu":
        _ensure_device_reachable()
        from ..ops.jax_kernel import JaxTPU

        return JaxTPU(spec)
    if name == "hybrid-tpu":
        # device majority under the tight base budget, stragglers to the
        # fastest host checker — the priced oracle-resolution plan
        # (ops/hybrid.py; measured in the bench_scale budget2k/hybrid rows)
        _ensure_device_reachable()
        from ..ops.hybrid import HybridDevice

        return HybridDevice(spec)
    if name == "auto-tpu":
        # per-history routing across the device strategies: pcomp for
        # partitionable specs, segdc for shattered histories, the plain
        # kernel otherwise (ops/router.py)
        _ensure_device_reachable()
        from ..ops.router import AutoDevice

        return AutoDevice(spec)
    if name == "pallas-tpu":
        # Mosaic-kernel prototype of the scalar-table search: the whole
        # iteration chunk runs inside one kernel launch instead of an XLA
        # while-loop (ops/pallas_kernel.py; scalar-table specs ≤32 ops)
        _ensure_device_reachable()
        from ..ops.pallas_kernel import PallasTPU

        return PallasTPU(spec)
    if name == "pcomp":
        from ..ops.pcomp import PComp

        return PComp(spec)
    if name == "pcomp-tpu":
        _ensure_device_reachable()
        from ..ops.jax_kernel import JaxTPU
        from ..ops.pcomp import PComp

        return PComp(spec, lambda pspec: JaxTPU(pspec))
    if name == "segdc":
        from ..ops.segdc import SegDC

        return SegDC(spec)
    if name == "segdc-tpu":
        _ensure_device_reachable()
        from ..ops.jax_kernel import JaxTPU
        from ..ops.segdc import SegDC

        # middles ride SegDC's default enumerator (native when the
        # toolchain is there); finals batch on device (JaxTPU init_states)
        return SegDC(spec, lambda s: JaxTPU(s))
    if name == "rootsplit":
        from ..ops.rootsplit import RootSplit

        return RootSplit(spec)
    if name == "rootsplit-tpu":
        _ensure_device_reachable()
        from ..ops.jax_kernel import JaxTPU
        from ..ops.rootsplit import RootSplit

        return RootSplit(spec, JaxTPU(spec))
    raise SystemExit(f"unknown backend {name!r}")


def _add_fault_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--p-drop", type=float, default=0.0)
    p.add_argument("--p-duplicate", type=float, default=0.0)
    p.add_argument("--p-delay", type=float, default=0.0)
    p.add_argument("--delay-steps", type=int, default=3,
                   help="delivery choices a delayed message is held for")
    p.add_argument("--crash-at", action="append", default=[],
                   metavar="NAME:N",
                   help="crash process NAME after N delivery choices "
                        "(repeatable; e.g. --crash-at primary:6)")
    p.add_argument("--partition", action="append", default=[],
                   metavar="A,B,...",
                   help="network partition: messages crossing the named "
                        "group's boundary are dropped (repeatable, one "
                        "group per flag; deterministic, so it composes "
                        "with explore)")


def _faults_from_args(args):
    crash_at = {}
    for spec in args.crash_at:
        name, _, n = spec.rpartition(":")
        if not name or not n.isdigit():
            raise SystemExit(f"--crash-at wants NAME:N, got {spec!r}")
        crash_at[name] = int(n)
    partitions = []
    for group in getattr(args, "partition", []):
        names = {x.strip() for x in group.split(",") if x.strip()}
        if not names:
            raise SystemExit(f"--partition wants A,B,..., got {group!r}")
        partitions.append(names)
    if not (args.p_drop or args.p_duplicate or args.p_delay or crash_at
            or partitions):
        return None
    return FaultPlan(p_drop=args.p_drop, p_duplicate=args.p_duplicate,
                     p_delay=args.p_delay, delay_steps=args.delay_steps,
                     crash_at=crash_at, partitions=partitions)


def _add_run_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--model", required=True, choices=sorted(MODELS))
    p.add_argument("--impl", default="racy")
    p.add_argument("--pids", type=int, default=None)
    p.add_argument("--ops", type=int, default=None)
    p.add_argument("--trials", type=int, default=100)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--schedules", type=int, default=4,
                   help="seeded schedules per generated program")
    p.add_argument("--backend", default="auto",
                   choices=_BACKENDS)  # bench keeps default "cpu": its
    # default denominator semantics must not drift silently
    p.add_argument("--failover", action="store_true",
                   help="wrap the backend in a FailoverBackend "
                        "(resilience/failover.py): on dispatch timeout / "
                        "device loss mid-run, undecided lanes degrade to "
                        "the exact host ladder (cpp -> memo) and the run "
                        "completes with identical verdicts; degradations "
                        "are reported in the timings log")
    p.add_argument("--minimize", action="store_true",
                   help="after the program-level shrink, minimize the "
                        "failing HISTORY itself through the batched "
                        "shrink plane (qsm_tpu/shrink): a 1-minimal "
                        "sub-history/reschedule printed alongside the "
                        "replayable counterexample (docs/SHRINK.md)")
    p.add_argument("--transport", default="memory",
                   choices=["memory", "tcp"],
                   help="scheduler-plane message transport (tcp = real "
                        "loopback sockets; histories are bit-identical)")
    p.add_argument("--workers", type=int, default=0,
                   help="worker processes for schedule execution "
                        "(0 = serial; histories are bit-identical)")
    p.add_argument("--trial-batch", type=int, default=1,
                   help="trials decided per backend batch; verdicts are "
                        "identical. DEVICE-ONLY knob: amortizes per-call "
                        "dispatch on a real accelerator — on host "
                        "backends and the CPU fallback the bigger padded "
                        "batch measures SLOWER (BENCH_E2E_r03..r05), so "
                        "leave the default 1 there")
    _add_fault_args(p)
    p.add_argument("--log", default=None, help="JSONL log path")
    p.add_argument("--save-regression", default=None,
                   help="write failing counterexample to this JSON file")


def cmd_run(args) -> int:
    entry = MODELS[args.model]
    spec, sut = make(args.model, args.impl)
    faults = _faults_from_args(args)
    cfg = PropertyConfig(
        n_trials=args.trials,
        n_pids=args.pids or entry.default_pids,
        max_ops=args.ops or entry.default_ops,
        seed=args.seed, faults=faults,
        schedules_per_program=args.schedules,
        transport=args.transport,
        executor_workers=args.workers,
        trial_batch=args.trial_batch,
        minimize_history=getattr(args, "minimize", False))
    log = JsonlLogger(path=args.log) if args.log else JsonlLogger()
    try:
        t0 = time.perf_counter()
        backend = _make_backend(args.backend, spec)
        if getattr(args, "failover", False):
            from ..resilience.failover import FailoverBackend

            backend = FailoverBackend(spec, backend)
        # pass a host-oracle backend through as the oracle too, so
        # _resolve's backend-is-oracle short-circuit fires (re-running an
        # identical search can only repeat the verdict).  "auto" IS the
        # default resolution oracle, and "cpp"/"cpu" would be rebuilt as
        # an equivalent checker inside prop_concurrent otherwise.
        # (--failover wraps those too; the wrap changes nothing for a
        # host backend, so the short-circuit is simply forfeited.)
        oracle = (backend if args.backend in ("cpu", "cpp", "auto")
                  and not getattr(args, "failover", False) else None)
        res = prop_concurrent(
            spec, sut, cfg, backend=backend, oracle=oracle,
            sut_factory=(SutFactory(args.model, args.impl)
                         if args.workers > 0 else None))
        dt = time.perf_counter() - t0
        log.emit("result", model=args.model, impl=args.impl, ok=res.ok,
                 trials=res.trials_run, histories=res.histories_checked,
                 undecided=res.undecided, seconds=round(dt, 3),
                 schedules=res.schedules_run,
                 schedule_diversity=round(res.schedule_diversity, 3),
                 timings={key: round(v, 3)
                          for key, v in sorted(res.timings.items())})
    finally:
        log.close()
    if res.ok:
        print(f"OK: {args.model}/{args.impl} passed {res.trials_run} trials "
              f"({res.histories_checked} histories, {dt:.1f}s)")
        if res.undecided:
            print(f"WARNING: {res.undecided} histories undecided "
                  "(budget exceeded on both backends)")
            return 2
        return 0
    cx = res.counterexample
    print(f"FAIL: {args.model}/{args.impl} — linearizability violation")
    print(format_counterexample(spec, cx))
    if cx.minimized_history is not None:
        # the batched shrink plane's 1-minimal artifact — smaller to
        # read; the (program, schedule) counterexample above is what
        # replays
        print(f"history-minimized to {len(cx.minimized_history)} op(s) "
              "(qsm_tpu/shrink; still a VIOLATION, not replayable):")
        print(format_history(spec, cx.minimized_history))
    fault_flags = ""
    if faults is not None:
        fault_flags = (f" --p-drop {args.p_drop}"
                       f" --p-duplicate {args.p_duplicate}"
                       f" --p-delay {args.p_delay}"
                       f" --delay-steps {args.delay_steps}")
        # every fault knob must round-trip through the hint, or the
        # pasted command replays a DIFFERENT fault plan and diverges
        fault_flags += "".join(f" --crash-at {c}" for c in args.crash_at)
        fault_flags += "".join(f" --partition {g}"
                               for g in getattr(args, "partition", []))
    print(f"replay: python -m qsm_tpu replay --model {args.model} "
          f"--impl {args.impl} --trial-seed '{cx.trial_seed}' "
          f"--pids {cfg.n_pids} --ops {cfg.max_ops} "
          f"--trials {cfg.n_trials}{fault_flags}")
    if args.save_regression:
        save_regression(args.save_regression, args.model, args.impl, spec,
                        cfg, cx)
        print(f"regression saved to {args.save_regression}")
    return 1


def cmd_replay(args) -> int:
    if args.regression:
        model, impl, seed_key, prog, hist, faults, spec_kwargs = \
            load_regression(args.regression)
        spec, sut = make(model, impl, spec_kwargs)
        print(f"replaying {model}/{impl} trial seed {seed_key!r}")
        # an exploration finding replays by its delivery-choice SCRIPT,
        # not by seeded randomness (schedule_key stamps it into the seed)
        from ..sched.systematic import parse_schedule_key

        info: dict = {}
        h = run_concurrent(sut, prog, seed=seed_key, faults=faults,
                           choices=parse_schedule_key(seed_key),
                           sched_info=info)
        same = h.fingerprint() == hist.fingerprint()
        print(f"history reproduced bit-identically: {same}")
        if info.get("choice_clamped"):
            print("WARNING: schedule script no longer matches the "
                  "interleaving tree (a scripted choice exceeded the live "
                  "branching factor and was clamped) — the model or fault "
                  "plan has drifted since this regression was captured")
    else:
        if not (args.model and args.trial_seed):
            raise SystemExit(
                "replay needs either --regression FILE or both "
                "--model and --trial-seed")
        spec, sut = make(args.model, args.impl)
        entry = MODELS[args.model]
        faults = _faults_from_args(args)
        cfg = PropertyConfig(n_trials=args.trials,
                             n_pids=args.pids or entry.default_pids,
                             max_ops=args.ops or entry.default_ops,
                             faults=faults)
        h = replay(spec, sut, args.trial_seed, cfg)
    if args.witness:
        # ONE search: the witness run's own verdict is the verdict (a
        # second check_histories would double the dominant cost)
        verdict, w = WingGongCPU().check_witness(spec, h)
        v = int(verdict)
    else:
        v = WingGongCPU().check_histories(spec, [h])[0]
    print(format_history(spec, h))
    print(f"verdict: {_VERDICT_NAMES[v]}")
    if args.witness and w is not None:
        # the verdict's own proof: the linearization order, replayed
        # search-free by verify_witness (ops/backend.py)
        from ..ops.backend import verify_witness

        steps = " -> ".join(
            f"{spec.CMDS[h.ops[j].cmd].name}[op{j}]" for j, _ in w)
        print(f"witness: {steps}")
        print(f"witness verifies (search-free replay): "
              f"{verify_witness(spec, h, w)}")
    return 0 if v == 1 else 1


def cmd_bench(args) -> int:
    entry = MODELS[args.model]
    spec = entry.make_spec()
    n_pids = args.pids or entry.default_pids
    n_ops = args.ops or entry.default_ops
    from .corpus import build_corpus

    hists = build_corpus(
        spec, (entry.impls["atomic"], entry.impls["racy"]),
        n=args.corpus, n_pids=n_pids, max_ops=n_ops, seed_prefix="bench")
    backend = _make_backend(args.backend, spec)
    if getattr(args, "unroll", None) is not None:
        # the kernel may sit behind a combinator (hybrid/router/segdc)
        for kern in (backend, getattr(backend, "device", None),
                     getattr(backend, "plain", None),
                     getattr(backend, "inner", None)):
            if kern is not None and hasattr(kern, "UNROLL"):
                kern.UNROLL = args.unroll
    backend.check_histories(spec, hists)  # warmup
    t0 = time.perf_counter()
    v = backend.check_histories(spec, hists)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "model": args.model, "backend": args.backend,
        "histories": len(hists), "seconds": round(dt, 3),
        "histories_per_sec": round(len(hists) / dt, 1),
        "undecided": int((v == 2).sum())}))
    return 0


def cmd_serve(args) -> int:
    """Run the check server (qsm_tpu/serve): warm engines, cross-request
    micro-batching, verdict cache, bounded admission — docs/SERVING.md.
    Prints ONE JSON line with the bound address, then serves until a
    ``shutdown`` request (or SIGINT)."""
    from ..serve.server import CheckServer

    if args.engine == "planned":
        # the planner-built device engine initializes jax backends at
        # first request: gate exactly like --backend tpu
        _ensure_device_reachable()
    peers = [a.strip() for a in (args.peers or "").split(",")
             if a.strip()]
    if peers and not args.replog_dir:
        raise SystemExit("--peers needs --replog-dir (gossip "
                         "replicates replog segments; a bankless node "
                         "has none to exchange)")
    server = CheckServer(
        host=args.host, port=args.port, unix_path=args.unix,
        engine=args.engine, max_lanes=args.max_lanes,
        mesh_devices=args.mesh_devices,
        flush_s=args.flush_ms / 1000.0, queue_depth=args.queue_depth,
        cache_path=args.cache, cache_entries=args.cache_entries,
        workers=args.workers, quarantine_after=args.quarantine_after,
        pcomp=not args.no_pcomp,
        trace_log=args.trace_log, flight_dir=args.flight_dir,
        metrics_port=args.metrics_port,
        node_id=args.node_id, replog_dir=args.replog_dir,
        replog_seal_rows=args.replog_seal_rows,
        peers=peers or None, gossip_s=args.gossip_s,
        gossip_fanout=args.gossip_fanout,
        max_sessions=args.max_sessions,
        session_dir=args.session_dir,
        lease_path=args.lease_host,
        slo=args.slo, slo_window_s=args.slo_window,
        devq_dir=args.devq_dir, devq_cap=args.devq_cap)
    warm = [m.strip() for m in args.warm.split(",")] if args.warm else []
    warm = [m for m in warm if m]
    unknown = sorted(set(warm) - set(MODELS))
    if unknown:
        # a typo'd --warm must fail loudly, not silently serve cold —
        # warmup is the amortization the flag exists for
        raise SystemExit(f"--warm: unknown models {unknown}; "
                         f"one of {sorted(MODELS)}")
    server.start()
    try:
        for model in warm:
            server.warm(model)
        print(json.dumps({"serving": server.address,
                          "engine": args.engine,
                          "node": args.node_id,
                          "replog": args.replog_dir,
                          "devq": args.devq_dir,
                          "peers": peers or None,
                          "workers": args.workers,
                          "max_lanes": args.max_lanes,
                          "mesh_devices": args.mesh_devices,
                          "flush_ms": args.flush_ms,
                          "queue_depth": args.queue_depth,
                          "cache": args.cache,
                          "trace_log": args.trace_log,
                          "flight_dir": args.flight_dir,
                          "metrics": (f"{args.host}:{server.metrics_port}"
                                      if server.metrics_port is not None
                                      else None)}), flush=True)
        server.wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


def cmd_fleet(args) -> int:
    """Run a fleet (qsm_tpu/fleet, docs/SERVING.md "Fleet"): N
    CheckServer node processes behind a :class:`FleetRouter` that
    speaks the unchanged client protocol — consistent-hash routing by
    the verdict-cache identity, node-loss re-dispatch, segmented
    replicated verdict logs with anti-entropy catch-up.  ``--addrs``
    fronts nodes you started yourself; otherwise ``--nodes N`` local
    node processes are spawned (and torn down with the router).
    Prints ONE JSON line with the router address + node map, then
    serves until a ``shutdown`` request (or SIGINT)."""
    import os
    import subprocess
    import tempfile

    from ..fleet.router import FleetRouter

    nodes: list = []
    procs: list = []
    if args.addrs:
        for i, addr in enumerate(a.strip() for a in args.addrs.split(",")
                                 if a.strip()):
            nodes.append((f"n{i}", addr))
    else:
        replog_root = args.replog_root or tempfile.mkdtemp(
            prefix="qsm_fleet_replog_")
        env = dict(os.environ)
        # nodes run the host ladder (engine auto); like pool workers,
        # none of them may race the operator's device plane
        env["JAX_PLATFORMS"] = "cpu"
        for i in range(args.nodes):
            cmd = [sys.executable, "-m", "qsm_tpu", "serve",
                   "--port", "0", "--node-id", f"n{i}",
                   "--replog-dir", os.path.join(replog_root, f"n{i}")]
            if args.session_root:
                cmd += ["--session-dir",
                        os.path.join(args.session_root, f"n{i}"),
                        "--max-sessions", str(args.max_sessions)]
            if args.devq_root:
                cmd += ["--devq-dir",
                        os.path.join(args.devq_root, f"n{i}")]
            if args.workers:
                cmd += ["--workers", str(args.workers)]
            if args.warm:
                cmd += ["--warm", args.warm]
            if args.collect_dir:
                # fleet-wide collection needs node span logs to scrape:
                # each spawned node traces beside its replog (fronted
                # --addrs nodes bring their own --trace-log)
                cmd += ["--trace-log",
                        os.path.join(replog_root, f"n{i}_trace.jsonl")]
            procs.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                          text=True, env=env))
        for i, proc in enumerate(procs):
            line = proc.stdout.readline()  # the serve banner line
            try:
                nodes.append((f"n{i}", json.loads(line)["serving"]))
            except (ValueError, KeyError):
                for p in procs:
                    p.terminate()
                    try:
                        p.wait(timeout=5.0)
                    except subprocess.TimeoutExpired:
                        p.kill()
                raise SystemExit(
                    f"node {i} failed to start (no serve banner)")
    if args.gossip_s and args.gossip_s > 0:
        # wire node-to-node gossip: each node gets every OTHER node as
        # a peer (the gossip.peers op — addresses are only known now).
        # Replication then no longer depends on any router living.
        from ..serve.protocol import LineChannel, connect, send_doc

        for nid, addr in nodes:
            peers = [[pid, paddr] for pid, paddr in nodes
                     if pid != nid]
            try:
                sock = connect(addr, timeout_s=5.0)
                try:
                    send_doc(sock, {"op": "gossip.peers",
                                    "peers": peers,
                                    "interval_s": args.gossip_s})
                    LineChannel(sock).read_line(timeout_s=5.0)
                finally:
                    sock.close()
            except (OSError, ValueError):
                # a node without a replog (--addrs fronting a plain
                # server) just doesn't gossip; the router sweep still
                # covers it
                pass
    router = FleetRouter(
        nodes, host=args.host, port=args.port, unix_path=args.unix,
        queue_depth=args.queue_depth,
        quarantine_after=args.quarantine_after,
        heartbeat_s=args.heartbeat_s,
        anti_entropy_s=args.anti_entropy_s,
        node_id=args.router_id,
        session_dir=args.session_journal,
        lease_path=args.lease_path,
        lease_ttl_s=args.lease_ttl_s,
        trace_log=args.trace_log, flight_dir=args.flight_dir,
        metrics_port=args.metrics_port,
        collect_dir=args.collect_dir, collect_s=args.collect_s,
        slo=args.slo, slo_window_s=args.slo_window)
    router.start()
    try:
        print(json.dumps({"fleet": router.address,
                          "router_id": args.router_id,
                          "role": router.ha_role,
                          "term": router.term,
                          "lease": args.lease_path,
                          "nodes": dict(nodes),
                          "spawned": len(procs),
                          "anti_entropy_s": args.anti_entropy_s,
                          "gossip_s": args.gossip_s,
                          "trace_log": args.trace_log,
                          "collect_dir": args.collect_dir,
                          "slo": args.slo,
                          "flight_dir": args.flight_dir}), flush=True)
        router.wait()
    except KeyboardInterrupt:
        pass
    finally:
        router.stop()
        for proc in procs:
            # deterministic node teardown: terminate → bounded wait →
            # kill escalation (the pool's reap discipline, fleet level)
            try:
                proc.terminate()
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=5.0)
            except OSError:
                pass
    return 0


def _render_stats_fleet(doc: dict) -> str:
    """The ``stats --serve ROUTER --fleet`` view: the router's own
    counters (the active/standby lease line first — which brain is
    live and under which term) plus one row per node — fleet health
    at a glance."""
    lines = [
        f"fleet router {doc.get('address', '?')}  uptime "
        f"{doc.get('uptime_s', 0)}s  requests {doc.get('requests', 0)} "
        f"histories {doc.get('histories', 0)}",
    ]
    lease = doc.get("lease") or {}
    if lease.get("enabled"):
        role = lease.get("role", "?")
        bits = [f"lease: {lease.get('holder', '?')} [{role.upper()}] "
                f"term {lease.get('term', 0)}  takeovers "
                f"{lease.get('takeovers', 0)}  ha_sheds "
                f"{lease.get('ha_sheds', 0)}"]
        if role == "active":
            bits.append(f"  expires_in {lease.get('expires_in_s', '?')}s")
        elif lease.get("active_holder"):
            bits.append(f"  active: {lease['active_holder']} term "
                        f"{lease.get('active_term', '?')}")
        lines.append("".join(bits))
    else:
        lines.append("lease: off (single router — no HA standby)")
    lines.append(
        f"node_faults {doc.get('node_faults', 0)}  redispatches "
        f"{doc.get('redispatches', 0)}  ladder_lanes "
        f"{doc.get('ladder_lanes', 0)}  node_sheds "
        f"{doc.get('node_sheds', 0)}")
    ae = doc.get("anti_entropy") or {}
    lines.append(f"anti-entropy sweeps {ae.get('sweeps', 0)}  segments "
                 f"{ae.get('segments_shipped', 0)}  subsumed "
                 f"{ae.get('segments_subsumed', 0)}  rows "
                 f"{ae.get('rows_shipped', 0)}")
    fleet_nodes = doc.get("fleet_nodes") or {}
    for n in (doc.get("membership") or {}).get("nodes", []):
        nid = n.get("node")
        state = ("QUARANTINED" if n.get("quarantined")
                 else "up" if n.get("healthy") else "DOWN")
        ns = fleet_nodes.get(nid) or {}
        if "error" in ns:
            detail = f"unreachable ({ns['error']})"
        else:
            cache = ns.get("cache") or {}
            replog = cache.get("replog") or {}
            detail = (f"requests {ns.get('requests', 0)}  histories "
                      f"{ns.get('histories', 0)}  bank "
                      f"{cache.get('entries', 0)} rows  segments "
                      f"{replog.get('sealed_segments', '-')}")
        lines.append(f"  {nid} [{state}] {n.get('address', '?')}  "
                     f"{detail}")
    return "\n".join(lines)


def cmd_submit(args) -> int:
    """Submit an external trace file (the ``check`` CLI's JSON format —
    a ``history`` or ``histories`` rows array) to a running check
    server.  Exit codes mirror ``check``'s batch form: 0 all
    linearizable, 1 some violation, 2 undecided, 3 shed/error."""
    from ..serve.client import CheckClient

    with open(args.trace) as f:
        doc = json.load(f)
    model = args.model or doc.get("model")
    if not model:
        raise SystemExit("trace has no 'model'; pass --model")
    rows = doc.get("histories")
    if rows is None:
        if "history" not in doc:
            raise SystemExit(
                "trace needs a 'history' (or 'histories') array of "
                "[pid, cmd, arg, resp, invoke_time, response_time] rows")
        rows = [doc["history"]]
    client = CheckClient(args.addr, timeout_s=args.timeout)
    try:
        res = client.check(model, rows,
                           spec_kwargs=doc.get("spec_kwargs") or None,
                           witness=args.witness,
                           deadline_s=args.deadline)
    finally:
        client.close()
    print(json.dumps(res))
    if not res.get("ok"):
        return 3
    if res.get("violations"):
        return 1
    return 2 if res.get("undecided") else 0


def cmd_ingest(args) -> int:
    """Turn a foreign event log (qsm_tpu/ingest, docs/MONITOR.md) into
    a first-class corpus: Jepsen/Knossos-style (``--format jepsen``)
    or porcupine-style (``--format porcupine``) EDN event lines decode
    into the repo's ONE history encoding, so the result feeds
    ``check``, ``submit``, ``shrink`` and bench unchanged.  Default:
    print (or ``--out``) the `check`-CLI JSON document.  ``--check``
    decides it in-process (exit 0 linearizable / 1 violation / 2
    undecided); ``--submit ADDR`` sends it to a running server (exit
    codes mirror ``submit``); ``--emit`` re-renders the canonical log
    text (the byte-stable round trip the golden tests pin).  Parse and
    domain errors exit 2, loudly — an adapter never guesses."""
    from ..ingest import EdnError, IngestError, emit_trace, parse_trace

    spec, _ = make(args.spec, "atomic",
                   json.loads(args.spec_kwargs)
                   if args.spec_kwargs else None)
    with open(args.trace) as f:
        text = f.read()
    try:
        rows = parse_trace(args.format, text, args.spec, spec)
    except (EdnError, IngestError) as e:
        print(f"ingest: {e}", file=sys.stderr)
        return 2
    from .report import history_from_rows

    h = history_from_rows(rows)
    if args.emit:
        out_text = emit_trace(args.format, h, args.spec, spec)
        if args.out:
            with open(args.out, "w") as f:
                f.write(out_text)
        else:
            sys.stdout.write(out_text)
        return 0
    doc = {"model": args.spec, "spec_kwargs": spec.spec_kwargs(),
           "format": args.format, "ops": len(h),
           "pending": h.n_pending,
           "history": [[o.pid, o.cmd, o.arg, o.resp, o.invoke_time,
                        o.response_time] for o in h.ops]}
    if args.submit:
        from ..serve.client import CheckClient

        client = CheckClient(args.submit, timeout_s=args.timeout)
        try:
            res = client.check(args.spec, [doc["history"]],
                               spec_kwargs=spec.spec_kwargs() or None,
                               witness=args.witness)
        finally:
            client.close()
        print(json.dumps(res))
        if not res.get("ok"):
            return 3
        return 1 if res.get("violations") else (
            2 if res.get("undecided") else 0)
    if args.check:
        from ..resilience.failover import host_fallback

        v = int(host_fallback(spec).check_histories(spec, [h])[0])
        print(json.dumps({**{k: doc[k] for k in
                             ("model", "format", "ops", "pending")},
                          "verdict": _VERDICT_NAMES[v]}))
        return 0 if v == 1 else (2 if v == 2 else 1)
    payload = json.dumps(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(payload + "\n")
        print(json.dumps({"out": args.out, "ops": len(h),
                          "pending": h.n_pending}))
    else:
        print(payload)
    return 0


def cmd_monitor(args) -> int:
    """Tail a growing foreign event log into a LIVE monitor session
    (docs/MONITOR.md): each appended line becomes a session event the
    moment it lands, the session's incremental frontier decides it,
    and a verdict flip prints the pushed payload — the 1-minimal
    shrink-plane repro with certificate — the moment it is decidable.
    With ``--addr`` the session is served by a running check server or
    fleet router (``session.*`` ops, seq-tracked and failover-safe);
    without it an in-process session decides locally (flip repro via
    ``shrink_history``).  Exit codes: 0 linearizable, 1 violation,
    2 undecided, 3 shed/error."""
    from ..ingest import EdnError, EventTailer, IngestError, tail_file

    spec, _ = make(args.spec, "atomic",
                   json.loads(args.spec_kwargs)
                   if args.spec_kwargs else None)
    tailer = EventTailer(args.format, args.spec, spec)
    flip_doc = None
    verdict = "LINEARIZABLE"
    try:
        if args.addr:
            from ..serve.client import CheckClient, SessionHandle

            client = CheckClient(args.addr, timeout_s=args.timeout)
            try:
                handle = SessionHandle(
                    client, args.spec,
                    spec_kwargs=spec.spec_kwargs() or None,
                    session=args.session, deadline_s=args.deadline)
                print(json.dumps({"session": handle.sid,
                                  "resumed": handle.last.get("resumed"),
                                  "trace": handle.trace}),
                      file=sys.stderr)
                for line in tail_file(args.trace, follow=args.follow,
                                      max_idle_s=args.max_idle):
                    events = tailer.events_for_line(line)
                    if not events:
                        continue
                    out = handle.append(events)
                    if not out.get("ok"):
                        print(json.dumps(out))
                        return 3
                    if out.get("flip"):
                        flip_doc = out["flip"]
                        print(json.dumps({"event": "session.flip",
                                          "session": handle.sid,
                                          **flip_doc}), flush=True)
                fin = handle.close(witness=args.witness)
                if not fin.get("ok"):
                    print(json.dumps(fin))
                    return 3
                verdict = fin.get("verdict", verdict)
                print(json.dumps(fin))
            finally:
                client.close()
        else:
            from ..monitor import MonitorSession
            from ..core.spec import projection_report

            proj = None
            if not projection_report(spec):
                p = spec.projected_spec()
                if p.name in MODELS:
                    proj = p
            s = MonitorSession("local", spec, proj_spec=proj)
            for line in tail_file(args.trace, follow=args.follow,
                                  max_idle_s=args.max_idle):
                events = tailer.events_for_line(line)
                if not events:
                    continue
                s.append(events)
                already = s.flip_pushed
                if s.decide() == 0 and not already:
                    s.flip_pushed = True
                    from ..shrink.shrinker import shrink_history

                    res = shrink_history(
                        spec, s.history(),
                        deadline_s=args.deadline,
                        certificate=True)
                    flip_doc = {
                        "verdict": "VIOLATION",
                        "initial_ops": res.initial_ops,
                        "final_ops": res.final_ops,
                        "one_minimal": res.one_minimal,
                        "complete": res.complete,
                        "repro": [[o.pid, o.cmd, o.arg, o.resp,
                                   o.invoke_time, o.response_time]
                                  for o in res.history.ops]}
                    if res.certificate is not None:
                        flip_doc["certificate"] = res.certificate
                    print(json.dumps({"event": "session.flip",
                                      "session": s.sid, **flip_doc}),
                          flush=True)
            v = s.close()
            verdict = _VERDICT_NAMES[v]
            print(json.dumps({"session": s.sid, "verdict": verdict,
                              **s.counters()}))
    except (EdnError, IngestError) as e:
        print(f"monitor: {e}", file=sys.stderr)
        return 2
    if args.save and flip_doc is not None:
        from ..resilience.checkpoint import atomic_write_json

        atomic_write_json(args.save, {
            "model": args.spec, "spec_kwargs": spec.spec_kwargs(),
            "history": flip_doc["repro"]})
        print(json.dumps({"saved": args.save}), file=sys.stderr)
    if verdict == "VIOLATION":
        return 1
    return 0 if verdict == "LINEARIZABLE" else 2


def _trace_fetch(args, client=None):
    """One fetch of the trace's events: the collected+own fleet view
    over the wire (``--addr``, the ``obs.trace`` op — each round-trip
    bounded by the ``fleet-probe`` policy preset; --follow reuses one
    ``client`` across polls instead of redialing) or the local span
    log (``--log``, causal closure included so a fleet log read
    locally renders the same tree)."""
    from ..obs import load_events, trace_closure

    if args.addr:
        from ..serve.client import CheckClient

        own = client is None
        if own:
            client = CheckClient(args.addr,
                                 timeout_s=_trace_poll_timeout())
        try:
            res = client.trace_events(args.trace_id)
        finally:
            if own:
                client.close()
        if not res.get("ok"):
            raise SystemExit(f"obs.trace refused: "
                             f"{res.get('error') or res}")
        return res.get("events") or []
    return trace_closure(load_events(args.log), args.trace_id)


def _trace_poll_timeout() -> float:
    from ..resilience.policy import preset

    return preset("fleet-probe").timeout_s or 5.0


def cmd_trace(args) -> int:
    """Reconstruct ONE request's causal tree from a span log
    (qsm_tpu/obs, docs/OBSERVABILITY.md): admission, every micro-batch
    (flush reason + worker id), pcomp sub-lanes, the recombine, shrink
    frontier rounds, and the cache bank — as an indented tree (default)
    or the raw event list (``--json``).  With ``--addr ROUTER`` the
    events come from the router's COLLECTED fleet log merged with its
    own spans (the ``obs.trace`` op), so the tree spans client →
    router → nodes → workers, route hops and HA takeovers included.
    ``--follow`` keeps polling and prints each NEW event of the trace
    as it lands (bounded poll; stops after ``--max-idle`` quiet
    seconds).  Exit 0 when events were found, 1 when the trace id has
    none in the log."""
    from ..obs import build_tree, render_tree

    if not args.addr and not args.log:
        raise SystemExit("trace needs --log PATH or --addr ADDR")
    # in follow mode, ONE client serves the initial fetch and every
    # poll (a fresh dial per fetch would cost a connection each)
    client = None
    if args.addr and args.follow:
        from ..serve.client import CheckClient

        client = CheckClient(args.addr, timeout_s=_trace_poll_timeout())
    events = _trace_fetch(args, client=client)
    source = args.addr or args.log
    if args.json and not args.follow:
        print(json.dumps(events))
    elif args.json:
        # follow mode streams JSONL: the initial events first, then
        # each new one as it lands — a consumer always sees the FULL
        # trace on one stream, never just the post-start tail
        for e in events:
            print(json.dumps(e), flush=True)
    elif events:
        print(f"trace {args.trace_id} ({len(events)} event(s), "
              f"source: {source})")
        print(render_tree(build_tree(events)))
    if args.follow:
        # live mode: the monitor-session debugging loop — poll, render
        # only what is NEW (dedup by span id), stop on a quiet window
        seen = {e.get("span") for e in events}
        idle_since = time.monotonic()
        try:
            while time.monotonic() - idle_since < args.max_idle:
                time.sleep(max(0.1, args.interval))
                fresh = [e for e in _trace_fetch(args, client=client)
                         if e.get("span") not in seen]
                if not fresh:
                    continue
                idle_since = time.monotonic()
                for e in fresh:
                    seen.add(e.get("span"))
                    events.append(e)
                    if args.json:
                        print(json.dumps(e), flush=True)
                    else:
                        at = " ".join(
                            f"{k}={v}" for k, v in
                            (e.get("attrs") or {}).items())
                        ms = (f" {e['ms']}ms"
                              if e.get("ms") is not None else "")
                        print(f"+ {e.get('name')}{ms}"
                              + (f" [{at}]" if at else ""), flush=True)
        except KeyboardInterrupt:
            pass
        finally:
            if client is not None:
                client.close()
    if not events:
        print(f"no events for trace {args.trace_id!r} in {source} "
              "(is the server running with --trace-log, and has the "
              "log rotated twice since?)", file=sys.stderr)
        return 1
    return 0


def cmd_health(args) -> int:
    """Ask a running server or fleet router for its SLO health (the
    ``health`` op, obs/slo.py).  Pinned exit codes: 0 ok, 1 degraded,
    2 breach, 3 unreachable/error — scriptable as a probe."""
    from ..obs import HEALTH_EXIT_CODES, HEALTH_EXIT_UNREACHABLE
    from ..serve.client import CheckClient

    try:
        client = CheckClient(args.addr, timeout_s=args.timeout)
    except (OSError, ConnectionError) as e:
        print(json.dumps({"ok": False, "error": f"{type(e).__name__}: "
                                                f"{e}"}))
        return HEALTH_EXIT_UNREACHABLE
    try:
        res = client.health()
    except (OSError, ConnectionError) as e:
        print(json.dumps({"ok": False, "error": f"{type(e).__name__}: "
                                                f"{e}"}))
        return HEALTH_EXIT_UNREACHABLE
    finally:
        client.close()
    print(json.dumps(res))
    if not res.get("ok"):
        return HEALTH_EXIT_UNREACHABLE
    return HEALTH_EXIT_CODES.get(str(res.get("status")),
                                 HEALTH_EXIT_UNREACHABLE)


def _render_stats_watch(doc: dict) -> str:
    """One refresh frame of ``qsm-tpu stats --watch``: the capacity
    numbers an operator watches, from the server's ``stats`` verb."""
    adm = doc.get("admission") or {}
    bat = doc.get("batcher") or {}
    cache = doc.get("cache") or {}
    pool = doc.get("pool")
    pc = doc.get("pcomp") or {}
    sh = doc.get("shrink") or {}
    obs = doc.get("obs") or {}
    lines = [
        f"qsm-tpu serve {doc.get('address', '?')}  "
        f"up {doc.get('uptime_s', 0):.0f}s  engine "
        f"{doc.get('engine_kind', '?')}  workers "
        f"{doc.get('workers', 0)}",
        f"  requests {doc.get('requests', 0)}  histories "
        f"{doc.get('histories', 0)}  serve_faults "
        f"{doc.get('serve_faults', 0)}  worker_faults "
        f"{doc.get('worker_faults', 0)}",
        f"  admission: in_flight {adm.get('in_flight', 0)}/"
        f"{adm.get('queue_depth', 0)} (peak "
        f"{adm.get('peak_in_flight', 0)})  shed queue="
        f"{adm.get('shed_queue', 0)} deadline="
        f"{adm.get('shed_deadline', 0)}",
        f"  batcher: batches {bat.get('batches', 0)}  lanes "
        f"{bat.get('lanes', 0)}  occupancy "
        f"{bat.get('mean_occupancy', 0.0)}",
        f"  cache: entries {cache.get('entries', 0)}  hit_rate "
        f"{cache.get('hit_rate', 0.0)}  bank_rows "
        f"{cache.get('bank_rows', 0)}",
        f"  pcomp: split {pc.get('split', 0)} -> "
        f"{pc.get('sub_lanes', 0)} sub-lanes ("
        f"{pc.get('sub_cache_hits', 0)} cached)  shrink: "
        f"{sh.get('requests', 0)} req / {sh.get('rounds', 0)} rounds",
    ]
    sess = doc.get("session")
    if sess:
        lines.append(
            f"  session: live {sess.get('sessions_live', 0)}  events "
            f"{sess.get('session_events', 0)}  advances "
            f"{sess.get('frontier_advances', 0)}  prefix_hits "
            f"{sess.get('prefix_hits', 0)}  flips "
            f"{sess.get('flips_pushed', 0)}")
    if pool:
        live = [w for w in pool.get("workers", []) if w.get("alive")]
        lines.append(
            f"  pool: {pool.get('live', 0)}/{pool.get('n_workers', 0)} "
            f"live  dispatches {pool.get('dispatches', 0)}  respawns "
            f"{pool.get('respawns', 0)}  quarantines "
            f"{pool.get('quarantines', 0)}  "
            + " ".join(f"w{w['wid']}:{w['dispatches']}" for w in live))
    tracing = obs.get("tracing") or {}
    flight = obs.get("flight") or {}
    lines.append(
        f"  obs: span_events {tracing.get('events', 0)}  flight_dumps "
        f"{flight.get('dumps', 0) if flight else 0}"
        + (f"  last_dump {flight.get('last_dump')}"
           if flight and flight.get("last_dump") else ""))
    return "\n".join(lines)


def cmd_stats(args) -> int:
    """Search-cost accounting for one backend on one corpus: the
    iterations-per-history / nodes-per-history decomposition of the
    ``vs_best_host`` gap as ONE JSON document (search/stats.py).  Also
    prints the corpus profile and the plan ``plan_search`` would pick for
    it — ``--planned`` actually runs the planned backend (device
    engines only; the planner's levers are the kernel driver's).
    ``--serve ADDR`` instead prints a RUNNING check server's aggregate
    stats (requests, batch occupancy, cache hit rate, shed counts, the
    per-engine SearchStats/resilience blocks every response rides, and
    — when the server runs ``--workers N`` — the pool block with
    per-worker rows: dispatches, faults, deaths, respawns, plus the
    quarantined-spec list)."""
    if getattr(args, "serve", None):
        from ..serve.client import CheckClient

        if getattr(args, "watch", False):
            # refreshing terminal view: one frame per interval until
            # interrupted (or --watch-count frames, for scripts/tests)
            n = 0
            try:
                while True:
                    client = CheckClient(args.serve)
                    try:
                        doc = client.stats().get("stats", {})
                    finally:
                        client.close()
                    frame = _render_stats_watch(doc)
                    # ANSI clear+home; a dumb pipe just gets frames
                    # separated by the escape (still line-parseable)
                    sys.stdout.write("\x1b[2J\x1b[H" if n else "")
                    print(frame, flush=True)
                    n += 1
                    if args.watch_count and n >= args.watch_count:
                        return 0
                    time.sleep(max(0.2, args.interval))
            except KeyboardInterrupt:
                return 0
        client = CheckClient(args.serve)
        try:
            doc = client.stats().get("stats", {})
        finally:
            client.close()
        if getattr(args, "fleet", False):
            # the fleet view: a router's per-node health/traffic table
            # (a plain server answers too — it just has no node rows)
            print(_render_stats_fleet(doc))
        else:
            print(json.dumps(doc))
        return 0
    if getattr(args, "fleet", False):
        raise SystemExit("--fleet needs --serve ADDR (a running fleet "
                         "router's stats verb is what it renders)")
    if getattr(args, "watch", False):
        raise SystemExit("--watch needs --serve ADDR (a running "
                         "server's stats verb is what refreshes)")
    import numpy as np

    from ..resilience.failover import FailoverBackend, collect_resilience
    from ..search import (collect_search_stats, plan_search, profile_corpus)
    from .corpus import build_corpus

    entry = MODELS[args.model]
    spec = entry.make_spec()
    hists = build_corpus(
        spec, (entry.impls["atomic"], entry.impls["racy"]),
        n=args.corpus, n_pids=args.pids or entry.default_pids,
        max_ops=args.ops or entry.default_ops, seed_prefix="stats")
    # spec included: the profile then measures the per-key decomposition
    # shape too, and the printed plan says whether (and why) it splits
    profile = profile_corpus(hists, spec)
    plan = plan_search(spec, profile,
                       platform=None if args.planned else "cpu")
    if args.planned:
        # the planned checker (search/planner.py build_backend); same
        # device-reachability contract as --backend tpu
        _ensure_device_reachable()
        from ..search.planner import build_backend

        backend = build_backend(spec, plan)
        bname = f"planned({plan.name})"
    else:
        backend = _make_backend(args.backend, spec)
        bname = args.backend
    if args.failover:
        backend = FailoverBackend(spec, backend)
        bname = backend.name
    t0 = time.perf_counter()
    v = backend.check_histories(spec, hists)
    dt = time.perf_counter() - t0
    st = collect_search_stats(backend)
    out = {
        "model": args.model, "backend": bname,
        "histories": len(hists), "seconds": round(dt, 3),
        "undecided": int((np.asarray(v) == 2).sum()),
        # fault-handling block (qsm_tpu/resilience): zeros on a clean
        # run — the stats artifact is self-describing about degradation
        "resilience": collect_resilience(backend),
        "profile": {
            "max_ops": profile.max_ops,
            "mean_ops": round(profile.mean_ops, 1),
            "pending_fraction": round(profile.pending_fraction, 3),
            "mean_segments": round(profile.mean_segments, 2),
        },
        "plan_for_corpus": plan.describe(),
        "search_stats": st.to_dict() if st is not None else None,
    }
    print(json.dumps(out))
    return 0


def cmd_coverage(args) -> int:
    entry = MODELS[args.model]
    spec, _ = make(args.model, args.impl)
    from ..core.generator import generate_program

    prog = generate_program(spec, seed=args.seed,
                            n_pids=args.pids or entry.default_pids,
                            max_ops=args.ops or entry.default_ops)
    stats = schedule_coverage(
        lambda: make(args.model, args.impl)[1], prog,
        seeds=[f"{args.seed}:{i}" for i in range(args.runs)])
    out = {
        "model": args.model, "ops": len(prog), "runs": stats.seeds,
        "distinct_schedules": stats.distinct_schedules,
        "distinct_histories": stats.distinct_histories,
        "schedule_diversity": round(stats.schedule_diversity, 3)}
    if args.exact:
        # ground truth from bounded-exhaustive enumeration: how much of
        # the real interleaving space did sampling actually touch?
        from ..sched.systematic import explore_program

        res = explore_program(lambda: make(args.model, args.impl)[1],
                              prog, spec, max_schedules=args.max_schedules,
                              check=False)  # counts only: skip verdicts
        out["exact"] = {"schedules": res.schedules_run,
                        "pruned_schedules": res.pruned_schedules,
                        "distinct_histories": res.distinct_histories,
                        "exhausted": res.exhausted}
        if res.exhausted and res.distinct_histories:
            out["sampled_history_coverage"] = round(
                stats.distinct_histories / res.distinct_histories, 3)
    print(json.dumps(out))
    return 0


def cmd_check(args) -> int:
    """Decide an EXTERNAL trace: the checker as a standalone tool.

    The trace file is JSON with a ``history`` array of
    ``[pid, cmd, arg, resp, invoke_time, response_time]`` rows (the
    regression-file encoding, so saved regressions check directly) and
    optionally ``model``/``spec_kwargs``.  Any outside system that can
    dump its operations in this shape gets the full backend stack —
    linearizability checking of unmodified concurrent systems, the
    trace-validation use the OmniLink paper frames (PAPERS.md), without
    running the scheduler plane at all.
    """
    from ..ops.backend import Verdict, verify_witness
    from .report import history_from_rows

    with open(args.trace) as f:
        doc = json.load(f)
    model = args.model or doc.get("model")
    if not model:
        raise SystemExit("trace has no 'model'; pass --model")
    if model not in MODELS:
        raise SystemExit(
            f"unknown model {model!r}; one of {sorted(MODELS)}")
    spec, _ = make(model, "atomic", doc.get("spec_kwargs") or None)
    if "histories" in doc:
        # batch form: decide MANY external traces in ONE backend call —
        # the vmap-shaped workload (BASELINE.json:9) offered to outside
        # systems; one JSON line with per-trace verdicts.  Exit codes:
        # 0 all linearizable, 1 some violation, 2 undecided only.
        if args.witness:
            raise SystemExit(
                "--witness applies to a single 'history' trace; the "
                "batch 'histories' form reports verdicts only")
        hs = [history_from_rows(rows) for rows in doc["histories"]]
        backend = _make_backend(args.backend, spec)
        vs = [int(x) for x in backend.check_histories(spec, hs)]
        und = [i for i, x in enumerate(vs)
               if x == int(Verdict.BUDGET_EXCEEDED)]
        if und and args.backend not in ("cpu", "cpp", "auto"):
            oracle = WingGongCPU(memo=True)
            res = oracle.check_histories(spec, [hs[i] for i in und])
            for i, x in zip(und, res):
                vs[i] = int(x)
        n_vio = sum(x == int(Verdict.VIOLATION) for x in vs)
        n_und = sum(x == int(Verdict.BUDGET_EXCEEDED) for x in vs)
        print(json.dumps({
            "model": model, "histories": len(hs),
            "verdicts": [_VERDICT_NAMES[x] for x in vs],
            "violations": n_vio, "undecided": n_und}))
        return 1 if n_vio else (2 if n_und else 0)
    if "history" not in doc:
        raise SystemExit(
            "trace needs a 'history' (or 'histories') array of "
            "[pid, cmd, arg, resp, invoke_time, response_time] rows")
    # row order is PRESERVED: witness op indices refer to the caller's
    # own rows (history_from_rows is the one shared decoder)
    h = history_from_rows(doc["history"])
    w = None
    if args.witness:
        # ONE search serves both verdict and witness (a second
        # check_histories would double the dominant cost — same rule as
        # replay --witness); the host oracle is the witness engine
        v, w = WingGongCPU(memo=True).check_witness(spec, h)
        v = int(v)
    else:
        backend = _make_backend(args.backend, spec)
        v = int(backend.check_histories(spec, [h])[0])
        if (v == int(Verdict.BUDGET_EXCEEDED)
                and args.backend not in ("cpu", "cpp", "auto")):
            # resolve a budget-bounded DEVICE deferral via the host
            # oracle, like the property layer; a host backend that
            # already exhausted the same oracle budget is left honest
            v = int(WingGongCPU(memo=True).check_histories(spec, [h])[0])
    # human rendering to stderr: stdout stays one machine-readable JSON
    # line for the scripted/external callers this command exists for
    print(format_history(spec, h), file=sys.stderr)
    out = {"model": model, "ops": len(h),
           "pending": h.n_pending,
           "verdict": _VERDICT_NAMES[v]}
    if w is not None:
        out["witness"] = w
        out["witness_verifies"] = verify_witness(spec, h, w)
    print(json.dumps(out))
    if v == int(Verdict.LINEARIZABLE):
        return 0
    return 2 if v == int(Verdict.BUDGET_EXCEEDED) else 1


def cmd_shrink(args) -> int:
    """Minimize a failing external trace (qsm_tpu/shrink,
    docs/SHRINK.md): the whole shrink frontier — drop-one/drop-pid/
    drop-key op subsets plus adjacent-commute schedule shrinks — decides
    in ONE planned batched dispatch per greedy round, and the result is
    a 1-minimal history whose certificate (one verify_witness-replayable
    linearization per drop-one neighbor) proves the minimality claim.
    With ``--addr`` the request is served by a running check server's
    ``shrink`` verb instead (frontier lanes ride its shared
    micro-batches and verdict cache).  Exit codes: 0 minimized, 1
    incomplete (best-so-far returned), 2 input not a violation,
    3 shed/error."""
    from ..ops.backend import Verdict
    from ..serve.protocol import history_to_rows
    from .report import history_from_rows

    with open(args.trace) as f:
        doc = json.load(f)
    model = args.model or doc.get("model")
    if not model:
        raise SystemExit("trace has no 'model'; pass --model")
    if model not in MODELS:
        raise SystemExit(
            f"unknown model {model!r}; one of {sorted(MODELS)}")
    if "history" not in doc:
        raise SystemExit(
            "shrink minimizes ONE failing trace: the file needs a "
            "'history' array of [pid, cmd, arg, resp, invoke_time, "
            "response_time] rows")
    h = history_from_rows(doc["history"])
    spec_kwargs = doc.get("spec_kwargs") or None
    if args.addr:
        # the server runs its own loop: the in-process-only knobs must
        # fail loudly, never be silently dropped
        if args.max_rounds is not None or args.max_lanes is not None:
            raise SystemExit("--max-rounds/--max-lanes tune the "
                             "in-process shrinker; the server uses its "
                             "own bounds (drop them, or drop --addr)")
        if args.emit_certificate:
            raise SystemExit("--emit-certificate is in-process only; "
                             "with --addr use --certificate (the "
                             "response carries the witnesses)")
        from ..serve.client import CheckClient

        client = CheckClient(args.addr, timeout_s=args.timeout)
        try:
            out = client.shrink(model, doc["history"],
                                spec_kwargs=spec_kwargs,
                                certificate=args.certificate,
                                deadline_s=args.deadline)
        finally:
            client.close()
        print(json.dumps(out))
        if not out.get("ok"):
            return 3
        if args.save and out.get("verdict") == "VIOLATION":
            from ..resilience.checkpoint import atomic_write_json

            atomic_write_json(args.save, {
                "model": model,
                "spec_kwargs": out.get("spec_kwargs")
                or doc.get("spec_kwargs") or {},
                "history": out["history"]})
            print(f"minimized trace saved to {args.save}",
                  file=sys.stderr)
        if out.get("verdict") != "VIOLATION":
            return 2
        return 0 if out.get("complete") else 1
    spec, _ = make(model, "atomic", spec_kwargs)
    from ..search.stats import collect_search_stats
    from ..shrink import (collect_shrink_stats, shrink_history,
                          verify_certificate)

    from ..shrink.shrinker import DEFAULT_MAX_LANES, DEFAULT_MAX_ROUNDS

    res = shrink_history(spec, h,
                         max_rounds=(args.max_rounds
                                     if args.max_rounds is not None
                                     else DEFAULT_MAX_ROUNDS),
                         max_lanes=(args.max_lanes
                                    if args.max_lanes is not None
                                    else DEFAULT_MAX_LANES),
                         deadline_s=args.deadline,
                         certificate=(args.certificate
                                      or args.emit_certificate))
    st = collect_shrink_stats(res)
    out = {
        "model": model,
        "verdict": _VERDICT_NAMES[int(res.verdict)],
        "initial_ops": res.initial_ops, "final_ops": res.final_ops,
        "ratio": round(res.ratio, 3), "rounds": res.rounds,
        "engine_calls": res.engine_calls, "lanes": res.lanes_checked,
        "memo_hits": res.memo_hits, "complete": res.complete,
        "one_minimal": res.one_minimal,
        "undecided_neighbors": res.undecided_neighbors,
        "history": history_to_rows(res.history),
        "why": res.why,
        "search": st.to_compact(),
    }
    if res.certificate is not None:
        # the certificate is replayed HERE (verify_witness, no search
        # trusted) so the one JSON line carries the audited claim, not
        # the raw witnesses alone
        out["certificate_audit"] = verify_certificate(
            spec, res.history, res.certificate)
        if args.emit_certificate:
            out["certificate"] = res.certificate
    # human rendering to stderr; stdout stays one machine-readable line
    print(format_history(spec, res.history), file=sys.stderr)
    print(json.dumps(out))
    if args.save and res.ok:
        from ..resilience.checkpoint import atomic_write_json

        atomic_write_json(args.save, {
            "model": model, "spec_kwargs": spec.spec_kwargs(),
            "history": history_to_rows(res.history)})
        print(f"minimized trace saved to {args.save}", file=sys.stderr)
    if not res.ok:
        return 2 if res.verdict != int(Verdict.VIOLATION) else 3
    return 0 if res.complete else 1


def cmd_lint(args) -> int:
    """Static spec/kernel/determinism analysis (qsm_tpu/analysis) —
    CPU-only by contract: the process is pinned to the CPU platform
    BEFORE anything imports jax, so the lint gate can never touch (or
    hang on) the chip tunnel it exists to protect.  Exit 1 on
    non-whitelisted error-severity findings, 0 otherwise; the seeded-bug
    fixtures that prove each rule still fires live in
    tests/test_lint.py."""
    # Exit-code contract (the watcher's seize gate depends on it):
    # 0 clean, 1 REAL FINDINGS (seize refused), 2 usage error, 3
    # analyzer trouble (waved through).  EVERYTHING — imports, platform
    # pinning, the run itself — sits inside the crash guard: a broken
    # analysis module exiting 1 would refuse every healed window of the
    # round as if it were a real finding.
    import os

    try:
        from .device import force_cpu_platform

        force_cpu_platform()
        from ..analysis import FAMILIES, render_text, run_lint

        wl = args.whitelist
        if wl is not None and not os.path.exists(wl):
            print(f"whitelist file not found: {wl}", file=sys.stderr)
            return 2
        models = args.models.split(",") if args.models else None
        if models:
            # validate HERE, not via a broad except around run_lint: a
            # ValueError from deep inside an analysis pass is analyzer
            # trouble (rc 3 with traceback), not a usage error
            unknown = sorted(set(models) - set(MODELS))
            if unknown:
                print(f"unknown model families {unknown}; one of "
                      f"{sorted(MODELS)}", file=sys.stderr)
                return 2
        families = args.family.split(",") if args.family else None
        if families:
            unknown = sorted(set(families) - set(FAMILIES))
            if unknown:
                print(f"unknown pass families {unknown}; one of "
                      f"{sorted(FAMILIES)}", file=sys.stderr)
                return 2
        # default-whitelist resolution (.qsmlint at the repo root when
        # present) happens INSIDE run_lint — one definition; the report
        # carries the resolved path back for the label
        rep = run_lint(models=models, retrace=not args.no_retrace,
                       whitelist=wl, families=families,
                       changed=args.changed,
                       cache=not args.no_cache)
        doc = rep.to_json()
        if args.out:
            # archived alongside bench artifacts (probe_watcher/CI) —
            # always the JSON form regardless of what stdout renders;
            # INSIDE the guard: an unwritable --out (disk full, bad
            # path) is analyzer trouble, not findings
            from ..resilience.checkpoint import atomic_write_text

            atomic_write_text(args.out, doc + "\n")
        if args.sarif:
            # the CI diff-annotation form (SARIF 2.1.0), always archived
            # to a file: stdout keeps its one-document contract
            from ..resilience.checkpoint import atomic_write_text

            atomic_write_text(args.sarif, rep.to_sarif() + "\n")
        if args.json:
            print(doc)
        else:
            print(render_text(rep.findings, rep.whitelisted))
            scope = ""
            if rep.changed is not None:
                n = (len(rep.changed["files"])
                     if rep.changed["files"] is not None else "all")
                scope = f"; changed since {rep.changed['ref']}: {n}"
            if rep.cache is not None:
                scope += (f"; cache {rep.cache['hits']} hit(s) / "
                          f"{rep.cache['misses']} miss(es)")
            print(f"({rep.seconds:.1f}s over models: "
                  f"{', '.join(rep.models)}; families: "
                  f"{','.join(rep.families)};"
                  f" whitelist: {rep.whitelist_path or 'none'}{scope})")
    except Exception as e:  # noqa: BLE001 — analyzer trouble, not findings
        import traceback

        traceback.print_exc()
        print(f"qsmlint crashed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 3
    return 0 if rep.ok else 1


def cmd_list(args) -> int:
    """Discoverability: every registry model (with sizes + impls) and
    every backend choice, as one JSON object.  Uses the compile-free
    native_status — a metadata command must never block on the
    first-use g++ build."""
    from ..native import native_status

    status = native_status()
    print(json.dumps({
        "models": {
            name: {"pids": e.default_pids, "ops": e.default_ops,
                   "impls": sorted(e.impls)}
            for name, e in sorted(MODELS.items())},
        "backends": list(_BACKENDS),
        "native": status,
        "native_available": status in ("loaded", "built"),
    }))
    return 0


def _result_line(r) -> dict:
    """The ONE ExploreResult → JSON mapping; every explore output path
    (single program, --programs sweep, --crash-sweep) merges its own
    context keys around this so the fields can't drift apart."""
    line = {"schedules_run": r.schedules_run,
            "pruned_schedules": r.pruned_schedules,
            "distinct_histories": r.distinct_histories,
            "exhausted": r.exhausted, "violations": r.violations,
            "undecided": r.undecided, "verified": r.verified}
    if r.violating is not None:
        # the replayable delivery-choice script that produced it
        line["violating_schedule"] = r.violating.seed
    return line


def cmd_explore(args) -> int:
    """Bounded-exhaustive schedule exploration of one generated program
    (sched/systematic.py): every interleaving, one batched verdict."""
    from ..core.generator import generate_program
    from ..sched.systematic import (explore_many, explore_program,
                                    shrink_explored)

    # usage errors before any backend construction: _make_backend can
    # probe a device (seconds) or exit on its own account, which must
    # never mask a cheap argument mistake
    if args.programs > 1 and (args.shrink or args.save_regression):
        raise SystemExit(
            "--programs is a sweep; combine --shrink/--save-regression "
            "with a single program (drop --programs)")
    if args.workers and args.programs <= 1:
        raise SystemExit(
            "--workers fans a --programs sweep over processes; a single "
            "program's tree enumerates serially (add --programs N)")
    cs = None  # parsed --crash-sweep: (name, lo, hi)
    if args.crash_sweep:
        if (args.programs > 1 or args.shrink or args.save_regression
                or args.crash_at):
            raise SystemExit(
                "--crash-sweep explores ONE program under a range of "
                "crash points; combine it only with --seed/--pids/--ops/"
                "--partition (drop --programs/--shrink/"
                "--save-regression/--crash-at)")
        name, _, rng = args.crash_sweep.partition(":")
        lo, _, hi = rng.partition("-")
        if not (name and lo.isdigit() and hi.isdigit()):
            raise SystemExit("--crash-sweep wants NAME:LO-HI "
                             "(e.g. primary:1-12)")
        if int(lo) > int(hi):
            # an empty range would print a VACUOUS all_verified summary
            raise SystemExit(f"--crash-sweep range is empty "
                             f"({lo}-{hi}); want LO <= HI")
        cs = (name, int(lo), int(hi))
    from ..sched.systematic import deterministic_faults

    faults = _faults_from_args(args)
    if not deterministic_faults(faults):
        raise SystemExit(
            "explore enumerates schedules exactly, which only composes "
            "with DETERMINISTIC fault plans (--crash-at/--partition); "
            "probabilistic faults (--p-drop/--p-duplicate/--p-delay) are "
            "seeded draws — use `run` sampling for those")
    spec, _ = make(args.model, args.impl)
    if cs is not None:
        # a typo'd process name would silently no-op every crash
        # (Scheduler.crash ignores unknown names) and "certify" the
        # fault-FREE system — validate against what a run really spawns
        from ..sched.runner import prepare_run

        probe_prog = generate_program(spec, seed=args.seed,
                                      n_pids=args.pids, max_ops=args.ops)
        probe_sched, _pr = prepare_run(make(args.model, args.impl)[1],
                                       probe_prog, seed=0)
        if cs[0] not in probe_sched.procs:
            raise SystemExit(
                f"--crash-sweep: no process named {cs[0]!r} is spawned "
                f"by {args.model}/{args.impl}; processes: "
                f"{sorted(probe_sched.procs)}")
    backend = (_make_backend(args.backend, spec)
               if args.backend else None)
    if args.programs > 1:
        # batched sweep: N trees enumerate host-side, ALL their histories
        # decide in one backend batch (the device-shaped workload)
        progs = [generate_program(spec, seed=args.seed + i,
                                  n_pids=args.pids, max_ops=args.ops)
                 for i in range(args.programs)]
        from ..models.registry import SutFactory

        results = explore_many(
            SutFactory(args.model, args.impl), progs, spec,
            backend=backend, max_schedules=args.max_schedules,
            prune=not args.no_prune, faults=faults,
            workers=args.workers)
        total_vio = sum(r.violations for r in results)
        for i, r in enumerate(results):
            print(json.dumps({"seed": args.seed + i, "ops": len(progs[i]),
                              **_result_line(r)}))
        print(json.dumps({
            "programs": len(results), "total_violations": total_vio,
            "total_undecided": sum(r.undecided for r in results),
            "all_verified": all(r.verified for r in results),
            "seconds": round(sum(r.seconds for r in results), 3)}))
        return 0 if total_vio == 0 else 1
    # explore defaults SMALL (2 pids x 6 ops): enumeration is exponential
    # in deliveries, so registry-default sizes are never implied here
    prog = generate_program(spec, seed=args.seed, n_pids=args.pids,
                            max_ops=args.ops)
    if cs is not None:
        # fault-tolerance certification: ONE command exhaustively explores
        # the program under EVERY crash point in the range — `verified` on
        # every line is a proof over the whole crash×schedule space
        name, lo, hi = cs
        total_vio = total_und = 0
        all_verified = True
        seconds = 0.0
        for k in range(lo, hi + 1):
            # extend any co-passed deterministic plan (--partition)
            # rather than silently discarding it
            plan = FaultPlan(crash_at={name: k},
                             partitions=faults.partitions if faults
                             else [])
            r = explore_program(
                lambda: make(args.model, args.impl)[1], prog, spec,
                backend=backend, max_schedules=args.max_schedules,
                prune=not args.no_prune, faults=plan)
            print(json.dumps({"crash_at": f"{name}:{k}",
                              **_result_line(r)}))
            total_vio += r.violations
            total_und += r.undecided
            all_verified = all_verified and r.verified
            seconds += r.seconds
        print(json.dumps({"crash_sweep": f"{name}:{lo}-{hi}",
                          "ops": len(prog),
                          "total_violations": total_vio,
                          "total_undecided": total_und,
                          "all_verified": all_verified,
                          "seconds": round(seconds, 3)}))
        # exit mirrors `run`: 1 = violations found, 2 = inconclusive
        # (no violation but the certification claim was NOT earned —
        # truncated trees or undecided verdicts), 0 = fully verified
        if total_vio:
            return 1
        return 0 if all_verified else 2
    res = explore_program(
        lambda: make(args.model, args.impl)[1], prog, spec,
        backend=backend, max_schedules=args.max_schedules,
        prune=not args.no_prune, faults=faults)
    shrink_steps = 0
    if res.violations and args.shrink:
        prog, res, shrink_steps = shrink_explored(
            lambda: make(args.model, args.impl)[1], prog, spec,
            backend=backend, max_schedules=args.max_schedules,
            initial=res,  # exploration is deterministic: reuse, don't redo
            faults=faults)
    out = {"model": args.model, "impl": args.impl, "ops": len(prog),
           **_result_line(res),
           "shrink_steps": shrink_steps, "seconds": res.seconds}
    print(json.dumps(out))
    if res.violating is not None:
        print(format_history(spec, res.violating), file=sys.stderr)
        if args.save_regression:
            from ..core.property import Counterexample

            cx = Counterexample(program=prog, history=res.violating,
                                trial=0, trial_seed=res.violating.seed,
                                shrink_steps=shrink_steps)
            # the fault plan is part of the finding: replay without it
            # runs a different (crash-free) execution
            cfg = PropertyConfig(n_pids=args.pids, max_ops=args.ops,
                                 faults=faults)
            save_regression(args.save_regression, args.model, args.impl,
                            spec, cfg, cx)
            print(f"regression saved to {args.save_regression}",
                  file=sys.stderr)
    return 0 if res.ok else 1


def cmd_fuzz(args) -> int:
    if args.addr:
        # closed-loop fleet fuzzing (docs/GENERATION.md): steered
        # generated corpora as check requests + monitor sessions
        # against a live fleet, every verdict re-proved locally.
        # Exit codes compose the two failure planes: wrong verdicts
        # trump everything, else the fleet's own health verdict
        # (same codes as `qsm-tpu health`).
        from ..gen.fleet import fuzz_fleet

        rep = fuzz_fleet(
            args.addr, args.models.split(","), rounds=args.rounds,
            batch=args.histories, seed=args.seed,
            session_every=args.session_every,
            deadline_s=args.deadline, timeout_s=args.timeout,
            checkpoint_dir=args.checkpoint_dir,
            log=lambda m: print(m, file=sys.stderr))
        print(json.dumps(rep))
        if rep["wrong_verdicts_total"]:
            return 1
        return int(rep["exit_code"])

    from .fuzz import fuzz_parity

    if {"device", "segdc", "auto", "hybrid"} & set(args.backends.split(",")):
        # same guard as --backend tpu: constructing JaxTPU (also the
        # inner of segdc/auto/hybrid) on a wedged chip tunnel hangs the
        # first in-process jax.devices() forever, and a cpu-pinned
        # process would run the lockstep kernel on host
        _ensure_device_reachable()
    rep = fuzz_parity(n_specs=args.specs, hists_per_spec=args.histories,
                      seed=args.seed, n_pids=args.pids, n_ops=args.ops,
                      p_pending=args.p_pending,
                      backends=tuple(args.backends.split(",")))
    out = {
        "specs": rep.specs, "histories": rep.histories,
        "linearizable": rep.linearizable, "violations": rep.violations,
        "budget_exceeded": rep.budget_exceeded,
        "mismatches": rep.mismatches[:20], "ok": rep.ok}
    if "cpp" in args.backends.split(","):
        out["cpp_native_histories"] = rep.cpp_native_histories
        if rep.cpp_native_histories == 0:
            # zero mismatches would prove nothing: every history fell
            # back to the same Python oracle being compared against
            out["cpp_vacuous"] = True
    print(json.dumps(out))
    return 0 if rep.ok else 1


def cmd_soak(args) -> int:
    """The ISSUE 18 chaos soak (gen/soak.py): durable sessions held
    open through rolling node restarts, an active-router SIGKILL and
    one node leave/join, with every flip and close verdict re-proved
    by a fresh memo oracle.  Prints the gate report as one JSON line;
    exit 0 only when ``gate_ok`` (zero wrong verdicts, zero lost
    flips, durable resumes banked, SLO health green)."""
    from ..gen.soak import soak_sessions

    rep = soak_sessions(
        sessions=args.sessions, ops_per_session=args.ops,
        model=args.model, seed=args.seed, workers=args.workers,
        max_sessions=args.max_sessions, lease_ttl_s=args.lease_ttl_s,
        fuzz_rounds=args.fuzz_rounds, run_dir=args.run_dir,
        faults=args.faults, log=lambda m: print(m, file=sys.stderr))
    print(json.dumps(rep))
    if not rep.get("gate_ok"):
        return 1
    return int(rep.get("exit_code", 0))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="qsm_tpu",
        description="TPU-native state-machine property testing / "
                    "linearizability checking")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("run", help="run prop_concurrent on a model/impl")
    _add_run_args(p)
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser("replay", help="reproduce a failure from seed or file")
    p.add_argument("--regression", default=None)
    p.add_argument("--witness", action="store_true",
                   help="on a LINEARIZABLE verdict, print the "
                        "linearization order and its search-free "
                        "verification")
    p.add_argument("--model", default=None, choices=sorted(MODELS))
    p.add_argument("--impl", default="racy")
    p.add_argument("--trial-seed", default=None)
    p.add_argument("--pids", type=int, default=None)
    p.add_argument("--ops", type=int, default=None)
    p.add_argument("--trials", type=int, default=100)
    _add_fault_args(p)
    p.set_defaults(fn=cmd_replay)

    p = sub.add_parser("bench", help="checker throughput on one model")
    p.add_argument("--model", default="cas", choices=sorted(MODELS))
    p.add_argument("--backend", default="cpu",
                   choices=_BACKENDS)
    p.add_argument("--pids", type=int, default=None)
    p.add_argument("--ops", type=int, default=None)
    p.add_argument("--corpus", type=int, default=256)
    p.add_argument("--unroll", type=int, default=None,
                   help="micro-steps per while-loop trip for device "
                        "kernels (default: auto — 8 on a real device, 1 "
                        "on the CPU platform; see docs/EXPERIMENTS.md)")
    p.set_defaults(fn=cmd_bench)

    p = sub.add_parser(
        "check", help="decide an external trace file (no scheduler)")
    p.add_argument("--trace", required=True,
                   help="JSON with a 'history' array of [pid, cmd, arg, "
                        "resp, invoke_time, response_time] rows")
    p.add_argument("--model", default=None, choices=sorted(MODELS),
                   help="overrides the trace's own 'model' field")
    p.add_argument("--backend", default="auto", choices=_BACKENDS)
    p.add_argument("--witness", action="store_true",
                   help="include the verified linearization order "
                        "(one host-oracle search serves verdict AND "
                        "witness; --backend is ignored)")
    p.set_defaults(fn=cmd_check)

    p = sub.add_parser(
        "shrink",
        help="minimize a failing external trace: frontier-at-once "
             "batched shrinking to a 1-minimal history with a "
             "verify_witness-replayable certificate (docs/SHRINK.md)")
    p.add_argument("--trace", required=True,
                   help="JSON with a 'history' array of [pid, cmd, arg, "
                        "resp, invoke_time, response_time] rows (the "
                        "`check` trace format)")
    p.add_argument("--model", default=None, choices=sorted(MODELS),
                   help="overrides the trace's own 'model' field")
    p.add_argument("--addr", default=None,
                   help="send to a running check server's `shrink` verb "
                        "instead of shrinking in-process (a,b = "
                        "multi-address failover across an HA router "
                        "pair)")
    p.add_argument("--certificate", action="store_true",
                   help="compute the 1-minimality certificate (one "
                        "witness per drop-one neighbor) and audit it "
                        "via verify_witness")
    p.add_argument("--emit-certificate", action="store_true",
                   help="also include the raw certificate witnesses in "
                        "the JSON output (in-process only; implies the "
                        "audit --certificate performs)")
    p.add_argument("--max-rounds", type=int, default=None,
                   help="greedy round cap (in-process only; default "
                        "256)")
    p.add_argument("--max-lanes", type=int, default=None,
                   help="frontier candidates decided per batched "
                        "dispatch (in-process only; default 512); a "
                        "truncated frontier is reported in `why` and "
                        "forfeits the 1-minimality claim, never silent")
    p.add_argument("--deadline", type=float, default=None,
                   help="seconds; past it the best-so-far history is "
                        "returned with complete=false and an honest why")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="client-side response bound (--addr mode)")
    p.add_argument("--save", default=None,
                   help="write the minimized history as a `check`-format "
                        "trace file (atomic)")
    p.set_defaults(fn=cmd_shrink)

    p = sub.add_parser("list", help="models, impls, and backend choices")
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser(
        "serve",
        help="run the check server (warm engines, micro-batching, "
             "verdict cache, bounded admission — docs/SERVING.md)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="TCP port (0 = ephemeral; the bound address is "
                        "printed as one JSON line)")
    p.add_argument("--unix", default=None,
                   help="serve on this UNIX socket path instead of TCP")
    p.add_argument("--engine", default="auto",
                   choices=["auto", "planned"],
                   help="auto = the warm host cpp->memo ladder (today's "
                        "fast path); planned = the plan_search-built "
                        "device checker (needs a reachable device)")
    p.add_argument("--workers", type=int, default=0,
                   help="dispatch micro-batches to N supervised engine "
                        "worker PROCESSES (serve/pool.py): checking "
                        "outscales one core, a crashed/wedged worker is "
                        "shed with its undecided lanes re-dispatched; "
                        "0 = check in-process (auto engine only)")
    p.add_argument("--quarantine-after", type=int, default=2,
                   help="a spec whose dispatches crashed this many "
                        "workers is quarantined to the in-process host "
                        "ladder (no respawn storm)")
    p.add_argument("--max-lanes", type=int, default=64,
                   help="micro-batch width: lanes coalesced per dispatch")
    p.add_argument("--mesh-devices", type=int, default=1,
                   help="shard the planned engine's lane axis over a mesh "
                        "of N devices (qsm_tpu/mesh, docs/MESH.md): plans "
                        "get mesh-divisible compile buckets and the "
                        "batcher's flush target rounds to mesh multiples "
                        "so one dispatch fills the whole mesh; verdicts "
                        "are bit-identical at any N (CPU bench: "
                        "XLA_FLAGS=--xla_force_host_platform_device_"
                        "count=N)")
    p.add_argument("--flush-ms", type=float, default=20.0,
                   help="micro-batch flush interval (latency floor for "
                        "a lone client)")
    p.add_argument("--queue-depth", type=int, default=1024,
                   help="admission bound on in-flight history lanes; "
                        "past it requests are SHED, never queued "
                        "unboundedly")
    p.add_argument("--cache", default=None,
                   help="persistent verdict-cache bank path (JSONL, "
                        "atomic; survives kill/restart)")
    p.add_argument("--cache-entries", type=int, default=4096)
    p.add_argument("--warm", default=None,
                   help="comma list of models to pre-build engines for")
    p.add_argument("--no-pcomp", action="store_true",
                   help="disable P-compositional request splitting: "
                        "long histories of decomposable specs check "
                        "whole instead of as per-key sub-lanes "
                        "(docs/PCOMP.md)")
    p.add_argument("--trace-log", default=None, metavar="PATH",
                   help="emit request-scoped span events to this JSONL "
                        "log (bounded rotation; qsm-tpu trace <id> "
                        "reconstructs one request's causal tree — "
                        "docs/OBSERVABILITY.md)")
    p.add_argument("--flight-dir", default=None, metavar="DIR",
                   help="enable the crash flight recorder: recent span "
                        "events per component, dumped atomically to "
                        "FLIGHT_<ts>.json on worker crash/quarantine, "
                        "SHED storms, fault-plane hits and stop()")
    p.add_argument("--metrics-port", type=int, default=None,
                   metavar="PORT",
                   help="serve live metrics in Prometheus exposition "
                        "format on GET /metrics at this port (0 = "
                        "ephemeral; printed in the serving line)")
    p.add_argument("--node-id", default=None, metavar="ID",
                   help="fleet node id (qsm_tpu/fleet): stamped on "
                        "every response so router-merged answers say "
                        "which node decided which lanes")
    p.add_argument("--replog-dir", default=None, metavar="DIR",
                   help="bank verdicts in a segmented replicated log "
                        "(fleet/replog.py) instead of --cache's single "
                        "file, and serve the replog.* anti-entropy ops")
    p.add_argument("--replog-seal-rows", type=int, default=256,
                   help="rows per sealed replog segment (the unit "
                        "anti-entropy replicates; smaller = fresher "
                        "replication, more segment files)")
    p.add_argument("--peers", default=None, metavar="A,B",
                   help="comma-separated peer node addresses for "
                        "node-to-node gossip anti-entropy "
                        "(fleet/gossip.py; needs --replog-dir): banked "
                        "verdicts keep converging with every router "
                        "dead")
    p.add_argument("--gossip-s", type=float, default=2.0,
                   help="gossip beat seconds (with --peers; 0 = the "
                        "agent exists but only the gossip.peers op "
                        "drives it)")
    p.add_argument("--gossip-fanout", type=int, default=2,
                   help="random peers contacted per gossip beat")
    p.add_argument("--slo", default=None, metavar="SPEC",
                   help="declare SLO objectives evaluated over a "
                        "sliding window of the live latency/shed "
                        "series (obs/slo.py), e.g. "
                        "'check=250ms:p99,shed_rate<0.01' — exposed "
                        "as burn-rate gauges, the `health` op and the "
                        "slo.breach flight-dump trigger")
    p.add_argument("--session-dir", default=None, metavar="DIR",
                   help="durable monitor sessions (monitor/store.py): "
                        "session state snapshots + bounded journal "
                        "tails under DIR; a restarted or cap-evicted "
                        "session resumes in O(doc) from its banked "
                        "prefixes with zero engine folds — and the "
                        "session cap then bounds memory, not open "
                        "sessions (docs/MONITOR.md \"Durability\")")
    p.add_argument("--max-sessions", type=int, default=256,
                   help="live monitor-session slots; with "
                        "--session-dir the LRU session past the cap "
                        "is evicted to the durable store, without it "
                        "the cap is hard (session.open SHEDs)")
    p.add_argument("--lease-host", default=None, metavar="PATH",
                   help="host the fleet HA lease on this node: serve "
                        "the lease.* ops over a FileLeaseStore at "
                        "PATH, so routers on OTHER hosts point "
                        "--lease-store tcp://this-node at it "
                        "(fleet/lease.py)")
    p.add_argument("--slo-window", type=float, default=60.0,
                   help="SLO sliding-window seconds")
    p.add_argument("--devq-dir", default=None, metavar="DIR",
                   help="persistent device-work queue (qsm_tpu/devq, "
                        "docs/WINDOWS.md): every plane banks device-"
                        "worthy work under DIR as fingerprint-keyed "
                        "items; a seized TPU window drains it in "
                        "score order and the verdicts land in the "
                        "cache.  Gossips with --peers; serves "
                        "devq.put/digests/pull/drain_report")
    p.add_argument("--devq-cap", type=int, default=512,
                   help="pending device-work items kept (lowest-"
                        "score eviction past the cap; tombstones "
                        "persist so evicted work re-banks cleanly)")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser(
        "fleet",
        help="run a multi-node fleet: N CheckServer nodes behind a "
             "fault-tolerant router (qsm_tpu/fleet; docs/SERVING.md)")
    p.add_argument("--nodes", type=int, default=2,
                   help="local node processes to spawn (ignored with "
                        "--addrs)")
    p.add_argument("--addrs", default=None,
                   help="comma-separated addresses of nodes you "
                        "started yourself (host:port or unix paths)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="router TCP port (0 = ephemeral, printed)")
    p.add_argument("--unix", default=None,
                   help="router UNIX socket path instead of TCP")
    p.add_argument("--workers", type=int, default=0,
                   help="worker processes per spawned node "
                        "(serve --workers)")
    p.add_argument("--warm", default=None,
                   help="comma-separated models each spawned node "
                        "warms at start")
    p.add_argument("--replog-root", default=None, metavar="DIR",
                   help="root directory for spawned nodes' segmented "
                        "verdict logs (default: a temp dir)")
    p.add_argument("--session-root", default=None, metavar="DIR",
                   help="give each spawned node a durable session "
                        "store under DIR/<node-id> (serve "
                        "--session-dir): sessions survive node "
                        "restarts and cap eviction")
    p.add_argument("--devq-root", default=None, metavar="DIR",
                   help="give each spawned node a persistent device-"
                        "work queue under DIR/<node-id> (serve "
                        "--devq-dir); with gossip on, banked work "
                        "converges fleet-wide so ANY node's seized "
                        "window can drain it (docs/WINDOWS.md)")
    p.add_argument("--session-journal", default=None, metavar="DIR",
                   help="durable ROUTER session journals "
                        "(monitor/store.py): point the active and "
                        "standby routers at the SAME dir (like "
                        "--lease-store) and a takeover rehydrates "
                        "sessions the standby never served, replaying "
                        "them onto the ring")
    p.add_argument("--max-sessions", type=int, default=256,
                   help="per-spawned-node live session slots (serve "
                        "--max-sessions; memory bound when "
                        "--session-root is set)")
    p.add_argument("--queue-depth", type=int, default=4096,
                   help="router admission bound (lanes in flight)")
    p.add_argument("--quarantine-after", type=int, default=3,
                   help="consecutive failed probes before a node is "
                        "quarantined one-way (re-admitted on "
                        "sustained health)")
    p.add_argument("--heartbeat-s", type=float, default=1.0,
                   help="membership probe beat seconds")
    p.add_argument("--anti-entropy-s", type=float, default=3.0,
                   help="anti-entropy sweep interval seconds (0 = "
                        "off)")
    p.add_argument("--trace-log", default=None, metavar="PATH",
                   help="router span log (qsm-tpu trace <id> shows "
                        "router->node hops)")
    p.add_argument("--flight-dir", default=None, metavar="DIR",
                   help="router flight-recorder dumps (node death/"
                        "quarantine/partition)")
    p.add_argument("--metrics-port", type=int, default=None,
                   metavar="PORT",
                   help="router Prometheus /metrics port (per-node "
                        "health + traffic series)")
    p.add_argument("--lease-path", "--lease-store", dest="lease_path",
                   default=None, metavar="STORE",
                   help="router-HA lease store (fleet/lease.py): run "
                        "several `qsm-tpu fleet` routers with the SAME "
                        "fleet config and lease store — one wins active "
                        "(term-stamped responses), the rest stand by "
                        "and take over on lease expiry; clients ride "
                        "it with a comma --addr list.  A filesystem "
                        "path flocks a local record; tcp://HOST:PORT "
                        "speaks the lease.* ops to a node started "
                        "with --lease-host, so routers span hosts")
    p.add_argument("--lease-ttl-s", type=float, default=3.0,
                   help="lease TTL seconds (renewed each beat; a dead "
                        "active is superseded within ~1.5x this)")
    p.add_argument("--router-id", default="router",
                   help="this router's id (the lease holder name and "
                        "the `node` stamp on router-answered "
                        "responses)")
    p.add_argument("--gossip-s", type=float, default=2.0,
                   help="wire spawned/fronted nodes for node-to-node "
                        "gossip anti-entropy at this beat (0 = off): "
                        "replication then survives every router dying")
    p.add_argument("--collect-dir", default=None, metavar="DIR",
                   help="fleet-wide span collection (obs/collect.py): "
                        "the router's beat scrapes every node's span "
                        "log (obs.spans, cursor-paged + idempotent) "
                        "into DIR/collected.jsonl — `qsm-tpu trace "
                        "<id> --addr ROUTER` then renders the full "
                        "cross-process causal tree; per-node cursors "
                        "persist so restarts re-ship nothing; spawned "
                        "nodes get --trace-log automatically")
    p.add_argument("--collect-s", type=float, default=1.0,
                   help="span-collection sweep interval seconds")
    p.add_argument("--slo", default=None, metavar="SPEC",
                   help="router SLO objectives (serve --slo grammar) "
                        "over route latency + sheds; `qsm-tpu health "
                        "--addr ROUTER` folds every node's health in")
    p.add_argument("--slo-window", type=float, default=60.0,
                   help="SLO sliding-window seconds")
    p.set_defaults(fn=cmd_fleet)

    p = sub.add_parser(
        "trace",
        help="reconstruct one request's causal tree from a span log "
             "(serve --trace-log) or a fleet router's collected log "
             "(--addr; docs/OBSERVABILITY.md)")
    p.add_argument("trace_id",
                   help="the trace id a check/shrink/SHED response "
                        "carried in its 'trace' field")
    p.add_argument("--log", default=None,
                   help="a local span-log path (its .1 rotation "
                        "predecessor is read too)")
    p.add_argument("--addr", default=None,
                   help="ask a running server/router for the trace "
                        "(obs.trace op); a router answers from its "
                        "COLLECTED fleet log, so the tree spans "
                        "client -> router -> nodes -> workers with "
                        "route hops and HA takeovers (a,b = "
                        "multi-address failover)")
    p.add_argument("--follow", action="store_true",
                   help="live mode: keep polling and print each NEW "
                        "event of the trace as it lands (round-trips "
                        "bounded by the fleet-probe policy preset; "
                        "stops after --max-idle quiet seconds)")
    p.add_argument("--interval", type=float, default=0.5,
                   help="--follow poll interval seconds")
    p.add_argument("--max-idle", type=float, default=30.0,
                   help="--follow: stop after this many seconds "
                        "without a new event")
    p.add_argument("--json", action="store_true",
                   help="print the raw event list instead of the tree")
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser(
        "health",
        help="SLO health of a running server or fleet router (the "
             "health op; exit 0 ok / 1 degraded / 2 breach / 3 "
             "unreachable)")
    p.add_argument("--addr", required=True,
                   help="server or router address (a,b = multi-"
                        "address failover)")
    p.add_argument("--timeout", type=float, default=10.0,
                   help="client-side response bound")
    p.set_defaults(fn=cmd_health)

    p = sub.add_parser(
        "submit",
        help="submit a trace file (the `check` format) to a running "
             "check server")
    p.add_argument("--addr", required=True,
                   help="server address: host:port or a UNIX socket "
                        "path; a comma list (a,b) enables bounded "
                        "multi-address failover across an HA router "
                        "pair — safe, every fleet op is idempotent")
    p.add_argument("--trace", required=True)
    p.add_argument("--model", default=None, choices=sorted(MODELS),
                   help="overrides the trace's own 'model' field")
    p.add_argument("--witness", action="store_true",
                   help="include verified linearization orders (served "
                        "from the cache bank on duplicates)")
    p.add_argument("--deadline", type=float, default=None,
                   help="per-request deadline seconds (default: the "
                        "'serve' policy preset); past it the server "
                        "answers SHED")
    p.add_argument("--timeout", type=float, default=60.0,
                   help="client-side response bound")
    p.set_defaults(fn=cmd_submit)

    p = sub.add_parser(
        "ingest",
        help="decode a Jepsen/Knossos- or porcupine-style event log "
             "into a first-class corpus (check/submit/shrink/bench "
             "take it unchanged)")
    p.add_argument("trace", help="the event-log file (EDN maps, one "
                                 "event per line)")
    p.add_argument("--format", required=True,
                   choices=("jepsen", "porcupine"))
    p.add_argument("--spec", required=True, choices=sorted(MODELS),
                   help="the model the trace's ops map onto "
                        "(ingest/specmap.py owns the vocabulary)")
    p.add_argument("--spec-kwargs", default=None,
                   help="JSON spec constructor kwargs (e.g. "
                        "'{\"n_keys\": 8}')")
    p.add_argument("--out", default=None,
                   help="write the decoded trace document here "
                        "(default: stdout)")
    p.add_argument("--check", action="store_true",
                   help="decide in-process (exit 0/1/2 = "
                        "linearizable/violation/undecided)")
    p.add_argument("--submit", default=None, metavar="ADDR",
                   help="submit to a running check server instead")
    p.add_argument("--witness", action="store_true",
                   help="with --submit: ask for the linearization")
    p.add_argument("--emit", action="store_true",
                   help="re-render the canonical log text (the "
                        "byte-stable round trip) instead of JSON")
    p.add_argument("--timeout", type=float, default=60.0)
    p.set_defaults(fn=cmd_ingest)

    p = sub.add_parser(
        "monitor",
        help="tail a growing event log into a LIVE monitor session: "
             "verdict flips print the minimized repro the moment "
             "they are decidable (docs/MONITOR.md)")
    p.add_argument("trace", help="the event-log file to tail")
    p.add_argument("--format", required=True,
                   choices=("jepsen", "porcupine"))
    p.add_argument("--spec", required=True, choices=sorted(MODELS))
    p.add_argument("--spec-kwargs", default=None)
    p.add_argument("--addr", default=None,
                   help="serve the session at this check server / "
                        "fleet router (session.* ops; omitted = an "
                        "in-process session)")
    p.add_argument("--session", default=None,
                   help="resume this server-side session id")
    p.add_argument("--follow", action="store_true",
                   help="keep tailing as the file grows (stops after "
                        "--max-idle quiet seconds)")
    p.add_argument("--max-idle", type=float, default=30.0)
    p.add_argument("--witness", action="store_true",
                   help="ask for the whole-stream witness at close")
    p.add_argument("--save", default=None,
                   help="write the flip's minimized repro here as a "
                        "`check`-format trace file")
    p.add_argument("--deadline", type=float, default=None)
    p.add_argument("--timeout", type=float, default=60.0)
    p.set_defaults(fn=cmd_monitor)

    p = sub.add_parser(
        "lint",
        help="static spec/kernel/determinism analysis (CPU-only; exit 1 "
             "on non-whitelisted error findings)")
    p.add_argument("--json", action="store_true",
                   help="print one JSON document instead of text")
    p.add_argument("--out", default=None,
                   help="also write the JSON findings document to this "
                        "path (probe_watcher/CI archive)")
    p.add_argument("--whitelist", default=None,
                   help="accepted-findings file (default: .qsmlint at "
                        "the repo root when present)")
    p.add_argument("--models", default=None,
                   help="comma list of registry families (default: all)")
    p.add_argument("--family", default=None,
                   help="comma list of registered pass-family ids "
                        "(a..i; default: all — docs/ANALYSIS.md)")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="REF",
                   help="lint only modules git-touched since REF "
                        "(default HEAD); whole-program families run "
                        "iff their scan set or triggers changed")
    p.add_argument("--sarif", default=None, metavar="PATH",
                   help="also write the findings as a SARIF 2.1.0 "
                        "document (CI diff annotation)")
    p.add_argument("--no-cache", action="store_true",
                   help="disable the on-disk per-file result cache "
                        "(.qsmlint-cache.json)")
    p.add_argument("--no-retrace", action="store_true",
                   help="skip the dynamic jit-cache retracing check "
                        "(the one pass that executes a backend)")
    p.set_defaults(fn=cmd_lint)

    p = sub.add_parser(
        "explore",
        help="bounded-exhaustive schedule exploration of one program")
    p.add_argument("--model", required=True, choices=sorted(MODELS))
    p.add_argument("--impl", default="racy")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--pids", type=int, default=2)
    p.add_argument("--ops", type=int, default=6)
    p.add_argument("--max-schedules", type=int, default=10_000)
    p.add_argument("--programs", type=int, default=1,
                   help="sweep N generated programs (seeds seed..seed+N-1)"
                        "; all trees' histories decide in ONE batch")
    p.add_argument("--workers", type=int, default=0,
                   help="fan the --programs tree enumerations over N "
                        "worker processes (0 = serial; results are "
                        "bit-identical either way)")
    p.add_argument("--backend", default=None, choices=_BACKENDS)
    p.add_argument("--shrink", action="store_true",
                   help="minimize a violating program by re-exploring "
                        "shrink candidates (violation by search, not by "
                        "one schedule's replay)")
    p.add_argument("--save-regression", default=None,
                   help="persist the violating (program, schedule) as a "
                        "replayable regression file")
    p.add_argument("--no-prune", action="store_true",
                   help="disable state-fingerprint subtree pruning (the "
                        "pruned walk visits the same distinct histories "
                        "in far fewer schedules; this flag forces the "
                        "raw lexicographic enumeration)")
    _add_fault_args(p)  # deterministic plans only (--crash-at and
    # --partition); probabilistic rates are refused with a clean message
    # in cmd_explore
    p.add_argument("--crash-sweep", default=None, metavar="NAME:LO-HI",
                   help="explore the program under crash_at={NAME: k} "
                        "for every k in [LO, HI] — one JSON line per "
                        "crash point plus a summary; `verified` on every "
                        "line proves the impl over the whole "
                        "crash-point × schedule space at this size")
    p.set_defaults(fn=cmd_explore)

    p = sub.add_parser(
        "fuzz", help="differential backend fuzzing over random specs; "
                     "with --addr, closed-loop coverage-guided fuzzing "
                     "of a live fleet (docs/GENERATION.md)")
    p.add_argument("--specs", type=int, default=10)
    p.add_argument("--histories", type=int, default=32,
                   help="histories per spec (differential mode) / per "
                        "round batch (--addr mode)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--pids", type=int, default=4)
    p.add_argument("--ops", type=int, default=10)
    p.add_argument("--p-pending", type=float, default=0.1)
    p.add_argument("--backends", default="memo,cpp,device",
                   help="comma list from {memo, cpp, device, segdc, auto, hybrid}")
    # closed-loop fleet mode (qsm_tpu/gen)
    p.add_argument("--addr", default=None,
                   help="soak a live fleet instead: server/router "
                        "address (comma list = failover set)")
    p.add_argument("--models", default="register,cas",
                   help="comma list of registry models to fuzz "
                        "(--addr mode)")
    p.add_argument("--rounds", type=int, default=4,
                   help="steering rounds per model (--addr mode)")
    p.add_argument("--session-every", type=int, default=2,
                   help="stream a monitor session every Nth round; "
                        "0 disables (--addr mode)")
    p.add_argument("--deadline", type=float, default=30.0,
                   help="per-request server deadline_s (--addr mode)")
    p.add_argument("--timeout", type=float, default=60.0,
                   help="client timeout_s (--addr mode)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="directory for per-model steering checkpoints "
                        "(resume rails, --addr mode)")
    p.set_defaults(fn=cmd_fuzz)

    p = sub.add_parser(
        "soak",
        help="chaos soak of durable sessions (gen/soak.py): spawn a "
             "3-node fleet + active/standby routers, hold N monitor "
             "sessions open through rolling node restarts, a SIGKILL "
             "of the active router and one node leave/join, every "
             "verdict re-proved by a fresh memo oracle "
             "(docs/MONITOR.md \"Durability\")")
    p.add_argument("--sessions", type=int, default=1000,
                   help="concurrent monitor sessions held open "
                        "through the fault schedule")
    p.add_argument("--ops", type=int, default=12,
                   help="events per session stream")
    p.add_argument("--model", default="register", choices=sorted(MODELS))
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--workers", type=int, default=8,
                   help="client threads driving the session verbs")
    p.add_argument("--max-sessions", type=int, default=256,
                   help="per-node live session slots (the memory "
                        "bound; the durable store holds the rest)")
    p.add_argument("--lease-ttl-s", type=float, default=1.0)
    p.add_argument("--fuzz-rounds", type=int, default=2,
                   help="PR 17 closed-loop rounds run against the "
                        "surviving router mid-soak (0 = skip)")
    p.add_argument("--run-dir", default=None, metavar="DIR",
                   help="keep the fleet's replogs/session stores/"
                        "lease here (default: a temp dir, removed)")
    p.add_argument("--faults", default=None, metavar="SPEC",
                   help="QSM_TPU_FAULTS grammar injected into every "
                        "spawned node (resilience/faults.py), e.g. "
                        "'raise:serve:0.01' — the rig's retry riders "
                        "must still land every verb exactly once")
    p.set_defaults(fn=cmd_soak)

    p = sub.add_parser(
        "stats",
        help="search-cost accounting (SearchStats) for one backend on "
             "one corpus: iters/nodes per history, memo prunes, "
             "compactions, plus the corpus profile and planner verdict")
    p.add_argument("--model", default="cas", choices=sorted(MODELS))
    p.add_argument("--backend", default="cpu", choices=_BACKENDS)
    p.add_argument("--planned", action="store_true",
                   help="run the plan_search-built device checker instead "
                        "of --backend (needs a reachable device, like "
                        "--backend tpu)")
    p.add_argument("--failover", action="store_true",
                   help="wrap the backend in a FailoverBackend and report "
                        "its degradation counters (resilience plane)")
    p.add_argument("--serve", default=None, metavar="ADDR",
                   help="print a running check server's aggregate stats "
                        "(requests, batch occupancy, cache hit rate, "
                        "sheds, per-engine search/resilience counters) "
                        "instead of running a corpus (a,b = "
                        "multi-address failover)")
    p.add_argument("--watch", action="store_true",
                   help="with --serve: a refreshing terminal view of "
                        "the live counters (Ctrl-C exits)")
    p.add_argument("--fleet", action="store_true",
                   help="with --serve ROUTER_ADDR: render the fleet "
                        "view (router counters + one health/traffic "
                        "row per node)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="--watch refresh interval seconds")
    p.add_argument("--watch-count", type=int, default=0,
                   help="--watch: exit after N frames (0 = forever; "
                        "scripts/tests)")
    p.add_argument("--pids", type=int, default=None)
    p.add_argument("--ops", type=int, default=None)
    p.add_argument("--corpus", type=int, default=64)
    p.set_defaults(fn=cmd_stats)

    p = sub.add_parser("coverage", help="schedule-coverage stats")
    p.add_argument("--model", required=True, choices=sorted(MODELS))
    p.add_argument("--impl", default="atomic")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--pids", type=int, default=None)
    p.add_argument("--ops", type=int, default=None)
    p.add_argument("--runs", type=int, default=100)
    p.add_argument("--exact", action="store_true",
                   help="also enumerate the interleaving tree (bounded) "
                        "and report what fraction of distinct histories "
                        "the sampled runs reached")
    p.add_argument("--max-schedules", type=int, default=10_000)
    p.set_defaults(fn=cmd_coverage)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
