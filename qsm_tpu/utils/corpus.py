"""Shared benchmark/driver corpus builder.

One place that defines "a realistic mixed corpus": alternate correct and
racy implementations over seeded programs, execute under the deterministic
scheduler, optionally drop pending ops.  Used by bench.py, the CLI bench
subcommand, and the driver entry points so they all measure the same thing.
"""

from __future__ import annotations

from typing import List, Sequence

from ..core.generator import generate_program
from ..core.history import History
from ..sched.runner import run_concurrent


def build_corpus(spec, sut_factories: Sequence, n: int, n_pids: int,
                 max_ops: int, seed_base: int = 0,
                 seed_prefix: str = "corpus",
                 complete: bool = True) -> List[History]:
    """``n`` histories cycling through ``sut_factories`` (callables taking
    the spec), with fully deterministic seeds."""
    hists = []
    for i in range(n):
        sut = sut_factories[i % len(sut_factories)](spec)
        prog = generate_program(spec, seed=seed_base + i, n_pids=n_pids,
                                max_ops=max_ops)
        h = run_concurrent(sut, prog, seed=f"{seed_prefix}:{i}")
        hists.append(h.completed() if complete else h)
    return hists
