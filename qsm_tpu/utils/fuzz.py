"""Differential fuzzing of the checker backends over RANDOM specs.

The curated parity suites (tests/test_parity.py and friends) pin
CPU == C++ == device verdicts on histories produced by the in-tree
models.  This module removes the "in-tree" qualifier: it generates
whole *specifications* at random — arbitrary seeded transition tables —
plus random concurrent histories against them, and asserts every backend
agrees with the exact Python oracle (SURVEY.md §4: the cross-backend
parity suite, property-tested; here the property ranges over specs too).

A random table is the adversarial case for the fast paths: it has no
algebraic structure for a bug to hide behind (canonical-form tricks,
idempotence, commutativity all absent), so ordering mistakes in the
search — candidate order, memo keying, budget accounting, precedence
masks — decohere from the oracle almost immediately.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.history import History, Op
from ..core.spec import CmdSig, Spec
from ..sched.runner import PENDING_T  # the one pending-time sentinel


class RandomTableSpec(Spec):
    """A spec whose fused step function IS a seeded random table.

    ``trans[s, c, a, r]`` is uniform over [0, n_states); ``ok[s, c, a, r]``
    is true with probability ``ok_bias`` (biased toward ok so random
    histories aren't all trivially non-linearizable).  Scalar state with
    ``scalar_state_bound == n_states`` by construction, so the spec rides
    the domain-table fast paths of all three backends.
    """

    name = "random_table"
    STATE_DIM = 1

    def __init__(self, seed: int, n_states: int = 8, n_cmds: int = 3,
                 max_args: int = 3, max_resps: int = 3,
                 ok_bias: float = 0.7):
        self.seed = seed
        self.n_states = n_states
        self.ok_bias = ok_bias
        # the DOMAIN bounds, not the observed maxima: spec_kwargs must
        # round-trip (Spec.max_resps is a derived property, hence _bound)
        self._max_args_bound = max_args
        self._max_resps_bound = max_resps
        rng = np.random.default_rng(seed)
        self.CMDS = tuple(
            CmdSig(f"c{i}", n_args=int(rng.integers(1, max_args + 1)),
                   n_resps=int(rng.integers(1, max_resps + 1)))
            for i in range(n_cmds))
        a = max(c.n_args for c in self.CMDS)
        r = max(c.n_resps for c in self.CMDS)
        self._trans = rng.integers(
            0, n_states, size=(n_states, n_cmds, a, r), dtype=np.int32)
        self._ok = rng.random((n_states, n_cmds, a, r)) < ok_bias

    def initial_state(self) -> np.ndarray:
        return np.zeros(1, np.int32)

    def scalar_state_bound(self, n_ops):
        return self.n_states

    def spec_kwargs(self):
        return {"seed": self.seed, "n_states": self.n_states,
                "n_cmds": len(self.CMDS),
                "max_args": self._max_args_bound,
                "max_resps": self._max_resps_bound,
                "ok_bias": self.ok_bias}

    def step_py(self, state, cmd, arg, resp):
        s = state[0]
        if not 0 <= s < self.n_states:
            # unreachable from the initial state (trans values are all in
            # range) but probed by compile_step_table when the native
            # backend rounds the table bound up: define as a failing
            # self-loop so every tabulation of this spec agrees
            return [s], False
        return ([int(self._trans[s, cmd, arg, resp])],
                bool(self._ok[s, cmd, arg, resp]))

    def step_jax(self, state, cmd, arg, resp):
        import jax.numpy as jnp

        # NO caching of the jnp arrays: inside a jit trace jnp.asarray
        # yields a TRACER (the constant is staged into that jaxpr);
        # caching it on self and reusing it in a LATER trace (the
        # kernel's next chunk-size compilation) is a leaked-tracer crash
        # (regression: tests/test_fuzz.py::..._safe_across_retraces).
        # Fresh asarray per call embeds the constant per-trace, like the
        # in-tree specs do.
        trans = jnp.asarray(self._trans)
        ok = jnp.asarray(self._ok)
        s = state[0]
        return (jnp.stack([trans[s, cmd, arg, resp]]),
                ok[s, cmd, arg, resp])


class RandomVectorSpec(Spec):
    """A random VECTOR-state spec: each state element evolves by its own
    seeded table (values within its declared bound), ``ok`` by a table
    over (element0, op).  Exists to fuzz the vector-state machinery the
    scalar table spec cannot reach: the device kernel's step sweep, the
    scalarization shadow (ops/scalarize.py — applied iff the bounds
    product is small), and the oracle's vector memo keys.
    """

    name = "random_vector"

    def __init__(self, seed: int, bounds: Tuple[int, ...] = (4, 4, 4),
                 n_cmds: int = 3, max_args: int = 3, max_resps: int = 3,
                 ok_bias: float = 0.7):
        self.seed = seed
        self.bounds = tuple(int(b) for b in bounds)
        self.STATE_DIM = len(self.bounds)
        self.ok_bias = ok_bias
        self._max_args_bound = max_args
        self._max_resps_bound = max_resps
        rng = np.random.default_rng(seed)
        self.CMDS = tuple(
            CmdSig(f"c{i}", n_args=int(rng.integers(1, max_args + 1)),
                   n_resps=int(rng.integers(1, max_resps + 1)))
            for i in range(n_cmds))
        a = max(c.n_args for c in self.CMDS)
        r = max(c.n_resps for c in self.CMDS)
        self._trans = [rng.integers(0, b, size=(b, n_cmds, a, r),
                                    dtype=np.int32)
                       for b in self.bounds]
        self._ok = rng.random((self.bounds[0], n_cmds, a, r)) < ok_bias

    def initial_state(self) -> np.ndarray:
        return np.zeros(self.STATE_DIM, np.int32)

    def state_elem_bounds(self):
        return list(self.bounds)

    def spec_kwargs(self):
        return {"seed": self.seed, "bounds": self.bounds,
                "n_cmds": len(self.CMDS),
                "max_args": self._max_args_bound,
                "max_resps": self._max_resps_bound,
                "ok_bias": self.ok_bias}

    def step_py(self, state, cmd, arg, resp):
        nxt = [int(t[int(s), cmd, arg, resp])
               for s, t in zip(state, self._trans)]
        return nxt, bool(self._ok[int(state[0]), cmd, arg, resp])

    def step_jax(self, state, cmd, arg, resp):
        import jax.numpy as jnp

        # fresh asarray per call — NEVER cache jnp arrays created under
        # a trace (see RandomTableSpec.step_jax)
        nxt = jnp.stack([jnp.asarray(t)[state[i], cmd, arg, resp]
                         for i, t in enumerate(self._trans)])
        ok = jnp.asarray(self._ok)[state[0], cmd, arg, resp]
        return nxt.astype(state.dtype), ok


def random_history(spec: Spec, rng: random.Random, n_pids: int,
                   n_ops: int, p_pending: float = 0.0) -> History:
    """A random well-formed concurrent history against ``spec``.

    Simulated clock: each tick either invokes a fresh op on an idle pid or
    completes an outstanding one, so per-pid ops are sequential while ops
    on different pids overlap arbitrarily — the full shape space the
    scheduler plane can emit, without needing a SUT (a random spec has no
    implementation; responses are drawn uniformly from the command's
    domain, so verdicts split between linearizable and violating).
    """
    remaining = n_ops
    outstanding: Dict[int, Op] = {}
    dead: set = set()  # pids whose op went pending: blocked forever
    done: List[Op] = []
    t = 0
    while remaining > 0 or outstanding:
        idle = [p for p in range(n_pids)
                if p not in outstanding and p not in dead]
        can_invoke = remaining > 0 and idle
        if not can_invoke and not outstanding:
            break  # every pid is dead; undone ops are simply not issued
        if can_invoke and (not outstanding or rng.random() < 0.5):
            pid = rng.choice(idle)
            cmd = rng.randrange(len(spec.CMDS))
            arg = rng.randrange(spec.CMDS[cmd].n_args)
            outstanding[pid] = Op(pid=pid, cmd=cmd, arg=arg, resp=-1,
                                  invoke_time=t, response_time=PENDING_T)
            remaining -= 1
        else:
            pid = rng.choice(sorted(outstanding))
            op = outstanding.pop(pid)
            if rng.random() < p_pending:
                done.append(op)  # never responds (crash/drop shape)
                dead.add(pid)    # a blocked pid can't invoke again
            else:
                resp = rng.randrange(spec.CMDS[op.cmd].n_resps)
                done.append(dataclasses.replace(
                    op, resp=resp, response_time=t))
        t += 1
    done.sort(key=lambda o: o.invoke_time)
    return History(done)


@dataclasses.dataclass
class FuzzReport:
    specs: int
    histories: int
    linearizable: int
    violations: int
    budget_exceeded: int
    mismatches: List[Tuple[int, int, str, int, int]]
    # (spec_seed, history_index, backend_name, oracle_verdict, got)
    # histories the cpp lane decided NATIVELY (0 = the lane was vacuous:
    # every history fell back to the same Python oracle being compared
    # against, so "zero mismatches" proves nothing about the C++ code)
    cpp_native_histories: int = 0
    # histories the hybrid lane's HOST TAIL decided (0 = the tiny device
    # budget decided everything and the tail path went unexercised)
    hybrid_tail_histories: int = 0

    @property
    def ok(self) -> bool:
        return not self.mismatches


def fuzz_parity(n_specs: int = 10, hists_per_spec: int = 32,
                seed: int = 0, n_pids: int = 4, n_ops: int = 10,
                p_pending: float = 0.1,
                backends: Sequence[str] = ("memo", "cpp", "device"),
                spec_kwargs: Optional[dict] = None,
                vector_bounds: Optional[Tuple[int, ...]] = None
                ) -> FuzzReport:
    """Differential sweep: for each random spec, every requested backend
    must agree with the exact (memo-free) Python oracle on every random
    history.  BUDGET_EXCEEDED never counts as a mismatch on its own —
    backends may defer — but a decided verdict that contradicts the
    oracle's decided verdict always does.

    ``vector_bounds`` switches the spec family from scalar random tables
    to :class:`RandomVectorSpec` with those element bounds — small
    products exercise the scalarization shadow, large ones the vector
    step-sweep kernel path.
    """
    from ..native import CppOracle
    from ..ops.backend import Verdict
    from ..ops.jax_kernel import JaxTPU
    from ..ops.wing_gong_cpu import WingGongCPU

    oracle = WingGongCPU(memo=False)
    lin = vio = bud = 0
    cpp_native = 0
    hybrid_tail = 0
    mismatches: List[Tuple[int, int, str, int, int]] = []
    for k in range(n_specs):
        spec_seed = seed * 1_000_003 + k
        if vector_bounds is not None:
            spec = RandomVectorSpec(spec_seed, bounds=vector_bounds,
                                    **(spec_kwargs or {}))
        else:
            spec = RandomTableSpec(spec_seed, **(spec_kwargs or {}))
        rng = random.Random(f"fuzz:{spec_seed}")
        hists = [random_history(spec, rng, n_pids, n_ops,
                                p_pending=p_pending)
                 for _ in range(hists_per_spec)]
        want = oracle.check_histories(spec, hists)
        lin += int((want == int(Verdict.LINEARIZABLE)).sum())
        vio += int((want == int(Verdict.VIOLATION)).sum())
        bud += int((want == int(Verdict.BUDGET_EXCEEDED)).sum())
        for name in backends:
            if name == "memo":
                backend = WingGongCPU(memo=True)
            elif name == "cpp":
                backend = CppOracle(spec)
            elif name == "device":
                backend = JaxTPU(spec)
            elif name == "segdc":
                from ..ops.segdc import SegDC

                backend = SegDC(spec, make_inner=lambda s: JaxTPU(s))
            elif name == "auto":
                # the strategy router over random specs: exercises the
                # per-history segdc/plain decision AND the native
                # middle-segment enumerator on spec shapes no in-tree
                # model has
                from ..ops.router import AutoDevice

                backend = AutoDevice(spec)
            elif name == "hybrid":
                # device majority + host tail as ONE backend; a tiny
                # device budget forces real traffic through BOTH sides
                # under fuzz (budget 200 let the device decide every
                # random-spec history; 12 splits the corpus)
                from ..ops.hybrid import HybridDevice

                backend = HybridDevice(spec, budget=12)
            elif name == "pallas":
                # the Mosaic prototype over random scalar tables —
                # interpret mode off-TPU, so the budget stays tight
                # (BUDGET deferrals are honest, never mismatches)
                from ..ops.pallas_kernel import PallasTPU

                backend = PallasTPU(spec, budget=4_000, mid_budget=0,
                                    rescue_budget=0)
            else:
                raise ValueError(f"unknown fuzz backend {name!r}")
            got = backend.check_histories(spec, hists)
            if name == "cpp":
                cpp_native += backend.native_histories
            elif name == "hybrid":
                hybrid_tail += backend.tail_histories
            for i, (w, g) in enumerate(zip(want, got)):
                undecided = int(Verdict.BUDGET_EXCEEDED)
                if int(g) == undecided or int(w) == undecided:
                    continue  # honest deferral (either side), never a
                    # mismatch on its own — only decided-vs-decided counts
                if int(w) != int(g):
                    mismatches.append((spec_seed, i, name, int(w), int(g)))
    return FuzzReport(specs=n_specs, histories=n_specs * hists_per_spec,
                      linearizable=lin, violations=vio,
                      budget_exceeded=bud, mismatches=mismatches,
                      cpp_native_histories=cpp_native,
                      hybrid_tail_histories=hybrid_tail)
