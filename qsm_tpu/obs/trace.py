"""Request-scoped spans — the serving stack's causal record.

Every window into the serve plane before this module was an
after-the-fact aggregate (``SearchStats``, ``stats()``): good for
capacity math, useless for "where did THIS request spend its 400 ms?".
A :class:`Tracer` answers that with the standard distributed-tracing
shape, zero-dependency and import-light (no jax, no third-party):

* a **trace id** is minted once per request at admission
  (:func:`new_trace_id`) and propagated through every stage the request
  touches — micro-batch flushes, pcomp sub-lanes, worker-pool frames,
  shrink frontier rounds, failover degradations, cache put/hit;
* each stage emits a **span event**: one JSON document with
  ``trace`` (the request), ``span`` (this event's own id), ``parent``
  (the span it is causally under), ``name`` (the taxonomy entry —
  docs/OBSERVABILITY.md), ``ts``/``ms`` and free-form attrs;
* events append to a **JSONL log with bounded-size rotation** (the
  ``atomic``-rails discipline: a torn tail is droppable, never
  poisonous) so ``qsm-tpu trace <trace_id>`` can reconstruct one
  request's full causal tree offline.

Cost contract: with no sink configured ``enabled`` is False and every
emit site in the serving stack guards on ONE attribute read — the
tracing-off serve path must stay within noise of a build with no obs
at all (BENCH_OBS_r11.json pins ≤5%).  With a sink, emission is one
dict → json.dumps → buffered write under a lock; durability comes from
the flight recorder (obs/flight.py), not fsync-per-event.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

# rotation default: one live file + one predecessor (``<path>.1``) —
# bounded disk however long the server lives; readers walk both
DEFAULT_MAX_BYTES = 16 * 1024 * 1024


def new_trace_id() -> str:
    """One request's identity across every stage (16 hex chars)."""
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    """One span event's identity inside its trace (10 hex chars)."""
    return uuid.uuid4().hex[:10]


class Span:
    """One timed stage, context-manager closed.

    ``with tracer.span("lane", trace=t, parent=root, index=0) as sp:``
    emits exactly one event on exit (success or exception — the
    discipline the QSM-OBS-SPAN lint pass gates) with the measured
    ``ms`` and ``status``; ``sp.id`` is the span id children parent
    under, ``sp.add(k=v)`` attaches attrs discovered mid-stage."""

    __slots__ = ("_tracer", "name", "trace", "id", "parent", "attrs",
                 "_t0")

    def __init__(self, tracer: "Tracer", name: str, trace: str,
                 parent: str, attrs: Dict):
        self._tracer = tracer
        self.name = name
        self.trace = trace
        self.id = new_span_id()
        self.parent = parent
        self.attrs = attrs
        self._t0 = 0.0

    def add(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, _tb) -> None:
        ms = round((time.monotonic() - self._t0) * 1000.0, 3)
        status = "ok" if exc_type is None else f"error:{exc_type.__name__}"
        self._tracer.emit(self.name, trace=self.trace, span=self.id,
                          parent=self.parent, ms=ms, status=status,
                          **self.attrs)


class _NullSpan:
    """The tracing-off span: every field readable, nothing emitted."""

    __slots__ = ()
    id = ""
    trace = ""
    parent = ""

    def add(self, **_attrs) -> "_NullSpan":
        return self

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *_exc) -> None:
        return None


NULL_SPAN = _NullSpan()


class Tracer:
    """Span-event sink: JSONL file with bounded rotation plus
    subscriber hooks (the flight recorder rides one).  Thread-safe —
    connection threads, dispatcher threads and the pool supervisor all
    emit concurrently."""

    def __init__(self, path: Optional[str] = None,
                 max_bytes: int = DEFAULT_MAX_BYTES):
        self.path = path
        self.max_bytes = max(4096, int(max_bytes))
        self.enabled = path is not None
        self._lock = threading.Lock()
        self._f = None
        self._bytes = 0
        self._hooks: List[Callable[[dict], None]] = []
        self.events = 0      # emitted this process (guarded)
        self.rotations = 0

    # ------------------------------------------------------------------
    def add_hook(self, fn: Callable[[dict], None]) -> None:
        """Subscribe to every emitted event (called under the emit
        lock: hooks must be cheap and never re-enter the tracer)."""
        with self._lock:
            self._hooks.append(fn)
            # a hook is a live consumer even with no file sink (the
            # flight recorder works with tracing-to-disk off)
            self.enabled = True

    def span(self, name: str, trace: str, parent: str = "",
             **attrs) -> Span:
        """A timed context-manager span; the no-op singleton when
        tracing is off (callers pay one attribute read + one branch)."""
        if not self.enabled:
            return NULL_SPAN
        return Span(self, name, trace, parent, attrs)

    def event(self, name: str, trace: str = "", parent: str = "",
              ms: Optional[float] = None, **attrs) -> str:
        """One instantaneous span event; returns its span id ("" when
        tracing is off) so children can parent under it."""
        if not self.enabled:
            return ""
        span = new_span_id()
        self.emit(name, trace=trace, span=span, parent=parent, ms=ms,
                  **attrs)
        return span

    def emit(self, name: str, trace: str = "", span: str = "",
             parent: str = "", ms: Optional[float] = None,
             status: str = "ok", **attrs) -> None:
        if not self.enabled:
            return
        doc = {"ts": round(time.time(), 4), "name": name,
               "trace": trace, "span": span or new_span_id(),
               "parent": parent, "status": status}
        if ms is not None:
            doc["ms"] = ms
        if attrs:
            doc["attrs"] = attrs
        with self._lock:
            self.events += 1
            hooks = list(self._hooks)
            if self.path is not None:
                self._write_locked(json.dumps(doc) + "\n")
        # hooks run OUTSIDE the emit lock: a trigger-fired flight dump
        # (ring serialization + atomic disk write) must stall only the
        # emitting thread, never every other thread's next emit — the
        # degraded situations that fire dumps are exactly the ones
        # where the disk may be slow (hooks keep their own locking)
        for hook in hooks:
            try:
                hook(doc)
            except Exception:  # noqa: BLE001 — a broken subscriber
                pass           # must never take tracing down

    def flush(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.flush()
                except OSError:
                    pass

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None

    # ------------------------------------------------------------------
    def _write_locked(self, line: str) -> None:
        try:
            if self._f is None:
                self._f = open(self.path, "a")
                self._bytes = self._f.tell()
            if self._bytes + len(line) > self.max_bytes:
                # bounded rotation: live file becomes <path>.1 (the one
                # predecessor kept), a fresh live file starts — trace
                # disk is O(2 * max_bytes) however long the server runs
                self._f.close()
                os.replace(self.path, f"{self.path}.1")
                self._f = open(self.path, "a")
                self._bytes = 0
                self.rotations += 1
            self._f.write(line)
            self._f.flush()
            self._bytes += len(line)
        except OSError:
            # a full disk must degrade tracing, never the serving plane
            self._f = None

    def snapshot(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled, "path": self.path,
                    "events": self.events, "rotations": self.rotations}


# ---------------------------------------------------------------------------
# offline reconstruction — `qsm-tpu trace <id>`
# ---------------------------------------------------------------------------

def load_events(path: str, trace_id: Optional[str] = None) -> List[dict]:
    """Events from the live log and its one rotation predecessor, in
    file order; garbled lines (a kill mid-write) are dropped, never
    fatal.  ``trace_id`` filters to one request's events."""
    out: List[dict] = []
    for p in (f"{path}.1", path):
        try:
            with open(p) as f:
                text = f.read()
        except OSError:
            continue
        for ln in text.splitlines():
            if not ln.strip():
                continue
            try:
                doc = json.loads(ln)
            except ValueError:
                continue
            if trace_id is not None and doc.get("trace") != trace_id:
                continue
            out.append(doc)
    return out


def trace_closure(events: List[dict], trace_id: str) -> List[dict]:
    """One trace's events PLUS their causal ancestors — the
    cross-process rule behind ``qsm-tpu trace <id> --addr``: a
    ``router.takeover`` or ``router.elect`` event carries no trace id
    (it belongs to every request of its term), but a request served
    under that term parents its root beneath it, so walking parent
    edges pulls the fleet-level cause into the request's tree.  Pure
    span/parent-edge traversal — wall clocks are never consulted, so
    per-process clock skew cannot reorder the tree.  Deduplicates by
    span id (a collection gap reset may have shipped an event twice);
    returns events in their original merged-file order."""
    by_span: Dict[str, dict] = {}
    for ev in events:
        sp = ev.get("span")
        if sp and sp not in by_span:
            by_span[sp] = ev
    picked: List[dict] = []
    seen: set = set()
    stack: List[str] = []
    for ev in events:
        if ev.get("trace") != trace_id:
            continue
        sp = ev.get("span")
        if sp in seen or by_span.get(sp) is not ev:
            continue  # duplicate shipment of the same span event
        seen.add(sp)
        picked.append(ev)
        if ev.get("parent"):
            stack.append(ev["parent"])
    while stack:
        sp = stack.pop()
        if not sp or sp in seen:
            continue
        seen.add(sp)
        ev = by_span.get(sp)
        if ev is None:
            continue  # rotated away: the child renders as a root
        picked.append(ev)
        if ev.get("parent"):
            stack.append(ev["parent"])
    order = {id(ev): i for i, ev in enumerate(events)}
    picked.sort(key=lambda ev: order[id(ev)])
    return picked


def build_tree(events: List[dict]) -> List[dict]:
    """Causal forest from one trace's events: each node is the event
    dict plus ``children`` (sorted by emit order).  An event whose
    parent span never emitted (dropped line, rotation boundary)
    becomes a root rather than vanishing — an incomplete tree must
    still show everything it has."""
    by_span: Dict[str, dict] = {}
    nodes = []
    for ev in events:
        node = {**ev, "children": []}
        nodes.append(node)
        if ev.get("span"):
            by_span[ev["span"]] = node
    roots = []
    for node in nodes:
        parent = by_span.get(node.get("parent") or "")
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    return roots


def render_tree(roots: List[dict]) -> str:
    """Human rendering of :func:`build_tree`'s forest (the `qsm-tpu
    trace` default output)."""
    lines: List[str] = []

    def _attrs(node: dict) -> str:
        parts = []
        if node.get("ms") is not None:
            parts.append(f"{node['ms']}ms")
        for k, v in (node.get("attrs") or {}).items():
            parts.append(f"{k}={v}")
        if node.get("status", "ok") != "ok":
            parts.append(node["status"])
        return (" [" + " ".join(parts) + "]") if parts else ""

    def _walk(node: dict, prefix: str, last: bool) -> None:
        joint = "`- " if last else "|- "
        lines.append(f"{prefix}{joint}{node['name']}{_attrs(node)}")
        child_prefix = prefix + ("   " if last else "|  ")
        kids = node["children"]
        for i, child in enumerate(kids):
            _walk(child, child_prefix, i == len(kids) - 1)

    for root in roots:
        lines.append(f"{root['name']}{_attrs(root)}")
        for i, child in enumerate(root["children"]):
            _walk(child, "", i == len(root["children"]) - 1)
    return "\n".join(lines)
