"""SLO / health plane — declarative objectives over the live metrics.

The shed-storm flight trigger (obs/flight.py) was the plane's one
built-in opinion about "unhealthy".  This module replaces opinions
with CONFIGURED objectives: ``--slo "check=250ms:p99,shed_rate<0.01"``
declares what the operator means by healthy, the evaluator measures it
over a sliding window FROM THE SAME histograms ``/metrics`` exposes
(one set of books — no second latency accounting that drifts), and
three surfaces consume one evaluation:

* **burn-rate gauges** — ``qsm_slo_burn_rate{objective=...}`` on the
  metrics registry (burn = measured/target; >1 means the objective is
  breached NOW over the window);
* **the ``health`` protocol op** — ``{"op": "health"}`` answers the
  per-objective table and an overall status (``ok`` / ``degraded`` /
  ``breach``), which ``qsm-tpu health`` maps to pinned exit codes
  (0 / 1 / 2; 3 = unreachable);
* **the ``slo.breach`` flight trigger** — the transition into breach
  emits one event, which the flight recorder dumps on (the shed-storm
  heuristic, promoted to a configured objective).

Grammar (comma-separated objectives)::

    <verb>=<duration>:<quantile>     e.g.  check=250ms:p99
    shed_rate<<fraction>             e.g.  shed_rate<0.01

``verb`` is a request-latency histogram label (``check`` / ``shrink``
/ ``session``); ``duration`` is ``<n>ms`` or ``<n>s``; ``quantile`` is
``p50``/``p95``/``p99``/``p999``-style.  Parse errors raise loudly at
configuration time — a typo'd objective must never silently evaluate
to "always healthy".

Window mechanics: the evaluator keeps a bounded ring of periodic
histogram/counter snapshots (taken lazily on evaluation, spaced by
``min_tick_s``); the windowed value is computed from the DELTA between
the freshest snapshot and the oldest one inside ``window_s``.  With
fewer than two snapshots (or an empty window) an objective reports
zero samples and burns 0 — absence of traffic is not a breach.
"""

from __future__ import annotations

import dataclasses
import re
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# severity order the health op and the CLI exit codes share
STATUS_ORDER = ("ok", "degraded", "breach")
# qsm-tpu health pinned exit codes (docs/OBSERVABILITY.md "Fleet")
HEALTH_EXIT_CODES = {"ok": 0, "degraded": 1, "breach": 2}
HEALTH_EXIT_UNREACHABLE = 3

_LATENCY_RE = re.compile(
    r"^(?P<verb>[a-z_]+)=(?P<num>\d+(?:\.\d+)?)(?P<unit>ms|s)"
    r":p(?P<q>\d{1,3})$")
_SHED_RE = re.compile(r"^shed_rate<(?P<limit>\d*\.?\d+)$")

GRAMMAR = ("objective grammar: '<verb>=<n>ms:p<QQ>' (e.g. "
           "check=250ms:p99) or 'shed_rate<FRAC' (e.g. "
           "shed_rate<0.01), comma-separated")


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declared objective (name is the bounded metric-label
    identity: ``check_p99_ms`` / ``shed_rate``)."""

    name: str
    kind: str               # "latency" | "shed_rate"
    verb: str = ""          # latency only: the histogram label
    quantile: float = 0.0   # latency only: 0..1
    target: float = 0.0     # seconds (latency) or fraction (shed_rate)

    def target_repr(self) -> str:
        if self.kind == "latency":
            return f"{self.target * 1000.0:g}ms:p{self.quantile:g}"
        return f"<{self.target:g}"


def parse_slo(spec: str,
              verbs: Sequence[str] = ("check", "shrink", "session")
              ) -> List[Objective]:
    """Parse one ``--slo`` string into objectives; raises ValueError
    with the grammar on anything malformed or an unknown verb."""
    out: List[Objective] = []
    for raw in str(spec).split(","):
        item = raw.strip()
        if not item:
            continue
        m = _SHED_RE.match(item)
        if m:
            limit = float(m.group("limit"))
            if not 0.0 < limit <= 1.0:
                raise ValueError(
                    f"slo objective {item!r}: shed_rate limit must be "
                    f"in (0, 1]; {GRAMMAR}")
            out.append(Objective(name="shed_rate", kind="shed_rate",
                                 target=limit))
            continue
        m = _LATENCY_RE.match(item)
        if m:
            verb = m.group("verb")
            if verb not in verbs:
                raise ValueError(
                    f"slo objective {item!r}: unknown verb {verb!r}; "
                    f"one of {sorted(verbs)}")
            digits = m.group("q")
            q = int(digits) / (10 ** len(digits))
            if not 0.0 < q < 1.0:
                raise ValueError(
                    f"slo objective {item!r}: quantile p{digits} is "
                    f"out of (0, 1); {GRAMMAR}")
            seconds = float(m.group("num")) * (
                0.001 if m.group("unit") == "ms" else 1.0)
            out.append(Objective(
                name=f"{verb}_p{digits}_ms", kind="latency", verb=verb,
                quantile=q, target=seconds))
            continue
        raise ValueError(f"cannot parse slo objective {item!r}; "
                         f"{GRAMMAR}")
    if not out:
        raise ValueError(f"empty slo spec {spec!r}; {GRAMMAR}")
    names = [o.name for o in out]
    dupes = sorted({n for n in names if names.count(n) > 1})
    if dupes:
        raise ValueError(f"duplicate slo objectives {dupes}")
    return out


def quantile_from_counts(bounds: Tuple[float, ...],
                         counts: Sequence[int], q: float) -> float:
    """The windowed twin of ``Histogram.quantile``: the estimated
    q-quantile from a DELTA count vector (linear interpolation inside
    the winning bucket).  0.0 with no observations."""
    total = sum(counts)
    if not total:
        return 0.0
    target = q * total
    seen = 0.0
    for i, c in enumerate(counts):
        if not c:
            continue
        if seen + c >= target:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i] if i < len(bounds) else bounds[-1]
            frac = (target - seen) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        seen += c
    return bounds[-1]


class SloEvaluator:
    """See module docstring.  One instance per server/router;
    thread-safe (the health op, the metrics scrape and the breach
    trigger all evaluate from different threads)."""

    def __init__(self, objectives: Sequence[Objective], *,
                 latency_hist,
                 requests_fn: Callable[[], float],
                 sheds_fn: Callable[[], float],
                 window_s: float = 60.0,
                 min_tick_s: Optional[float] = None,
                 warn_frac: float = 0.8,
                 on_breach: Optional[Callable[[dict], None]] = None):
        self.objectives = list(objectives)
        self.hist = latency_hist
        self.requests_fn = requests_fn
        self.sheds_fn = sheds_fn
        self.window_s = max(0.5, float(window_s))
        self.min_tick_s = (min_tick_s if min_tick_s is not None
                           else min(1.0, self.window_s / 10.0))
        self.min_tick_s = max(0.01, self.min_tick_s)
        self.warn_frac = warn_frac
        self.on_breach = on_breach
        self._lock = threading.Lock()
        # bounded by construction: window/tick snapshots plus slack —
        # the evaluator is O(window/tick) memory however long it runs
        cap = int(self.window_s / self.min_tick_s) + 4
        self._snaps: deque = deque(maxlen=cap)
        self._breached: set = set()
        self.breaches = 0    # ok->breach transitions (monotonic)
        self.evaluations = 0

    # ------------------------------------------------------------------
    def _verbs(self) -> List[str]:
        return sorted({o.verb for o in self.objectives
                       if o.kind == "latency"})

    def _take_snapshot(self, now: float) -> dict:
        return {
            "t": now,
            "hist": {v: self.hist.counts(verb=v) for v in self._verbs()},
            "requests": float(self.requests_fn()),
            "sheds": float(self.sheds_fn()),
        }

    def _window_pair(self, now: float):
        """(freshest snapshot, baseline snapshot) for the sliding
        window — baseline is the newest snapshot at least ``window_s``
        old, else the oldest held (a young evaluator reports the
        window it actually has)."""
        snap = self._take_snapshot(now)
        if (not self._snaps
                or now - self._snaps[-1]["t"] >= self.min_tick_s):
            self._snaps.append(snap)
        base = None
        for s in self._snaps:
            if now - s["t"] >= self.window_s:
                base = s
            else:
                break
        if base is None:
            base = self._snaps[0]
        return snap, base

    # ------------------------------------------------------------------
    def evaluate(self) -> dict:
        """One evaluation: the per-objective table and the overall
        status; fires ``on_breach`` once per ok→breach transition."""
        now = time.monotonic()
        fired: List[dict] = []
        with self._lock:
            self.evaluations += 1
            cur, base = self._window_pair(now)
            window_actual = max(0.0, cur["t"] - base["t"])
            rows: List[dict] = []
            worst = "ok"
            for obj in self.objectives:
                row = self._evaluate_one(obj, cur, base)
                rows.append(row)
                if row["status"] == "breach":
                    worst = "breach"
                    if obj.name not in self._breached:
                        self._breached.add(obj.name)
                        self.breaches += 1
                        fired.append(row)
                else:
                    self._breached.discard(obj.name)
                    if row["status"] == "degraded" and worst == "ok":
                        worst = "degraded"
            doc = {"status": worst,
                   "window_s": self.window_s,
                   "window_actual_s": round(window_actual, 2),
                   "objectives": rows}
        if self.on_breach is not None:
            for row in fired:
                try:
                    self.on_breach(row)
                except Exception:  # noqa: BLE001 — a broken trigger
                    pass           # must never take evaluation down
        return doc

    def _evaluate_one(self, obj: Objective, cur: dict,
                      base: dict) -> dict:
        if obj.kind == "latency":
            c0 = base["hist"].get(obj.verb) or []
            c1 = cur["hist"].get(obj.verb) or []
            delta = [max(0, a - b) for a, b in
                     zip(c1, c0 or [0] * len(c1))]
            samples = sum(delta)
            value = quantile_from_counts(self.hist.bounds, delta,
                                         obj.quantile)
            burn = (value / obj.target) if samples else 0.0
            row_value = round(value * 1000.0, 3)   # ms, human-facing
            target_repr = round(obj.target * 1000.0, 3)
        else:
            reqs = cur["requests"] - base["requests"]
            sheds = cur["sheds"] - base["sheds"]
            samples = int(max(0, reqs))
            value = (sheds / reqs) if reqs > 0 else 0.0
            burn = (value / obj.target) if samples else 0.0
            row_value = round(value, 5)
            target_repr = obj.target
        if samples and burn > 1.0:
            status = "breach"
        elif samples and burn >= self.warn_frac:
            status = "degraded"
        else:
            status = "ok"
        return {"objective": obj.name, "kind": obj.kind,
                "target": target_repr, "value": row_value,
                "burn_rate": round(burn, 4), "samples": int(samples),
                "status": status}

    # ------------------------------------------------------------------
    def metric_samples(self):
        """Scrape-time collector samples: burn-rate / breached gauges
        per objective (bounded label set — objective names come from
        the configuration string, never from request data)."""
        doc = self.evaluate()
        out = []
        for row in doc["objectives"]:
            labels = {"objective": row["objective"]}
            out.append(("qsm_slo_burn_rate", "gauge",
                        "windowed measured/target ratio (>1 = breach)",
                        labels, float(row["burn_rate"])))
            out.append(("qsm_slo_breached", "gauge",
                        "1 while the objective is breached over the "
                        "window", labels,
                        1.0 if row["status"] == "breach" else 0.0))
            out.append(("qsm_slo_samples", "gauge",
                        "observations inside the objective's window",
                        labels, float(row["samples"])))
        out.append(("qsm_slo_breach_transitions_total", "counter",
                    "ok->breach transitions observed", {},
                    float(self.breaches)))
        return out

    def snapshot(self) -> dict:
        with self._lock:
            return {"objectives": [
                        {"name": o.name, "kind": o.kind,
                         "target": o.target_repr()}
                        for o in self.objectives],
                    "window_s": self.window_s,
                    "breaches": self.breaches,
                    "evaluations": self.evaluations}


#: The window-utilization target (ISSUE 20): a drained window should
#: spend at least this fraction of its wall clock in engine dispatch.
WINDOW_UTILIZATION_TARGET = 0.8


def utilization_objective(value,
                          target: float = WINDOW_UTILIZATION_TARGET
                          ) -> dict:
    """``window_utilization`` as one more objective row (qsm_tpu/devq).

    Shaped exactly like :meth:`SloEvaluator._evaluate_one`'s rows so
    the ``health`` verb can append it to a configured objective table
    (or report it alone).  This is a LOWER-bound objective — burn is
    target/measured, >1 means the last window wasted device time — and
    ``value=None`` (no window drained yet) reports zero samples and
    burns 0: rare windows are the subsystem's premise, their absence
    is never an incident."""
    if value is None:
        burn, samples, status, row_value = 0.0, 0, "ok", None
    else:
        v = float(value)
        burn = (target / v) if v > 0 else float("inf")
        samples = 1
        if burn > 1.25:
            status = "breach"
        elif burn > 1.0:
            status = "degraded"
        else:
            status = "ok"
        row_value = round(v, 4)
        burn = round(burn, 4) if burn != float("inf") else burn
    return {"objective": f"window_utilization>={target}",
            "kind": "utilization", "target": target,
            "value": row_value, "burn_rate": burn,
            "samples": samples, "status": status}


def worst_status(statuses) -> str:
    """The fleet-health fold: the most severe of a set of statuses
    (unknown strings read as ``degraded`` — an unreachable node is a
    health problem, not a breach proof)."""
    worst = 0
    for s in statuses:
        try:
            worst = max(worst, STATUS_ORDER.index(s))
        except ValueError:
            worst = max(worst, STATUS_ORDER.index("degraded"))
    return STATUS_ORDER[worst]
