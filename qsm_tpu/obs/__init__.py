"""``qsm_tpu.obs`` — the trace/metrics/flight observability plane.

The serving stack became a genuinely distributed pipeline (admission →
micro-batcher → pcomp sub-lanes → worker pool → verdict bank) whose
only windows were after-the-fact aggregates.  This package is the
end-to-end trace plane (docs/OBSERVABILITY.md):

* ``trace``   — request-scoped :class:`~qsm_tpu.obs.trace.Span` /
  :class:`~qsm_tpu.obs.trace.Tracer`: trace ids minted at admission,
  JSONL span log with bounded rotation, and the offline causal-tree
  reconstruction behind ``qsm-tpu trace <trace_id>``;
* ``metrics`` — a lock-cheap counter/gauge/histogram registry with
  scrape-time collectors, rendered in Prometheus exposition format
  (``qsm-tpu serve --metrics-port N``) and as the ``qsm-tpu stats
  --watch`` terminal view;
* ``flight``  — the crash flight recorder: a fixed-size in-memory ring
  of recent span events per component, dumped atomically to
  ``FLIGHT_<ts>.json`` on worker crash/quarantine, SHED storms,
  fault-plane hits and ``CheckServer.stop()``.

:class:`Observability` bundles the three per server instance; the
module-level :func:`set_global` / :func:`emit_global` hooks let
deep engine layers (``resilience/failover.py``, ``ops/hybrid.py``,
``resilience/faults.py``) report degradations without carrying an obs
handle through every constructor.  Everything here is zero-dependency
and import-light (no jax, no third-party) — the worker processes and
the lint gate can import it for free.
"""

from __future__ import annotations

import threading
from typing import Optional

from .collect import SpanCollector, read_span_page
from .flight import FlightRecorder, load_dump, recent_events
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      MetricsServer, parse_exposition)
from .slo import (HEALTH_EXIT_CODES, HEALTH_EXIT_UNREACHABLE,
                  Objective, SloEvaluator, parse_slo, worst_status)
from .trace import (NULL_SPAN, Span, Tracer, build_tree, load_events,
                    new_span_id, new_trace_id, render_tree,
                    trace_closure)

# events that flip the flight recorder's dump trigger the moment they
# are emitted (beyond the shed-storm window the server drives itself).
# The fleet tier's node-loss events (fleet/router.py, fleet/
# membership.py) ride the same rule: a dead/partitioned/quarantined
# NODE leaves an artifact naming the doomed dispatches' trace ids,
# exactly like a SIGKILLed pool worker one level down.
_DUMP_TRIGGERS = {"worker.shed": "worker_crash",
                  "pool.quarantine": "quarantine",
                  "fault.hit": "fault_plane",
                  "node.shed": "node_death",
                  "node.partition": "partition",
                  "fleet.quarantine": "node_quarantine",
                  # a router-HA takeover is a fleet-level incident by
                  # definition (the old brain is dead, wedged or
                  # partitioned): the new active's first act leaves an
                  # artifact recording what it observed when it took
                  # the term (fleet/router.py _promote)
                  "router.takeover": "router_takeover",
                  # a monitor session deciding a VIOLATION is the
                  # production incident the whole plane exists for:
                  # the dump names the session's trace id even when no
                  # client ever reads the flip response
                  # (serve/server.py _session_flip)
                  "session.flip": "session_flip",
                  # a configured SLO objective crossing into breach is
                  # the operator's OWN definition of an incident — the
                  # shed-storm heuristic promoted to a declared
                  # objective (obs/slo.py; the evaluator emits the
                  # event once per ok->breach transition)
                  "slo.breach": "slo_breach"}


class Observability:
    """One server's trace + metrics + flight bundle.

    ``metrics`` is ALWAYS live (a registry write is a lock around a
    float add — cheap enough for the default path); span emission and
    the flight ring are opt-in via ``trace_log`` / ``flight_dir``, and
    every emit site in the serving stack guards on the single ``on``
    attribute so the tracing-off path stays within noise of a build
    with no obs at all (BENCH_OBS_r11.json)."""

    def __init__(self, trace_log: Optional[str] = None,
                 flight_dir: Optional[str] = None, *,
                 trace_max_bytes: Optional[int] = None,
                 flight_events: int = 256,
                 shed_storm: int = 32):
        kw = {}
        if trace_max_bytes is not None:
            kw["max_bytes"] = trace_max_bytes
        self.tracer = Tracer(path=trace_log, **kw)
        self.metrics = MetricsRegistry()
        self.flight: Optional[FlightRecorder] = None
        if flight_dir is not None:
            self.flight = FlightRecorder(flight_dir,
                                         max_events=flight_events,
                                         storm_threshold=shed_storm)
            self.tracer.add_hook(self._on_event)
        # ONE attribute read at every emit site (the ≤5% contract)
        self.on = self.tracer.enabled

    # ------------------------------------------------------------------
    def _on_event(self, doc: dict) -> None:
        self.flight.record(doc)
        reason = _DUMP_TRIGGERS.get(doc.get("name"))
        if reason is not None:
            self.flight.dump(reason, extra={"event": doc})

    # -- emission (thin delegates, all guarded by ``on``) --------------
    def span(self, name: str, trace: str, parent: str = "", **attrs):
        if not self.on:
            return NULL_SPAN
        return self.tracer.span(name, trace, parent, **attrs)

    def event(self, name: str, trace: str = "", parent: str = "",
              ms: Optional[float] = None, **attrs) -> str:
        if not self.on:
            return ""
        return self.tracer.event(name, trace=trace, parent=parent,
                                 ms=ms, **attrs)

    # -- flight conveniences -------------------------------------------
    def note_shed(self) -> Optional[str]:
        if self.flight is None:
            return None
        return self.flight.note_shed()

    def flight_path(self) -> Optional[str]:
        """The most recent flight dump, if one fired (SHED responses
        carry it so a shed client can hand the operator an artifact)."""
        if self.flight is None:
            return None
        return self.flight.last_dump_path

    def dump_flight(self, reason: str, force: bool = False
                    ) -> Optional[str]:
        if self.flight is None:
            return None
        return self.flight.dump(reason, force=force)

    def close(self) -> None:
        self.tracer.close()

    def snapshot(self) -> dict:
        return {
            "tracing": self.tracer.snapshot(),
            "flight": (self.flight.snapshot()
                       if self.flight is not None else None),
        }


# ---------------------------------------------------------------------------
# the global hook deep layers report through (failover/hybrid/faults)
# ---------------------------------------------------------------------------

_global_lock = threading.Lock()
_global_obs: Optional[Observability] = None


def set_global(obs: Optional[Observability]) -> None:
    """Install (or clear) the process-global observability sink.  The
    check server installs itself on ``start()`` — last server wins,
    which matches the one-server-per-process deployment shape."""
    global _global_obs
    with _global_lock:
        _global_obs = obs


def global_obs() -> Optional[Observability]:
    with _global_lock:
        return _global_obs


def emit_global(name: str, trace: str = "", **attrs) -> None:
    """Emit one event into the global sink, if any — the zero-plumbing
    path for layers (failover degradation, fault-plane hits) that must
    stay constructible without an obs handle.  No sink, or a sink with
    tracing off, costs one lock + one attribute read."""
    obs = global_obs()
    if obs is None or not obs.on:
        return
    obs.event(name, trace=trace, **attrs)


__all__ = [
    "Counter", "FlightRecorder", "Gauge", "HEALTH_EXIT_CODES",
    "HEALTH_EXIT_UNREACHABLE", "Histogram", "MetricsRegistry",
    "MetricsServer", "NULL_SPAN", "Objective", "Observability",
    "SloEvaluator", "Span", "SpanCollector", "Tracer", "build_tree",
    "emit_global", "global_obs", "load_dump", "load_events",
    "new_span_id", "new_trace_id", "parse_exposition", "parse_slo",
    "read_span_page", "recent_events", "render_tree", "set_global",
    "trace_closure", "worst_status",
]
