"""Live metrics — a lock-cheap counter/gauge/histogram registry.

The serve plane already keeps every number a capacity decision needs
(admission/batcher/cache/pool snapshots, SearchStats) — but only as a
pull-the-whole-JSON ``stats`` verb.  This registry gives those numbers
a live, scrapeable surface with two deliberate properties:

* **Lock-cheap writes.**  A counter increment or histogram observation
  is one small-critical-section lock around a float add — safe from
  any thread, never on a path that holds a serving-plane lock.
* **Collectors, not copies.**  Most serve metrics are *derived* from
  counters the plane already maintains; re-counting them here would
  create a second set of books that drifts.  A registered collector is
  called at scrape time and yields samples straight from the one
  authoritative snapshot — which is why the ``/metrics`` endpoint and
  ``qsm-tpu stats`` reconcile by construction (pinned in
  tests/test_obs.py).

Rendering is the Prometheus plaintext exposition format (version
0.0.4): ``qsm-tpu serve --metrics-port N`` serves it on
``GET /metrics`` (obs/metrics.py :class:`MetricsServer`), and
``qsm-tpu stats --watch`` renders the same registry as a refreshing
terminal view.

Cardinality contract: metric NAMES and label VALUES come from bounded
sets (worker ids, flush reasons, verdict names) — never from
per-request data like history fingerprints.  The QSM-OBS-CARDINALITY
lint pass (analysis/obs_passes.py) gates the code-level twin of this
rule.
"""

from __future__ import annotations

import bisect
import threading
from typing import Callable, Dict, Iterable, List, Optional, Tuple

# (name, type, help, labels, value) — what collectors yield and the
# renderer consumes; ONE shape for owned metrics and collected ones
Sample = Tuple[str, str, str, Dict[str, str], float]

# dispatch/request latency buckets (seconds): sub-ms cache hits up to
# the wedge-detection region; fixed and few — the histogram is O(1)
# memory however many observations arrive
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0)


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter, optionally labeled (bounded label values
    only — see the module cardinality contract)."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._vals: Dict[tuple, float] = {}

    def inc(self, n: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._vals[key] = self._vals.get(key, 0.0) + n

    def value(self, **labels) -> float:
        with self._lock:
            return self._vals.get(_label_key(labels), 0.0)

    def samples(self) -> Iterable[Sample]:
        with self._lock:
            vals = dict(self._vals)
        for key, v in sorted(vals.items()):
            yield (self.name, "counter", self.help, dict(key), v)


class Gauge:
    """Last-write-wins instantaneous value."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._vals: Dict[tuple, float] = {}

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._vals[_label_key(labels)] = float(v)

    def value(self, **labels) -> float:
        with self._lock:
            return self._vals.get(_label_key(labels), 0.0)

    def samples(self) -> Iterable[Sample]:
        with self._lock:
            vals = dict(self._vals)
        for key, v in sorted(vals.items()):
            yield (self.name, "gauge", self.help, dict(key), v)


class Histogram:
    """Fixed-bucket latency histogram with quantile estimation.

    Buckets are cumulative (Prometheus ``le`` semantics); quantiles
    are estimated by linear interpolation inside the winning bucket —
    exact enough for p50/p99 dashboards at O(len(buckets)) memory,
    which is the point (a reservoir would be per-request state)."""

    def __init__(self, name: str, help: str = "",
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        self.bounds = tuple(sorted(buckets))
        self._lock = threading.Lock()
        # per-label-set: [bucket counts..., +Inf count], sum
        self._counts: Dict[tuple, List[int]] = {}
        self._sums: Dict[tuple, float] = {}

    def observe(self, v: float, **labels) -> None:
        key = _label_key(labels)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * (len(self.bounds) + 1)
                self._sums[key] = 0.0
            counts[i] += 1
            self._sums[key] += v

    def quantile(self, q: float, **labels) -> float:
        """Estimated q-quantile (0..1) for one label set; 0.0 with no
        observations."""
        key = _label_key(labels)
        with self._lock:
            counts = list(self._counts.get(key, ()))
        total = sum(counts)
        if not total:
            return 0.0
        target = q * total
        seen = 0.0
        for i, c in enumerate(counts):
            if not c:
                continue
            if seen + c >= target:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = (self.bounds[i] if i < len(self.bounds)
                      else self.bounds[-1])
                frac = (target - seen) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            seen += c
        return self.bounds[-1]

    def count(self, **labels) -> int:
        with self._lock:
            return sum(self._counts.get(_label_key(labels), ()))

    def counts(self, **labels) -> List[int]:
        """One label set's raw bucket-count vector (bucket order =
        ``self.bounds`` + the +Inf overflow) — what the SLO plane
        snapshots so sliding windows read the SAME books as
        ``/metrics`` (obs/slo.py)."""
        with self._lock:
            return list(self._counts.get(
                _label_key(labels), [0] * (len(self.bounds) + 1)))

    def samples(self) -> Iterable[Sample]:
        with self._lock:
            counts = {k: list(v) for k, v in self._counts.items()}
            sums = dict(self._sums)
        for key, cs in sorted(counts.items()):
            labels = dict(key)
            cum = 0
            for bound, c in zip(self.bounds, cs):
                cum += c
                yield (f"{self.name}_bucket", "histogram", self.help,
                       {**labels, "le": repr(float(bound))}, float(cum))
            cum += cs[-1]
            yield (f"{self.name}_bucket", "histogram", self.help,
                   {**labels, "le": "+Inf"}, float(cum))
            yield (f"{self.name}_count", "histogram", self.help,
                   labels, float(cum))
            yield (f"{self.name}_sum", "histogram", self.help,
                   labels, round(sums[key], 6))


class MetricsRegistry:
    """Named metrics plus scrape-time collectors (module docstring).
    One instance per server — tests get isolation for free."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: "Dict[str, object]" = {}
        self._collectors: List[Callable[[], Iterable[Sample]]] = []

    # -- owned metrics -------------------------------------------------
    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help), Counter)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help), Gauge)

    def histogram(self, name: str, help: str = "",
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._get(name, lambda: Histogram(name, help, buckets),
                         Histogram)

    def _get(self, name, make, want):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = make()
            elif not isinstance(m, want):
                raise TypeError(f"metric {name!r} already registered as "
                                f"{type(m).__name__}")
            return m

    # -- collectors ----------------------------------------------------
    def register_collector(
            self, fn: Callable[[], Iterable[Sample]]) -> None:
        with self._lock:
            self._collectors.append(fn)

    def unregister_collector(
            self, fn: Callable[[], Iterable[Sample]]) -> None:
        """Remove a collector (a stopped server must not keep feeding
        — and being pinned by — a registry that outlives it); unknown
        collectors are ignored."""
        with self._lock:
            try:
                self._collectors.remove(fn)
            except ValueError:
                pass

    # -- rendering -----------------------------------------------------
    def collect(self) -> List[Sample]:
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        out: List[Sample] = []
        for m in metrics:
            out.extend(m.samples())
        for fn in collectors:
            try:
                out.extend(fn())
            except Exception:  # noqa: BLE001 — one broken collector
                continue       # must not take the scrape down
        return out

    def render(self) -> str:
        """Prometheus plaintext exposition (0.0.4)."""
        lines: List[str] = []
        seen_meta = set()
        for name, mtype, help_, labels, value in self.collect():
            base = name
            for suffix in ("_bucket", "_count", "_sum"):
                if mtype == "histogram" and name.endswith(suffix):
                    base = name[: -len(suffix)]
                    break
            if base not in seen_meta:
                seen_meta.add(base)
                if help_:
                    lines.append(f"# HELP {base} {help_}")
                lines.append(f"# TYPE {base} {mtype}")
            if labels:
                lbl = ",".join(
                    f'{k}="{v}"' for k, v in sorted(labels.items()))
                lines.append(f"{name}{{{lbl}}} {_fmt(value)}")
            else:
                lines.append(f"{name} {_fmt(value)}")
        return "\n".join(lines) + "\n"

    def values(self) -> Dict[str, float]:
        """Flat ``name{labels}`` → value map (the reconciliation tests'
        view; label-free names map bare)."""
        out: Dict[str, float] = {}
        for name, _t, _h, labels, value in self.collect():
            if labels:
                lbl = ",".join(
                    f'{k}="{v}"' for k, v in sorted(labels.items()))
                out[f"{name}{{{lbl}}}"] = value
            else:
                out[name] = value
        return out


def _fmt(v: float) -> str:
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def parse_exposition(text: str) -> Dict[str, float]:
    """Inverse of :meth:`MetricsRegistry.render` for tests and the
    stats-reconciliation check: ``name{labels}`` → value."""
    out: Dict[str, float] = {}
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln or ln.startswith("#"):
            continue
        name, _, val = ln.rpartition(" ")
        try:
            out[name] = float(val)
        except ValueError:
            continue
    return out


class MetricsServer:
    """Tiny plaintext ``GET /metrics`` endpoint on a daemon thread.

    Deliberately not a framework: one ``ThreadingHTTPServer`` whose
    only routes are ``/metrics`` (the registry exposition) and
    ``/healthz``; everything else is 404.  Bound to loopback by
    default, ephemeral port supported (``port=0`` → ``self.port``)."""

    def __init__(self, registry: MetricsRegistry,
                 host: str = "127.0.0.1", port: int = 0):
        self.registry = registry
        self.host = host
        self.port = port
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsServer":
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        registry = self.registry

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server's contract
                if self.path.split("?")[0] == "/metrics":
                    body = registry.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                elif self.path == "/healthz":
                    body = b"ok\n"
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                else:
                    body = b"not found\n"
                    self.send_response(404)
                    self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *_args) -> None:
                return  # scrapes must not spam the server's stderr

        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        kwargs={"poll_interval": 0.2},
                                        daemon=True, name="qsm-metrics")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(2.0)
