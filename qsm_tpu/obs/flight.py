"""Crash flight recorder — the last N events, dumped when it matters.

"The pool wedged overnight" used to be a log-archaeology session: the
trace log (when enabled) holds millions of lines, the stats counters
say only *that* something shed, and a SIGKILLed worker leaves no
in-process evidence at all.  The recorder keeps a FIXED-SIZE in-memory
ring of recent span events per component (``serve``, ``pool``,
``admission``, ``cache``, ``failover``, ``fault`` — the prefix before
the first ``.`` in the event name) and dumps the whole ring atomically
to ``FLIGHT_<ts>_<reason>.json`` when one of the trigger conditions
fires:

* a pool worker is shed (crash/wedge/SIGKILL) or a spec is quarantined;
* a SHED storm (``storm_threshold`` sheds inside ``storm_window_s``);
* a fault-plane rule fires (``QSM_TPU_FAULTS`` hits in production mean
  someone is fault-drilling the live server — worth an artifact);
* ``CheckServer.stop()`` (the post-mortem baseline: what was in flight
  at teardown).

Dumps are rate-limited (``min_interval_s``) so a crash loop produces
a bounded number of artifacts, and written through the
``resilience.checkpoint.atomic_write_json`` rails — a dump can never
be torn.  The ring records even when JSONL tracing is off: it is
O(max_events) memory and append-only cheap, and the whole point is
having evidence precisely when nobody thought to enable tracing.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional


class FlightRecorder:
    """See module docstring.  Thread-safe; one instance per server."""

    def __init__(self, dir: str, *, max_events: int = 256,
                 min_interval_s: float = 5.0,
                 storm_threshold: int = 32,
                 storm_window_s: float = 10.0):
        self.dir = dir
        self.max_events = max(8, int(max_events))
        self.min_interval_s = min_interval_s
        self.storm_threshold = max(1, int(storm_threshold))
        self.storm_window_s = storm_window_s
        self._lock = threading.Lock()
        self._rings: Dict[str, Deque[dict]] = {}
        # bounded even before the window prune runs (QSM-SERVE-UNBOUNDED
        # discipline): the threshold is the only length that matters
        self._sheds: Deque[float] = deque(
            maxlen=max(self.storm_threshold * 4, 64))
        # None, not 0.0: ``time.monotonic()`` has an arbitrary epoch
        # (boot time on Linux), so a 0.0 sentinel wrongly suppresses
        # the FIRST dump whenever uptime < min_interval_s
        self._last_dump: Optional[float] = None
        self.recorded = 0
        self.dumps = 0
        self.dumps_suppressed = 0
        self.last_dump_path: Optional[str] = None
        self.last_dump_reason: Optional[str] = None

    # ------------------------------------------------------------------
    def record(self, doc: dict) -> None:
        """Ring-append one span event under its component (the event
        name's prefix).  O(1), bounded, never raises."""
        component = str(doc.get("name", "?")).split(".", 1)[0]
        with self._lock:
            ring = self._rings.get(component)
            if ring is None:
                ring = self._rings[component] = deque(
                    maxlen=self.max_events)
            ring.append(doc)
            self.recorded += 1

    def note_shed(self) -> Optional[str]:
        """Count one SHED toward the storm window; returns the dump
        path when this shed tipped the threshold.  The window clears
        only when a dump actually LANDED: a storm that trips inside
        another dump's rate-limit shadow keeps re-arming on every
        further shed and produces its artifact the moment the limiter
        opens, instead of silently resetting (docs/SERVING.md promises
        "a SHED storm fires a dump by itself")."""
        now = time.monotonic()
        with self._lock:
            self._sheds.append(now)
            while self._sheds and now - self._sheds[0] > self.storm_window_s:
                self._sheds.popleft()
            storm = len(self._sheds) >= self.storm_threshold
        if not storm:
            return None
        path = self.dump("shed_storm")
        if path is not None:
            with self._lock:
                self._sheds.clear()  # one dump per storm, not per shed
        return path

    # ------------------------------------------------------------------
    def dump(self, reason: str, extra: Optional[dict] = None,
             force: bool = False) -> Optional[str]:
        """Write the rings to ``FLIGHT_<unix_ms>_<reason>.json``
        atomically; returns the path, or None when rate-limited (the
        crash-loop bound) or the directory is unwritable."""
        now = time.monotonic()
        with self._lock:
            if (not force and self._last_dump is not None
                    and now - self._last_dump < self.min_interval_s):
                self.dumps_suppressed += 1
                return None
            self._last_dump = now
            rings = {k: list(v) for k, v in self._rings.items()}
            n_dump = self.dumps
        doc = {
            "artifact": "qsm_tpu_flight",
            "version": 1,
            "reason": reason,
            "captured_unix": round(time.time(), 3),
            "events": sum(len(v) for v in rings.values()),
            "components": {k: v for k, v in sorted(rings.items())},
        }
        if extra:
            doc["extra"] = extra
        ts_ms = int(time.time() * 1000)
        path = os.path.join(
            self.dir, f"FLIGHT_{ts_ms}_{n_dump}_{reason}.json")
        try:
            os.makedirs(self.dir, exist_ok=True)
            from ..resilience.checkpoint import atomic_write_json

            atomic_write_json(path, doc, indent=1)
        except OSError:
            return None  # an unwritable dir degrades the recorder only
        with self._lock:
            self.dumps += 1
            self.last_dump_path = path
            self.last_dump_reason = reason
        return path

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "dir": self.dir,
                "recorded": self.recorded,
                "rings": {k: len(v)
                          for k, v in sorted(self._rings.items())},
                "dumps": self.dumps,
                "dumps_suppressed": self.dumps_suppressed,
                "last_dump": self.last_dump_path,
                "last_reason": self.last_dump_reason,
            }


def load_dump(path: str) -> dict:
    """Read one flight dump (tests and the debugging walkthrough)."""
    import json

    with open(path) as f:
        return json.load(f)


def recent_events(dump: dict, component: Optional[str] = None
                  ) -> List[dict]:
    """Flatten a dump's per-component rings back into one list
    (optionally one component), preserving per-ring order."""
    comps = dump.get("components", {})
    if component is not None:
        return list(comps.get(component, ()))
    out: List[dict] = []
    for _k, ring in sorted(comps.items()):
        out.extend(ring)
    return out
