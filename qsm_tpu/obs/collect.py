"""Fleet-wide span collection — scrape any process's span log.

PR 11 gave every process a span log; PRs 12–14 made the system a fleet
— so the causal story of one request is now scattered across many logs
on many hosts.  This module is the collection plane that stitches it:

* :func:`read_span_page` — one cursor-paged, bounded, idempotent read
  of a span log (the serve plane exposes it as the ``obs.spans``
  protocol op): the cursor identifies WHERE in WHICH file the last
  page ended, so a re-scrape ships zero duplicate events, a scrape
  across the log's one-file rotation keeps reading from the rotated
  predecessor, and a scrape that outlived two rotations reports an
  honest ``gap`` instead of silently re-shipping or losing order.
* :class:`SpanCollector` — the router-side consumer: per-node cursors
  persisted atomically (a restarted router resumes where it stopped,
  re-shipping nothing), pages bounded per node per sweep, every
  collected event stamped with its source ``node``, appended to ONE
  rotation-bounded collected log that ``qsm-tpu trace <id> --addr``
  reconstructs whole-fleet causal trees from.

Cursor semantics (docs/OBSERVABILITY.md "Fleet"): a cursor is
``{"sig": <first-line fingerprint of the file being read>,
"off": <byte offset past the last complete line consumed>}``.  The
live log's first line is stable for the file's whole life (the tracer
appends, never truncates — restarts keep appending), so ``sig`` is a
rotation-epoch identity that needs no server-side state: when the live
file's sig no longer matches, the cursor's file is either the ``.1``
predecessor (keep draining it, then hop to the live file at offset 0)
or gone (two rotations — the page answers ``gap: true`` and restarts
from the oldest surviving file).  Torn tails (a kill mid-write) are
never consumed: the offset only ever advances past complete lines, so
the next page re-reads the completed line, not half of it.

Clock skew policy: collection NEVER orders events by wall clock across
processes.  Causality comes from the propagated ``trace``/``span``/
``parent`` ids (the router stamps its ``node.dispatch`` span id into
each sub-request's ``parent`` field, so a node's ``request`` root pins
under the router edge that caused it); per-file emit order is kept as
the sibling order.  :func:`~qsm_tpu.obs.trace.trace_closure` then
rebuilds one cross-process tree purely from those edges.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Callable, Dict, List, Optional

# one obs.spans page: hard bound on events however large the caller's
# ask (a scrape must never balloon one response past what a LineChannel
# read comfortably buffers)
MAX_PAGE_EVENTS = 2048
DEFAULT_PAGE_EVENTS = 512
# collected-log rotation bound (one live file + one predecessor, the
# tracer's own discipline): fleet-wide collection is bounded disk
# however long the router lives
DEFAULT_COLLECTED_BYTES = 32 * 1024 * 1024


def _first_line_sig(path: str) -> str:
    """Fingerprint of a span file's first complete line — stable for
    the file's whole life (append-only), changed exactly at rotation.
    "" = no file, or no complete first line yet."""
    try:
        with open(path, "rb") as f:
            head = f.read(4096)
    except OSError:
        return ""
    nl = head.find(b"\n")
    if nl < 0:
        return ""
    return hashlib.sha256(head[:nl]).hexdigest()[:16]


def _read_complete_lines(path: str, off: int, max_events: int):
    """Up to ``max_events`` complete JSON lines from ``path`` past byte
    ``off``.  Returns ``(events, new_off, drained)`` — ``new_off``
    advances only past COMPLETE lines (a torn tail is re-read next
    page, never half-consumed) and ``drained`` says the file holds no
    further complete line right now."""
    events: List[dict] = []
    try:
        with open(path, "rb") as f:
            f.seek(off)
            buf = b""
            new_off = off
            while len(events) < max_events:
                nl = buf.find(b"\n")
                if nl < 0:
                    chunk = f.read(65536)
                    if not chunk:
                        return events, new_off, True
                    buf += chunk
                    continue
                line, buf = buf[:nl], buf[nl + 1:]
                new_off += nl + 1
                if not line.strip():
                    continue
                try:
                    events.append(json.loads(line.decode()))
                except (ValueError, UnicodeDecodeError):
                    continue  # a garbled line is droppable, not fatal
            drained = not buf and not f.read(1)
            return events, new_off, drained
    except OSError:
        return [], off, True


def read_span_page(path: str, cursor: Optional[dict] = None,
                   max_events: int = DEFAULT_PAGE_EVENTS) -> dict:
    """One bounded page of span events from a rotating span log (module
    docstring has the cursor semantics).  Pure function over the files
    — the serving side keeps no per-scraper state, which is what makes
    the op idempotent and restart-safe on BOTH ends."""
    max_events = max(1, min(int(max_events), MAX_PAGE_EVENTS))
    prev = f"{path}.1"
    live_sig = _first_line_sig(path)
    prev_sig = _first_line_sig(prev)
    gap = False
    sig = off = None
    if cursor is not None:
        sig = str(cursor.get("sig") or "")
        off = max(0, int(cursor.get("off") or 0))
    if cursor is not None and not sig:
        # a cursor minted against a live file that had no identity yet
        # (scraped mid-rotation, or before the first event): we are
        # positioned at the live head — restarting from the
        # predecessor here would re-ship everything already consumed
        read_path, sig = path, live_sig
    elif sig and sig == live_sig:
        read_path = path
    elif sig and sig == prev_sig:
        read_path = prev
    else:
        # first scrape (no cursor), or the cursor's file rotated away:
        # start from the oldest surviving file.  A lost file is an
        # honest gap, never a silent re-ship-from-zero of data we may
        # have already consumed elsewhere.
        gap = bool(sig)
        read_path, sig, off = ((prev, prev_sig, 0) if prev_sig
                               else (path, live_sig, 0))
    events, new_off, drained = _read_complete_lines(read_path, off,
                                                    max_events)
    if drained and read_path == prev:
        # predecessor exhausted: the next page starts the live file
        out_cursor = {"sig": live_sig, "off": 0}
        more = bool(live_sig)
    else:
        out_cursor = {"sig": sig, "off": new_off}
        more = not drained
    return {"events": events, "cursor": out_cursor, "more": more,
            "gap": gap}


def span_page_response(tracer, req: dict) -> dict:
    """The ``obs.spans`` op's whole answer over one process's tracer —
    THE shared implementation (serve/server.py and fleet/router.py
    both delegate here, so the cursor semantics cannot drift between
    the two surfaces)."""
    if tracer.path is None:
        return {"id": req.get("id"), "ok": True, "enabled": False,
                "events": [], "cursor": req.get("cursor"),
                "more": False}
    tracer.flush()
    cursor = req.get("cursor")
    page = read_span_page(
        tracer.path, cursor if isinstance(cursor, dict) else None,
        max_events=int(req.get("max_events") or DEFAULT_PAGE_EVENTS))
    return {"id": req.get("id"), "ok": True, "enabled": True, **page}


class _RotatingSink:
    """Pre-serialized JSONL appender with the tracer's one-predecessor
    rotation bound — the collected log's disk is O(2 × max_bytes)
    however long collection runs."""

    def __init__(self, path: str, max_bytes: int):
        self.path = path
        self.max_bytes = max(4096, int(max_bytes))
        self._f = None
        self._bytes = 0
        self._closed = False
        self.rotations = 0

    def write_line(self, line: str) -> None:
        if self._closed:
            # a sweep racing close() (router stop joins threads with a
            # bound shorter than a fetch timeout) must not silently
            # reopen the file and leak the handle past teardown
            return
        try:
            if self._f is None:
                os.makedirs(os.path.dirname(self.path) or ".",
                            exist_ok=True)
                self._f = open(self.path, "a")
                self._bytes = self._f.tell()
            if self._bytes + len(line) > self.max_bytes:
                self._f.close()
                os.replace(self.path, f"{self.path}.1")
                self._f = open(self.path, "a")
                self._bytes = 0
                self.rotations += 1
            self._f.write(line)
            self._bytes += len(line)
        except OSError:
            self._f = None  # full disk degrades collection only

    def flush(self) -> None:
        if self._f is not None:
            try:
                self._f.flush()
            except OSError:
                pass

    def close(self) -> None:
        self._closed = True
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None


class SpanCollector:
    """The fleet-wide collected span log (module docstring).

    ``dir`` holds the collected log (``collected.jsonl`` + its one
    rotation predecessor) and the persisted per-node cursors
    (``cursors.json``, written atomically once per sweep) — a
    restarted router resumes every node exactly where it stopped and
    re-ships zero events.  One sweep is bounded by
    ``max_pages_per_node × page_events`` events per node; a backlog
    drains over beats, it never makes one sweep unbounded."""

    CURSORS = "cursors.json"
    LOG = "collected.jsonl"

    def __init__(self, dir: str, *,
                 max_bytes: int = DEFAULT_COLLECTED_BYTES,
                 page_events: int = DEFAULT_PAGE_EVENTS,
                 max_pages_per_node: int = 8):
        self.dir = dir
        self.page_events = max(1, min(int(page_events), MAX_PAGE_EVENTS))
        self.max_pages_per_node = max(1, int(max_pages_per_node))
        self._lock = threading.Lock()
        self._sink = _RotatingSink(os.path.join(dir, self.LOG),
                                   max_bytes)
        self._cursors: Dict[str, dict] = {}
        self._load_cursors()
        self.sweeps = 0
        self.events_collected = 0
        self.pages = 0
        self.gaps = 0
        self.node_failures = 0

    @property
    def out_path(self) -> str:
        return self._sink.path

    def _load_cursors(self) -> None:
        try:
            with open(os.path.join(self.dir, self.CURSORS)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return
        if isinstance(doc, dict):
            self._cursors = {str(k): v for k, v in doc.items()
                             if isinstance(v, dict)}

    def _save_cursors(self) -> None:
        from ..resilience.checkpoint import atomic_write_json

        try:
            os.makedirs(self.dir, exist_ok=True)
            atomic_write_json(os.path.join(self.dir, self.CURSORS),
                              self._cursors)
        except OSError:
            pass  # unpersisted cursors cost a re-ship, never the sweep

    # ------------------------------------------------------------------
    def sweep(self, node_ids, fetch: Callable[[str, Optional[dict],
                                               int], dict]) -> dict:
        """One collection sweep: pull bounded pages from each node via
        ``fetch(node_id, cursor, max_events) -> obs.spans response``
        (which may raise — a dead node costs its own bounded fetch,
        never the sweep).  Events are stamped with their source node
        and appended to the collected log; cursors persist once at the
        end.  The lock guards only sink/cursor mutation, never a
        network fetch — a wedged node must not block ``snapshot()``
        (the router's stats op) for a whole fetch timeout."""
        collected = gaps = pages = failures = 0
        for nid in node_ids:
            with self._lock:
                cursor = self._cursors.get(nid)
            for _page in range(self.max_pages_per_node):
                try:
                    resp = fetch(nid, cursor, self.page_events)
                except Exception:  # noqa: BLE001 — scrape, don't die
                    failures += 1
                    break
                if not resp.get("ok", True) or not resp.get(
                        "enabled", True):
                    break
                if resp.get("gap"):
                    gaps += 1
                cursor = resp.get("cursor") or cursor
                with self._lock:
                    for ev in resp.get("events") or []:
                        if not isinstance(ev, dict):
                            continue
                        if "node" not in ev:
                            ev = {**ev, "node": nid}
                        self._sink.write_line(json.dumps(ev) + "\n")
                        collected += 1
                    self._cursors[nid] = cursor
                pages += 1
                if not resp.get("more"):
                    break
        with self._lock:
            self._sink.flush()
            self._save_cursors()
            self.sweeps += 1
            self.events_collected += collected
            self.pages += pages
            self.gaps += gaps
            self.node_failures += failures
        return {"events": collected, "pages": pages, "gaps": gaps,
                "node_failures": failures}

    def close(self) -> None:
        with self._lock:
            self._sink.close()

    def snapshot(self) -> dict:
        with self._lock:
            return {"dir": self.dir,
                    "sweeps": self.sweeps,
                    "events_collected": self.events_collected,
                    "pages": self.pages,
                    "gaps": self.gaps,
                    "node_failures": self.node_failures,
                    "rotations": self._sink.rotations,
                    "nodes": sorted(self._cursors)}
