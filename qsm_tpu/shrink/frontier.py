"""Shrink-frontier generation — every candidate of one greedy round.

The reference's QuickCheck shrinker minimizes the *program* and re-runs
it; this plane minimizes the *history* itself — the failing artifact —
so external traces (``qsm-tpu check``/``submit`` inputs, serve-plane
requests) shrink without a scheduler or SUT in the loop.  A candidate is
always a **transformation of the failing history** whose verdict is
decided by re-checking, never assumed:

* **op-subset shrinks** — drop ops, keeping original timestamps (the
  real-time precedence order of the survivors is a sub-order of the
  original's — ``History.subhistory``):

  - *drop-key*: with a VALIDATED per-key projection (``CmdSig.proj``,
    core/spec.py — exactly the gate ops/pcomp.py trusts), every op of
    one partition key is dropped at once — the coarsest sound subset
    axis, and the reason a 256-op kv counterexample collapses in a few
    rounds instead of ~256;
  - *drop-pid*: every op of one pid (the racy pair usually lives in two
    pids; the other fourteen are noise);
  - *drop-one*: each single op — the axis 1-minimality is DEFINED on.

* **schedule shrinks** — commute one adjacent, non-overlapping pair of
  ops toward a canonical order: ops ``i → j`` consecutive in invocation
  order with ``resp_i < inv_j`` swap their time intervals when ``i``
  sorts after ``j`` by ``(cmd, arg, pid, resp)``.  Size stays equal but
  the inversion count against the canonical order strictly drops, so
  greedy acceptance terminates (shrinker.py's lexicographic measure:
  ``(n_ops, inversions)``).

Every generator here is a bounded loop over the ops of ONE history —
the frontier of an ``n``-op history is at most ``keys + pids + n +
(n-1)`` candidates before dedup, and :func:`shrink_frontier` caps it
explicitly (``max_lanes``) rather than trusting arithmetic: the
QSM-SHRINK-UNBOUNDED lint rule (analysis/shrink_passes.py) gates the
code-level twin of this promise.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Tuple

from ..core.history import History
from ..core.spec import Spec, projection_report


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One frontier member: the transformed history plus provenance.

    ``order`` is the canonical sort key of the whole frontier —
    ``(n_ops, kind_rank, index)`` — so "the smallest still-failing
    candidate" means the same history on every engine, platform and
    serve path (decomposed == undecomposed shrink parity rests on it).
    """

    history: History
    kind: str       # "drop-key" | "drop-pid" | "drop-one" | "swap"
    detail: int     # key / pid / op index / left-op index

    @property
    def order(self) -> Tuple[int, int, int]:
        rank = {"drop-key": 0, "drop-pid": 1, "drop-one": 2,
                "swap": 3}[self.kind]
        return (len(self.history), rank, self.detail)


def _canon_key(op) -> Tuple[int, int, int, int]:
    return (op.cmd, op.arg, op.pid, op.resp)


def inversions(history: History) -> int:
    """Adjacent-comparable inversion count against the canonical op
    order — the schedule-shrink half of the termination measure.  O(n²)
    on histories that are already small by the time swaps matter."""
    keys = [_canon_key(o) for o in history.ops]
    return sum(1 for i in range(len(keys))
               for j in range(i + 1, len(keys)) if keys[i] > keys[j])


def drop_key_candidates(spec: Spec, history: History
                        ) -> Iterator[Candidate]:
    """One candidate per partition key (all that key's ops dropped) —
    only when the spec's projection VALIDATES (an invalid declaration
    must never steer shrinking: the key axis would group ops
    arbitrarily) and the history touches more than one key."""
    if projection_report(spec):
        return
    from ..ops.pcomp import history_keys

    try:
        sorted_keys = history_keys(spec, history)
    except ValueError:
        return  # runtime non-totality: refuse the axis, like pcomp
    if len(sorted_keys) < 2:
        return
    groups: dict = {k: [] for k in sorted_keys}
    for j, op in enumerate(history.ops):
        groups[spec.partition_key(op.cmd, op.arg)].append(j)
    all_idx = set(range(len(history.ops)))
    for key in sorted_keys:
        yield Candidate(history.subhistory(all_idx - set(groups[key])),
                        "drop-key", key)


def drop_pid_candidates(history: History) -> Iterator[Candidate]:
    pids: dict = {}
    for j, op in enumerate(history.ops):
        pids.setdefault(op.pid, []).append(j)
    if len(pids) < 2:
        return
    all_idx = set(range(len(history.ops)))
    for pid in sorted(pids):
        yield Candidate(history.subhistory(all_idx - set(pids[pid])),
                        "drop-pid", pid)


def drop_one_candidates(history: History) -> Iterator[Candidate]:
    n = len(history.ops)
    for j in range(n):
        yield Candidate(
            history.subhistory([i for i in range(n) if i != j]),
            "drop-one", j)


def swap_candidates(history: History) -> Iterator[Candidate]:
    """Commute adjacent non-overlapping pairs toward canonical order
    (module docstring).  The swapped pair exchanges time INTERVALS, so
    the result is a history of the same ops under a different schedule;
    pending ops never swap (a pending op precedes nothing, so there is
    no adjacency to commute)."""
    ops = history.ops
    for i in range(len(ops) - 1):
        a, b = ops[i], ops[i + 1]
        if a.is_pending or b.is_pending:
            continue
        if not a.response_time < b.invoke_time:
            continue  # overlapping: no real-time order to commute
        if _canon_key(a) <= _canon_key(b):
            continue  # already canonical: swapping would not shrink
        swapped = list(ops)
        swapped[i] = dataclasses.replace(
            b, invoke_time=a.invoke_time, response_time=a.response_time)
        swapped[i + 1] = dataclasses.replace(
            a, invoke_time=b.invoke_time, response_time=b.response_time)
        yield Candidate(History(swapped, seed=history.seed,
                                program_id=history.program_id),
                        "swap", i)


def shrink_frontier(spec: Spec, history: History,
                    max_lanes: int = 512,
                    schedule: bool = True,
                    ) -> Tuple[List[Candidate], int]:
    """The whole frontier of one round, deduped by fingerprint and
    sorted by :attr:`Candidate.order` (smallest-first — the greedy
    selection rule), capped at ``max_lanes``.

    Returns ``(candidates, truncated)``: a nonzero ``truncated`` count
    is surfaced in the shrinker's ``why`` — a bounded frontier must say
    it was bounded, never silently narrow the search (the no-silent-caps
    discipline)."""
    seen = {history.fingerprint()}
    out: List[Candidate] = []
    gens = [drop_key_candidates(spec, history),
            drop_pid_candidates(history),
            drop_one_candidates(history)]
    if schedule:
        gens.append(swap_candidates(history))
    for gen in gens:
        for cand in gen:
            fp = cand.history.fingerprint()
            if fp in seen:
                continue
            seen.add(fp)
            out.append(cand)
    out.sort(key=lambda c: c.order)
    truncated = max(0, len(out) - max_lanes)
    return out[:max_lanes], truncated
