"""qsm_tpu.shrink — the batched shrink plane (counterexample minimization).

The source paper's fifth capability: QuickCheck shrinking re-checks
thousands of candidate histories per failure, one at a time on CPU —
precisely the workload the batched checkers exist for.  This package
minimizes a failing HISTORY (not the program): the whole shrink frontier
— op-subset shrinks (drop-one / drop-pid / drop-key via the validated
``KeyProj`` projection) and schedule shrinks (commute adjacent
non-overlapping ops) — is generated host-side, deduped against a
fingerprint memo riding the serve verdict cache's row format, decided in
ONE planned dispatch per round, and recursed greedily on the smallest
still-failing candidate.  The result is a 1-MINIMAL history (every
further single-op drop passes) plus a certificate — one
``verify_witness``-replayable linearization per drop-one neighbor — so
the minimization is audited, not trusted (docs/SHRINK.md).

Surfaces: ``shrink_history`` (in-process), the ``shrink`` serve verb
(qsm_tpu/serve — frontier lanes ride the shared micro-batcher and bank
in the verdict cache), the ``qsm-tpu shrink`` CLI subcommand, and
``shrink_*`` counters in ``SearchStats`` / bench rows.
"""

from .frontier import Candidate, inversions, shrink_frontier
from .shrinker import (ShrinkResult, Shrinker, collect_shrink_stats,
                       minimality_certificate, shrink_history,
                       verify_certificate)

__all__ = [
    "Candidate",
    "ShrinkResult",
    "Shrinker",
    "collect_shrink_stats",
    "inversions",
    "minimality_certificate",
    "shrink_frontier",
    "shrink_history",
    "verify_certificate",
]
