"""Greedy frontier-at-once history minimization.

The loop (docs/SHRINK.md):

1. decide the INPUT history — anything but VIOLATION returns unshrunken
   (the shrinker minimizes counterexamples, it never manufactures them);
2. generate the whole shrink frontier (frontier.py), answer what the
   fingerprint memo already knows, decide every remaining candidate in
   ONE batched engine dispatch;
3. step to the SMALLEST still-failing candidate (first VIOLATION in the
   frontier's canonical order) and recurse; a same-size schedule
   candidate is only ever reachable when no strictly smaller candidate
   fails, and it strictly reduces the inversion count — the measure
   ``(n_ops, inversions)`` drops lexicographically every accepted round,
   so the greedy recursion TERMINATES without trusting ``max_rounds``;
4. terminate when no candidate fails: every single-op drop now passes
   (or is honestly ``undecided_neighbors``-counted), i.e. the result is
   1-MINIMAL, and the certificate phase turns that claim into proof —
   one ``check_witness`` per drop-one neighbor (stitched through the
   per-key split when it pays, plain otherwise), each replayable by
   ``verify_witness`` with no search trusted.

Soundness of greedy recursion: every accepted step is a history the
engine DECIDED to be a VIOLATION — nothing is inferred from the parent's
verdict (an op-subset of a violating history may well be linearizable,
which is exactly why the frontier is re-checked).  The memo keys
candidates by the serve verdict cache's row identity
(``serve.cache.fingerprint_key``), so a sub-history reachable through
two different drop paths — or the same candidate re-generated in a later
round — is never re-checked, in-process or across serve requests.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..core.history import History
from ..core.spec import Spec, projection_report
from ..ops.backend import Verdict, verify_witness
from ..serve.cache import fingerprint_key
from .frontier import Candidate, shrink_frontier

# hard caps — backstops behind the lexicographic termination measure,
# never the primary bound (an n-op history can accept at most n
# size-reducing rounds plus O(n²) inversion-reducing swaps)
DEFAULT_MAX_ROUNDS = 256
DEFAULT_MAX_LANES = 512


@dataclasses.dataclass
class ShrinkResult:
    """The minimized history plus everything needed to audit it."""

    ok: bool                   # input was a VIOLATION; shrinking ran
    verdict: int               # verdict of the INPUT history
    history: History           # minimized (== input when not shrunk)
    initial_ops: int = 0
    final_ops: int = 0
    rounds: int = 0
    engine_calls: int = 0      # batched dispatches issued
    lanes_checked: int = 0     # candidate lanes those dispatches carried
    memo_hits: int = 0         # candidates answered without re-checking
    complete: bool = True      # False: deadline/cap/shed cut the loop
    one_minimal: bool = False  # every single-op drop decided LINEARIZABLE
    undecided_neighbors: int = 0  # drop-one neighbors left BUDGET_EXCEEDED
    why: List[str] = dataclasses.field(default_factory=list)
    # 1-minimality proof: per drop-one neighbor of the minimized history,
    # the witness of ITS linearizability — [{"drop": j, "witness": [...],
    # "stitched": bool, "verifies": bool}]; None when not requested or
    # the input never shrank
    certificate: Optional[List[dict]] = None

    @property
    def ratio(self) -> float:
        return (self.final_ops / self.initial_ops
                if self.initial_ops else 1.0)

    def search_stats(self):
        """The shrink plane's own cost record (search/stats.py):
        shrink_* counters the CLI/bench rows thread through."""
        from ..search.stats import SearchStats

        return SearchStats(
            engine="shrink",
            histories=self.lanes_checked,
            shrink_rounds=self.rounds,
            shrink_lanes=self.lanes_checked,
            shrink_memo_hits=self.memo_hits,
            # clamped to >= 1: 0 is the "never shrank" sentinel
            # (SearchStats.absorb's min-merge guard), and a 1024->2
            # shrink must not round into it
            shrink_ratio_pct=(max(1, round(100.0 * self.ratio))
                              if self.ok and self.initial_ops else 0),
        )


class Shrinker:
    """One shrink run: frontier generation + memoised batched deciding.

    ``decide(histories) -> verdict array | None`` is the ONLY engine
    access — in-process it wraps a planner-built backend
    (:func:`shrink_history`), in the serve plane it submits lanes
    through the shared micro-batcher (serve/server.py).  ``None`` means
    the decider shed (deadline/overload): the run stops honestly with
    best-so-far.  ``bank`` (optional, the serve verdict cache or any
    get/put of its row format) is consulted before dispatch and — when
    ``bank_put`` — fed after, so candidate verdicts persist across runs;
    the per-run memo additionally remembers BUDGET_EXCEEDED, which the
    bank refuses by design.
    """

    def __init__(self, spec: Spec,
                 decide: Callable[[Sequence[History]], Optional[np.ndarray]],
                 bank=None, bank_put: bool = True,
                 max_rounds: int = DEFAULT_MAX_ROUNDS,
                 max_lanes: int = DEFAULT_MAX_LANES,
                 deadline: Optional[float] = None,
                 schedule_shrinks: bool = True):
        self.spec = spec
        self.decide = decide
        self.bank = bank
        self.bank_put = bank_put
        self.max_rounds = max_rounds
        self.max_lanes = max_lanes
        self.deadline = deadline  # absolute time.monotonic() bound
        self.schedule_shrinks = schedule_shrinks
        self._memo: Dict[str, int] = {}   # fingerprint_key -> verdict
        self.rounds = 0
        self.engine_calls = 0
        self.lanes_checked = 0
        self.memo_hits = 0
        self.truncated = 0
        self.why: List[str] = []

    # ------------------------------------------------------------------
    def _timed_out(self) -> bool:
        return (self.deadline is not None
                and time.monotonic() >= self.deadline)

    def _verdicts(self, hists: Sequence[History]) -> Optional[List[int]]:
        """Memo-first batched deciding: one ``decide`` call for all
        misses; decided verdicts memo (and bank) so no fingerprint is
        ever dispatched twice."""
        keys = [fingerprint_key(self.spec, h) for h in hists]
        out: List[Optional[int]] = [None] * len(hists)
        miss_idx: List[int] = []
        for i, key in enumerate(keys):
            v = self._memo.get(key)
            if v is None and self.bank is not None:
                e = self.bank.get(key)
                if e is not None:
                    v = int(e.verdict)
            if v is not None:
                out[i] = v
                self.memo_hits += 1
            else:
                miss_idx.append(i)
        if miss_idx:
            decided = self.decide([hists[i] for i in miss_idx])
            if decided is None:
                return None  # the decider shed: stop with best-so-far
            self.engine_calls += 1
            self.lanes_checked += len(miss_idx)
            for i, v in zip(miss_idx, decided):
                v = int(v)
                out[i] = v
                self._memo[keys[i]] = v
                if self.bank is not None and self.bank_put:
                    self.bank.put(keys[i], v)
        return [int(v) for v in out]

    # ------------------------------------------------------------------
    def _bank_undecided(self, hists: List[History]) -> None:
        """Shrink seam (qsm_tpu/devq): a round's BUDGET_EXCEEDED
        candidates are exactly the lanes a seized device window can
        afford to decide — bank them so the next drain settles the
        frontier and the memo answers on a retried shrink.  Free (one
        global read) when no queue is configured."""
        from ..devq.queue import bank_histories, global_devq

        if global_devq() is None:
            return
        if getattr(self.spec, "spec_kwargs", None) is None:
            return  # not registry-reconstructible: the drain could
            # never rebuild this spec, so the item would be dead weight
        bank_histories(self.spec, hists, plane="shrink")

    # ------------------------------------------------------------------
    def run(self, history: History) -> ShrinkResult:
        first = self._verdicts([history])
        if first is None:
            return ShrinkResult(ok=False, verdict=int(Verdict.BUDGET_EXCEEDED),
                                history=history, complete=False,
                                why=self.why + ["shed before the input "
                                                "history was decided"])
        verdict = first[0]
        if verdict != int(Verdict.VIOLATION):
            self.why.append(
                "input history is not a VIOLATION "
                f"(verdict {Verdict(verdict).name}); nothing to minimize")
            return ShrinkResult(ok=False, verdict=verdict, history=history,
                                initial_ops=len(history),
                                final_ops=len(history), why=self.why,
                                engine_calls=self.engine_calls,
                                lanes_checked=self.lanes_checked,
                                memo_hits=self.memo_hits)
        best = history
        complete = True
        last_frontier: List[Candidate] = []
        last_verdicts: List[int] = []
        last_trunc = 0
        while self.rounds < self.max_rounds:
            if self._timed_out():
                complete = False
                self.why.append(
                    f"deadline fired after {self.rounds} round(s); "
                    "returning best-so-far")
                break
            cands, trunc = shrink_frontier(
                self.spec, best, max_lanes=self.max_lanes,
                schedule=self.schedule_shrinks)
            self.truncated += trunc
            last_trunc = trunc
            if trunc:
                self.why.append(
                    f"round {self.rounds + 1}: frontier truncated by "
                    f"{trunc} candidate(s) at max_lanes={self.max_lanes}")
            if not cands:
                break
            verdicts = self._verdicts([c.history for c in cands])
            if verdicts is None:
                complete = False
                self.why.append(
                    f"decider shed in round {self.rounds + 1}; "
                    "returning best-so-far")
                break
            self.rounds += 1
            last_frontier, last_verdicts = cands, verdicts
            undecided_now = [c.history for c, v in zip(cands, verdicts)
                             if v == int(Verdict.BUDGET_EXCEEDED)]
            if undecided_now:
                self._bank_undecided(undecided_now)
            fail = next((i for i, v in enumerate(verdicts)
                         if v == int(Verdict.VIOLATION)), None)
            if fail is None:
                break  # 1-minimal (modulo undecided neighbors)
            best = cands[fail].history
        else:
            complete = False
            self.why.append(f"round cap {self.max_rounds} reached; "
                            "returning best-so-far")
        # 1-minimality accounting over EVERY size-(n-1) candidate of the
        # final frontier, whatever axis generated it (a single-op pid's
        # drop-one neighbor dedupes into its drop-pid twin — same
        # history, same obligation); a truncated final frontier may have
        # cut size-(n-1) candidates entirely, so truncation forfeits the
        # claim outright rather than counting only what survived.
        # Counted ONLY on complete runs: an incomplete exit's
        # last_frontier may belong to a PREVIOUS best (the deadline
        # fired after an accepted step), and reporting another
        # history's undecided candidates as this one's neighbors would
        # be wrong data — incomplete runs already forfeit one_minimal.
        undecided = sum(
            1 for c, v in zip(last_frontier, last_verdicts)
            if len(c.history) == len(best) - 1
            and v == int(Verdict.BUDGET_EXCEEDED)) if complete else 0
        one_minimal = complete and undecided == 0 and last_trunc == 0
        if complete and undecided:
            self.why.append(
                f"{undecided} single-op-drop neighbor(s) stayed "
                "undecided (BUDGET_EXCEEDED): 1-minimality is not "
                "claimed")
        if complete and not undecided and last_trunc:
            self.why.append(
                f"final frontier truncated by {last_trunc} candidate(s) "
                f"at max_lanes={self.max_lanes}: some single-op drops "
                "were never checked, so 1-minimality is not claimed")
        return ShrinkResult(
            ok=True, verdict=verdict, history=best,
            initial_ops=len(history), final_ops=len(best),
            rounds=self.rounds, engine_calls=self.engine_calls,
            lanes_checked=self.lanes_checked, memo_hits=self.memo_hits,
            complete=complete, one_minimal=one_minimal,
            undecided_neighbors=undecided, why=self.why)


# ---------------------------------------------------------------------------
# certificates: the 1-minimality proof
# ---------------------------------------------------------------------------

def minimality_certificate(spec: Spec, history: History,
                           oracle=None, pcomp=None,
                           deadline: Optional[float] = None) -> List[dict]:
    """One witness per drop-one neighbor of ``history``: each neighbor is
    LINEARIZABLE (that is what 1-minimal MEANS) and the witness replays
    search-free through ``verify_witness`` — stitched through the
    per-key split when it pays (ops/pcomp.py), plain otherwise.  A
    neighbor the searches cannot decide (or a deadline cut) yields a
    ``{"undecided": True}`` row — the certificate never overstates."""
    from ..ops.pcomp import NotDecomposableError, split_gain
    from ..ops.wing_gong_cpu import WingGongCPU

    if oracle is None:
        oracle = WingGongCPU(memo=True)
    if pcomp is None and not projection_report(spec):
        from ..ops.pcomp import PComp

        try:
            pcomp = PComp(spec)
        except NotDecomposableError:
            pcomp = None
    rows: List[dict] = []
    n = len(history.ops)
    for j in range(n):
        if deadline is not None and time.monotonic() >= deadline:
            rows.append({"drop": j, "undecided": True,
                         "why": "deadline fired mid-certificate"})
            continue
        neighbor = history.subhistory([i for i in range(n) if i != j])
        stitched = False
        if pcomp is not None and split_gain(spec, neighbor):
            v, w = pcomp.check_witness(spec, neighbor)
            stitched = True
        else:
            v, w = oracle.check_witness(spec, neighbor)
        if int(v) != int(Verdict.LINEARIZABLE) or w is None:
            rows.append({"drop": j, "undecided": True,
                         "verdict": Verdict(int(v)).name})
            continue
        rows.append({"drop": j, "witness": [list(p) for p in w],
                     "stitched": stitched,
                     "verifies": verify_witness(spec, neighbor, w)})
    return rows


def verify_certificate(spec: Spec, history: History,
                       certificate: Sequence[dict],
                       reconfirm_violation: bool = True) -> dict:
    """Independent audit of a shrink result: replay every neighbor
    witness through ``verify_witness`` (no search trusted) and — when
    asked — re-find the minimized history's VIOLATION with a FRESH memo
    oracle.  The return block is what bench rows and tests assert on."""
    from ..ops.wing_gong_cpu import WingGongCPU

    n = len(history.ops)
    replayed = 0
    failed = 0
    undecided = 0
    covered = set()
    for row in certificate:
        j = row.get("drop")
        if row.get("undecided"):
            undecided += 1
            continue
        neighbor = history.subhistory([i for i in range(n) if i != j])
        if verify_witness(spec, neighbor, [tuple(p) for p in row["witness"]]):
            replayed += 1
            covered.add(j)
        else:
            failed += 1
    out = {
        "neighbors": n,
        "witnesses_replayed": replayed,
        "witnesses_failed": failed,
        "undecided": undecided,
        "one_minimal_proved": failed == 0 and covered == set(range(n)),
    }
    if reconfirm_violation:
        fresh = WingGongCPU(memo=True)
        out["violation_reconfirmed"] = int(
            fresh.check_histories(spec, [history])[0]
        ) == int(Verdict.VIOLATION)
    return out


# ---------------------------------------------------------------------------
# the in-process entry point
# ---------------------------------------------------------------------------

def shrink_history(spec: Spec, history: History, *,
                   backend=None, bank=None,
                   max_rounds: int = DEFAULT_MAX_ROUNDS,
                   max_lanes: int = DEFAULT_MAX_LANES,
                   deadline_s: Optional[float] = None,
                   certificate: bool = True,
                   schedule_shrinks: bool = True) -> ShrinkResult:
    """Minimize one failing history; the ``qsm-tpu shrink`` CLI and the
    bench drive exactly this.

    ``backend=None`` builds the planned host dispatch for THIS history's
    profile (search/planner.py ``build_host_backend``): ``PComp``
    outermost when the validated projection pays — every frontier
    candidate then decides as per-key sub-lanes in smaller compile
    buckets — else the ``FailoverBackend``-wrapped cpp→memo host ladder.
    ``bank``, when given, is a ``serve.cache.VerdictCache``(-shaped)
    store the memo rides across runs."""
    from ..search.planner import (build_host_backend, plan_search,
                                  profile_corpus)

    deadline = (time.monotonic() + deadline_s
                if deadline_s is not None else None)
    why: List[str] = []
    if backend is None:
        profile = profile_corpus([history], spec)
        plan = plan_search(spec, profile, platform="cpu")
        backend = build_host_backend(spec, plan)
        why.append(f"engine={getattr(backend, 'name', type(backend).__name__)}"
                   f" (plan {plan.name}: "
                   f"decompose_keys={'on' if plan.decompose_keys else 'off'})")

    def decide(hists: Sequence[History]):
        return backend.check_histories(spec, hists)

    shrinker = Shrinker(spec, decide, bank=bank,
                        max_rounds=max_rounds, max_lanes=max_lanes,
                        deadline=deadline,
                        schedule_shrinks=schedule_shrinks)
    shrinker.why.extend(why)
    res = shrinker.run(history)
    if certificate and res.ok and res.complete:
        res.certificate = minimality_certificate(spec, res.history,
                                                 deadline=deadline)
    # fold the engine's own cost record into the result's so callers
    # (CLI/bench) report one self-describing block
    res._engine = backend  # noqa: SLF001 — kept for stats collection
    return res


def collect_shrink_stats(res: ShrinkResult):
    """``SearchStats`` for a finished in-process run: the shrink_*
    counters plus the engine's absorbed search record."""
    from ..search.stats import collect_search_stats

    st = res.search_stats()
    engine = getattr(res, "_engine", None)
    if engine is not None:
        sub = collect_search_stats(engine)
        if sub is not None:
            st.engine = f"shrink({sub.engine})"
            st.absorb(sub)
    return st
