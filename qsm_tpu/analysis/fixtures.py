"""Seeded-bug fixtures — TRUE POSITIVES the lint gate must keep catching.

Each fixture plants exactly the class of defect one pass family exists
for; tests/test_lint.py asserts the analyzer flags each with the right
rule_id.  A lint whose true positives rot is a green light with the bulb
removed — these fixtures are the bulb check.  Nothing here is exported
through the package ``__init__`` and nothing in the production paths
imports this module.
"""

from __future__ import annotations

from ..models.cas import CAS, READ, WRITE, CasSpec
from ..ops.jax_kernel import JaxTPU


class ParityBrokenCasSpec(CasSpec):
    """Seeded bug for QSM-SPEC-PARITY: ``step_jax`` acks EVERY cas as
    successful (``resp == 1``) while ``step_py`` keeps the real
    compare — the exact divergence class where the device kernel would
    bless histories the oracle rejects."""

    name = "parity_broken_cas"

    def step_jax(self, state, cmd, arg, resp):
        import jax.numpy as jnp

        value = state[0]
        old = arg // self.n_values
        new = arg % self.n_values
        succ = value == old
        ok = jnp.where(
            cmd == READ, resp == value,
            jnp.where(cmd == WRITE, resp == 0, resp == 1))  # <-- bug
        new_value = jnp.where(
            cmd == WRITE, arg,
            jnp.where((cmd == CAS) & succ, new, value))
        return jnp.stack([new_value.astype(state.dtype)]), ok


class RetracingJaxTPU(JaxTPU):
    """Seeded bug for QSM-KERN-RETRACE: the chunk executable is keyed on
    a per-call nonce, so every batch re-jits the stepper — the
    silent-recompile failure mode that costs 20-40 s per call inside a
    real window."""

    name = "retracing_jax_tpu"

    def _chunk_fn(self, n_ops, batch, slots, chunk, donate=True):
        import jax

        nonce = len(self._compiled)  # <-- bug: per-call cache key
        _, run_one = self._stepper(n_ops, slots)

        def run_chunk(carry, cmd, arg, resp, valid, precedes):
            return run_one(carry, cmd, arg, resp, valid, precedes,
                           chunk=chunk)

        fn = jax.jit(jax.vmap(run_chunk, in_axes=(0, 0, 0, 0, 0, 0)))
        self._compiled[("chunk-nonce", nonce, n_ops, batch, slots,
                        chunk)] = fn
        return fn


class UnorderedSchedulerStub:
    """Seeded bug for the determinism passes: a delivery loop whose
    choice is fed from set iteration order (QSM-DET-SET-ITER), an
    unseeded module-level RNG (QSM-DET-RANDOM), a wall-clock read
    (QSM-DET-TIME) and an id()-keyed sort (QSM-DET-ID).  Never executed;
    tests point the sched AST pass at this file and assert every rule
    fires."""

    def __init__(self):
        self.pool = []

    def deliver_one(self):
        import random
        import time

        pending = set(self.pool)
        for inflight in pending:  # set order decides delivery
            if random.random() < 0.5:  # unseeded draw
                break
        k = int(time.time()) % max(len(self.pool), 1)  # clock-fed pick
        order = sorted(self.pool, key=id)  # address-ordered tiebreak
        return order[k]


class UnboundedDeviceProbeStub:
    """Seeded bug for the resilience passes: a bare ``jax.devices()``
    (QSM-RES-DEVICES — blocks forever on a wedged tunnel), a subprocess
    wait without a timeout (QSM-RES-SUBPROC) and a numeric timeout
    literal handed to the probe (QSM-RES-TIMEOUT-LITERAL) — next to a
    ``watchdog``-bounded twin the pass must NOT flag.  Never executed;
    tests point the resilience AST pass at this file and assert the
    three rules fire exactly once each."""

    def probe_unbounded(self):
        import jax

        return str(jax.devices()[0])  # <-- bug: can block forever

    def wait_unbounded(self):
        import subprocess
        import sys

        return subprocess.run([sys.executable, "-c", "pass"],
                              capture_output=True)  # <-- bug: no timeout

    def probe_with_literal(self):
        from ..utils.device import probe_default_backend

        return probe_default_backend(45.0)  # <-- bug: scattered constant

    def probe_bounded(self):
        """The sanctioned form — must NOT be flagged."""
        import jax

        from ..resilience.policy import watchdog

        return watchdog(lambda: jax.devices(), 45.0, label="fixture")


class UnreapedWorkerPoolStub:
    """Seeded bug for the pool passes (family f), REAP half: a worker
    ``Popen`` inside a class with NO bounded reap path anywhere in it
    (QSM-POOL-REAP — leaked/zombie workers accumulate for the server's
    whole lifetime).  Never executed; tests point the pool AST pass at
    this file and assert the rule fires exactly once."""

    def spawn_unreaped(self):
        import subprocess
        import sys

        return subprocess.Popen([sys.executable, "-c", "pass"])  # <-- bug


class RespawnStormPoolStub:
    """Seeded bug for the pool passes (family f), RESPAWN half: a
    while-True respawn loop with no backoff sleep (QSM-POOL-RESPAWN —
    an instantly-dying worker turns it into a spawn storm).  The reap
    is bounded, so ONLY the respawn rule fires on this class."""

    def respawn_forever(self):
        import subprocess
        import sys

        while True:                      # <-- bug: no backoff, no bound
            proc = subprocess.Popen([sys.executable, "-c", "pass"])
            proc.wait(timeout=5.0)       # reap is bounded: REAP is clean


class ReapedWorkerPoolStub:
    """The sanctioned twins the pool passes must NOT flag: spawn with a
    terminate → bounded wait → kill escalation in the same class, and a
    stop-flag-gated respawn loop with exponential-backoff sleeps (the
    serve/pool.py discipline)."""

    def __init__(self):
        import threading

        self._stop = threading.Event()

    def spawn_and_reap(self):
        import subprocess
        import sys

        proc = subprocess.Popen([sys.executable, "-c", "pass"])
        proc.terminate()
        try:
            proc.wait(timeout=2.0)       # bounded reap
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=5.0)
        return proc

    def respawn_with_backoff(self):
        import subprocess
        import sys
        import time

        backoff = 0.5
        while not self._stop.is_set():   # gated, backed off: sanctioned
            proc = subprocess.Popen([sys.executable, "-c", "pass"])
            proc.wait(timeout=5.0)
            time.sleep(backoff)
            backoff = min(backoff * 2, 30.0)


class DeadlockingLockPairStub:
    """Seeded bug for the race passes (family g), ORDER half: lock_b
    taken while holding lock_a on one path and lock_a while holding
    lock_b on the other (QSM-RACE-ORDER — two threads interleaving
    these paths deadlock, which on the serving stack is a wedged
    server).  Never executed; tests point the whole-program race pass
    at this file and assert the cycle fires exactly once."""

    def __init__(self):
        import threading

        self.lock_a = threading.Lock()
        self.lock_b = threading.Lock()
        self.balance = 0

    def transfer_ab(self):
        with self.lock_a:
            with self.lock_b:
                self.balance += 1

    def refund_ba(self):
        with self.lock_b:
            with self.lock_a:            # <-- bug: AB/BA cycle
                self.balance -= 1


class OrderedLockPairStub:
    """The sanctioned twin: both paths take lock_a before lock_b — one
    global acquisition order, no cycle, must NOT be flagged."""

    def __init__(self):
        import threading

        self.lock_a = threading.Lock()
        self.lock_b = threading.Lock()
        self.balance = 0

    def transfer(self):
        with self.lock_a:
            with self.lock_b:
                self.balance += 1

    def audit(self):
        with self.lock_a:
            with self.lock_b:
                self.balance -= 1


class UnguardedCounterStub:
    """Seeded bug for the race passes (family g), UNGUARDED half: a
    shared counter written under ``_lock`` on one line and with no lock
    two lines later, from a thread-target loop (QSM-RACE-UNGUARDED —
    the torn/lost-update shape a single-module lint cannot see)."""

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.count = 0

    def start(self):
        import threading

        threading.Thread(target=self._drain, daemon=True).start()

    def _drain(self):
        while not self._stop.is_set():
            with self._lock:
                self.count += 1          # guarded write: the discipline
            self.count -= 1              # <-- bug: unguarded write


class GuardedCounterStub:
    """The sanctioned twin: every post-``__init__`` write holds the one
    guard lock — must NOT be flagged."""

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.count = 0

    def start(self):
        import threading

        threading.Thread(target=self._drain, daemon=True).start()

    def _drain(self):
        while not self._stop.is_set():
            with self._lock:
                self.count += 1
            with self._lock:
                self.count -= 1


class UnjoinedThreadStub:
    """Seeded bug for the race passes (family g), LIFECYCLE half: a
    thread whose target loops forever without consulting any stop flag,
    retained on an attribute in a class with no bounded ``join``
    (QSM-THREAD-LIFECYCLE — teardown can never complete)."""

    def start(self):
        import threading

        t = threading.Thread(target=self._pump, daemon=True)
        t.start()
        self._pump_thread = t            # retained, never joined

    def _pump(self):
        import time

        while True:                      # <-- bug: no stop flag/deadline
            time.sleep(0.01)


class StoppableThreadStub:
    """The sanctioned twin: stop-flag-gated target loop plus a bounded
    join on the teardown path — must NOT be flagged."""

    def __init__(self):
        import threading

        self._stop = threading.Event()
        self._thread = None

    def start(self):
        import threading

        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)       # bounded join

    def _pump(self):
        import time

        while not self._stop.is_set():
            time.sleep(0.01)


class LeakedPipeStub:
    """Seeded bug for the race passes (family g), LEAK half: a pipe
    acquired and dropped — neither end closed on any path, nothing
    handed off (QSM-RES-LEAK — a long-lived server leaks descriptors
    until accept() fails with EMFILE) — next to the try/finally twin
    the pass must NOT flag."""

    def open_unclosed(self):
        import os

        r, w = os.pipe()                 # <-- bug: never closed
        return "opened"

    def open_closed(self):
        """The sanctioned form: closed on every exit."""
        import os

        r, w = os.pipe()
        try:
            return "opened"
        finally:
            os.close(r)
            os.close(w)


class UnboundedServeAcceptStub:
    """Seeded bug for the serve passes (family e): a ``while True``
    accept loop with no deadline or shutdown check (QSM-SERVE-ACCEPT —
    a wedged peer or a stop request leaves the thread blocked forever)
    and an unbounded admission queue (QSM-SERVE-UNBOUNDED) — next to
    the two sanctioned twins the passes must NOT flag (a stop-flag-
    gated loop, and a settimeout-bounded poll over a bounded queue).
    Never executed; tests point the serve AST pass at this file and
    assert each rule fires exactly once."""

    def __init__(self):
        import threading

        self._stop = threading.Event()

    def serve_forever_unbounded(self, sock):
        import queue

        backlog = queue.Queue()        # <-- bug: unbounded admission queue
        while True:                    # <-- bug: no deadline/shutdown check
            conn, _ = sock.accept()
            backlog.put(conn)

    def serve_stop_gated(self, sock):
        """Sanctioned: the loop test IS the shutdown check."""
        import queue

        backlog = queue.Queue(maxsize=64)   # ok: bounded
        while not self._stop.is_set():
            conn, _ = sock.accept()
            backlog.put(conn, block=False)

    def serve_deadline_polled(self, sock):
        """Sanctioned: settimeout bounds every accept; the loop polls."""
        sock.settimeout(0.5)
        while True:
            try:
                conn, _ = sock.accept()
            except OSError:
                if self._stop.is_set():
                    return
                continue
            conn.close()


class UnboundedFrontierStub:
    """Seeded bug for the shrink passes (family h): a while-True loop
    that grows the shrink frontier with no round or size cap
    (QSM-SHRINK-UNBOUNDED — one shrink request becomes an unbounded CPU
    burn on micro-batch lanes shared with paying traffic).  Never
    executed; tests point the shrink AST pass at this file and assert
    the rule fires exactly once."""

    def frontier_forever(self, history):
        out = []
        while True:                      # <-- bug: no cap, no break
            out.append(list(history))
        return out


class BoundedFrontierStub:
    """The sanctioned twins the shrink pass must NOT flag: a bounded
    ``for`` sweep over the ops (the frontier.py shape), and a while-True
    accumulator whose break is gated on an explicit size cap."""

    MAX_LANES = 512

    def frontier_over_ops(self, history):
        out = []
        for j in range(len(history)):    # bounded by the ops themselves
            out.append(history[:j] + history[j + 1:])
        return out

    def frontier_capped(self, history):
        out = []
        while True:
            out.append(list(history))
            if len(out) >= self.MAX_LANES:   # explicit cap: sanctioned
                break
        return out


# ---------------------------------------------------------------------------
# P-compositionality projection fixtures (QSM-SPEC-PCOMP — pass family a)
# ---------------------------------------------------------------------------

from ..core.spec import KeyProj  # noqa: E402
from ..models.kv import KvSpec  # noqa: E402


class NonTotalPartitionKvSpec(KvSpec):
    """Seeded bug for QSM-SPEC-PCOMP: the spec still advertises
    ``projected_spec`` but PUT declares no KeyProj — partition_key is
    not total, so a split would have to drop or guess where PUTs land.
    The validator must refuse (and the planner must stamp the refusal
    into its ``why`` instead of splitting)."""

    name = "non_total_partition_kv"

    def __init__(self, n_keys: int = 4, n_values: int = 4):
        super().__init__(n_keys=n_keys, n_values=n_values)
        get, put = self.CMDS
        import dataclasses

        self.CMDS = (get, dataclasses.replace(put, proj=None))  # <-- bug


class UnfaithfulProjectionKvSpec(KvSpec):
    """Seeded bug for QSM-SPEC-PCOMP: PUT's KeyProj declares stride 1 —
    the key becomes the PACKED (key*n_values+value) arg, so the split
    scatters one key's writes across many sub-histories while the
    projected WRITE arg is always 0.  The sampled faithfulness check
    must catch the disagreement with the whole spec's step."""

    name = "unfaithful_projection_kv"

    def __init__(self, n_keys: int = 4, n_values: int = 4):
        super().__init__(n_keys=n_keys, n_values=n_values)
        get, put = self.CMDS
        import dataclasses

        self.CMDS = (get, dataclasses.replace(
            put, proj=KeyProj(pcmd=1, stride=1)))  # <-- bug: wrong stride


class SanctionedProjectionKvSpec(KvSpec):
    """Sanctioned twin: a KvSpec subclass whose declarations are exactly
    the sound ones — must stay CLEAN under QSM-SPEC-PCOMP (the pass
    flags unsound declarations, not subclassing)."""

    name = "sanctioned_projection_kv"


class UnclosedSpanStub:
    """Seeded bug for QSM-OBS-SPAN: a span opened by hand (assigned,
    manually entered) instead of through a ``with`` — a raise between
    open and close orphans it and the causal tree loses the stage.
    Never executed; tests point the obs pass at this file and assert
    the rule fires here and NOT on the sanctioned twin below."""

    def __init__(self, tracer):
        self.tracer = tracer

    def work(self, trace):
        sp = self.tracer.span("work", trace)  # <-- bug: no with/return
        sp.__enter__()
        result = len(trace)  # a raise here would orphan the span
        sp.__exit__(None, None, None)
        return result


class ClosedSpanStub:
    """Sanctioned twin: the with-statement close (exception-safe) and
    the delegating-return form — must stay CLEAN under QSM-OBS-SPAN."""

    def __init__(self, tracer):
        self.tracer = tracer

    def work(self, trace):
        with self.tracer.span("work", trace) as sp:
            sp.add(ok=True)
            return len(trace)

    def make_span(self, trace):
        # the wrapper form: the CALLER's with owns the close
        return self.tracer.span("work", trace)


class UnboundedMetricStub:
    """Seeded bug for QSM-OBS-CARDINALITY: metric identity synthesized
    from per-request data — an f-string metric name keyed by a history
    fingerprint, and a concatenated label value — each distinct value
    mints a new time series (cardinality explosion).  Never executed."""

    def bump(self, registry, fingerprint):
        # <-- bug: one time series PER FINGERPRINT
        registry.counter(f"qsm_hits_{fingerprint}").inc()

    def observe(self, hist, key, dt):
        hist.observe(dt, bucket="k:" + key)  # <-- bug: unbounded label


class BoundedMetricStub:
    """Sanctioned twin: fixed metric names, bounded label values
    (a worker id cast with str()) — must stay CLEAN under
    QSM-OBS-CARDINALITY."""

    def bump(self, registry, wid):
        registry.counter("qsm_hits_total").inc(wid=str(wid))

    def observe(self, hist, wid, dt):
        hist.observe(dt, wid=str(wid))


class UnboundedRedispatchRouterStub:
    """Seeded bug for QSM-FLEET-REDISPATCH: a while-True re-dispatch
    loop — no attempt budget, no failed-node exclusion.  Every node
    down (a full partition) spins this forever instead of degrading to
    the ladder or shedding, and the ring keeps answering the same dead
    node for the same key.  Never executed."""

    def __init__(self, links):
        self.links = links

    def route(self, doc):
        while True:  # <-- bug: unbounded, never excludes the corpse
            link = self.links[0]
            try:
                return link.request(doc, 5.0)
            except Exception:  # noqa: BLE001 — the seeded shape
                continue


class NonExcludingRedispatchRouterStub:
    """Seeded bug (the second QSM-FLEET-REDISPATCH form): the attempt
    budget exists but nothing excludes the failed node — the ring
    walk hands the same corpse back every attempt, so the budget buys
    nothing.  Never executed."""

    def __init__(self, ring, links):
        self.ring = ring
        self.links = links

    def route(self, key, doc):
        for _attempt in range(3):  # bounded, but...
            target = self.ring.node_for(key, {"n0", "n1"})
            link = self.links[target]
            try:
                return link.request(doc, 5.0)
            except Exception:  # noqa: BLE001 — ...same target next time
                continue
        return None


class BoundedRedispatchRouterStub:
    """Sanctioned twin: bounded attempts + a ``tried`` exclusion set
    fed into the ring walk (the fleet/router.py shape) — must stay
    CLEAN under QSM-FLEET-REDISPATCH."""

    def __init__(self, ring, links):
        self.ring = ring
        self.links = links

    def route(self, key, doc, allowed):
        tried = set()
        target = self.ring.node_for(key, allowed)
        for _attempt in range(3):
            if target is None:
                break
            tried.add(target)
            link = self.links[target]
            try:
                return link.request(doc, 5.0)
            except Exception:  # noqa: BLE001 — excluded, try the next
                target = self.ring.node_for(key, allowed, exclude=tried)
                continue


class TermlessTakeoverRouterStub:
    """Seeded bugs for QSM-FLEET-LEASE (the router-HA promotion
    discipline, fleet/lease.py): ``promote_forever`` spins a
    while-True around lease acquisition (unbounded standby-promote
    loop — with a live active holding the lease it never stands
    down); ``promote_blind`` acquires without ever consulting the
    record's term or expiry (the split-brain grab the lease exists to
    exclude).  Never executed."""

    def __init__(self, lease):
        self.lease = lease

    def promote_forever(self):
        while True:  # <-- bug: unbounded, spins against a live active
            rec = self.lease.acquire()
            if rec is not None:
                return rec

    def promote_blind(self, healthy):
        if healthy():
            # <-- bug: no term/expiry consult before the grab
            return self.lease.acquire()
        return None


class LeasedTakeoverRouterStub:
    """Sanctioned twin: one beat-driven attempt per observation — the
    record is read, its term and expiry consulted, and acquisition
    attempted at most once per beat (the fleet/router.py ``ha_beat``
    shape) — must stay CLEAN under QSM-FLEET-LEASE."""

    def __init__(self, lease, grace_s=1.0):
        self.lease = lease
        self.grace_s = grace_s
        self.term = 0

    def beat(self, probe):
        rec = self.lease.read()
        if rec is not None and not self.lease.expired(rec,
                                                      self.grace_s):
            return None              # the incumbent's term is live
        if not probe():
            return None              # no independent view of the fleet
        got = self.lease.acquire(self.grace_s)
        if got is not None:
            self.term = got["term"]  # the term rides every response
        return got


class ColdRebalanceRouterStub:
    """Seeded bugs for QSM-FLEET-HANDOFF (the elastic-membership
    discipline, ISSUE 18): ``join_cold`` mutates the ring with no
    replog seeding — the newcomer owns key ranges whose banked verdict
    rows it does not hold, so every key routed there re-folds from
    scratch; ``leave_sticky`` retires a node without touching its
    routed sessions — each one still names the corpse as owner and
    every next verb re-dispatches into the void.  Never executed."""

    def __init__(self, membership, links, sessions):
        self.membership = membership
        self.links = links
        self.sessions = sessions

    def join_cold(self, nid, addr):
        # <-- bug: the ring moves, the rows do not
        self.membership.add_node(nid, addr)
        self.links[nid] = object()
        return True

    def leave_sticky(self, nid):
        self.links.pop(nid, None)
        # <-- bug: the retiree's sessions keep it as owner
        return self.membership.remove_node(nid)


class RebalancingRouterStub:
    """Sanctioned twin: a join seeds the newcomer with an on-the-spot
    anti-entropy sweep (gossip-driven, subsumption-bounded — nodes
    already holding the rows ship nothing) and a leave invalidates
    every routed session the retiree owned, so each journal replays
    onto the new ring owner on its next verb (live migration,
    exactly-once by seq; the fleet/router.py ``_handle_membership``
    shape) — must stay CLEAN under QSM-FLEET-HANDOFF."""

    def __init__(self, membership, sessions):
        self.membership = membership
        self.sessions = sessions

    def join(self, nid, addr):
        joined = self.membership.add_node(nid, addr)
        if joined:
            return self.anti_entropy_sweep()
        return {}

    def leave(self, nid):
        left = self.membership.remove_node(nid)
        migrated = 0
        if left:
            for sess in self.sessions.values():
                if sess.node == nid:
                    sess.node = None
                    migrated += 1
        return migrated

    def anti_entropy_sweep(self):
        return {"segments_shipped": 0}


class UnboundedSessionBufferStub:
    """Seeded bug for the monitor passes (family k): a session object
    whose event buffer grows on every append with NO cap comparison and
    NO eviction anywhere in the class (QSM-MON-UNBOUNDED — a long-lived
    production monitor accumulates it until the serving plane OOMs).
    Never executed; tests point the monitor AST pass at this file and
    assert the rule fires for exactly this class."""

    def __init__(self):
        self.events = []
        self.window = []

    def append(self, event):
        self.events.append(event)        # <-- bug: no cap, no eviction
        self.window.append(event)        # <-- bug: window never evicts

    def verdict(self):
        return 1 if self.window else 1


class BoundedSessionBufferStub:
    """The sanctioned twins the monitor pass must NOT flag: a capped
    event log (the session.py ``max_events`` shape) and a window whose
    decided prefix is EVICTED by pruning reassignment (the frontier.py
    shape) — must stay CLEAN under QSM-MON-UNBOUNDED."""

    MAX_EVENTS = 65_536

    def __init__(self):
        self.events = []
        self.window = []

    def append(self, event):
        if len(self.events) >= self.MAX_EVENTS:   # explicit cap
            raise RuntimeError("session event cap reached")
        self.events.append(event)
        self.window.append(event)

    def commit_prefix(self, cut):
        self.window = self.window[cut:]           # decided-prefix evict


class UnboundedSeedPoolStub:
    """Seeded bug for the generation passes (family m): a fuzzing
    campaign accumulator — a seed corpus AND a kept-flips log — grown
    once per round with NO cap comparison and NO eviction anywhere in
    the class (QSM-GEN-UNBOUNDED — an open-ended soak accumulates it
    until the driving host OOMs).  Never executed; tests point the gen
    AST pass at this file and assert the rule fires for exactly this
    class."""

    def __init__(self):
        self.seeds = []
        self.flips = []

    def keep(self, entry, violated):
        self.seeds.append(entry)         # <-- bug: corpus never evicts
        if violated:
            self.flips.append(entry)     # <-- bug: flip log unbounded

    def best(self):
        return max(self.seeds, default=None)


class BoundedSeedPoolStub:
    """The sanctioned twins the gen pass must NOT flag: a
    capacity-evicted corpus (the steer.py ``SeedPool.add`` shape) and a
    flip log pruned to a tail window by reassignment (the kept-flips
    shape) — must stay CLEAN under QSM-GEN-UNBOUNDED."""

    CAP = 16
    FLIP_KEEP = 64

    def __init__(self):
        self.seeds = []
        self.flips = []

    def keep(self, entry, violated):
        self.seeds.append(entry)
        self.seeds.sort()
        while len(self.seeds) > self.CAP:         # explicit cap
            self.seeds.pop()                      # worst-scored evict
        if violated:
            self.flips.append(entry)
            self.flips = self.flips[-self.FLIP_KEEP:]   # tail window


# --- family (l): protocol-conformance fixtures ---------------------------
#
# A miniature wire sub-program per rule: an egress class (``_send`` +
# ``_handle``) declaring its op vocabulary as class attrs, plus the
# caller paths that exercise it.  The extractor treats each class pair
# as its own protocol; none of these names collide with the live
# serve/protocol.py vocabulary and nothing here ever runs.


class MiswiredProtocolStub:
    """Seeded bugs for QSM-PROTO-UNHANDLED + QSM-PROTO-FIELDS: the
    class declares ``mis.ghost`` but only dispatches ``mis.ping``, and
    its ``mis.ping`` response writes ``echo`` while the client below
    reads ``echo_payload`` — the exact drift class a hand-synced
    protocol accumulates."""

    OPS = ("mis.ping", "mis.ghost")

    def _send(self, ch, doc):
        doc["node"] = "stub"
        ch.write(doc)

    def _handle(self, ch, req):
        op = req.get("op", "mis.ping")
        if op == "mis.ping":
            self._send(ch, {"id": req.get("id"), "ok": True,
                            "echo": req.get("payload")})


class MiswiredProtocolClientStub:
    """The caller half of the miswired pair: sends the undispatched
    ``mis.ghost`` (QSM-PROTO-UNHANDLED at the send site) and reads the
    never-written ``echo_payload`` key (QSM-PROTO-FIELDS)."""

    def __init__(self, link):
        self.link = link

    def ping(self, text):
        resp = self.link.request({"op": "mis.ping", "payload": text},
                                 5.0)
        return resp.get("echo_payload")      # <-- bug: never written

    def ghost(self):
        return self.link.request({"op": "mis.ghost"}, 5.0)  # <-- bug


class WiredProtocolStub:
    """Sanctioned twin: every declared op dispatched, every response
    key the client reads written by the handler — must stay CLEAN
    under QSM-PROTO-UNHANDLED and QSM-PROTO-FIELDS."""

    OPS = ("wired.ping",)

    def _send(self, ch, doc):
        doc["node"] = "stub"
        ch.write(doc)

    def _handle(self, ch, req):
        op = req.get("op", "wired.ping")
        if op == "wired.ping":
            self._send(ch, {"id": req.get("id"), "ok": True,
                            "echo": req.get("payload")})


class WiredProtocolClientStub:
    """Caller half of the clean twin: reads exactly what the handler
    writes."""

    def __init__(self, link):
        self.link = link

    def ping(self, text):
        resp = self.link.request({"op": "wired.ping",
                                  "payload": text}, 5.0)
        return resp.get("echo")


class UnstampedEgressStub:
    """Seeded bug for QSM-PROTO-EGRESS: an egress class (it has the
    one ``_send``) whose handler answers through a raw ``send_doc``
    instead — the response reaches the wire without the node stamp
    every consumer correlates on."""

    OPS = ("egress.ping",)

    def _send(self, ch, doc):
        doc["node"] = "stub"
        ch.write(doc)

    def _handle(self, ch, req):
        from ..serve.protocol import send_doc

        op = req.get("op", "egress.ping")
        if op == "egress.ping":
            doc = {"id": req.get("id"), "ok": True}
            send_doc(ch, doc)                # <-- bug: bypasses _send


class UnstampedEgressCallerStub:
    """Caller keeping ``egress.ping`` reachable (the coverage checks
    must not mask the egress bug with an unrelated finding)."""

    def __init__(self, link):
        self.link = link

    def ping(self):
        return self.link.request({"op": "egress.ping"}, 5.0)


class StampedEgressStub:
    """Sanctioned twin: same dispatch shape, response routed through
    the class's ``_send`` — must stay CLEAN under QSM-PROTO-EGRESS."""

    OPS = ("egress.pong",)

    def _send(self, ch, doc):
        doc["node"] = "stub"
        ch.write(doc)

    def _handle(self, ch, req):
        op = req.get("op", "egress.pong")
        if op == "egress.pong":
            self._send(ch, {"id": req.get("id"), "ok": True})


class StampedEgressCallerStub:
    """Caller keeping ``egress.pong`` reachable."""

    def __init__(self, link):
        self.link = link

    def pong(self):
        return self.link.request({"op": "egress.pong"}, 5.0)


class RetryProtocolServerStub:
    """The server half of the retry fixtures: dispatches a mutating
    ``retry.reset`` and a read-only ``retry.get``, declaring only the
    latter replay-safe — exactly the split QSM-PROTO-RETRY-IDEMPOTENT
    checks call paths against."""

    OPS = ("retry.reset", "retry.get")
    IDEMPOTENT_OPS = ("retry.get",)

    def _send(self, ch, doc):
        doc["node"] = "stub"
        ch.write(doc)

    def _handle(self, ch, req):
        op = req.get("op", "retry.get")
        if op == "retry.reset":
            self._send(ch, {"id": req.get("id"), "ok": True})
        elif op == "retry.get":
            self._send(ch, {"id": req.get("id"), "ok": True})


class RetriedMutationClientStub:
    """Seeded bug for QSM-PROTO-RETRY-IDEMPOTENT: re-sends the
    mutating ``retry.reset`` across attempts in an except-continue
    failover loop — a dropped ACK replays the mutation."""

    def __init__(self, link):
        self.link = link

    def reset(self):
        req = {"op": "retry.reset"}
        for _ in range(3):
            try:
                return self.link.ask(req, 5.0)  # <-- bug: retried
            except OSError:
                continue
        return None


class IdempotentRetryClientStub:
    """Sanctioned twin: the same failover loop re-sends only the
    declared-idempotent ``retry.get`` — must stay CLEAN under
    QSM-PROTO-RETRY-IDEMPOTENT."""

    def __init__(self, link):
        self.link = link

    def get(self):
        req = {"op": "retry.get"}
        for _ in range(3):
            try:
                return self.link.ask(req, 5.0)
            except OSError:
                continue
        return None


# --- family (n): mesh-dispatch fixtures ----------------------------------
#
# Never executed (the imports inside the method bodies never run);
# tests point the mesh AST pass at this file and assert each rule
# fires on its seeded stub and stays silent on the sanctioned twin.


class HardcodedMeshStub:
    """Seeded bugs for QSM-MESH-HARDCODE: a topology slot pinned by
    indexing the device enumeration, and a literal device count baked
    into a mesh constructor — the two shapes that make a sharded
    program run only on the box it was written on."""

    def pin_first_device(self):
        # watchdogged (family d's sanctioned probe form) so exactly
        # ONE family owns this bug: the INDEXING is the mesh defect
        import jax

        from ..resilience.policy import watchdog

        return watchdog(lambda: jax.devices()[0],  # <-- bug: slot pin
                        5.0, label="fixture")

    def build_fixed_mesh(self):
        from ..mesh import make_mesh

        return make_mesh(8)              # <-- bug: literal device count


class ShapePolymorphicMeshStub:
    """The sanctioned twin: the lane-axis width is threaded as a
    parameter and device enumeration is only ever *counted*, never
    indexed — must stay CLEAN under QSM-MESH-HARDCODE."""

    def __init__(self, n_devices):
        self.n_devices = n_devices

    def build_mesh(self):
        from ..mesh import make_mesh

        return make_mesh(self.n_devices)     # threaded: sanctioned

    def lane_width(self):
        import jax

        from ..resilience.policy import watchdog

        return watchdog(lambda: len(jax.devices()),  # counted, not
                        5.0, label="fixture")        # indexed: clean


class TransferringDispatchStub:
    """Seeded bug for QSM-MESH-TRANSFER: the same function applies the
    lane sharding AND pulls the result back to host — the dispatch
    path funnels the whole lane axis through one device's memory while
    reporting an N-device mesh."""

    def shard_then_pull(self, arrays, sharding):
        import jax
        import numpy as np

        shards = [jax.device_put(a, sharding) for a in arrays]
        return [np.asarray(s) for s in shards]   # <-- bug: host pull


class DeviceResidentDispatchStub:
    """The sanctioned twin: sharding application and host readback
    live in DIFFERENT functions (the jax_kernel.py ``_shard_carry`` /
    ``_compact_carry_host`` split) — must stay CLEAN under
    QSM-MESH-TRANSFER."""

    def shard(self, arrays, sharding):
        import jax

        return [jax.device_put(a, sharding) for a in arrays]

    def pull(self, shards):
        import numpy as np

        return [np.asarray(s) for s in shards]


# --- family (o): device-work-queue fixtures -------------------------------
#
# Never executed; tests point the devq AST pass at this file and assert
# each rule fires on its seeded stub and stays silent on the sanctioned
# twin (tests/test_lint.py).


class UnboundedDevqStub:
    """Seeded bug for QSM-DEVQ-UNBOUNDED: a work queue every plane on
    every fleet node feeds, growing a pending map and a done log with
    NO cap comparison and NO eviction anywhere in the class — the
    family-(k) OOM pathology recurring at the fleet's shared choke
    point."""

    def __init__(self):
        self.pending = []
        self.done = []

    def put(self, item):
        self.pending.append(item)        # <-- bug: no cap, no eviction
        self.done.append(item.key)       # <-- bug: tombstones unbounded


class BoundedDevqStub:
    """The sanctioned twin the devq pass must NOT flag: pending is
    capped by lowest-score eviction (the queue.py ``_evict_over_cap``
    shape) and the done-tombstone log is pruned to a tail window —
    must stay CLEAN under QSM-DEVQ-UNBOUNDED."""

    CAP = 512

    def __init__(self):
        self.pending = []
        self.done = []

    def put(self, item):
        self.pending.append(item)
        if len(self.pending) > self.CAP:          # explicit cap
            self.pending.pop(0)                   # lowest-score evict
        self.done.append(item.key)
        self.done = self.done[-4 * self.CAP:]     # tail-window prune


class DeadlineBlindDrainStub:
    """Seeded bug for QSM-DEVQ-DRAIN: a drain loop that runs until the
    queue empties without ever consulting the window deadline — a
    snatched-away chip leaves it wedged on a device it no longer
    owns."""

    def drain_queue(self, queue):
        results = {}
        while queue.pending():                   # <-- bug: no deadline
            item = queue.pending()[0]
            results[item.key] = self.dispatch(item)
            queue.mark_done(item.key)
        return results

    def dispatch(self, item):
        return 1


class DeadlineGatedDrainStub:
    """The sanctioned twin: every iteration consults the remaining
    window time before starting an item (the drain.py
    ``DrainScheduler.drain`` shape) — must stay CLEAN under
    QSM-DEVQ-DRAIN."""

    def __init__(self, window_end):
        self.window_end = window_end

    def drain_queue(self, queue, now):
        results = {}
        while queue.pending():
            remaining = self.window_end - now()  # deadline consulted
            if remaining <= 0.0:
                break
            item = queue.pending()[0]
            results[item.key] = self.dispatch(item)
            queue.mark_done(item.key)
        return results

    def dispatch(self, item):
        return 1
