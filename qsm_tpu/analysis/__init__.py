"""``qsm_tpu.analysis`` — the ``qsmlint`` static analyzer.

Round 5 proved the scarcest resource is a live TPU window (717 probes,
9 device hits — VERDICT.md); any defect that survives until a window
opens wastes the one thing that cannot be bought back.  This package is
the "decide cheaply first" lever at the code level: three CPU-only pass
families that catch window-burning defects before any device backend is
constructed —

* spec soundness (``spec_passes``): step_py/step_jax parity, domain
  consistency, declared-bound soundness, precondition reachability;
* kernel trace hazards (``kernel_passes``): retracing, dtype promotion,
  host transfers in traced loop bodies, the Pallas VMEM envelope;
* scheduler determinism (``sched_passes``): nondeterminism sources
  outside the seeded RNG;
* resilience (``resilience_passes``): unbounded device calls — bare
  ``jax.devices()`` outside a watchdog, subprocess waits without a
  timeout, scattered probe-timeout literals the named
  :data:`~qsm_tpu.resilience.policy.PRESETS` replaced;
* pool (``pool_passes``): worker-process lifecycle hazards — spawns
  without a bounded reap path, respawn loops without backoff
* serve (``serve_passes``): the serving plane's structural hazards —
  accept/recv loops without a deadline or shutdown check, unbounded
  queue growth in admission paths;
* race (``race_passes`` on the ``callgraph`` whole-program substrate):
  interprocedural lock/thread hazards across serve + resilience +
  tools — lock-order deadlock cycles, unguarded shared writes, thread
  lifecycle without stop/join, leaked fds/sockets;
* protocol (``protocol_model`` + ``protocol_passes``, the second
  whole-program family): the wire contract extracted as a committed
  artifact (``PROTOCOL.json``/``docs/PROTOCOL.md``) and enforced —
  unhandled or caller-less ops, request/response field drift,
  ``_send``-bypassing egress, non-idempotent ops on retry paths, SHED
  docs carrying verdict keys, artifact drift.

Families are registered declaratively in ``engine.FAMILIES`` (id,
scan set, runner); ``--family g`` selects by id, ``--changed`` scopes
to git-touched modules, and per-file findings are cached on disk by
content digest (``incremental.py``).

Entry points: :func:`run_lint` (the engine), ``python -m qsm_tpu lint``
(the CLI gate), tests/test_lint.py (the tier-1 gate) and the
probe_watcher pre-seize hook.  Rules, severities and the whitelist
format are documented in docs/ANALYSIS.md.
"""

from .findings import (ERROR, INFO, WARNING, Finding, Whitelist,
                       render_json, render_sarif, render_text,
                       sort_findings, split_whitelisted)
from .engine import (DEFAULT_OPS_FILES, DEFAULT_POOL_FILES,
                     DEFAULT_PROTOCOL_FILES, DEFAULT_RACE_FILES,
                     DEFAULT_RESILIENCE_FILES, DEFAULT_SCHED_FILES,
                     DEFAULT_SERVE_FILES, FAMILIES, Family, LintReport,
                     default_whitelist_path, run_lint)

__all__ = [
    "ERROR", "WARNING", "INFO", "Finding", "Whitelist", "LintReport",
    "run_lint", "render_text", "render_json", "render_sarif",
    "sort_findings", "split_whitelisted", "default_whitelist_path",
    "FAMILIES", "Family",
    "DEFAULT_OPS_FILES", "DEFAULT_SCHED_FILES",
    "DEFAULT_RESILIENCE_FILES", "DEFAULT_SERVE_FILES",
    "DEFAULT_POOL_FILES", "DEFAULT_RACE_FILES",
    "DEFAULT_PROTOCOL_FILES",
]
