"""Mesh-dispatch passes (pass family *n* of docs/ANALYSIS.md): the
sharded-substrate discipline.

The mesh substrate (qsm_tpu/mesh/) has ONE contract: every consumer
rides a lane axis whose width is a *parameter* — the same program must
run on 1, 2 or 8 devices with bit-identical verdicts (docs/MESH.md).
Two source-level defect classes break that contract silently:

* ``QSM-MESH-HARDCODE`` (error) — a literal device count baked into a
  mesh constructor (``make_mesh(8)``, ``make_mesh_2d(2, 4)``,
  ``Mesh(...)`` with an int-literal positional arg) or direct
  device-array indexing (``jax.devices()[0]``,
  ``jax.local_devices()[i]``).  Either pins the program to one
  topology: the code "works" on the box it was written on and
  misshards — or crashes — on every other mesh.  Device counts must
  be threaded (``make_mesh(n)`` / ``mesh_device_count(...)``); the
  mesh helpers themselves (qsm_tpu/mesh/topology.py) build meshes
  from parameters only and scan clean under this rule — that is the
  point: topology.py is the ONE place device enumeration lives.

* ``QSM-MESH-TRANSFER`` (error) — a host transfer (``np.asarray`` /
  ``np.array`` / ``jax.device_get`` / ``.item()``) in the SAME
  function that applies a sharding (a ``device_put`` call or a
  ``NamedSharding`` construction).  Pulling a freshly sharded value
  back to host serializes the whole lane axis through one device's
  memory — the dispatch path keeps single-device wall-clock while
  reporting an N-device mesh.  Scope is the function: the sanctioned
  shape keeps sharding application and host readback in different
  functions (jax_kernel.py ``_shard_carry`` vs ``_compact_carry_host``
  — the compaction path gathers on host FIRST, then re-applies the
  sharding through the dedicated helper).

Both rules are deliberately structural (AST only, no imports executed):
the parity *behaviour* is gated by tests/test_mesh.py and the bench
parity cell; this family catches the shape of the regression before a
multi-chip window ever opens.

Scan set: qsm_tpu/mesh/ + the sharded consumers (ops/jax_kernel.py,
search/planner.py, serve/batcher.py) + tools/bench_mesh.py.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, List, Optional, Tuple

from .astutil import attr_chain, call_name, parse_module
from .findings import ERROR, Finding

# constructors whose positional device-count arguments must be
# threaded, never literal
_MESH_CTORS = {"make_mesh", "make_mesh_2d", "Mesh"}

# callables returning the process's device array; subscripting their
# result pins a topology index
_DEVICE_ENUMS = {("jax", "devices"), ("jax", "local_devices")}

# host-transfer call shapes (family b's kernel pass hunts these inside
# traced bodies; here the scope is any sharding-applying function)
_NP_PULLS = {"asarray", "array"}
_NP_MODULES = {"np", "numpy"}


def _functions(tree: ast.Module) -> Iterator[Tuple[str, ast.AST]]:
    """(qualified name, node) for every function, including methods —
    ``Cls.fn`` for methods, ``fn`` at module level, ``outer.inner``
    for nested defs."""
    def walk(node: ast.AST, prefix: str) -> Iterator[Tuple[str, ast.AST]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = f"{prefix}{child.name}"
                yield name, child
                yield from walk(child, f"{name}.")
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.")
            else:
                yield from walk(child, prefix)
    yield from walk(tree, "")


def _own_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Nodes of ``fn``'s own body, nested function defs excluded — the
    transfer rule is function-scoped, and a nested def is its own
    scope (it is reported under its own qualified name)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _is_device_enum_subscript(node: ast.AST) -> bool:
    return (isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Call)
            and tuple(attr_chain(node.value.func)) in _DEVICE_ENUMS)


def _literal_mesh_ctor_arg(node: ast.AST) -> Optional[int]:
    """The first int-literal positional arg of a mesh constructor call,
    or None when the call is clean/not a mesh ctor."""
    if not (isinstance(node, ast.Call)
            and call_name(node) in _MESH_CTORS):
        return None
    for arg in node.args:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, int) \
                and not isinstance(arg.value, bool):
            return arg.value
    return None


def _is_sharding_apply(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and call_name(node) in ("device_put", "NamedSharding"))


def _host_pull(node: ast.AST) -> Optional[str]:
    """The spelled name of a host-transfer call, or None."""
    if not isinstance(node, ast.Call):
        return None
    chain = attr_chain(node.func)
    if len(chain) >= 2 and chain[0] in _NP_MODULES \
            and chain[-1] in _NP_PULLS:
        return ".".join(chain)
    if chain and chain[-1] == "device_get":
        return ".".join(chain)
    if isinstance(node.func, ast.Attribute) and node.func.attr == "item" \
            and not node.args:
        return ".item()"
    return None


def check_mesh_file(path: str, root: Optional[str] = None
                    ) -> List[Finding]:
    tree = parse_module(path)
    relpath = path
    if root:
        try:
            relpath = os.path.relpath(path, root)
        except ValueError:
            pass
    out: List[Finding] = []
    for fn_name, fn in _functions(tree):
        applies: List[Tuple[int, str]] = []
        pulls: List[Tuple[int, str]] = []
        for node in _own_nodes(fn):
            if _is_device_enum_subscript(node):
                out.append(Finding(
                    ERROR, "QSM-MESH-HARDCODE",
                    f"{relpath}:{fn_name}:{node.lineno}",
                    "indexes the device enumeration directly "
                    "(jax.devices()[i]) — pins a topology slot, so the "
                    "same program misshards on any other mesh shape",
                    "build placements through qsm_tpu.mesh helpers "
                    "(make_mesh / batch_sharding) and let NamedSharding "
                    "place the lanes"))
            n = _literal_mesh_ctor_arg(node)
            if n is not None:
                out.append(Finding(
                    ERROR, "QSM-MESH-HARDCODE",
                    f"{relpath}:{fn_name}:{node.lineno}",
                    f"hardcodes a device count ({n}) into a mesh "
                    "constructor — the lane axis width must be a "
                    "parameter or the program only runs on one "
                    "topology",
                    "thread the count (make_mesh(n_devices)) or derive "
                    "it (mesh_device_count())"))
            if _is_sharding_apply(node):
                applies.append((node.lineno, call_name(node)))
            pull = _host_pull(node)
            if pull is not None:
                pulls.append((node.lineno, pull))
        if applies and pulls:
            a_line, a_name = applies[0]
            p_line, p_name = pulls[0]
            out.append(Finding(
                ERROR, "QSM-MESH-TRANSFER",
                f"{relpath}:{fn_name}:{p_line}",
                f"host transfer ({p_name}, line {p_line}) in the same "
                f"function that applies a sharding ({a_name}, line "
                f"{a_line}) — the sharded dispatch path funnels the "
                "whole lane axis through one device's host memory",
                "split the function: apply shardings in one helper "
                "(the _shard_carry shape), gather on host in another "
                "(_compact_carry_host gathers FIRST, then re-applies "
                "through the helper)"))
    return out
