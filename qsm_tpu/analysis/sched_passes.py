"""Determinism/race passes (pass family *c* of docs/ANALYSIS.md).

The scheduler plane's whole value is the determinism contract:
``(sut, program, seed, faults) -> identical History, bit for bit``
(sched/runner.py) — replay, shrinking, systematic exploration and the
regression corpus all assume it.  Any nondeterminism source OUTSIDE the
scheduler's seeded RNG breaks that silently: a violation found once can
never be reproduced, and a window spent hunting it is wasted.

AST lints over sched/scheduler.py, sched/pool.py, sched/transport.py,
sched/runner.py (and any file handed to :func:`check_sched_file`):

* ``QSM-DET-RANDOM``   — module-level ``random.*`` / ``np.random.*``
  calls (anything but constructing a seeded ``random.Random``); the
  scheduler's own ``self.rng`` is the only sanctioned RNG.
* ``QSM-DET-SET-ITER`` — iteration over a set literal / ``set()``-built
  value: set order is salted per process, so any delivery choice fed
  from it diverges across runs.
* ``QSM-DET-ID``       — ``id()`` used as a value (sort keys,
  comparisons): CPython address order is allocation order, not logical
  order.
* ``QSM-DET-TIME``     — wall-clock reads (``time.time`` /
  ``perf_counter`` / ``monotonic``); warning severity — timing *stats*
  are legitimate, timing-fed *decisions* are not, and the whitelist
  records the reviewed-legitimate sites.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .astutil import attr_chain, parse_module, rel_location
from .findings import ERROR, WARNING, Finding

_TIME_FNS = {"time", "time_ns", "perf_counter", "perf_counter_ns",
             "monotonic", "monotonic_ns", "process_time"}
_SET_BUILDERS = {"set", "frozenset"}


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Set):
        return True
    if isinstance(node, ast.SetComp):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in _SET_BUILDERS:
        return True
    return False


def _scope_nodes(scope: ast.AST):
    """Walk one lexical scope WITHOUT descending into nested function
    defs (each nested def is its own scope)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(node))


def _set_bound_names(scope: ast.AST) -> set:
    """Names assigned a set expression within ONE scope — a one-level
    dataflow approximation, enough to catch
    ``pending = set(...) ... for x in pending``.  Scope-local so a
    set-typed name in one function cannot flag a same-named list in
    another."""
    names = set()
    for node in _scope_nodes(scope):
        if isinstance(node, ast.Assign) and _is_set_expr(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
    return names


def check_sched_file(path: str, root: Optional[str] = None
                     ) -> List[Finding]:
    tree = parse_module(path)
    out: List[Finding] = []

    # per-scope set-name map: node id -> set names of its enclosing
    # scope (module scope counts as one)
    scopes = [tree] + [n for n in ast.walk(tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
    scope_of = {}
    for scope in scopes:
        local = _set_bound_names(scope)
        for node in _scope_nodes(scope):
            scope_of[id(node)] = local

    for node in ast.walk(tree):
        set_names = scope_of.get(id(node), set())
        loc = rel_location(path, getattr(node, "lineno", 0), root)

        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if len(chain) >= 2 and chain[0] == "random" \
                    and chain[-1] != "Random":
                out.append(Finding(
                    ERROR, "QSM-DET-RANDOM", loc,
                    f"module-level random.{'.'.join(chain[1:])}() in the "
                    "scheduler plane",
                    "all nondeterminism must flow from the scheduler's "
                    "seeded rng (Scheduler.rng) or the run stays "
                    "unreplayable"))
            elif len(chain) >= 2 and chain[0] == "random" \
                    and chain[-1] == "Random" \
                    and not node.args and not node.keywords:
                # the constructor exemption is for SEEDED construction
                # only: Random() with no seed draws from OS entropy —
                # the same unreplayable nondeterminism, one step removed
                out.append(Finding(
                    ERROR, "QSM-DET-RANDOM", loc,
                    "UNSEEDED random.Random() construction in the "
                    "scheduler plane",
                    "pass an explicit seed; an entropy-seeded rng makes "
                    "every run unreplayable"))
            elif len(chain) >= 3 and chain[0] in ("np", "numpy") \
                    and chain[1] == "random":
                out.append(Finding(
                    ERROR, "QSM-DET-RANDOM", loc,
                    f"{'.'.join(chain)}() in the scheduler plane",
                    "np.random global state is process-wide and "
                    "unseeded here; use the scheduler's seeded rng"))
            elif len(chain) == 2 and chain[0] == "time" \
                    and chain[1] in _TIME_FNS:
                out.append(Finding(
                    WARNING, "QSM-DET-TIME", loc,
                    f"wall-clock read time.{chain[1]}() in the "
                    "scheduler plane",
                    "fine for timing stats; a delivery/ordering "
                    "decision fed from it breaks replay — whitelist "
                    "reviewed-legitimate sites in .qsmlint"))
            elif isinstance(node.func, ast.Name) and node.func.id == "id":
                out.append(Finding(
                    ERROR, "QSM-DET-ID", loc,
                    "id() used as a value in the scheduler plane",
                    "CPython addresses are allocation-ordered; any "
                    "comparison/sort over them is run-dependent"))
            # sorted(..., key=id) / min/max(..., key=id)
            for kw in node.keywords:
                if kw.arg == "key" and isinstance(kw.value, ast.Name) \
                        and kw.value.id == "id":
                    out.append(Finding(
                        ERROR, "QSM-DET-ID", loc,
                        "sort/selection keyed on id() in the scheduler "
                        "plane",
                        "address order is not deterministic across "
                        "runs"))

        if isinstance(node, (ast.For, ast.AsyncFor)):
            it = node.iter
            if _is_set_expr(it) or (isinstance(it, ast.Name)
                                    and it.id in set_names):
                out.append(Finding(
                    ERROR, "QSM-DET-SET-ITER", loc,
                    "iteration over an unordered set in the scheduler "
                    "plane",
                    "set order is hash-salted per process; sort the "
                    "elements (or keep a list) before any choice is "
                    "fed from the iteration"))
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            for comp in node.generators:
                it = comp.iter
                if _is_set_expr(it) or (isinstance(it, ast.Name)
                                        and it.id in set_names):
                    out.append(Finding(
                        ERROR, "QSM-DET-SET-ITER",
                        rel_location(path, getattr(node, "lineno", 0),
                                     root),
                        "comprehension over an unordered set in the "
                        "scheduler plane",
                        "set order is hash-salted per process; sort "
                        "before deriving any ordered value"))
    return out
