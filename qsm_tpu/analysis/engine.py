"""``qsmlint`` orchestration — declarative pass families over the
in-tree corpus.

One entry point, :func:`run_lint`, drives the :data:`FAMILIES`
registry: each family declares its id (the ``--family`` letter), its
scan set and how it runs — ``per_file`` (independent single-module
checks, cacheable per file) or ``whole`` (semantic or whole-program
runs).  Family (g)'s wider scan set (serve + resilience + tools) and
any future family ride the same declaration; nothing special-cases
the engine.  CPU-only by contract: callers pin the platform
(utils/cli.py cmd_lint forces it) and nothing here constructs a
device backend — the entire point is deciding cheaply BEFORE any TPU
window opens.

Incrementality (``analysis/incremental.py``): per-file findings are
cached on disk keyed by content digest + analyzer fingerprint, so the
full-tree run re-checks only what changed; ``changed=REF`` narrows
the scan itself to git-touched modules.  Both stamp the ``--json``
report (``cache``, ``changed``) so an archived lint says what it
actually looked at.

Consumed by ``python -m qsm_tpu lint`` (exit 1 on non-whitelisted
error findings), tests/test_lint.py (the tier-1 gate) and
tools/probe_watcher.py (the pre-seize hook that refuses to spend a
healing window on statically-broken code).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import (Callable, Dict, List, Optional, Sequence, Set,
                    Tuple, Union)

from .findings import (ERROR, Finding, Whitelist, render_json,
                       split_whitelisted)

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(_PKG_DIR)

# the five lineariser engine modules the kernel passes cover
DEFAULT_OPS_FILES = ("ops/jax_kernel.py", "ops/pallas_kernel.py",
                     "ops/segdc.py", "ops/rootsplit.py", "ops/pcomp.py")
# the scheduler plane the determinism passes cover
DEFAULT_SCHED_FILES = ("sched/scheduler.py", "sched/pool.py",
                       "sched/transport.py", "sched/runner.py")
# every module that touches the device substrate or spawns bounded
# children — the resilience passes' beat (repo-root-relative: the tools
# live outside the package)
DEFAULT_RESILIENCE_FILES = (
    "qsm_tpu/ops/jax_kernel.py", "qsm_tpu/ops/pallas_kernel.py",
    "qsm_tpu/ops/segdc.py", "qsm_tpu/ops/rootsplit.py",
    "qsm_tpu/ops/pcomp.py", "qsm_tpu/utils/device.py",
    "qsm_tpu/utils/cli.py", "qsm_tpu/native/__init__.py",
    "bench.py", "tools/probe_watcher.py", "tools/bench_configs.py",
    "tools/bench_e2e.py", "tools/bench_scale.py",
    "tools/bench_search.py", "tools/bench_host_baseline.py",
    "tools/bench_serve.py", "tools/soak_prune.py",
    "qsm_tpu/serve/pool.py", "qsm_tpu/serve/worker.py")
# the serving plane the serve passes cover (repo-root-relative): every
# module that accepts connections, buffers lanes, or drives the server
# — including the pool supervisor and the worker's own recv loops
DEFAULT_SERVE_FILES = (
    "qsm_tpu/serve/server.py", "qsm_tpu/serve/batcher.py",
    "qsm_tpu/serve/admission.py", "qsm_tpu/serve/cache.py",
    "qsm_tpu/serve/client.py", "qsm_tpu/serve/protocol.py",
    "qsm_tpu/serve/pool.py", "qsm_tpu/serve/worker.py",
    "qsm_tpu/serve/frames.py", "tools/bench_serve.py",
    # the fleet tier accepts connections and loops over node links —
    # same accept/recv/queue discipline as the single-node plane
    "qsm_tpu/fleet/router.py", "qsm_tpu/fleet/membership.py",
    "qsm_tpu/fleet/replog.py", "qsm_tpu/fleet/lease.py",
    "qsm_tpu/fleet/gossip.py", "tools/bench_fleet.py")
# the worker-lifecycle modules the pool passes cover: everything that
# spawns, supervises, or benches worker processes
DEFAULT_POOL_FILES = (
    "qsm_tpu/serve/pool.py", "qsm_tpu/serve/worker.py",
    "tools/bench_serve.py")
# the race family's whole-program scan set — the widest beat: every
# module where threads, locks and child processes coordinate (serve +
# resilience planes and the tools that drive them); analyzed as ONE
# closed program, not file by file
DEFAULT_RACE_FILES = (
    "qsm_tpu/serve/server.py", "qsm_tpu/serve/batcher.py",
    "qsm_tpu/serve/admission.py", "qsm_tpu/serve/cache.py",
    "qsm_tpu/serve/client.py", "qsm_tpu/serve/protocol.py",
    "qsm_tpu/serve/pool.py", "qsm_tpu/serve/worker.py",
    "qsm_tpu/serve/frames.py",
    "qsm_tpu/resilience/policy.py", "qsm_tpu/resilience/failover.py",
    "qsm_tpu/resilience/faults.py", "qsm_tpu/resilience/checkpoint.py",
    # the P-compositional split plane: server sub-lane fan-out joins
    # across connection + dispatcher threads (serve/server.py _SubJoin),
    # and the combinator/planner it rides — one closed program with the
    # rest of the serving stack
    "qsm_tpu/ops/pcomp.py", "qsm_tpu/search/planner.py",
    # the shrink plane: the shrink verb runs the greedy loop on a
    # connection thread while its candidate lanes resolve from
    # dispatcher threads, and the shrink bank/counters are shared
    # across connections — same closed program
    "qsm_tpu/shrink/frontier.py", "qsm_tpu/shrink/shrinker.py",
    # the fleet tier: router connection/group threads, the membership
    # probe thread, the lease/gossip beat loops and the anti-entropy
    # loop share counters, links and node records — one closed
    # program with the serving stack
    "qsm_tpu/fleet/router.py", "qsm_tpu/fleet/membership.py",
    "qsm_tpu/fleet/replog.py", "qsm_tpu/fleet/lease.py",
    "qsm_tpu/fleet/gossip.py",
    # the monitor plane: session objects are driven from connection
    # threads while the manager's totals and the router's journals are
    # read from stats/replay paths — same closed program; the durable
    # session store (ISSUE 18) does disk IO under per-session locks
    "qsm_tpu/monitor/frontier.py", "qsm_tpu/monitor/session.py",
    "qsm_tpu/monitor/store.py",
    # the fleet-observability plane: the collector's sweep runs on the
    # router's beat thread while obs.trace readers and the federation
    # fan-out run on connection threads; the SLO evaluator is hit from
    # health ops, metrics scrapes and the breach trigger concurrently
    "qsm_tpu/obs/collect.py", "qsm_tpu/obs/slo.py",
    # the durable-session chaos soak: worker threads drive per-thread
    # clients while the rig SIGKILLs and respawns the fleet under them
    "qsm_tpu/gen/soak.py",
    # the device-work queue: banked from connection threads (the check/
    # pcomp seams), the gossip beat thread (anti-entropy) and the
    # shrink/monitor planes, drained by the watcher's drain process —
    # one shared RLock'd structure across the whole closed program
    "qsm_tpu/devq/queue.py", "qsm_tpu/devq/drain.py",
    "tools/bench_serve.py", "tools/bench_pcomp.py",
    "tools/bench_shrink.py", "tools/bench_fleet.py",
    "tools/probe_watcher.py", "tools/soak_prune.py",
    "tools/soak_sessions.py")

# the shrink-plane modules the frontier-bound pass covers (family h):
# the plane itself plus its bench driver
DEFAULT_SHRINK_FILES = (
    "qsm_tpu/shrink/frontier.py", "qsm_tpu/shrink/shrinker.py",
    "tools/bench_shrink.py")

# the fleet-tier modules the re-dispatch + lease + handoff passes
# cover (family j): the tier itself (HA lease and gossip modules
# included) plus its soak benches — the r13 fleet soak and the r18
# durable-session chaos rig
DEFAULT_FLEET_FILES = (
    "qsm_tpu/fleet/router.py", "qsm_tpu/fleet/membership.py",
    "qsm_tpu/fleet/replog.py", "qsm_tpu/fleet/lease.py",
    "qsm_tpu/fleet/gossip.py", "tools/bench_fleet.py",
    "qsm_tpu/gen/soak.py", "tools/soak_sessions.py")

# the monitor-plane modules the session-bound pass covers (family k):
# the streaming sessions + frontiers, the ingest adapters that feed
# them, and the monitor bench driver
DEFAULT_MONITOR_FILES = (
    "qsm_tpu/monitor/frontier.py", "qsm_tpu/monitor/session.py",
    "qsm_tpu/monitor/store.py",
    "qsm_tpu/ingest/adapters.py", "qsm_tpu/ingest/edn.py",
    "qsm_tpu/ingest/specmap.py", "qsm_tpu/ingest/tail.py",
    "tools/bench_monitor.py")

# the trace-plane discipline beat (family i): everything that opens
# spans or writes metrics — the obs plane itself (collection and SLO
# modules included), the serving stack that emits through it, the
# resilience layers that report into the global sink, and — since the
# plane went fleet-wide (ISSUE 15) — the fleet tier, the monitor
# sessions and the ingest adapters, whose emit sites previously sat
# outside the family's gate
DEFAULT_OBS_FILES = (
    "qsm_tpu/obs/__init__.py", "qsm_tpu/obs/trace.py",
    "qsm_tpu/obs/metrics.py", "qsm_tpu/obs/flight.py",
    "qsm_tpu/obs/collect.py", "qsm_tpu/obs/slo.py",
    "qsm_tpu/serve/server.py", "qsm_tpu/serve/batcher.py",
    "qsm_tpu/serve/admission.py", "qsm_tpu/serve/cache.py",
    "qsm_tpu/serve/client.py", "qsm_tpu/serve/protocol.py",
    "qsm_tpu/serve/pool.py", "qsm_tpu/serve/worker.py",
    "qsm_tpu/serve/frames.py",
    "qsm_tpu/resilience/policy.py", "qsm_tpu/resilience/failover.py",
    "qsm_tpu/resilience/faults.py", "qsm_tpu/resilience/checkpoint.py",
    "qsm_tpu/fleet/router.py", "qsm_tpu/fleet/membership.py",
    "qsm_tpu/fleet/replog.py", "qsm_tpu/fleet/lease.py",
    "qsm_tpu/fleet/gossip.py",
    "qsm_tpu/monitor/frontier.py", "qsm_tpu/monitor/session.py",
    "qsm_tpu/monitor/store.py",
    "qsm_tpu/ingest/adapters.py", "qsm_tpu/ingest/edn.py",
    "qsm_tpu/ingest/specmap.py", "qsm_tpu/ingest/tail.py",
    # the devq plane: the server's devq verbs emit obs events and the
    # drain report feeds the window_utilization SLO objective
    "qsm_tpu/devq/queue.py", "qsm_tpu/devq/drain.py",
    "tools/bench_obs.py", "tools/bench_fleet.py",
    "tools/bench_monitor.py")

# the generation-plane modules the campaign-bound pass covers (family
# m): the fuzzer core + steering loop + fleet shim, and the gen bench
# driver (ISSUE 17)
DEFAULT_GEN_FILES = (
    "qsm_tpu/gen/core.py", "qsm_tpu/gen/profile.py",
    "qsm_tpu/gen/steer.py", "qsm_tpu/gen/fleet.py",
    "tools/bench_gen.py")

# the mesh-dispatch scan set (family n): the substrate itself, the
# consumers that thread a sharding through compile buckets and flush
# targets, and the mesh bench driver (ISSUE 19).  topology.py is IN
# the set on purpose: the one place device enumeration may live must
# itself scan clean (parameters only, no literal counts).
DEFAULT_MESH_FILES = (
    "qsm_tpu/mesh/topology.py", "qsm_tpu/mesh/dispatch.py",
    "qsm_tpu/ops/jax_kernel.py", "qsm_tpu/search/planner.py",
    "qsm_tpu/serve/batcher.py",
    "tools/bench_mesh.py")

# the device-work-queue scan set (family o): the queue + drain plane
# itself and its window/bench drivers (ISSUE 20).  monitor/session.py
# has a ``_drain`` of its own (the reorder-buffer flush) and is
# deliberately NOT here — its bounds are family (k)'s jurisdiction.
DEFAULT_DEVQ_FILES = (
    "qsm_tpu/devq/queue.py", "qsm_tpu/devq/drain.py",
    "tools/window_drain.py", "tools/bench_devq.py")

# the wire-contract scan set (family l): the contract source, every
# module that dispatches or sends protocol ops, the helpers whose
# return docs become responses, and the CLI consumer paths.  The
# worker pipe (serve/pool.py + serve/worker.py + frames.py framing) is
# a DIFFERENT plane — supervisor⇄worker ops like ``warm``/``ping``/
# ``exit`` are deliberately outside the socket contract.
# ``PROTOCOL.json`` itself is listed so ``--changed`` re-runs the
# drift gate when only the committed artifact moved; the extractor
# skips non-``.py`` entries.
DEFAULT_PROTOCOL_FILES = (
    "qsm_tpu/serve/protocol.py", "qsm_tpu/serve/server.py",
    "qsm_tpu/serve/client.py", "qsm_tpu/serve/admission.py",
    "qsm_tpu/serve/frames.py",
    "qsm_tpu/fleet/router.py", "qsm_tpu/fleet/gossip.py",
    "qsm_tpu/fleet/membership.py", "qsm_tpu/fleet/lease.py",
    "qsm_tpu/obs/collect.py", "qsm_tpu/monitor/session.py",
    "qsm_tpu/utils/cli.py",
    "PROTOCOL.json")


def default_whitelist_path() -> str:
    return os.path.join(REPO_ROOT, ".qsmlint")


# ---------------------------------------------------------------------------
# the declarative family registry
# ---------------------------------------------------------------------------

class _LintRun:
    """Per-run context the family runners share: validated model
    names, lazily-built specs, knobs."""

    def __init__(self, names: List[str], retrace: bool, seed: int):
        self.names = names
        self.retrace = retrace
        self.seed = seed
        self._specs: Optional[List[tuple]] = None
        # family (l) stashes its contract summary here so the report
        # can carry a ``protocol`` block without a second extraction
        self.protocol_summary: Optional[dict] = None

    @property
    def specs(self) -> List[tuple]:
        if self._specs is None:
            from ..models.registry import MODELS

            self._specs = [(n, MODELS[n].make_spec(), f"model:{n}")
                           for n in self.names]
        return self._specs


@dataclasses.dataclass(frozen=True)
class Family:
    """One registered pass family.

    ``per_file`` families run an independent check over each file of
    their scan set (cacheable per file, subsettable by ``--changed``);
    ``whole`` families run once over the full set — semantic passes
    (spec/kernel, which execute code) and the whole-program race
    analysis (whose findings are a property of the set, not of any one
    file).  ``triggers`` are the extra path prefixes that force a
    ``whole`` family to run under ``--changed`` even when no scan-set
    file itself changed (spec soundness depends on the model sources,
    not on any linted file)."""

    fid: str                       # the --family id ("a".."g")
    key: str                       # passes-timing / report key
    title: str
    files: Tuple[str, ...] = ()
    base: str = "repo"             # scan-set paths relative to: repo|pkg
    per_file: Optional[Callable[[str, str], List[Finding]]] = None
    whole: Optional[Callable[["_LintRun", List[str]],
                             List[Finding]]] = None
    triggers: Tuple[str, ...] = ()
    cacheable: bool = True

    def resolve(self, rel: str) -> str:
        if os.path.isabs(rel):
            return rel
        root = _PKG_DIR if self.base == "pkg" else REPO_ROOT
        return os.path.join(root, rel)

    def repo_rel(self, rel: str) -> str:
        """Scan-set entry as a repo-relative path (the --changed and
        cache-key form)."""
        try:
            return os.path.relpath(self.resolve(rel), REPO_ROOT)
        except ValueError:
            return rel


def _run_spec(ctx: _LintRun, _files: List[str]) -> List[Finding]:
    from .kernel_passes import check_step_dtypes
    from .spec_passes import check_spec

    out: List[Finding] = []
    for _name, spec, loc in ctx.specs:
        out += check_spec(spec, loc, seed=ctx.seed)
        out += check_step_dtypes(spec, loc)
    return out


def _retrace_corpora(entry, spec):
    """Two same-bucket corpora for the retrace check (the second call
    must hit the warm compile cache); falls back to re-running the first
    corpus when generation buckets them differently."""
    from ..core.history import bucket_for
    from ..utils.corpus import build_corpus

    impls = (entry.impls["atomic"], entry.impls["racy"]) \
        if {"atomic", "racy"} <= set(entry.impls) \
        else tuple(entry.impls.values())[:2]
    a = build_corpus(spec, impls, n=6, n_pids=4, max_ops=10,
                     seed_base=0, seed_prefix="lint_a")
    b = build_corpus(spec, impls, n=6, n_pids=4, max_ops=10,
                     seed_base=100, seed_prefix="lint_b")
    bucket = bucket_for(max(len(h) for h in a) or 1)
    if bucket_for(max(len(h) for h in b) or 1) != bucket:
        b = a  # identical re-check is still a valid retrace probe
    return [a, b]


def _run_kernel(ctx: _LintRun, files: List[str]) -> List[Finding]:
    from ..models.registry import MODELS
    from .kernel_passes import (check_host_transfers, check_pallas_vmem,
                                check_retracing)

    out: List[Finding] = []
    for path in files:
        out += check_host_transfers(path, root=REPO_ROOT)
    out += check_pallas_vmem(
        [(spec, loc) for _, spec, loc in ctx.specs],
        "qsm_tpu/ops/pallas_kernel.py:MAX_PALLAS_STATES")
    if ctx.retrace and ctx.specs:
        # one representative family is enough: the check exercises the
        # DRIVER's compile-key discipline, which is spec-independent
        name, spec, _loc = ctx.specs[0]
        from ..ops.jax_kernel import JaxTPU

        backend = JaxTPU(spec, budget=2_000, mid_budget=0,
                         rescue_budget=0, rescue_slots=64)
        backend.CHUNK_SCHEDULE = (512,)   # one chunk shape: any cache
        backend.DOUBLE_BUFFER = False     # growth is a real retrace
        out += check_retracing(
            spec, backend, _retrace_corpora(MODELS[name], spec),
            "qsm_tpu/ops/jax_kernel.py")
    return out


def _per_file_sched(path: str, root: str) -> List[Finding]:
    from .sched_passes import check_sched_file

    return check_sched_file(path, root=root)


def _per_file_resilience(path: str, root: str) -> List[Finding]:
    from .resilience_passes import check_resilience_file

    return check_resilience_file(path, root=root)


def _per_file_serve(path: str, root: str) -> List[Finding]:
    from .serve_passes import check_serve_file

    return check_serve_file(path, root=root)


def _per_file_pool(path: str, root: str) -> List[Finding]:
    from .pool_passes import check_pool_file

    return check_pool_file(path, root=root)


def _run_race(_ctx: _LintRun, files: List[str]) -> List[Finding]:
    from .race_passes import check_race_project

    return check_race_project(files, root=REPO_ROOT)


def _per_file_shrink(path: str, root: str) -> List[Finding]:
    from .shrink_passes import check_shrink_file

    return check_shrink_file(path, root=root)


def _per_file_obs(path: str, root: str) -> List[Finding]:
    from .obs_passes import check_obs_file

    return check_obs_file(path, root=root)


def _per_file_fleet(path: str, root: str) -> List[Finding]:
    from .fleet_passes import check_fleet_file

    return check_fleet_file(path, root=root)


def _per_file_monitor(path: str, root: str) -> List[Finding]:
    from .monitor_passes import check_monitor_file

    return check_monitor_file(path, root=root)


def _per_file_gen(path: str, root: str) -> List[Finding]:
    from .gen_passes import check_gen_file

    return check_gen_file(path, root=root)


def _per_file_mesh(path: str, root: str) -> List[Finding]:
    from .mesh_passes import check_mesh_file

    return check_mesh_file(path, root=root)


def _per_file_devq(path: str, root: str) -> List[Finding]:
    from .devq_passes import check_devq_file

    return check_devq_file(path, root=root)


def _run_protocol(ctx: _LintRun, files: List[str]) -> List[Finding]:
    # one extraction serves both the conformance passes and the
    # report's ``protocol`` summary block (bench_report trends it);
    # not cacheable so the summary is present on every run and the
    # drift verdict always reflects the committed artifact
    from . import protocol_passes
    from .protocol_model import ProtocolModel

    model = ProtocolModel([p for p in files if p.endswith(".py")],
                          root=REPO_ROOT)
    ctx.protocol_summary = model.summary()
    return protocol_passes.check_model(model, root=REPO_ROOT)


FAMILIES: Dict[str, Family] = {f.fid: f for f in (
    Family(fid="a", key="spec",
           title="spec soundness (parity, domains, bounds, dtypes, "
                 "projections)",
           whole=_run_spec, cacheable=False,
           triggers=("qsm_tpu/models/", "qsm_tpu/core/",
                     # projection consumers: a pcomp/planner change can
                     # shift what QSM-SPEC-PCOMP must hold, so --changed
                     # runs re-validate the spec family too — and the
                     # shrink plane's drop-key axis trusts the same
                     # validated projection, so a shrink change
                     # re-validates it as well
                     "qsm_tpu/ops/pcomp.py", "qsm_tpu/search/planner.py",
                     "qsm_tpu/shrink/", "tools/bench_shrink.py",
                     "qsm_tpu/analysis/spec_passes.py",
                     "qsm_tpu/analysis/kernel_passes.py")),
    Family(fid="b", key="kernel",
           title="kernel trace hazards (host xfer, VMEM, retrace)",
           files=DEFAULT_OPS_FILES, base="pkg",
           whole=_run_kernel, cacheable=False,
           triggers=("qsm_tpu/ops/", "qsm_tpu/models/", "qsm_tpu/core/",
                     "qsm_tpu/analysis/kernel_passes.py",
                     "qsm_tpu/analysis/astutil.py")),
    Family(fid="c", key="sched",
           title="scheduler determinism",
           files=DEFAULT_SCHED_FILES, base="pkg",
           per_file=_per_file_sched,
           triggers=("qsm_tpu/analysis/sched_passes.py",
                     "qsm_tpu/analysis/astutil.py")),
    Family(fid="d", key="resilience",
           title="unbounded device/subprocess calls",
           files=DEFAULT_RESILIENCE_FILES,
           per_file=_per_file_resilience,
           triggers=("qsm_tpu/analysis/resilience_passes.py",
                     "qsm_tpu/analysis/astutil.py")),
    Family(fid="e", key="serve",
           title="serving-plane loops and queues",
           files=DEFAULT_SERVE_FILES, per_file=_per_file_serve,
           triggers=("qsm_tpu/analysis/serve_passes.py",
                     "qsm_tpu/analysis/astutil.py")),
    Family(fid="f", key="pool",
           title="worker-process lifecycle",
           files=DEFAULT_POOL_FILES, per_file=_per_file_pool,
           triggers=("qsm_tpu/analysis/pool_passes.py",
                     "qsm_tpu/analysis/astutil.py")),
    Family(fid="g", key="race",
           title="interprocedural lock/thread races (whole-program)",
           files=DEFAULT_RACE_FILES, whole=_run_race,
           triggers=("qsm_tpu/analysis/callgraph.py",
                     "qsm_tpu/analysis/race_passes.py",
                     "qsm_tpu/analysis/astutil.py")),
    Family(fid="h", key="shrink",
           title="shrink-plane frontier bounds",
           files=DEFAULT_SHRINK_FILES, per_file=_per_file_shrink,
           triggers=("qsm_tpu/analysis/shrink_passes.py",
                     "qsm_tpu/analysis/astutil.py")),
    Family(fid="i", key="obs",
           title="trace-plane discipline (span close, metric "
                 "cardinality)",
           files=DEFAULT_OBS_FILES, per_file=_per_file_obs,
           triggers=("qsm_tpu/analysis/obs_passes.py",
                     "qsm_tpu/analysis/astutil.py")),
    Family(fid="j", key="fleet",
           title="fleet re-dispatch + lease + handoff discipline "
                 "(bounded attempts, failed-node exclusion, "
                 "term/expiry-gated promotion, seeded joins, migrated "
                 "leaves)",
           files=DEFAULT_FLEET_FILES, per_file=_per_file_fleet,
           triggers=("qsm_tpu/analysis/fleet_passes.py",
                     "qsm_tpu/analysis/astutil.py")),
    Family(fid="k", key="monitor",
           title="monitor-session bounds (capped buffers, "
                 "decided-prefix eviction)",
           files=DEFAULT_MONITOR_FILES, per_file=_per_file_monitor,
           triggers=("qsm_tpu/analysis/monitor_passes.py",
                     "qsm_tpu/analysis/astutil.py")),
    Family(fid="l", key="protocol",
           title="wire-contract conformance (unhandled ops, field "
                 "drift, egress stamping, retry idempotency, SHED "
                 "purity)",
           files=DEFAULT_PROTOCOL_FILES,
           whole=_run_protocol, cacheable=False,
           triggers=("qsm_tpu/serve/", "qsm_tpu/fleet/",
                     "qsm_tpu/analysis/protocol_model.py",
                     "qsm_tpu/analysis/protocol_passes.py",
                     "qsm_tpu/analysis/callgraph.py",
                     "qsm_tpu/analysis/astutil.py",
                     "PROTOCOL.json")),
    Family(fid="m", key="gen",
           title="generation-campaign bounds (capacity-evicted seed "
                 "pools, tail-windowed flip logs)",
           files=DEFAULT_GEN_FILES, per_file=_per_file_gen,
           triggers=("qsm_tpu/analysis/gen_passes.py",
                     # family m's scan shares family k's class scan
                     "qsm_tpu/analysis/monitor_passes.py",
                     "qsm_tpu/analysis/astutil.py")),
    Family(fid="n", key="mesh",
           title="mesh-dispatch discipline (no hardcoded device "
                 "counts, no host transfer inside sharded dispatch)",
           files=DEFAULT_MESH_FILES, per_file=_per_file_mesh,
           triggers=("qsm_tpu/analysis/mesh_passes.py",
                     "qsm_tpu/analysis/astutil.py")),
    Family(fid="o", key="devq",
           title="device-work-queue discipline (bounded banked work, "
                 "deadline-consulting drain loops)",
           files=DEFAULT_DEVQ_FILES, per_file=_per_file_devq,
           triggers=("qsm_tpu/analysis/devq_passes.py",
                     # family o's scan shares family k's class scan
                     # and family m's ownership refinement
                     "qsm_tpu/analysis/monitor_passes.py",
                     "qsm_tpu/analysis/gen_passes.py",
                     "qsm_tpu/analysis/astutil.py")),
)}


@dataclasses.dataclass
class LintReport:
    findings: List[Finding]          # non-whitelisted
    whitelisted: List[Finding]
    passes: Dict[str, float]         # family key -> seconds
    seconds: float
    models: List[str]
    whitelist_path: Optional[str] = None  # the file actually loaded
    families: List[str] = dataclasses.field(default_factory=list)
    cache: Optional[dict] = None     # {path, hits, misses}
    changed: Optional[dict] = None   # {ref, files} when --changed ran
    protocol: Optional[dict] = None  # family (l) contract summary

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def ok(self) -> bool:
        """True when no non-whitelisted error-severity findings."""
        return not self.errors

    def _meta(self) -> dict:
        meta = {"ok": self.ok,
                "seconds": round(self.seconds, 3),
                "passes": {k: round(v, 3)
                           for k, v in self.passes.items()},
                "models": self.models,
                "families": self.families}
        if self.cache is not None:
            meta["cache"] = self.cache
        if self.changed is not None:
            meta["changed"] = self.changed
        if self.protocol is not None:
            meta["protocol"] = self.protocol
        return meta

    def to_json(self) -> str:
        return render_json(self.findings, self.whitelisted,
                           meta=self._meta())

    def to_sarif(self) -> str:
        from .findings import render_sarif

        return render_sarif(self.findings, self.whitelisted,
                            meta=self._meta())


def _resolve_whitelist(whitelist: Union[None, str, Whitelist]
                       ) -> Optional[Whitelist]:
    if isinstance(whitelist, Whitelist):
        return whitelist
    if isinstance(whitelist, str):
        return Whitelist.load(whitelist)
    path = default_whitelist_path()
    if os.path.exists(path):
        return Whitelist.load(path)
    return None


def _resolve_families(families: Optional[Sequence[str]]
                      ) -> List[Family]:
    if families is None:
        return list(FAMILIES.values())
    unknown = [f for f in families if f not in FAMILIES]
    if unknown:
        raise ValueError(f"unknown pass families {unknown}; "
                         f"one of {sorted(FAMILIES)}")
    # dedupe, order-preserving: "--family e,e" must not double every
    # finding in the archived report
    return [FAMILIES[f] for f in dict.fromkeys(families)]


def run_lint(models: Optional[Sequence[str]] = None,
             retrace: bool = True,
             whitelist: Union[None, str, Whitelist] = None,
             families: Optional[Sequence[str]] = None,
             changed: Optional[str] = None,
             cache: Union[bool, str] = True,
             file_overrides: Optional[Dict[str, Sequence[str]]] = None,
             seed: int = 0) -> LintReport:
    """Run the registered pass families.

    ``families`` — family ids to run (None = all registered).
    ``changed`` — a git ref: narrow per-file families to modules
    touched since it (whole families run iff their scan set or
    triggers were touched); git trouble falls back to the full tree.
    ``cache`` — True (default cache path), a path, or False: reuse
    per-file findings whose content digest is unchanged.
    ``file_overrides`` — family id -> replacement scan set (tests)."""
    from ..models.registry import MODELS
    from . import incremental

    t_start = time.perf_counter()
    names = list(models) if models else sorted(MODELS)
    unknown = [n for n in names if n not in MODELS]
    if unknown:
        raise ValueError(f"unknown model families {unknown}; "
                         f"one of {sorted(MODELS)}")
    fams = _resolve_families(families)
    ctx = _LintRun(names, retrace, seed)

    changed_set: Optional[Set[str]] = None
    changed_meta: Optional[dict] = None
    if changed is not None:
        changed_set = incremental.changed_files(REPO_ROOT, changed)
        changed_meta = {"ref": changed,
                        "files": (sorted(changed_set)
                                  if changed_set is not None else None),
                        "git_ok": changed_set is not None}

    lint_cache: Optional[incremental.LintCache] = None
    if cache:
        cache_path = (cache if isinstance(cache, str)
                      else incremental.default_cache_path(REPO_ROOT))
        lint_cache = incremental.LintCache(cache_path)

    findings: List[Finding] = []
    passes: Dict[str, float] = {}
    for fam in fams:
        t0 = time.perf_counter()
        findings += _run_family(fam, ctx, changed_set, lint_cache,
                                file_overrides or {})
        passes[fam.key] = time.perf_counter() - t0
    if lint_cache is not None:
        lint_cache.save()

    wl = _resolve_whitelist(whitelist)
    kept, allowed = split_whitelisted(findings, wl)
    return LintReport(findings=kept, whitelisted=allowed, passes=passes,
                      seconds=time.perf_counter() - t_start,
                      models=names,
                      whitelist_path=wl.path if wl else None,
                      families=[f.fid for f in fams],
                      cache=(lint_cache.stats() if lint_cache else None),
                      changed=changed_meta,
                      protocol=ctx.protocol_summary)


def _run_family(fam: Family, ctx: _LintRun,
                changed_set: Optional[Set[str]],
                cache, file_overrides: Dict[str, Sequence[str]]
                ) -> List[Finding]:
    from . import incremental

    file_set = list(file_overrides.get(fam.fid, fam.files))
    rel_paths = [fam.repo_rel(r) for r in file_set]
    abs_paths = [fam.resolve(r) for r in file_set]

    # a touched trigger (the family's own pass source) re-lints the
    # whole scan set even when no scanned file changed: a rule edit
    # must be exercised, not skipped, under --changed
    trigger_hit = (changed_set is not None and fam.triggers
                   and any(p.startswith(fam.triggers)
                           for p in changed_set))

    if fam.per_file is not None:
        out: List[Finding] = []
        for rel, path in zip(rel_paths, abs_paths):
            if (changed_set is not None and rel not in changed_set
                    and not trigger_hit):
                continue
            if cache is not None and fam.cacheable:
                key = f"{fam.fid}:{rel}:{incremental.file_digest(path)}"
                hit = cache.get(key)
                if hit is not None:
                    out += hit
                    continue
                found = fam.per_file(path, REPO_ROOT)
                cache.put(key, found)
            else:  # --no-cache: don't hash files for a discarded key
                found = fam.per_file(path, REPO_ROOT)
            out += found
        return out

    # whole-set family: under --changed it runs iff its scan set or
    # trigger prefixes were touched; cacheable ones key on the set
    if changed_set is not None:
        if not trigger_hit and not any(rel in changed_set
                                       for rel in rel_paths):
            return []
    if cache is not None and fam.cacheable:
        key = f"{fam.fid}:<set>:{incremental.combined_digest(abs_paths)}"
        hit = cache.get(key)
        if hit is not None:
            return hit
        found = fam.whole(ctx, abs_paths)
        cache.put(key, found)
        return found
    return fam.whole(ctx, abs_paths)
