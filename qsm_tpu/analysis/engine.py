"""``qsmlint`` orchestration — every pass family over the in-tree corpus.

One entry point, :func:`run_lint`: spec soundness passes over every
registry model family, kernel trace-hazard passes over the five
lineariser engine modules (ops/jax_kernel.py, ops/pallas_kernel.py,
ops/segdc.py, ops/rootsplit.py, ops/pcomp.py), determinism passes over
the scheduler plane (sched/).  CPU-only by contract: callers pin the
platform (utils/cli.py cmd_lint forces it) and nothing here constructs
a device backend — the entire point is deciding cheaply BEFORE any TPU
window opens.

Consumed by ``python -m qsm_tpu lint`` (exit 1 on non-whitelisted
error findings), tests/test_lint.py (the tier-1 gate) and
tools/probe_watcher.py (the pre-seize hook that refuses to spend a
healing window on statically-broken code).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Dict, List, Optional, Sequence, Union

from .findings import (ERROR, Finding, Whitelist, render_json,
                       split_whitelisted)

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(_PKG_DIR)

# the five lineariser engine modules the kernel passes cover
DEFAULT_OPS_FILES = ("ops/jax_kernel.py", "ops/pallas_kernel.py",
                     "ops/segdc.py", "ops/rootsplit.py", "ops/pcomp.py")
# the scheduler plane the determinism passes cover
DEFAULT_SCHED_FILES = ("sched/scheduler.py", "sched/pool.py",
                       "sched/transport.py", "sched/runner.py")
# every module that touches the device substrate or spawns bounded
# children — the resilience passes' beat (repo-root-relative: the tools
# live outside the package)
DEFAULT_RESILIENCE_FILES = (
    "qsm_tpu/ops/jax_kernel.py", "qsm_tpu/ops/pallas_kernel.py",
    "qsm_tpu/ops/segdc.py", "qsm_tpu/ops/rootsplit.py",
    "qsm_tpu/ops/pcomp.py", "qsm_tpu/utils/device.py",
    "qsm_tpu/utils/cli.py", "qsm_tpu/native/__init__.py",
    "bench.py", "tools/probe_watcher.py", "tools/bench_configs.py",
    "tools/bench_e2e.py", "tools/bench_scale.py",
    "tools/bench_search.py", "tools/bench_host_baseline.py",
    "tools/bench_serve.py", "tools/soak_prune.py",
    "qsm_tpu/serve/pool.py", "qsm_tpu/serve/worker.py")
# the serving plane the serve passes cover (repo-root-relative): every
# module that accepts connections, buffers lanes, or drives the server
# — including the pool supervisor and the worker's own recv loops
DEFAULT_SERVE_FILES = (
    "qsm_tpu/serve/server.py", "qsm_tpu/serve/batcher.py",
    "qsm_tpu/serve/admission.py", "qsm_tpu/serve/cache.py",
    "qsm_tpu/serve/client.py", "qsm_tpu/serve/protocol.py",
    "qsm_tpu/serve/pool.py", "qsm_tpu/serve/worker.py",
    "qsm_tpu/serve/frames.py", "tools/bench_serve.py")
# the worker-lifecycle modules the pool passes cover: everything that
# spawns, supervises, or benches worker processes
DEFAULT_POOL_FILES = (
    "qsm_tpu/serve/pool.py", "qsm_tpu/serve/worker.py",
    "tools/bench_serve.py")


def default_whitelist_path() -> str:
    return os.path.join(REPO_ROOT, ".qsmlint")


@dataclasses.dataclass
class LintReport:
    findings: List[Finding]          # non-whitelisted
    whitelisted: List[Finding]
    passes: Dict[str, float]         # pass family -> seconds
    seconds: float
    models: List[str]
    whitelist_path: Optional[str] = None  # the file actually loaded

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def ok(self) -> bool:
        """True when no non-whitelisted error-severity findings."""
        return not self.errors

    def to_json(self) -> str:
        return render_json(
            self.findings, self.whitelisted,
            meta={"ok": self.ok,
                  "seconds": round(self.seconds, 3),
                  "passes": {k: round(v, 3)
                             for k, v in self.passes.items()},
                  "models": self.models})


def _resolve_whitelist(whitelist: Union[None, str, Whitelist]
                       ) -> Optional[Whitelist]:
    if isinstance(whitelist, Whitelist):
        return whitelist
    if isinstance(whitelist, str):
        return Whitelist.load(whitelist)
    path = default_whitelist_path()
    if os.path.exists(path):
        return Whitelist.load(path)
    return None


def _retrace_corpora(entry, spec):
    """Two same-bucket corpora for the retrace check (the second call
    must hit the warm compile cache); falls back to re-running the first
    corpus when generation buckets them differently."""
    from ..core.history import bucket_for
    from ..utils.corpus import build_corpus

    impls = (entry.impls["atomic"], entry.impls["racy"]) \
        if {"atomic", "racy"} <= set(entry.impls) \
        else tuple(entry.impls.values())[:2]
    a = build_corpus(spec, impls, n=6, n_pids=4, max_ops=10,
                     seed_base=0, seed_prefix="lint_a")
    b = build_corpus(spec, impls, n=6, n_pids=4, max_ops=10,
                     seed_base=100, seed_prefix="lint_b")
    bucket = bucket_for(max(len(h) for h in a) or 1)
    if bucket_for(max(len(h) for h in b) or 1) != bucket:
        b = a  # identical re-check is still a valid retrace probe
    return [a, b]


def run_lint(models: Optional[Sequence[str]] = None,
             retrace: bool = True,
             whitelist: Union[None, str, Whitelist] = None,
             ops_files: Optional[Sequence[str]] = None,
             sched_files: Optional[Sequence[str]] = None,
             resilience_files: Optional[Sequence[str]] = None,
             serve_files: Optional[Sequence[str]] = None,
             pool_files: Optional[Sequence[str]] = None,
             seed: int = 0) -> LintReport:
    from ..models.registry import MODELS
    from .kernel_passes import (check_host_transfers, check_pallas_vmem,
                                check_retracing, check_step_dtypes)
    from .pool_passes import check_pool_file
    from .resilience_passes import check_resilience_file
    from .sched_passes import check_sched_file
    from .serve_passes import check_serve_file
    from .spec_passes import check_spec

    t_start = time.perf_counter()
    names = list(models) if models else sorted(MODELS)
    unknown = [n for n in names if n not in MODELS]
    if unknown:
        raise ValueError(f"unknown model families {unknown}; "
                         f"one of {sorted(MODELS)}")
    findings: List[Finding] = []
    passes: Dict[str, float] = {}

    # --- (a) spec soundness + step_jax dtype abstract eval ---------------
    t0 = time.perf_counter()
    specs = []
    for name in names:
        spec = MODELS[name].make_spec()
        loc = f"model:{name}"
        specs.append((name, spec, loc))
        findings += check_spec(spec, loc, seed=seed)
        findings += check_step_dtypes(spec, loc)
    passes["spec"] = time.perf_counter() - t0

    # --- (b) kernel trace hazards ----------------------------------------
    t0 = time.perf_counter()
    for rel in (ops_files if ops_files is not None else DEFAULT_OPS_FILES):
        path = rel if os.path.isabs(rel) else os.path.join(_PKG_DIR, rel)
        findings += check_host_transfers(path, root=REPO_ROOT)
    findings += check_pallas_vmem(
        [(spec, loc) for _, spec, loc in specs],
        "qsm_tpu/ops/pallas_kernel.py:MAX_PALLAS_STATES")
    if retrace and specs:
        # one representative family is enough: the check exercises the
        # DRIVER's compile-key discipline, which is spec-independent
        name, spec, _loc = specs[0]
        from ..ops.jax_kernel import JaxTPU

        backend = JaxTPU(spec, budget=2_000, mid_budget=0,
                         rescue_budget=0, rescue_slots=64)
        backend.CHUNK_SCHEDULE = (512,)   # one chunk shape: any cache
        backend.DOUBLE_BUFFER = False     # growth is a real retrace
        findings += check_retracing(
            spec, backend, _retrace_corpora(MODELS[name], spec),
            "qsm_tpu/ops/jax_kernel.py")
    passes["kernel"] = time.perf_counter() - t0

    # --- (c) determinism / race ------------------------------------------
    t0 = time.perf_counter()
    for rel in (sched_files if sched_files is not None
                else DEFAULT_SCHED_FILES):
        path = rel if os.path.isabs(rel) else os.path.join(_PKG_DIR, rel)
        findings += check_sched_file(path, root=REPO_ROOT)
    passes["sched"] = time.perf_counter() - t0

    # --- (d) resilience: unbounded device calls --------------------------
    t0 = time.perf_counter()
    for rel in (resilience_files if resilience_files is not None
                else DEFAULT_RESILIENCE_FILES):
        # repo-root-relative by convention: the tool modules live
        # outside the package (bench.py, tools/)
        path = rel if os.path.isabs(rel) else os.path.join(REPO_ROOT, rel)
        findings += check_resilience_file(path, root=REPO_ROOT)
    passes["resilience"] = time.perf_counter() - t0

    # --- (e) serve: unbounded accept loops / queues ----------------------
    t0 = time.perf_counter()
    for rel in (serve_files if serve_files is not None
                else DEFAULT_SERVE_FILES):
        path = rel if os.path.isabs(rel) else os.path.join(REPO_ROOT, rel)
        findings += check_serve_file(path, root=REPO_ROOT)
    passes["serve"] = time.perf_counter() - t0

    # --- (f) pool: unreaped workers / respawn storms ---------------------
    t0 = time.perf_counter()
    for rel in (pool_files if pool_files is not None
                else DEFAULT_POOL_FILES):
        path = rel if os.path.isabs(rel) else os.path.join(REPO_ROOT, rel)
        findings += check_pool_file(path, root=REPO_ROOT)
    passes["pool"] = time.perf_counter() - t0

    wl = _resolve_whitelist(whitelist)
    kept, allowed = split_whitelisted(findings, wl)
    return LintReport(findings=kept, whitelisted=allowed, passes=passes,
                      seconds=time.perf_counter() - t_start,
                      models=names,
                      whitelist_path=wl.path if wl else None)
