"""Protocol-conformance passes — lint family (l), docs/ANALYSIS.md §l.

Checks the extracted protocol IR (:mod:`protocol_model`) against the
contract declared in ``serve/protocol.py``:

* **QSM-PROTO-UNHANDLED** — an op some path sends with no handler
  anywhere; a declared op nobody handles or nobody calls; an op on
  the wire that the contract does not declare.
* **QSM-PROTO-FIELDS** — a response key a consumer reads that no
  handler of that op ever writes; a request key a handler reads that
  no sender ever sets.  Envelope keys are exempt; ops whose response
  is built dynamically (unresolvable call) stand down in that
  direction.
* **QSM-PROTO-EGRESS** — a raw ``send_doc``/``sendall`` inside an
  egress class (one that defines ``_send`` + ``_handle``) but outside
  its ``_send``: the response would skip node/term stamping.
* **QSM-PROTO-RETRY-IDEMPOTENT** — an op reachable from a retrying
  call path (client failover, ``NodeLink`` fresh-socket retry, router
  re-dispatch) that the contract does not declare idempotent.
* **QSM-PROTO-SHED** — a single response doc carrying ``shed`` along
  with verdict/witness keys: SHED must never be a verdict.
* **QSM-PROTO-DRIFT** — the committed ``PROTOCOL.json`` no longer
  matches a fresh extraction (``make protocol`` regenerates).

Coverage and drift checks only run when the scanned file set carries
the contract source (``serve/protocol.py``) — fixture sub-programs
exercise the per-site rules without inheriting the live vocabulary.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Set

from .findings import ERROR, Finding
from .protocol_model import (PROTOCOL_ARTIFACT, ProtocolModel,
                             render_protocol_json)

# keys that mean "this doc carries a verdict/witness" — a shed doc
# carrying any of them violates the SHED-is-never-a-verdict contract
_VERDICT_KEYS = frozenset((
    "verdict", "verdicts", "witness", "witnesses", "violations",
    "flip", "history", "histories",
))


def check_protocol_project(paths: Sequence[str],
                           root: Optional[str] = None,
                           protocol_path: Optional[str] = None,
                           ) -> List[Finding]:
    model = ProtocolModel([p for p in paths if p.endswith(".py")],
                          root=root)
    return check_model(model, root=root, protocol_path=protocol_path)


def check_model(model: ProtocolModel,
                root: Optional[str] = None,
                protocol_path: Optional[str] = None) -> List[Finding]:
    findings: List[Finding] = []
    findings += _check_unhandled(model)
    findings += _check_fields(model)
    findings += _check_egress(model)
    findings += _check_retry_idempotent(model)
    findings += _check_shed(model)
    if model.contract.source is not None:
        findings += _check_drift(model, root, protocol_path)
    return findings


def _loc(model: ProtocolModel, qual: str, line: int) -> str:
    return model.project.rel_loc(qual, line)


def _check_unhandled(model: ProtocolModel) -> List[Finding]:
    out: List[Finding] = []
    handled = {op for op, _cls in model.handlers}
    called = {s.op for s in model.send_sites}
    for site in model.send_sites:
        if site.op not in handled:
            out.append(Finding(
                ERROR, "QSM-PROTO-UNHANDLED",
                _loc(model, site.qual, site.line),
                f"op {site.op!r} is sent here but no handler "
                f"dispatches it (no egress class `_handle` branch "
                f"names it)",
                "add a dispatch branch, or drop the dead request"))
    if not model.contract.declared:
        return out
    declared = model.contract.ops
    src = model.contract.source or "<class declarations>"
    for op in sorted(declared - handled):
        out.append(Finding(
            ERROR, "QSM-PROTO-UNHANDLED", f"{src}:OPS",
            f"declared op {op!r} has no handler in the scanned tree",
            "implement the handler or retire the op from OPS"))
    for op in sorted((declared & handled) - called):
        out.append(Finding(
            ERROR, "QSM-PROTO-UNHANDLED", f"{src}:OPS",
            f"declared op {op!r} is handled but no caller path "
            f"sends it",
            "wire a client path or retire the dead op"))
    for op in sorted((called | handled) - declared):
        where = next((_loc(model, s.qual, s.line)
                      for s in model.send_sites if s.op == op),
                     f"{src}:OPS")
        out.append(Finding(
            ERROR, "QSM-PROTO-UNHANDLED", where,
            f"op {op!r} is on the wire but not declared in OPS "
            f"({src})",
            "add it to OPS (and IDEMPOTENT_OPS if retry-safe)"))
    return out


def _check_fields(model: ProtocolModel) -> List[Finding]:
    out: List[Finding] = []
    resp_env = model.contract.response_envelope
    req_env = model.contract.request_envelope
    egress_stamps: Set[str] = set()
    for info in model.egress.values():
        egress_stamps.update(info["stamps"])
    for op in model.ops_seen():
        handlers = [h for (hop, _c), h in sorted(model.handlers.items())
                    if hop == op]
        senders = [s for s in model.send_sites if s.op == op]
        # response direction: every consumer-read key must be written
        # by some handler of the op (skip when any handler's response
        # is dynamic — we cannot enumerate what it writes)
        if handlers and not any(h.dynamic_response for h in handlers):
            written: Set[str] = set()
            for h in handlers:
                written |= h.response_keys_written
            written |= resp_env | egress_stamps
            for key, qual, line in sorted(
                    set(model.consumer_reads.get(op, ()))):
                if key not in written:
                    out.append(Finding(
                        ERROR, "QSM-PROTO-FIELDS",
                        _loc(model, qual, line),
                        f"response key {key!r} of op {op!r} is read "
                        f"here but no handler of that op writes it",
                        "write the key in the handler or drop the "
                        "dead read"))
        # request direction: every key a handler reads must be set by
        # some sender (skip when any sender's request is dynamic, or
        # when a forwarding path re-sends an unknowable superset).
        # Reads at a shared-helper root are attributed to every
        # co-dispatched op, so the settable set unions the senders of
        # the whole co-dispatch group — per-branch reads stay precise.
        group = model.co_dispatched.get(op, set()) | {op}
        g_senders = [s for s in model.send_sites if s.op in group]
        if senders and not any(s.dynamic_request for s in g_senders):
            settable: Set[str] = set(req_env)
            for s in g_senders:
                settable |= s.request_keys
            forwarded = any(h.forwards_request for h in handlers)
            for h in handlers:
                for key in sorted(h.request_keys_read - settable):
                    if forwarded:
                        continue
                    out.append(Finding(
                        ERROR, "QSM-PROTO-FIELDS",
                        f"{h.path}:{h.cls}._handle",
                        f"handler {h.cls} reads request key {key!r} "
                        f"of op {op!r} but no sender sets it",
                        "set the key at a call site or drop the "
                        "dead read"))
    return out


def _check_egress(model: ProtocolModel) -> List[Finding]:
    out: List[Finding] = []
    for qual, line, name in model.egress_violations:
        fn = model.project.functions[qual]
        out.append(Finding(
            ERROR, "QSM-PROTO-EGRESS", _loc(model, qual, line),
            f"raw {name}() inside egress class {fn.cls} bypasses its "
            f"one `_send` (responses would skip node/term stamping)",
            "route the response through self._send"))
    return out


def _check_retry_idempotent(model: ProtocolModel) -> List[Finding]:
    out: List[Finding] = []
    idempotent = model.contract.idempotent
    for site in model.send_sites:
        if not site.retried or site.op in idempotent:
            continue
        via = ", ".join(v.split(":")[-1]
                        for v in sorted(site.retry_via)) or "?"
        out.append(Finding(
            ERROR, "QSM-PROTO-RETRY-IDEMPOTENT",
            _loc(model, site.qual, site.line),
            f"op {site.op!r} rides a retrying call path (via {via}) "
            f"but is not in the declared idempotent set",
            "make the op replay-safe and add it to IDEMPOTENT_OPS "
            "in serve/protocol.py, or move it off the retry path"))
    return out


def _check_shed(model: ProtocolModel) -> List[Finding]:
    out: List[Finding] = []
    seen: Set[str] = set()
    for (op, cls), h in sorted(model.handlers.items()):
        for keys, _dyn, merged in h.response_alts:
            if merged or "shed" not in keys:
                continue
            bad = sorted(keys & _VERDICT_KEYS)
            if not bad:
                continue
            loc = f"{h.path}:{cls}.{op}"
            if loc in seen:
                continue
            seen.add(loc)
            out.append(Finding(
                ERROR, "QSM-PROTO-SHED", loc,
                f"a shed response of op {op!r} also carries verdict/"
                f"witness key(s) {', '.join(repr(b) for b in bad)} — "
                f"SHED must never read as a verdict",
                "strip verdict keys from the shed doc"))
    return out


def _check_drift(model: ProtocolModel, root: Optional[str],
                 protocol_path: Optional[str]) -> List[Finding]:
    root = root or os.getcwd()
    path = protocol_path or os.path.join(root, PROTOCOL_ARTIFACT)
    fresh = render_protocol_json(model)
    try:
        with open(path) as f:
            committed = f.read()
    except OSError:
        return [Finding(
            ERROR, "QSM-PROTO-DRIFT", PROTOCOL_ARTIFACT,
            "committed PROTOCOL.json is missing",
            "run `make protocol` and commit the artifact")]
    if committed != fresh:
        return [Finding(
            ERROR, "QSM-PROTO-DRIFT", PROTOCOL_ARTIFACT,
            "committed PROTOCOL.json does not match a fresh "
            "extraction from this tree",
            "run `make protocol` and commit the regenerated "
            "artifact")]
    return []
