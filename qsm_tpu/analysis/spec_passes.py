"""Spec soundness passes (pass family *a* of docs/ANALYSIS.md).

A spec defect is the cheapest possible window-burner: ``step_py`` is the
oracle's truth, ``step_jax`` is what the device kernel traces, and every
declared bound (``scalar_state_bound`` / ``state_elem_bounds``) gates a
fast path whose soundness the kernel TRUSTS (ops/jax_kernel.py gathers a
precomputed step-table row instead of re-evaluating the spec).  Any
divergence survives until a real chip decides a history differently
from the oracle — which round 5 priced at one wasted healing window.

All passes are CPU-only and semantic (they execute the spec, they do
not parse it):

* ``QSM-SPEC-SIG``         — CmdSig domain consistency (positive arg /
  resp domains, unique names, STATE_DIM vs initial_state agreement).
* ``QSM-SPEC-RESP-DOMAIN`` — ``resp_domain(c)`` ⊆ ``[0, n_resps)`` for
  every command (subclasses may override the derived default).
* ``QSM-SPEC-GEN``         — sampled ``gen_cmd`` stays inside the
  declared (cmd, arg) domains and respects its own precondition.
* ``QSM-SPEC-REACH``       — every command is issuable (precondition
  true somewhere) and ok-respondable on sampled reachable states.
* ``QSM-SPEC-BOUND``       — ``scalar_state_bound``/``state_elem_bounds``
  hold along simulated ok-step trajectories whose ARGS are in-domain and
  whose RESPS are arbitrary (the bound's exact contract, core/spec.py).
* ``QSM-SPEC-PARITY``      — ``step_py`` vs ``step_jax`` agreement:
  exhaustive over the declared scalar state space when tabulable, else
  sampled over trajectory-reachable states; resps include out-of-domain
  values (SUTs can return anything).
* ``QSM-SPEC-PCOMP``       — a declared per-key projection
  (``CmdSig.proj`` / ``projected_spec``) must VALIDATE: total
  partition_key, in-domain projected ops, faithful + independent per-key
  semantics (``core.spec.projection_report``).  An unsound declaration
  would let the P-compositional checkers split a history they must not
  (unsound verdicts); the planner refuses at plan time, and this pass
  refuses before any corpus is even built.
"""

from __future__ import annotations

import random
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core.history import OP_BUCKETS
from ..core.spec import Spec
from .findings import ERROR, INFO, WARNING, Finding

# The op-count the bound/parity contracts are checked at: the LARGEST
# history bucket the kernels accept (core/history.py), not some smaller
# convenience size — a growth-shaped bound like the ticket dispenser's
# ``n_ops + 1`` reaches states up to this ceiling on-device, and a
# step_jax divergence that only bites near the top of the range must
# not lint green (review finding on the first version, which checked
# at 16 ops while the kernels run up to 128).
KERNEL_OPS_CEILING = OP_BUCKETS[-1]

# out-of-domain responses every pass mixes in: the kernel sees whatever
# a (buggy) SUT produced, so contracts must hold for arbitrary ints
_EXTRA_RESPS = (-1, 1 << 20)

_MAX_PARITY_STATES = 256   # states fed to the vmapped step_jax compare
# (> ticket's bound(128) = 129, so every in-tree scalar spec stays
# EXHAUSTIVE at the ops ceiling)
_MAX_PAIR_SAMPLES = 96     # (cmd, arg) pairs per state when not exhaustive


def _pairs(spec: Spec, rng: random.Random) -> List[Tuple[int, int]]:
    """All (cmd, arg) pairs when small, else a deterministic sample."""
    total = sum(c.n_args for c in spec.CMDS)
    if total <= _MAX_PAIR_SAMPLES:
        return [(c, a) for c, sig in enumerate(spec.CMDS)
                for a in range(sig.n_args)]
    out = []
    for _ in range(_MAX_PAIR_SAMPLES):
        c = rng.randrange(spec.n_cmds)
        out.append((c, rng.randrange(spec.CMDS[c].n_args)))
    return out


def _resps_for(spec: Spec, cmd: int, state: Sequence[int]
               ) -> List[int]:
    """In-domain resps (capped) + out-of-domain extras + the state-shaped
    resp a buggy SUT could echo (ticket dispenser: resp == state makes an
    out-of-domain TAKE ok — exactly the case that made bounding by
    n_tickets unsound, models/counter.py)."""
    dom = list(spec.resp_domain(cmd))[:32]
    extras = [int(state[0]), int(state[0]) + 1, *_EXTRA_RESPS]
    seen = set(dom)
    return dom + [r for r in extras if not (r in seen or seen.add(r))]


def check_sigs(spec: Spec, location: str) -> List[Finding]:
    out: List[Finding] = []
    if not spec.CMDS:
        return [Finding(ERROR, "QSM-SPEC-SIG", location,
                        "spec declares an empty command alphabet",
                        "define CMDS with at least one CmdSig")]
    names = [c.name for c in spec.CMDS]
    if len(set(names)) != len(names):
        out.append(Finding(ERROR, "QSM-SPEC-SIG", location,
                           f"duplicate command names in CMDS: {names}",
                           "command names must be unique"))
    for i, sig in enumerate(spec.CMDS):
        if sig.n_args < 1 or sig.n_resps < 1:
            out.append(Finding(
                ERROR, "QSM-SPEC-SIG", location,
                f"command {sig.name!r} (#{i}) has empty domain "
                f"(n_args={sig.n_args}, n_resps={sig.n_resps})",
                "n_args/n_resps must be >= 1 (1 means 'no argument')"))
    init = np.asarray(spec.initial_state())
    if init.shape != (spec.STATE_DIM,):
        out.append(Finding(
            ERROR, "QSM-SPEC-SIG", location,
            f"initial_state() shape {init.shape} != (STATE_DIM,) "
            f"= ({spec.STATE_DIM},)",
            "STATE_DIM and initial_state() must agree — the kernel "
            "pads carries to STATE_DIM"))
    elif init.dtype != np.int32:
        out.append(Finding(
            WARNING, "QSM-SPEC-SIG", location,
            f"initial_state() dtype {init.dtype} is not int32",
            "the device kernel runs int32 state vectors"))
    return out


def check_resp_domain(spec: Spec, location: str) -> List[Finding]:
    out: List[Finding] = []
    for c, sig in enumerate(spec.CMDS):
        dom = list(spec.resp_domain(c))
        bad = [r for r in dom if not 0 <= r < sig.n_resps]
        if bad:
            out.append(Finding(
                ERROR, "QSM-SPEC-RESP-DOMAIN", location,
                f"resp_domain({sig.name!r}) contains {bad[:4]} outside "
                f"[0, {sig.n_resps})",
                "pending-op expansion enumerates resp_domain; values "
                "outside n_resps break the expansion/table contract"))
        if not dom:
            out.append(Finding(
                ERROR, "QSM-SPEC-RESP-DOMAIN", location,
                f"resp_domain({sig.name!r}) is empty",
                "every command needs at least one completion response"))
    return out


def check_gen(spec: Spec, location: str, samples: int = 96,
              seed: int = 0) -> List[Finding]:
    """Sampled generator soundness ALONG A TRAJECTORY (the state
    advances through ok steps between draws — a generator that only
    misbehaves away from the initial state must not lint green):
    every (cmd, arg) stays in the declared domains (error), and the
    precondition holds for most draws (warning past 25% violations —
    ``Spec.gen_cmd``'s documented fallback may legitimately return a
    precondition-violating pair when rejection sampling exhausts its
    tries, so occasional misses are not a defect)."""
    rng = random.Random(seed)
    state = [int(x) for x in np.asarray(spec.initial_state())]
    bad_pre = 0
    for _ in range(samples):
        cmd, arg = spec.gen_cmd(rng, state)
        if not (0 <= cmd < spec.n_cmds
                and 0 <= arg < spec.CMDS[cmd].n_args):
            return [Finding(
                ERROR, "QSM-SPEC-GEN", location,
                f"gen_cmd produced ({cmd}, {arg}) outside the declared "
                f"command/arg domains at state {state}",
                "the device backend trusts generator args to be "
                "in-domain (JaxTPU._args_in_domain only gates replayed "
                "histories)")]
        if not spec.precondition(state, cmd, arg):
            bad_pre += 1
        # advance like the real runner would: take any ok step
        for resp in _resps_for(spec, cmd, state):
            ns, ok = spec.step_py(list(state), cmd, arg, resp)
            if ok:
                state = [int(x) for x in ns]
                break
    if bad_pre > samples // 4:
        return [Finding(
            WARNING, "QSM-SPEC-GEN", location,
            f"gen_cmd violated its own precondition on {bad_pre}/"
            f"{samples} sampled draws",
            "rejection sampling that falls back this often means the "
            "precondition is near-unsatisfiable or gen_cmd ignores it "
            "— generated programs would not mean what the spec says")]
    return []


def _walk(spec: Spec, n_ops: int, n_walks: int, seed: int):
    """Simulated ok-step trajectories (args in-domain, resps arbitrary).

    Returns (visited_states, bound_findings_args) where each trajectory
    takes at most ``n_ops`` ok steps from the initial state — matching
    the scalar_state_bound contract exactly (a bound may legitimately
    depend on the history length, e.g. the ticket dispenser's
    ``n_ops + 1``)."""
    rng = random.Random(seed)
    init = [int(x) for x in np.asarray(spec.initial_state())]
    visited = {tuple(init)}
    violations: List[Tuple[List[int], int, int, int, List[int]]] = []
    sbound = spec.scalar_state_bound(n_ops) if spec.STATE_DIM == 1 else None
    ebounds = spec.state_elem_bounds()

    def in_bounds(st: List[int]) -> bool:
        if sbound is not None and not 0 <= st[0] < sbound:
            return False
        if ebounds is not None and any(
                not 0 <= v < b for v, b in zip(st, ebounds)):
            return False
        return True

    for w in range(n_walks):
        state = list(init)
        for _ in range(n_ops):
            steps = []
            for cmd, arg in _pairs(spec, rng):
                for resp in _resps_for(spec, cmd, state):
                    ns, ok = spec.step_py(list(state), cmd, arg, resp)
                    if ok:
                        steps.append((cmd, arg, resp,
                                      [int(x) for x in ns]))
            if not steps:
                break
            cmd, arg, resp, ns = steps[rng.randrange(len(steps))]
            if not in_bounds(ns) and len(violations) < 4:
                violations.append((list(state), cmd, arg, resp, ns))
            state = ns
            visited.add(tuple(state))
    return visited, violations


def check_bounds_and_reach(spec: Spec, location: str,
                           n_ops: int = KERNEL_OPS_CEILING,
                           n_walks: int = 12, seed: int = 0
                           ) -> Tuple[List[Finding], set]:
    """(findings, visited_states) from simulated trajectories."""
    out: List[Finding] = []
    init = [int(x) for x in np.asarray(spec.initial_state())]
    sbound = spec.scalar_state_bound(n_ops) if spec.STATE_DIM == 1 else None
    ebounds = spec.state_elem_bounds()
    if ebounds is not None and len(ebounds) != spec.STATE_DIM:
        out.append(Finding(
            ERROR, "QSM-SPEC-BOUND", location,
            f"state_elem_bounds has {len(ebounds)} entries for "
            f"STATE_DIM={spec.STATE_DIM}",
            "one exclusive bound per state element"))
        ebounds = None
    if sbound is not None and not 0 <= init[0] < sbound:
        out.append(Finding(
            ERROR, "QSM-SPEC-BOUND", location,
            f"initial state {init[0]} outside declared scalar bound "
            f"[0, {sbound})", "the step-table gather would read garbage"))
    if ebounds is not None and any(
            not 0 <= v < b for v, b in zip(init, ebounds)):
        out.append(Finding(
            ERROR, "QSM-SPEC-BOUND", location,
            f"initial state {init} outside state_elem_bounds {ebounds}",
            "scalarized packing (ops/scalarize.py) requires in-bounds "
            "states"))

    visited, violations = _walk(spec, n_ops, n_walks, seed)
    for state, cmd, arg, resp, ns in violations:
        out.append(Finding(
            ERROR, "QSM-SPEC-BOUND", location,
            f"ok step {spec.CMDS[cmd].name}(arg={arg}, resp={resp}) "
            f"from {state} reaches {ns}, outside the declared bound "
            f"(scalar_state_bound({n_ops})="
            f"{sbound if sbound is not None else '-'}, "
            f"state_elem_bounds={ebounds})",
            "the device fast paths trust the bound: a reachable "
            "out-of-bound state makes the table gather/packing unsound"))

    # reachability: every command issuable and ok-respondable somewhere
    sample = list(visited)[:256]
    for c, sig in enumerate(spec.CMDS):
        issuable = respondable = False
        for st in sample:
            st = list(st)
            for a in range(min(sig.n_args, 32)):
                if spec.precondition(st, c, a):
                    issuable = True
                    for r in _resps_for(spec, c, st):
                        _, ok = spec.step_py(list(st), c, a, r)
                        if ok:
                            respondable = True
                            break
                if respondable:
                    break
            if respondable:
                break
        if not issuable:
            out.append(Finding(
                WARNING, "QSM-SPEC-REACH", location,
                f"command {sig.name!r} is never issuable (precondition "
                f"false at every sampled reachable state, "
                f"{len(sample)} states)",
                "dead commands silently shrink the tested alphabet"))
        elif not respondable:
            out.append(Finding(
                WARNING, "QSM-SPEC-REACH", location,
                f"command {sig.name!r} has no ok response at any "
                "sampled reachable state",
                "an un-satisfiable postcondition makes every history "
                "containing the command a violation"))
    return out, visited


def check_parity(spec: Spec, location: str,
                 n_ops: int = KERNEL_OPS_CEILING,
                 visited: Optional[set] = None, seed: int = 0
                 ) -> List[Finding]:
    """``step_py`` vs ``step_jax`` — ONE vmapped jitted evaluation over
    the sampled (state, cmd, arg, resp) grid, compared elementwise."""
    import jax
    import jax.numpy as jnp

    rng = random.Random(seed)
    sbound = spec.scalar_state_bound(n_ops) if spec.STATE_DIM == 1 else None
    if sbound is not None and sbound <= _MAX_PARITY_STATES:
        states = [[s] for s in range(sbound)]   # exhaustive
        exhaustive = True
    else:
        pool = sorted(visited or {tuple(
            int(x) for x in np.asarray(spec.initial_state()))})
        rng.shuffle(pool)
        states = [list(s) for s in pool[:_MAX_PARITY_STATES]]
        exhaustive = False

    samples: List[Tuple[List[int], int, int, int]] = []
    for st in states:
        for cmd, arg in _pairs(spec, rng):
            for resp in _resps_for(spec, cmd, st):
                samples.append((st, cmd, arg, resp))
    if not samples:
        return [Finding(INFO, "QSM-SPEC-PARITY", location,
                        "parity pass ran vacuously (no samples)", "")]

    st_arr = jnp.asarray(np.asarray([s for s, *_ in samples], np.int32))
    cmd_arr = jnp.asarray(np.asarray([c for _, c, _, _ in samples],
                                     np.int32))
    arg_arr = jnp.asarray(np.asarray([a for _, _, a, _ in samples],
                                     np.int32))
    resp_arr = jnp.asarray(np.asarray([r for *_, r in samples], np.int32))
    jax_states, jax_oks = jax.jit(jax.vmap(spec.step_jax))(
        st_arr, cmd_arr, arg_arr, resp_arr)
    jax_states = np.asarray(jax_states)
    jax_oks = np.asarray(jax_oks)

    out: List[Finding] = []
    for i, (st, cmd, arg, resp) in enumerate(samples):
        py_ns, py_ok = spec.step_py(list(st), cmd, arg, resp)
        py_ns = [int(x) for x in py_ns]
        jx_ns = [int(x) for x in np.atleast_1d(jax_states[i])]
        jx_ok = bool(jax_oks[i])
        if bool(py_ok) != jx_ok or (py_ok and py_ns != jx_ns):
            out.append(Finding(
                ERROR, "QSM-SPEC-PARITY", location,
                f"step_py/step_jax diverge on state={st} "
                f"{spec.CMDS[cmd].name}(arg={arg}, resp={resp}): "
                f"py -> ({py_ns}, ok={bool(py_ok)}), "
                f"jax -> ({jx_ns}, ok={jx_ok})",
                "the oracle checks step_py, the device kernel traces "
                "step_jax — any divergence is a wrong device verdict "
                "waiting for a window"))
            if len(out) >= 4:
                break
    if not out and not exhaustive:
        out.append(Finding(
            INFO, "QSM-SPEC-PARITY", location,
            f"parity sampled ({len(samples)} tuples over "
            f"{len(states)} reachable states), not exhaustive", ""))
    return out


def check_projection(spec: Spec, location: str,
                     seed: int = 0) -> List[Finding]:
    """QSM-SPEC-PCOMP: validate a declared per-key projection.

    A spec that declares nothing is silent (whole-history checking is
    always sound); a spec that DECLARES decomposability — any
    ``CmdSig.proj``, or a ``projected_spec`` attribute — must pass the
    compile-time validator, because every P-compositional consumer
    (ops/pcomp.py, the planner's decompose_keys stage, the serve split
    plane) trusts exactly that report."""
    from ..core.spec import projection_report

    declares = (hasattr(spec, "projected_spec")
                or any(c.proj is not None for c in spec.CMDS))
    if not declares:
        return []
    problems = projection_report(spec, seed=seed)
    return [Finding(ERROR, "QSM-SPEC-PCOMP", location,
                    f"declared per-key projection is unsound: {p}",
                    "fix the KeyProj declaration/projected spec, or "
                    "remove it — the planner refuses to decompose "
                    "(never splits unsoundly), so the declaration only "
                    "misleads")
            for p in problems]


def check_spec(spec: Spec, location: str,
               n_ops: int = KERNEL_OPS_CEILING,
               seed: int = 0) -> List[Finding]:
    """All spec soundness passes for one spec instance."""
    out = check_sigs(spec, location)
    if any(f.severity == ERROR for f in out):
        return out  # later passes would crash on a malformed alphabet
    out += check_resp_domain(spec, location)
    out += check_gen(spec, location, seed=seed)
    bound_findings, visited = check_bounds_and_reach(
        spec, location, n_ops=n_ops, seed=seed)
    out += bound_findings
    out += check_parity(spec, location, n_ops=n_ops, visited=visited,
                        seed=seed)
    out += check_projection(spec, location, seed=seed)
    return out
