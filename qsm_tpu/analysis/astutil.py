"""Shared AST plumbing for the lint passes.

Both the kernel trace-hazard passes (ops/) and the determinism passes
(sched/) are source-level lints: parse the module, find the functions of
interest, walk their bodies.  The helpers here keep the two pass
families on one parser so location formatting (``relpath:line``) and the
loop-body discovery protocol cannot drift apart.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

# Call names whose FUNCTION argument is traced device code: the argument
# index of that function per callee name.  jax.lax.while_loop(cond, body,
# init) traces args 0 and 1; fori_loop(lo, hi, body, init) arg 2;
# scan(f, ...) arg 0; pallas_call(kernel, ...) arg 0 (the whole kernel
# body runs on-device).
TRACED_FN_ARGS: Dict[str, Tuple[int, ...]] = {
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "scan": (0,),
    "pallas_call": (0,),
}


def parse_module(path: str) -> ast.Module:
    with open(path) as f:
        return ast.parse(f.read(), filename=path)


def rel_location(path: str, lineno: int, root: Optional[str] = None) -> str:
    """``relpath:line`` — repo-relative when ``root`` contains ``path``."""
    if root:
        try:
            path = os.path.relpath(path, root)
        except ValueError:
            pass
    return f"{path}:{lineno}"


def attr_chain(node: ast.AST) -> List[str]:
    """``jax.lax.while_loop`` -> ["jax", "lax", "while_loop"]; [] when the
    expression is not a plain name/attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return []


def call_name(call: ast.Call) -> Optional[str]:
    """Last component of the called name (``pl.pallas_call`` ->
    ``pallas_call``), or None for computed callees."""
    chain = attr_chain(call.func)
    return chain[-1] if chain else None


def collect_function_defs(tree: ast.Module) -> Dict[str, List[ast.AST]]:
    """name -> FunctionDef nodes anywhere in the module (nested included).
    Same-name collisions across scopes over-approximate — acceptable for
    a lint (a false transitive edge can only widen the flagged set)."""
    defs: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    return defs


def traced_function_names(tree: ast.Module) -> Set[str]:
    """Names of functions passed as traced bodies to while_loop /
    fori_loop / scan / pallas_call, plus the transitive closure of
    module-local functions they call (build_stepper's ``body_k`` calls
    ``micro`` calls ``body`` — all three are traced)."""
    defs = collect_function_defs(tree)
    flagged: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        idxs = TRACED_FN_ARGS.get(name or "")
        if not idxs:
            continue
        for i in idxs:
            if i < len(node.args) and isinstance(node.args[i], ast.Name):
                arg = node.args[i].id
                if arg in defs:
                    flagged.add(arg)
    # transitive closure over local defs referenced from flagged functions
    changed = True
    while changed:
        changed = False
        for name in list(flagged):
            for fn in defs.get(name, ()):
                for sub in ast.walk(fn):
                    if (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Name)
                            and sub.func.id in defs
                            and sub.func.id not in flagged):
                        flagged.add(sub.func.id)
                        changed = True
    return flagged


def iter_flagged_bodies(tree: ast.Module, names: Set[str]):
    """Yield (function_name, node) for every AST node inside the named
    function defs (the defs themselves excluded)."""
    defs = collect_function_defs(tree)
    for name in sorted(names):
        for fn in defs.get(name, ()):
            for node in ast.walk(fn):
                if node is fn:
                    continue
                yield name, node
