"""Fleet passes (pass family *j* of docs/ANALYSIS.md): re-dispatch
discipline for the multi-node tier.

The fleet router's defining move is RE-DISPATCH: a node that crashes,
wedges or partitions mid-request loses its sub-request, and the lanes
go to another node.  Done wrong, that move converts one dead node into
a fleet-wide outage: an unbounded retry loop spins the router forever
on a deterministic failure, and a loop that never EXCLUDES the node it
just watched fail hands the same lanes back to the same corpse (the
consistent-hash ring, by design, keeps answering the same node for the
same key).  The router's own disciplines — bounded attempts from the
``fleet-route`` preset, the ``tried`` exclusion set fed into the ring
walk (fleet/router.py ``_dispatch_group``) — exist for exactly this;
this pass family is the gate that keeps future fleet code on them.

AST lint over the fleet modules and the fleet bench tool:

* ``QSM-FLEET-REDISPATCH`` (error) — a loop with the re-dispatch shape
  (a ``.request(...)``/``.dispatch(...)`` call whose exception handler
  ``continue``s the loop) that is either:

  - a constant-``True`` ``while`` — no bounded attempt budget: a
    deterministic failure (every node partitioned) spins forever; or
  - bounded, but with NO failed-target exclusion inside the loop — no
    ``X.add(...)`` on a tried/excluded set and no ``exclude=`` keyword
    on the target-picking call: the ring hands the same dead node
    back every attempt, so the budget buys nothing.

  Sanctioned form: ``for _ in range(policy.attempts)`` +
  ``tried.add(target)`` before the request + ``node_for(key,
  exclude=tried)`` on failure (fleet/router.py is the model).

* ``QSM-FLEET-LEASE`` (error) — the router-HA promotion discipline
  (ISSUE 13, fleet/lease.py).  A promotion is a call through a
  lease-named handle (``lease.acquire(...)`` / ``self.lease.promote``
  etc.) and must be:

  - **bounded** — a constant-``True`` ``while`` wrapping the acquire
    is an unbounded standby-promote loop: with the lease held by a
    live active, the standby spins forever instead of standing by on
    its beat cadence; and
  - **term/expiry-gated** — a function that promotes without ever
    consulting the lease's term or expiry (no ``term``/``expir``
    token anywhere in it) can grab the lease while the record it
    never read is still live, which is exactly the split-brain the
    lease exists to exclude.

  Sanctioned form: one beat-driven attempt per observation —
  ``rec = lease.read(); if lease.expired(rec, grace): ...
  lease.acquire()`` with the term riding the response
  (fleet/router.py ``ha_beat`` is the model).
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from .astutil import attr_chain, parse_module
from .findings import ERROR, Finding

_DISPATCH_CALLS = {"request", "dispatch"}
# promotion verbs reached through a lease-named handle (the handle
# requirement keeps ordinary Lock/Semaphore .acquire() out of scope)
_PROMOTE_CALLS = {"acquire", "promote", "takeover", "take_over"}
_CONSULT_TOKENS = ("term", "expir")


def _is_const_true(test: ast.AST) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


def _function_map(tree: ast.Module) -> dict:
    owner: dict = {}
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(fn):
                owner[id(sub)] = fn.name  # innermost wins
    return owner


def _loop_redispatches(loop: ast.AST) -> bool:
    """The re-dispatch shape: a try whose body carries a dispatch-like
    attribute call and whose handler ``continue``s the loop (failure →
    try elsewhere), both inside this loop."""
    for node in ast.walk(loop):
        if not isinstance(node, ast.Try):
            continue
        has_dispatch = any(
            isinstance(sub, ast.Call)
            and (chain := attr_chain(sub.func))
            and chain[-1] in _DISPATCH_CALLS and len(chain) >= 2
            for stmt in node.body for sub in ast.walk(stmt))
        if not has_dispatch:
            continue
        for handler in node.handlers:
            if any(isinstance(sub, ast.Continue)
                   for stmt in handler.body for sub in ast.walk(stmt)):
                return True
    return False


def _loop_excludes_failed(loop: ast.AST) -> bool:
    """An exclusion discipline inside the loop: a ``X.add(...)`` call
    (the tried/excluded set) or an ``exclude=`` keyword on any call
    (the ring-walk form)."""
    for node in ast.walk(loop):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if chain and chain[-1] == "add" and len(chain) >= 2:
            return True
        if any(kw.arg == "exclude" for kw in node.keywords):
            return True
    return False


def _is_lease_promote(call: ast.Call) -> bool:
    """``<...lease...>.acquire(...)`` — a promotion through a
    lease-named handle (``lease.acquire``, ``self._lease.promote``)."""
    chain = attr_chain(call.func)
    if not chain or len(chain) < 2:
        return False
    if chain[-1] not in _PROMOTE_CALLS:
        return False
    return any("lease" in part.lower() for part in chain[:-1])


def _consults_lease_state(fn: ast.AST) -> bool:
    """Does this function ever read a term/expiry-named thing — a name,
    an attribute, or a ``rec["term"]``-style string key?"""
    for node in ast.walk(fn):
        text = None
        if isinstance(node, ast.Name):
            text = node.id
        elif isinstance(node, ast.Attribute):
            text = node.attr
        elif isinstance(node, ast.Constant) and isinstance(node.value,
                                                          str):
            text = node.value
        if text is not None and any(tok in text.lower()
                                    for tok in _CONSULT_TOKENS):
            return True
    return False


def _check_lease_discipline(tree: ast.Module, relpath: str
                            ) -> List[Finding]:
    """QSM-FLEET-LEASE (module docstring): per function, an unbounded
    promote loop outranks (and subsumes) the termless finding — one
    finding per broken promotion path, not two."""
    out: List[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        promotes = [n for n in ast.walk(fn)
                    if isinstance(n, ast.Call) and _is_lease_promote(n)]
        if not promotes:
            continue
        unbounded = None
        for loop in ast.walk(fn):
            if isinstance(loop, ast.While) \
                    and _is_const_true(loop.test) \
                    and any(isinstance(n, ast.Call)
                            and _is_lease_promote(n)
                            for n in ast.walk(loop)):
                unbounded = loop
                break
        if unbounded is not None:
            out.append(Finding(
                ERROR, "QSM-FLEET-LEASE",
                f"{relpath}:{fn.name}:{unbounded.lineno}",
                "unbounded standby-promote loop — a while-True around "
                "lease acquisition spins against a live active "
                "forever; promotion belongs on the beat cadence, one "
                "gated attempt per observation",
                "drive promotion from the lease beat: read the "
                "record, consult lease.expired(rec, grace), and "
                "attempt acquire at most once per beat "
                "(fleet/router.py ha_beat is the model)"))
        elif not _consults_lease_state(fn):
            out.append(Finding(
                ERROR, "QSM-FLEET-LEASE",
                f"{relpath}:{fn.name}:{promotes[0].lineno}",
                "promotion path that never consults lease term/expiry "
                "— acquiring without reading the record can take the "
                "lease while the incumbent's term is still live: the "
                "split-brain the lease exists to exclude",
                "gate the acquire on the observed record "
                "(lease.read() + lease.expired(rec, grace)) and carry "
                "the returned term onto every response"))
    return out


def check_fleet_file(path: str, root: Optional[str] = None
                     ) -> List[Finding]:
    tree = parse_module(path)
    relpath = path
    if root:
        try:
            relpath = os.path.relpath(path, root)
        except ValueError:
            pass
    fn_of = _function_map(tree)
    out: List[Finding] = _check_lease_discipline(tree, relpath)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.While, ast.For)):
            continue
        if not _loop_redispatches(node):
            continue
        name = fn_of.get(id(node), "<module>")
        unbounded = (isinstance(node, ast.While)
                     and _is_const_true(node.test))
        if unbounded:
            out.append(Finding(
                ERROR, "QSM-FLEET-REDISPATCH",
                f"{relpath}:{name}:{node.lineno}",
                "re-dispatch loop with no bounded attempt budget — a "
                "deterministic failure (every node down or "
                "partitioned) spins this while-True forever instead "
                "of degrading to the ladder or shedding",
                "bound attempts with `for _ in range(policy.attempts)` "
                "(the fleet-route preset) and fall through to the "
                "in-process ladder (fleet/router.py _dispatch_group "
                "is the model)"))
        elif not _loop_excludes_failed(node):
            out.append(Finding(
                ERROR, "QSM-FLEET-REDISPATCH",
                f"{relpath}:{name}:{node.lineno}",
                "re-dispatch loop that never excludes the failed node "
                "— the consistent-hash ring answers the same target "
                "for the same key, so every attempt returns to the "
                "corpse and the budget buys nothing",
                "track a `tried` set (tried.add(target) before the "
                "request) and pick the next target with "
                "node_for(key, exclude=tried)"))
    return out
