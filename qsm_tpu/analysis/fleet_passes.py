"""Fleet passes (pass family *j* of docs/ANALYSIS.md): re-dispatch
discipline for the multi-node tier.

The fleet router's defining move is RE-DISPATCH: a node that crashes,
wedges or partitions mid-request loses its sub-request, and the lanes
go to another node.  Done wrong, that move converts one dead node into
a fleet-wide outage: an unbounded retry loop spins the router forever
on a deterministic failure, and a loop that never EXCLUDES the node it
just watched fail hands the same lanes back to the same corpse (the
consistent-hash ring, by design, keeps answering the same node for the
same key).  The router's own disciplines — bounded attempts from the
``fleet-route`` preset, the ``tried`` exclusion set fed into the ring
walk (fleet/router.py ``_dispatch_group``) — exist for exactly this;
this pass family is the gate that keeps future fleet code on them.

AST lint over the fleet modules and the fleet bench tool:

* ``QSM-FLEET-REDISPATCH`` (error) — a loop with the re-dispatch shape
  (a ``.request(...)``/``.dispatch(...)`` call whose exception handler
  ``continue``s the loop) that is either:

  - a constant-``True`` ``while`` — no bounded attempt budget: a
    deterministic failure (every node partitioned) spins forever; or
  - bounded, but with NO failed-target exclusion inside the loop — no
    ``X.add(...)`` on a tried/excluded set and no ``exclude=`` keyword
    on the target-picking call: the ring hands the same dead node
    back every attempt, so the budget buys nothing.

  Sanctioned form: ``for _ in range(policy.attempts)`` +
  ``tried.add(target)`` before the request + ``node_for(key,
  exclude=tried)`` on failure (fleet/router.py is the model).

* ``QSM-FLEET-LEASE`` (error) — the router-HA promotion discipline
  (ISSUE 13, fleet/lease.py).  A promotion is a call through a
  lease-named handle (``lease.acquire(...)`` / ``self.lease.promote``
  etc.) and must be:

  - **bounded** — a constant-``True`` ``while`` wrapping the acquire
    is an unbounded standby-promote loop: with the lease held by a
    live active, the standby spins forever instead of standing by on
    its beat cadence; and
  - **term/expiry-gated** — a function that promotes without ever
    consulting the lease's term or expiry (no ``term``/``expir``
    token anywhere in it) can grab the lease while the record it
    never read is still live, which is exactly the split-brain the
    lease exists to exclude.

  Sanctioned form: one beat-driven attempt per observation —
  ``rec = lease.read(); if lease.expired(rec, grace): ...
  lease.acquire()`` with the term riding the response
  (fleet/router.py ``ha_beat`` is the model).

* ``QSM-FLEET-HANDOFF`` (error) — the elastic-membership discipline
  (ISSUE 18, fleet/membership.py).  A ring mutation is a call through
  a handle (``membership.add_node(...)`` / ``self.membership.
  remove_node(...)``), and the mutating function must carry the
  matching handoff discipline:

  - a JOIN with no replog handoff anywhere in the function (no
    ``handoff``/``anti_entropy``/``sweep`` token) leaves the newcomer
    owning key ranges whose banked verdict rows it does not hold —
    every key routed there re-folds from scratch and a flip banked on
    the previous owner is invisible until some later sweep; and
  - a LEAVE with no session migration (no ``migrat`` token) leaves
    every routed session naming the retiree as owner — each next verb
    re-dispatches into the void instead of replaying the journal onto
    the new ring owner.

  Sanctioned form: ``add_node`` reporting a join triggers an
  ``anti_entropy_sweep()`` on the spot (gossip-driven,
  subsumption-bounded — nodes already holding the rows ship nothing);
  ``remove_node`` reporting a leave invalidates the retiree's routed
  sessions (``sess.node = None``, counted as migrated) so each
  journal replays onto the new owner on its next verb, exactly-once
  by seq (fleet/router.py ``_handle_membership`` is the model).
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from .astutil import attr_chain, parse_module
from .findings import ERROR, Finding

_DISPATCH_CALLS = {"request", "dispatch"}
# promotion verbs reached through a lease-named handle (the handle
# requirement keeps ordinary Lock/Semaphore .acquire() out of scope)
_PROMOTE_CALLS = {"acquire", "promote", "takeover", "take_over"}
_CONSULT_TOKENS = ("term", "expir")
_RING_JOIN_CALLS = {"add_node"}
_RING_LEAVE_CALLS = {"remove_node"}
_HANDOFF_TOKENS = ("handoff", "anti_entropy", "sweep")
_MIGRATE_TOKENS = ("migrat",)


def _is_const_true(test: ast.AST) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


def _function_map(tree: ast.Module) -> dict:
    owner: dict = {}
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(fn):
                owner[id(sub)] = fn.name  # innermost wins
    return owner


def _loop_redispatches(loop: ast.AST) -> bool:
    """The re-dispatch shape: a try whose body carries a dispatch-like
    attribute call and whose handler ``continue``s the loop (failure →
    try elsewhere), both inside this loop."""
    for node in ast.walk(loop):
        if not isinstance(node, ast.Try):
            continue
        has_dispatch = any(
            isinstance(sub, ast.Call)
            and (chain := attr_chain(sub.func))
            and chain[-1] in _DISPATCH_CALLS and len(chain) >= 2
            for stmt in node.body for sub in ast.walk(stmt))
        if not has_dispatch:
            continue
        for handler in node.handlers:
            if any(isinstance(sub, ast.Continue)
                   for stmt in handler.body for sub in ast.walk(stmt)):
                return True
    return False


def _loop_excludes_failed(loop: ast.AST) -> bool:
    """An exclusion discipline inside the loop: a ``X.add(...)`` call
    (the tried/excluded set) or an ``exclude=`` keyword on any call
    (the ring-walk form)."""
    for node in ast.walk(loop):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if chain and chain[-1] == "add" and len(chain) >= 2:
            return True
        if any(kw.arg == "exclude" for kw in node.keywords):
            return True
    return False


def _is_lease_promote(call: ast.Call) -> bool:
    """``<...lease...>.acquire(...)`` — a promotion through a
    lease-named handle (``lease.acquire``, ``self._lease.promote``)."""
    chain = attr_chain(call.func)
    if not chain or len(chain) < 2:
        return False
    if chain[-1] not in _PROMOTE_CALLS:
        return False
    return any("lease" in part.lower() for part in chain[:-1])


def _mentions(fn: ast.AST, tokens) -> bool:
    """Does this function ever touch a thing named for one of
    ``tokens`` — a name, an attribute, or a ``rec["term"]``-style
    string key?"""
    for node in ast.walk(fn):
        text = None
        if isinstance(node, ast.Name):
            text = node.id
        elif isinstance(node, ast.Attribute):
            text = node.attr
        elif isinstance(node, ast.Constant) and isinstance(node.value,
                                                          str):
            text = node.value
        if text is not None and any(tok in text.lower()
                                    for tok in tokens):
            return True
    return False


def _consults_lease_state(fn: ast.AST) -> bool:
    """Does this function ever read a term/expiry-named thing?"""
    return _mentions(fn, _CONSULT_TOKENS)


def _ring_mutation(call: ast.Call, verbs) -> bool:
    """``<...>.add_node(...)`` / ``<...>.remove_node(...)`` — a ring
    mutation through a handle (the handle requirement keeps the
    Membership method DEFINITIONS, which are bare names, out of
    scope)."""
    chain = attr_chain(call.func)
    return bool(chain) and len(chain) >= 2 and chain[-1] in verbs


def _check_handoff_discipline(tree: ast.Module, relpath: str
                              ) -> List[Finding]:
    """QSM-FLEET-HANDOFF (module docstring): per function, at most one
    finding per broken direction — a join with no handoff, a leave
    with no migration."""
    out: List[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        joins = [n for n in ast.walk(fn) if isinstance(n, ast.Call)
                 and _ring_mutation(n, _RING_JOIN_CALLS)]
        leaves = [n for n in ast.walk(fn) if isinstance(n, ast.Call)
                  and _ring_mutation(n, _RING_LEAVE_CALLS)]
        if joins and not _mentions(fn, _HANDOFF_TOKENS):
            out.append(Finding(
                ERROR, "QSM-FLEET-HANDOFF",
                f"{relpath}:{fn.name}:{joins[0].lineno}",
                "ring join without replog handoff — the newcomer owns "
                "key ranges whose banked verdict rows it does not "
                "hold, so every key routed there re-folds from "
                "scratch and a flip banked on the previous owner is "
                "invisible until some later sweep",
                "seed the newcomer on the spot: run "
                "anti_entropy_sweep() (gossip-driven, "
                "subsumption-bounded — nodes already holding the rows "
                "ship nothing) when add_node reports a join "
                "(fleet/router.py _handle_membership is the model)"))
        if leaves and not _mentions(fn, _MIGRATE_TOKENS):
            out.append(Finding(
                ERROR, "QSM-FLEET-HANDOFF",
                f"{relpath}:{fn.name}:{leaves[0].lineno}",
                "ring leave without session migration — every routed "
                "session still naming the retired node as owner "
                "re-dispatches into the void on its next verb instead "
                "of replaying its journal onto the new ring owner",
                "invalidate the retiree's sessions under each session "
                "lock (sess.node = None, counted as migrated) so each "
                "journal replays onto the new owner on the next verb, "
                "exactly-once by seq (fleet/router.py "
                "_handle_membership is the model)"))
    return out


def _check_lease_discipline(tree: ast.Module, relpath: str
                            ) -> List[Finding]:
    """QSM-FLEET-LEASE (module docstring): per function, an unbounded
    promote loop outranks (and subsumes) the termless finding — one
    finding per broken promotion path, not two."""
    out: List[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        promotes = [n for n in ast.walk(fn)
                    if isinstance(n, ast.Call) and _is_lease_promote(n)]
        if not promotes:
            continue
        unbounded = None
        for loop in ast.walk(fn):
            if isinstance(loop, ast.While) \
                    and _is_const_true(loop.test) \
                    and any(isinstance(n, ast.Call)
                            and _is_lease_promote(n)
                            for n in ast.walk(loop)):
                unbounded = loop
                break
        if unbounded is not None:
            out.append(Finding(
                ERROR, "QSM-FLEET-LEASE",
                f"{relpath}:{fn.name}:{unbounded.lineno}",
                "unbounded standby-promote loop — a while-True around "
                "lease acquisition spins against a live active "
                "forever; promotion belongs on the beat cadence, one "
                "gated attempt per observation",
                "drive promotion from the lease beat: read the "
                "record, consult lease.expired(rec, grace), and "
                "attempt acquire at most once per beat "
                "(fleet/router.py ha_beat is the model)"))
        elif not _consults_lease_state(fn):
            out.append(Finding(
                ERROR, "QSM-FLEET-LEASE",
                f"{relpath}:{fn.name}:{promotes[0].lineno}",
                "promotion path that never consults lease term/expiry "
                "— acquiring without reading the record can take the "
                "lease while the incumbent's term is still live: the "
                "split-brain the lease exists to exclude",
                "gate the acquire on the observed record "
                "(lease.read() + lease.expired(rec, grace)) and carry "
                "the returned term onto every response"))
    return out


def check_fleet_file(path: str, root: Optional[str] = None
                     ) -> List[Finding]:
    tree = parse_module(path)
    relpath = path
    if root:
        try:
            relpath = os.path.relpath(path, root)
        except ValueError:
            pass
    fn_of = _function_map(tree)
    out: List[Finding] = _check_lease_discipline(tree, relpath)
    out += _check_handoff_discipline(tree, relpath)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.While, ast.For)):
            continue
        if not _loop_redispatches(node):
            continue
        name = fn_of.get(id(node), "<module>")
        unbounded = (isinstance(node, ast.While)
                     and _is_const_true(node.test))
        if unbounded:
            out.append(Finding(
                ERROR, "QSM-FLEET-REDISPATCH",
                f"{relpath}:{name}:{node.lineno}",
                "re-dispatch loop with no bounded attempt budget — a "
                "deterministic failure (every node down or "
                "partitioned) spins this while-True forever instead "
                "of degrading to the ladder or shedding",
                "bound attempts with `for _ in range(policy.attempts)` "
                "(the fleet-route preset) and fall through to the "
                "in-process ladder (fleet/router.py _dispatch_group "
                "is the model)"))
        elif not _loop_excludes_failed(node):
            out.append(Finding(
                ERROR, "QSM-FLEET-REDISPATCH",
                f"{relpath}:{name}:{node.lineno}",
                "re-dispatch loop that never excludes the failed node "
                "— the consistent-hash ring answers the same target "
                "for the same key, so every attempt returns to the "
                "corpse and the budget buys nothing",
                "track a `tried` set (tried.add(target) before the "
                "request) and pick the next target with "
                "node_for(key, exclude=tried)"))
    return out
