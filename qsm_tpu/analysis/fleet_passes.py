"""Fleet passes (pass family *j* of docs/ANALYSIS.md): re-dispatch
discipline for the multi-node tier.

The fleet router's defining move is RE-DISPATCH: a node that crashes,
wedges or partitions mid-request loses its sub-request, and the lanes
go to another node.  Done wrong, that move converts one dead node into
a fleet-wide outage: an unbounded retry loop spins the router forever
on a deterministic failure, and a loop that never EXCLUDES the node it
just watched fail hands the same lanes back to the same corpse (the
consistent-hash ring, by design, keeps answering the same node for the
same key).  The router's own disciplines — bounded attempts from the
``fleet-route`` preset, the ``tried`` exclusion set fed into the ring
walk (fleet/router.py ``_dispatch_group``) — exist for exactly this;
this pass family is the gate that keeps future fleet code on them.

AST lint over the fleet modules and the fleet bench tool:

* ``QSM-FLEET-REDISPATCH`` (error) — a loop with the re-dispatch shape
  (a ``.request(...)``/``.dispatch(...)`` call whose exception handler
  ``continue``s the loop) that is either:

  - a constant-``True`` ``while`` — no bounded attempt budget: a
    deterministic failure (every node partitioned) spins forever; or
  - bounded, but with NO failed-target exclusion inside the loop — no
    ``X.add(...)`` on a tried/excluded set and no ``exclude=`` keyword
    on the target-picking call: the ring hands the same dead node
    back every attempt, so the budget buys nothing.

  Sanctioned form: ``for _ in range(policy.attempts)`` +
  ``tried.add(target)`` before the request + ``node_for(key,
  exclude=tried)`` on failure (fleet/router.py is the model).
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from .astutil import attr_chain, parse_module
from .findings import ERROR, Finding

_DISPATCH_CALLS = {"request", "dispatch"}


def _is_const_true(test: ast.AST) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


def _function_map(tree: ast.Module) -> dict:
    owner: dict = {}
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(fn):
                owner[id(sub)] = fn.name  # innermost wins
    return owner


def _loop_redispatches(loop: ast.AST) -> bool:
    """The re-dispatch shape: a try whose body carries a dispatch-like
    attribute call and whose handler ``continue``s the loop (failure →
    try elsewhere), both inside this loop."""
    for node in ast.walk(loop):
        if not isinstance(node, ast.Try):
            continue
        has_dispatch = any(
            isinstance(sub, ast.Call)
            and (chain := attr_chain(sub.func))
            and chain[-1] in _DISPATCH_CALLS and len(chain) >= 2
            for stmt in node.body for sub in ast.walk(stmt))
        if not has_dispatch:
            continue
        for handler in node.handlers:
            if any(isinstance(sub, ast.Continue)
                   for stmt in handler.body for sub in ast.walk(stmt)):
                return True
    return False


def _loop_excludes_failed(loop: ast.AST) -> bool:
    """An exclusion discipline inside the loop: a ``X.add(...)`` call
    (the tried/excluded set) or an ``exclude=`` keyword on any call
    (the ring-walk form)."""
    for node in ast.walk(loop):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if chain and chain[-1] == "add" and len(chain) >= 2:
            return True
        if any(kw.arg == "exclude" for kw in node.keywords):
            return True
    return False


def check_fleet_file(path: str, root: Optional[str] = None
                     ) -> List[Finding]:
    tree = parse_module(path)
    relpath = path
    if root:
        try:
            relpath = os.path.relpath(path, root)
        except ValueError:
            pass
    fn_of = _function_map(tree)
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.While, ast.For)):
            continue
        if not _loop_redispatches(node):
            continue
        name = fn_of.get(id(node), "<module>")
        unbounded = (isinstance(node, ast.While)
                     and _is_const_true(node.test))
        if unbounded:
            out.append(Finding(
                ERROR, "QSM-FLEET-REDISPATCH",
                f"{relpath}:{name}:{node.lineno}",
                "re-dispatch loop with no bounded attempt budget — a "
                "deterministic failure (every node down or "
                "partitioned) spins this while-True forever instead "
                "of degrading to the ladder or shedding",
                "bound attempts with `for _ in range(policy.attempts)` "
                "(the fleet-route preset) and fall through to the "
                "in-process ladder (fleet/router.py _dispatch_group "
                "is the model)"))
        elif not _loop_excludes_failed(node):
            out.append(Finding(
                ERROR, "QSM-FLEET-REDISPATCH",
                f"{relpath}:{name}:{node.lineno}",
                "re-dispatch loop that never excludes the failed node "
                "— the consistent-hash ring answers the same target "
                "for the same key, so every attempt returns to the "
                "corpse and the budget buys nothing",
                "track a `tried` set (tried.add(target) before the "
                "request) and pick the next target with "
                "node_for(key, exclude=tried)"))
    return out
