"""Serve passes (pass family *e* of docs/ANALYSIS.md): the serving
plane's two structural hazards.

A long-lived server fails differently from a one-shot tool: nothing
exits, so an unbounded blocking call holds a thread forever and an
unbounded queue converts overload into memory growth and silent latency
collapse.  The serving plane's own disciplines (serve/protocol.py's
deadline-polled reads, serve/admission.py's bounded lanes) exist for
exactly these; this pass family is the gate that keeps future serve
code on them.

AST lints over the serve modules (qsm_tpu/serve/) and the serve bench
tool:

* ``QSM-SERVE-ACCEPT`` (error) — an ``accept()``/``recv*()``/
  ``readline()`` loop with a constant-true test inside a function that
  never calls ``settimeout``: no deadline and no shutdown check, so a
  wedged peer (or a stop request) leaves the thread blocked forever.
  Sanctioned forms: gate the loop on a stop flag (a non-constant test)
  or bound the socket with ``settimeout`` and poll.
* ``QSM-SERVE-UNBOUNDED`` (error) — an unbounded queue construction
  (``queue.Queue()`` / ``LifoQueue()`` / ``PriorityQueue()`` without a
  positive ``maxsize``, ``queue.SimpleQueue()`` which cannot be
  bounded, or ``collections.deque()`` without ``maxlen``) in an
  admission path: load must shed explicitly at a bound
  (serve/admission.py), never accumulate.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from .astutil import attr_chain, parse_module
from .findings import ERROR, Finding

_BLOCKING_CALLS = {"accept", "recv", "recvfrom", "recv_into", "readline"}
_QUEUE_CLASSES = {"Queue", "LifoQueue", "PriorityQueue"}


def _is_const_true(test: ast.AST) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


def _enclosing_function_map(tree: ast.Module) -> dict:
    owner: dict = {}
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(fn):
                owner[id(sub)] = fn  # innermost wins (visited last)
    return owner


def _has_settimeout(fn: Optional[ast.AST]) -> bool:
    if fn is None:
        return False
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain[-1] == "settimeout":
                return True
    return False


def _queue_bound(node: ast.Call) -> Optional[ast.AST]:
    """The maxsize/maxlen argument expression, or None when absent OR a
    constant ≤ 0 — the stdlib spells 'infinite' as ``maxsize=0`` (and
    accepts negatives), so those are exactly the unbounded forms the
    rule exists for, not bounds."""
    bound = None
    if node.args:
        bound = node.args[0]
    else:
        for kw in node.keywords:
            if kw.arg in ("maxsize", "maxlen"):
                bound = kw.value
                break
    if bound is None:
        return None
    v = bound
    neg = False
    if isinstance(v, ast.UnaryOp) and isinstance(v.op, ast.USub):
        v, neg = v.operand, True
    if isinstance(v, ast.Constant) and isinstance(v.value, (int, float)) \
            and not isinstance(v.value, bool) \
            and (neg or v.value <= 0):
        return None  # an explicit 'infinite' spelling
    return bound


def check_serve_file(path: str, root: Optional[str] = None
                     ) -> List[Finding]:
    tree = parse_module(path)
    relpath = path
    if root:
        try:
            relpath = os.path.relpath(path, root)
        except ValueError:
            pass
    owner = _enclosing_function_map(tree)
    out: List[Finding] = []

    for node in ast.walk(tree):
        if isinstance(node, ast.While):
            if not _is_const_true(node.test):
                continue  # a stop-flag-gated loop IS the shutdown check
            blocking = None
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    chain = attr_chain(sub.func)
                    if chain and chain[-1] in _BLOCKING_CALLS:
                        blocking = chain[-1]
                        break
            if blocking is None:
                continue
            fn = owner.get(id(node))
            if _has_settimeout(fn):
                continue  # deadline-polled: the sanctioned bounded form
            name = fn.name if fn is not None else "<module>"
            out.append(Finding(
                ERROR, "QSM-SERVE-ACCEPT",
                f"{relpath}:{name}:{node.lineno}",
                f"while-True {blocking}() loop with no deadline or "
                "shutdown check — a wedged peer (or a stop request) "
                "blocks this thread forever",
                "gate the loop on a stop flag, or settimeout the socket "
                "and poll (serve/protocol.py LineChannel is the model)"))
        elif isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if not chain:
                continue
            fn = owner.get(id(node))
            name = fn.name if fn is not None else "<module>"
            loc = f"{relpath}:{name}:{node.lineno}"
            if chain[-1] == "SimpleQueue" and len(chain) <= 2:
                out.append(Finding(
                    ERROR, "QSM-SERVE-UNBOUNDED", loc,
                    "queue.SimpleQueue() cannot be bounded — overload "
                    "accumulates instead of shedding",
                    "use queue.Queue(maxsize=...) behind the admission "
                    "controller (serve/admission.py)"))
            elif chain[-1] in _QUEUE_CLASSES and len(chain) <= 2 \
                    and _queue_bound(node) is None:
                out.append(Finding(
                    ERROR, "QSM-SERVE-UNBOUNDED", loc,
                    f"{'.'.join(chain)}() without maxsize — unbounded "
                    "queue growth in an admission path converts overload "
                    "into memory growth and silent latency collapse",
                    "pass maxsize= (and SHED explicitly when full — "
                    "serve/admission.py)"))
            elif chain[-1] == "deque" and len(chain) <= 2 \
                    and _queue_bound(node) is None:
                out.append(Finding(
                    ERROR, "QSM-SERVE-UNBOUNDED", loc,
                    "collections.deque() without maxlen in the serve "
                    "plane — unbounded buffering",
                    "pass maxlen= or bound via the admission controller"))
    return out
