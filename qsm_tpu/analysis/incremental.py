"""Incremental lint plumbing: git-diff scoping and the on-disk
per-file result cache.

The lint gate runs constantly — every probe_watcher seize attempt,
every CI lane, every ``--changed`` developer loop — over a tree that
is almost entirely unchanged between runs.  Findings of the AST pass
families are a pure function of (file content, analyzer source), so
they are cacheable by content digest:

* :func:`changed_files` — the ``--changed [REF]`` scope: repo-relative
  paths touched since ``REF`` (``git diff --name-only`` plus untracked
  files), or None when git is unavailable (callers fall back to the
  full tree rather than guessing).  Both subprocess calls are bounded
  (the QSM-RES-SUBPROC discipline applies to the analyzer itself).
* :class:`LintCache` — a JSON document mapping cache keys (family id +
  file path + content sha256) to finding rows.  The whole cache is
  keyed on an **analyzer fingerprint** (digest over
  ``qsm_tpu/analysis/*.py``): editing any pass invalidates everything,
  so a rule change can never serve stale verdicts.  Writes go through
  ``resilience.checkpoint.atomic_write_text`` — the artifact-write
  primitive every other on-disk state in the repo uses.

Dynamic/semantic families (spec parity, the retrace probe) are NOT
cached: their results depend on executed code, not file bytes alone.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
from typing import Dict, List, Optional, Sequence, Set

from .findings import Finding

_CACHE_VERSION = 1
_GIT_TIMEOUT_S = 10.0


def default_cache_path(repo_root: str) -> str:
    return os.path.join(repo_root, ".qsmlint-cache.json")


def changed_files(repo_root: str, ref: str = "HEAD"
                  ) -> Optional[Set[str]]:
    """Repo-relative paths changed since ``ref`` (worktree + index)
    plus untracked files; None when git cannot answer (not a repo, no
    git, bad ref) — the caller then lints the full tree."""
    out: Set[str] = set()
    for cmd in (["git", "diff", "--name-only", ref],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            r = subprocess.run(cmd, cwd=repo_root, capture_output=True,
                               text=True, timeout=_GIT_TIMEOUT_S)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if r.returncode != 0:
            return None
        out.update(ln.strip() for ln in r.stdout.splitlines()
                   if ln.strip())
    return out


def file_digest(path: str) -> str:
    """sha256 of the file bytes; empty string for an unreadable file
    (which therefore never cache-hits)."""
    try:
        with open(path, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()
    except OSError:
        return ""


def combined_digest(paths: Sequence[str]) -> str:
    """Order-insensitive digest over a file set (the whole-program
    family's cache unit: any member changing invalidates the set)."""
    h = hashlib.sha256()
    for p in sorted(paths):
        h.update(p.encode())
        h.update(file_digest(p).encode())
    return h.hexdigest()


def analyzer_fingerprint() -> str:
    """Digest over the analysis package sources: a rule edit must
    invalidate every cached verdict."""
    pkg = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    for name in sorted(os.listdir(pkg)):
        if name.endswith(".py"):
            h.update(name.encode())
            h.update(file_digest(os.path.join(pkg, name)).encode())
    return h.hexdigest()


class LintCache:
    """Content-keyed finding cache (see module docstring)."""

    def __init__(self, path: str):
        self.path = path
        self.hits = 0
        self.misses = 0
        self._dirty = False
        self._entries: Dict[str, List[dict]] = {}
        self._fingerprint = analyzer_fingerprint()
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return
        if (doc.get("version") == _CACHE_VERSION
                and doc.get("analyzer") == self._fingerprint
                and isinstance(doc.get("entries"), dict)):
            self._entries = doc["entries"]

    def get(self, key: str) -> Optional[List[Finding]]:
        rows = self._entries.get(key)
        if rows is None:
            self.misses += 1
            return None
        self.hits += 1
        try:
            return [Finding.from_dict(r) for r in rows]
        except (TypeError, KeyError):
            self.misses += 1
            self.hits -= 1
            return None

    def put(self, key: str, findings: Sequence[Finding]) -> None:
        # a new digest for the same (family, file) supersedes the old
        # one — without this the cache grows a dead row per edit,
        # forever (keys are "fid:rel:sha256"; sha has no colon)
        prefix = key.rsplit(":", 1)[0] + ":"
        for stale in [k for k in self._entries
                      if k.startswith(prefix) and k != key]:
            del self._entries[stale]
        self._entries[key] = [f.to_dict() for f in findings]
        self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        from ..resilience.checkpoint import atomic_write_text

        doc = {"version": _CACHE_VERSION,
               "analyzer": self._fingerprint,
               "entries": self._entries}
        try:
            atomic_write_text(self.path, json.dumps(doc) + "\n")
            self._dirty = False
        except OSError:
            pass  # an unwritable cache degrades to a slower lint

    def stats(self) -> dict:
        return {"path": self.path, "hits": self.hits,
                "misses": self.misses}
