"""Monitor passes (pass family *k* of docs/ANALYSIS.md): session bounds.

A monitor session is a LONG-LIVED accumulator by design — events arrive
for as long as the monitored system runs — so the plane's one structural
promise is that nothing it accumulates is unbounded: event logs are
capped, frontier state sets are capped, and committed-prefix ops are
EVICTED from the windows (qsm_tpu/monitor module docstrings).  A session
buffer grown without a cap or eviction is the failure mode that turns
one quiet production monitor into an OOM of the whole serving plane a
week later.

* ``QSM-MON-UNBOUNDED`` (error) — a class whose instance-attribute
  container GROWS (``self.X.append/extend/add/insert``, or
  ``heapq.heappush(self.X, …)``) while NOTHING in the class either
  compares against a bound wherever that attribute is involved (a cap
  check like ``len(self.X) >= self.max_events``) or evicts from it
  (``self.X.pop/popitem/clear``, ``heappop(self.X)``, ``del
  self.X[…]``, or a pruning reassignment ``self.X = self.X[cut:]`` —
  the decided-prefix eviction shape).  Scope is the CLASS: the grow
  site and its discipline legitimately live in different methods
  (``append`` checks the cap, ``_apply`` grows).

Scan set: qsm_tpu/monitor/ + qsm_tpu/ingest/ + tools/bench_monitor.py.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from .astutil import attr_chain, parse_module
from .findings import ERROR, Finding

_GROW_CALLS = {"append", "extend", "add", "insert"}
_EVICT_CALLS = {"pop", "popitem", "clear", "popleft"}
_HEAP_GROW = {"heappush"}
_HEAP_EVICT = {"heappop"}


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` (or deeper ``self.X.y`` → ``X``) attribute name."""
    chain = attr_chain(node)
    if chain and len(chain) >= 2 and chain[0] == "self":
        return chain[1]
    return None


def _mentions_attr(node: ast.AST, attr: str) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and _self_attr(sub) == attr:
            return True
    return False


class _ClassScan:
    __slots__ = ("grows", "disciplined")

    def __init__(self):
        # attr -> (method name, lineno, how) of the first grow site
        self.grows: Dict[str, tuple] = {}
        self.disciplined: Set[str] = set()


def _scan_class(cls: ast.ClassDef) -> _ClassScan:
    out = _ClassScan()
    owner: dict = {}
    for fn in ast.walk(cls):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(fn):
                owner[id(sub)] = fn
    for node in ast.walk(cls):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if not chain:
                continue
            tail = chain[-1]
            # self.X.append(...) / self.X.pop(...)
            if isinstance(node.func, ast.Attribute):
                attr = _self_attr(node.func.value)
                if attr is not None:
                    if tail in _GROW_CALLS and attr not in out.grows:
                        fn = owner.get(id(node))
                        out.grows[attr] = (
                            fn.name if fn else "<class>", node.lineno,
                            f".{tail}()")
                    if tail in _EVICT_CALLS:
                        out.disciplined.add(attr)
            # heapq.heappush(self.X, ...) / heappop(self.X)
            if tail in _HEAP_GROW | _HEAP_EVICT and node.args:
                attr = _self_attr(node.args[0])
                if attr is not None:
                    if tail in _HEAP_GROW and attr not in out.grows:
                        fn = owner.get(id(node))
                        out.grows[attr] = (
                            fn.name if fn else "<class>", node.lineno,
                            f"{tail}()")
                    if tail in _HEAP_EVICT:
                        out.disciplined.add(attr)
        elif isinstance(node, ast.Compare):
            # a cap check anywhere in the class that INVOLVES the attr
            # (len(self.X) >= cap, len(self.X) + n > self.max_events)
            for sub in ast.walk(node):
                a = (_self_attr(sub)
                     if isinstance(sub, ast.Attribute) else None)
                if a is not None:
                    out.disciplined.add(a)
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    a = _self_attr(tgt.value)
                    if a is not None:
                        out.disciplined.add(a)
        elif isinstance(node, ast.Assign):
            # pruning reassignment: self.X = <expr mentioning self.X>
            for tgt in node.targets:
                a = (_self_attr(tgt)
                     if isinstance(tgt, ast.Attribute) else None)
                if a is not None and _mentions_attr(node.value, a):
                    out.disciplined.add(a)
    return out


def check_monitor_file(path: str, root: Optional[str] = None
                       ) -> List[Finding]:
    tree = parse_module(path)
    relpath = path
    if root:
        try:
            relpath = os.path.relpath(path, root)
        except ValueError:
            pass
    out: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        scan = _scan_class(cls)
        for attr, (fn_name, lineno, how) in sorted(scan.grows.items()):
            if attr in scan.disciplined:
                continue
            out.append(Finding(
                ERROR, "QSM-MON-UNBOUNDED",
                f"{relpath}:{cls.name}.{fn_name}:{lineno}",
                f"session buffer self.{attr} grows ({how}) with no cap "
                "comparison or eviction anywhere in the class — a "
                "long-lived monitor session accumulates it until the "
                "serving plane OOMs",
                "compare its size against an explicit bound before "
                "growing (session.py max_events is the model) or evict "
                "decided-prefix entries (frontier.py's window "
                "reassignment)"))
    return out
