"""Resilience passes (pass family *d* of docs/ANALYSIS.md): unbounded
device calls.

Round 1's first lesson (VERDICT.md): a wedged chip tunnel makes the
first in-process ``jax.devices()`` block FOREVER — not fail, block — and
a blocked probe loses whatever window the process was about to spend.
The resilience plane (qsm_tpu/resilience) exists so every call that can
touch the device substrate is bounded by a :func:`~qsm_tpu.resilience.
policy.watchdog` or runs in a subprocess with a timeout; this pass
family is the gate that keeps future code on that discipline.

AST lints over the engine modules (ops/), the device plumbing
(utils/device.py, utils/cli.py) and the artifact tools (bench.py,
tools/*.py):

* ``QSM-RES-DEVICES``  (error) — a direct ``jax.devices()`` /
  ``jax.local_devices()`` / ``jax.device_count()`` call outside a
  ``watchdog(...)``-bounded region.  On a wedged tunnel these block
  uninterruptibly; they must run under the watchdog, inside a bounded
  subprocess probe (a string snippet is invisible to this pass — and
  correctly so), or carry a reviewed ``.qsmlint`` entry explaining why
  the site cannot hang (e.g. post-probe provenance stamps on an
  already-pinned platform).
* ``QSM-RES-SUBPROC``  (error) — ``subprocess.run`` /
  ``check_output`` / ``check_call`` without a ``timeout=`` keyword, or
  ``Popen.…communicate()`` without one: an unbounded wait on a child
  that may itself be probing a wedged device.
* ``QSM-RES-TIMEOUT-LITERAL`` (warning) — a numeric timeout literal
  passed to ``probe_default_backend`` / ``probe_or_force_cpu``:
  per-site constants are exactly what resilience/policy.py's named
  PRESETS replaced; pass a policy (or nothing) so a retune edits one
  file, not six call sites.

Bounded regions are computed like kernel_passes' traced-body discovery:
any node lexically inside an argument of a ``watchdog(...)`` call, plus
the bodies of module-local functions whose NAME is passed to
``watchdog``.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Set

from .astutil import attr_chain, collect_function_defs, parse_module
from .findings import ERROR, WARNING, Finding

# jax entry points that initialize the backend and block forever on a
# wedged tunnel (the probe subprocess exists because of them)
_DEVICE_CALLS = {"devices", "local_devices", "device_count",
                 "local_device_count"}
_SUBPROC_WAITS = {"run", "check_output", "check_call", "call"}
_PROBE_FNS = {"probe_default_backend", "probe_or_force_cpu"}


def _watchdogged_nodes(tree: ast.Module) -> Set[int]:
    """ids of AST nodes inside a watchdog-bounded region."""
    defs = collect_function_defs(tree)
    bounded: Set[int] = set()
    bounded_fn_names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if not chain or chain[-1] != "watchdog":
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name) and arg.id in defs:
                bounded_fn_names.add(arg.id)
            for sub in ast.walk(arg):
                bounded.add(id(sub))
    for name in bounded_fn_names:
        for fn in defs.get(name, ()):
            for sub in ast.walk(fn):
                bounded.add(id(sub))
    return bounded


def _is_number(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool)
    # -5 / +5 parse as UnaryOp(Constant)
    return isinstance(node, ast.UnaryOp) and _is_number(node.operand)


def _enclosing_function_map(tree: ast.Module) -> dict:
    """node id -> innermost enclosing function name.  Findings carry
    function-qualified locations (``path:funcname:line``) so a whitelist
    entry pins the ONE reviewed function — a file-wide prefix would
    silently accept future unbounded device calls anywhere in exactly
    the modules most likely to grow them."""
    owner: dict = {}
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(fn):
                owner[id(sub)] = fn.name  # innermost wins (visited last)
    return owner


def check_resilience_file(path: str, root: Optional[str] = None
                          ) -> List[Finding]:
    tree = parse_module(path)
    relpath = path
    if root:
        try:
            relpath = os.path.relpath(path, root)
        except ValueError:
            pass
    bounded = _watchdogged_nodes(tree)
    owner = _enclosing_function_map(tree)
    out: List[Finding] = []

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = owner.get(id(node))
        loc = f"{relpath}:{fn}:{getattr(node, 'lineno', 0)}" if fn \
            else f"{relpath}:{getattr(node, 'lineno', 0)}"
        chain = attr_chain(node.func)
        kwargs = {kw.arg for kw in node.keywords}

        if chain and chain[0] == "jax" and chain[-1] in _DEVICE_CALLS \
                and id(node) not in bounded:
            out.append(Finding(
                ERROR, "QSM-RES-DEVICES", loc,
                f"unbounded {'.'.join(chain)}() — blocks forever on a "
                "wedged chip tunnel",
                "wrap in resilience.policy.watchdog, probe via a bounded "
                "subprocess (utils/device.py), or whitelist with a "
                "reviewed why-this-cannot-hang note"))
        elif len(chain) >= 2 and chain[0] == "subprocess" \
                and chain[-1] in _SUBPROC_WAITS \
                and "timeout" not in kwargs:
            out.append(Finding(
                ERROR, "QSM-RES-SUBPROC", loc,
                f"subprocess.{chain[-1]}() without timeout= — an "
                "unbounded wait on a child that may be probing a "
                "wedged device",
                "pass timeout= (a RetryPolicy preset's timeout_s; "
                "resilience/policy.py)"))
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr == "communicate" \
                and "timeout" not in kwargs:
            out.append(Finding(
                ERROR, "QSM-RES-SUBPROC", loc,
                "Popen.communicate() without timeout= — an unbounded "
                "wait on the child",
                "pass timeout= (resilience/policy.py preset bound)"))
        elif chain and chain[-1] in _PROBE_FNS:
            literal = [a for a in node.args if _is_number(a)] + \
                [kw.value for kw in node.keywords
                 if kw.arg in ("timeout_s", "probe_timeout_s")
                 and _is_number(kw.value)]
            if literal:
                out.append(Finding(
                    WARNING, "QSM-RES-TIMEOUT-LITERAL", loc,
                    f"numeric timeout literal passed to {chain[-1]} — "
                    "a scattered constant the named PRESETS table "
                    "replaced",
                    "pass policy=preset(name) (resilience/policy.py) "
                    "or let the callee use its preset default"))
    return out
