"""Generation passes (pass family *m* of docs/ANALYSIS.md): campaign
accumulator bounds.

A fuzzing campaign is open-ended BY DESIGN — the steering loop
(qsm_tpu/gen/steer.py) runs for as many rounds as a budget allows, and
a fleet soak (gen/fleet.py) for as long as an operator leaves it
running — so everything the plane retains across rounds must be
bounded: the seed pool is capacity-evicted, kept flip histories are
pruned to a tail window, and audit provenance is capped.  An
accumulator grown once per round without a cap is the failure mode
that turns a week-long soak into an OOM of the machine driving it —
the exact pathology the monitor plane's family (k) gates, recurring
one plane over.

* ``QSM-GEN-UNBOUNDED`` (error) — a class whose instance-attribute
  container GROWS (``self.X.append/extend/add/insert``, or
  ``heapq.heappush(self.X, …)``) while NOTHING in the class either
  compares against a bound wherever that attribute is involved (the
  ``SeedPool.add`` cap shape) or evicts from it (``pop``/``del``/
  ``clear``, or a pruning reassignment ``self.X = self.X[-keep:]`` —
  the kept-flips tail window).  Scope is the CLASS: grow site and
  discipline legitimately live in different methods.

The structural scan rides family (k)'s — deliberately: one definition
of "bounded" (monitor_passes.py ``_scan_class``), two planes held to
it — with one refinement: growth is only attributed to attributes the
class itself OWNS as raw container literals (``self.X = []`` / ``{}``
/ ``set()`` / ``deque()``).  A ``self.pool.add(…)`` where ``pool`` is
another object (``SeedPool()``) is delegation, and the delegate — in
the scan set itself — is where its bound is gated; double-reporting
it at every call site would punish exactly the encapsulation the
remediation asks for.

Scan set: qsm_tpu/gen/ + tools/bench_gen.py.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Set

from .astutil import parse_module
from .findings import ERROR, Finding
from .monitor_passes import _scan_class, _self_attr

_CONTAINER_CTORS = {"list", "dict", "set", "deque", "defaultdict",
                    "OrderedDict", "Counter"}


def _raw_container_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attributes the class initializes as bare containers — the ones
    whose growth discipline must live in THIS class."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        literal = isinstance(value, (ast.List, ast.Dict, ast.Set,
                                     ast.ListComp, ast.DictComp,
                                     ast.SetComp))
        if isinstance(value, ast.Call):
            fn = value.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            literal = name in _CONTAINER_CTORS
        if not literal:
            continue
        for tgt in targets:
            if isinstance(tgt, ast.Attribute):
                a = _self_attr(tgt)
                if a is not None:
                    out.add(a)
    return out


def check_gen_file(path: str, root: Optional[str] = None
                   ) -> List[Finding]:
    tree = parse_module(path)
    relpath = path
    if root:
        try:
            relpath = os.path.relpath(path, root)
        except ValueError:
            pass
    out: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        scan = _scan_class(cls)
        owned = _raw_container_attrs(cls)
        for attr, (fn_name, lineno, how) in sorted(scan.grows.items()):
            if attr in scan.disciplined or attr not in owned:
                continue
            out.append(Finding(
                ERROR, "QSM-GEN-UNBOUNDED",
                f"{relpath}:{cls.name}.{fn_name}:{lineno}",
                f"campaign accumulator self.{attr} grows ({how}) with "
                "no cap comparison or eviction anywhere in the class — "
                "an open-ended fuzzing campaign accumulates it once per "
                "round until the driving host OOMs",
                "compare its size against an explicit bound before "
                "growing (steer.py SeedPool.add is the model) or prune "
                "to a tail window by reassignment (the kept-flips "
                "shape)"))
    return out
