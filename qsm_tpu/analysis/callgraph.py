"""Whole-program symbol table, call graph and lock summaries — the
interprocedural substrate of pass family (g) (``race_passes.py``).

The single-module AST passes (families c–f) cannot see the hazards the
threaded serving stack actually has: a lock-order cycle needs *both*
acquisition paths, an unguarded shared write needs to know which lock
guards the attribute's *other* writes (possibly in a different method,
behind a call), and "is this function running on a thread?" is a
property of the call graph, not of any one function.  This module
builds, over a closed set of project files:

* a **symbol table** — every class (with its lock/event attributes,
  ``__init__``-assigned attributes and best-effort attribute types)
  and every function/method, nested defs included, keyed by a
  qualified name ``relpath:Class.method``;
* a **call graph** — calls resolved by a conservative ladder
  (``self.m`` → own class; annotated parameters and constructed
  locals; unique name in the same module, then project-wide;
  ambiguity drops the edge rather than guessing);
* **per-function lock summaries** — for every lock acquisition,
  attribute write and call site, the set of locks held *at that
  point*, inferred from ``with lock:`` blocks and
  ``acquire()``/``release()`` pairs, then propagated along the call
  graph both ways: ``entry_held`` (locks guaranteed held on entry —
  the intersection over all known call sites) and
  ``trans_acquires`` (locks a call may take downstream);
* **thread roots** — functions passed as ``threading.Thread``
  targets, functions that escape as callbacks (passed as a call
  argument), and everything call-reachable from them.

Everything is a deliberate approximation tuned for a lint: a dropped
ambiguous edge can only *miss* a hazard (the single-module families
still cover their ground), never invent one, and the seeded fixtures
in ``fixtures.py`` pin the true positives that must keep firing.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .astutil import attr_chain, parse_module

# threading constructors that create a mutual-exclusion object: an
# attribute/local assigned one of these becomes a trackable lock id
LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
              "BoundedSemaphore"}
EVENT_CTORS = {"Event"}
THREAD_CTORS = {"Thread"}

_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


@dataclasses.dataclass
class ClassInfo:
    name: str
    path: str                      # repo-relative module path
    lineno: int
    lock_attrs: Set[str] = dataclasses.field(default_factory=set)
    event_attrs: Set[str] = dataclasses.field(default_factory=set)
    init_attrs: Set[str] = dataclasses.field(default_factory=set)
    # best-effort attr -> class-name (``self.pool = WorkerPool(...)``,
    # ``self.handle: Optional[WorkerHandle] = None``)
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)
    # attr -> element class for list-of-instances attributes
    # (``self._slots = [_Slot(i) for i in ...]``)
    attr_elem_types: Dict[str, str] = dataclasses.field(
        default_factory=dict)
    has_bounded_join: bool = False


@dataclasses.dataclass
class ThreadStart:
    """One ``threading.Thread(target=...)`` creation site."""

    site_qual: str
    lineno: int
    target_qual: Optional[str]     # resolved target function, if any
    retained: bool                 # stored on an attribute / container


@dataclasses.dataclass
class FunctionInfo:
    qual: str                      # "relpath:Class.method" / "relpath:fn"
    name: str
    cls: Optional[str]
    path: str
    node: ast.AST
    # summaries (Project._summarize):
    acquires: List[Tuple[str, int, frozenset]] = \
        dataclasses.field(default_factory=list)
    writes: List[Tuple[str, int, frozenset]] = \
        dataclasses.field(default_factory=list)
    calls: List[Tuple[str, int, frozenset]] = \
        dataclasses.field(default_factory=list)  # (callee qual, ln, held)
    thread_starts: List[ThreadStart] = \
        dataclasses.field(default_factory=list)
    # interprocedural results (Project._propagate):
    entry_held: frozenset = frozenset()
    trans_acquires: frozenset = frozenset()
    thread_reachable: bool = False
    escapes: bool = False          # handed off as a callback


def _ann_class(ann: Optional[ast.AST]) -> Optional[str]:
    """Class name out of an annotation: ``X``, ``Optional[X]``,
    ``"X"`` — best effort, None otherwise."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.split("[")[-1].rstrip("]").split(".")[-1] or None
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Subscript):
        return _ann_class(ann.slice)
    if isinstance(ann, ast.Attribute):
        return ann.attr
    return None


def _ctor_name(expr: ast.AST, among: Set[str]) -> Optional[str]:
    """``Cls(...)`` / ``mod.Cls(...)`` when ``Cls`` is in ``among``."""
    if isinstance(expr, ast.Call):
        chain = attr_chain(expr.func)
        if chain and chain[-1] in among and len(chain) <= 2:
            return chain[-1]
    return None


def _contains_ctor(expr: ast.AST, among: Set[str]) -> bool:
    """True when any Call inside ``expr`` constructs one of ``among``
    (covers ``setdefault(k, threading.Lock())``)."""
    return any(isinstance(n, ast.Call) and _ctor_name(n, among)
               for n in ast.walk(expr))


def _elem_ctor(expr: ast.AST, classes: Set[str]) -> Optional[str]:
    """Element class of ``[Cls(...) for ...]`` / ``[Cls(a), Cls(b)]``."""
    elts: List[ast.AST] = []
    if isinstance(expr, ast.ListComp):
        elts = [expr.elt]
    elif isinstance(expr, (ast.List, ast.Tuple)):
        elts = list(expr.elts)
    for e in elts:
        name = _ctor_name(e, classes)
        if name:
            return name
    return None


def is_bounded_join(call: ast.Call) -> bool:
    """``x.join(2.0)`` / ``x.join(timeout=...)`` — a join carrying any
    bound.  A bare ``join()`` can block forever and does not count.
    One predicate for both scopes of the lifecycle rule (class-level
    in :class:`Project`, module-level in ``race_passes``)."""
    chain = attr_chain(call.func)
    return (bool(chain) and chain[-1] == "join" and len(chain) >= 2
            and (bool(call.args)
                 or any(kw.arg == "timeout" for kw in call.keywords)))


def _walk_no_defs(node: ast.AST):
    """Walk ``node`` (inclusive) without descending into nested
    function/class definitions or lambdas — those are separate scopes
    summarized on their own."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, _DEFS + (ast.Lambda,)):
                continue
            stack.append(child)


class Project:
    """Symbol table + call graph + lock summaries over a file set."""

    def __init__(self, paths: Sequence[str], root: Optional[str] = None):
        self.root = root
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self._methods: Dict[str, Dict[str, str]] = {}   # cls -> name->qual
        self._by_name: Dict[str, List[str]] = {}        # name -> [qual]
        self.modules: Dict[str, ast.Module] = {}
        for path in paths:
            rel = path
            if root:
                try:
                    rel = os.path.relpath(path, root)
                except ValueError:
                    pass
            try:
                tree = parse_module(path)
            except (OSError, SyntaxError):
                continue  # absent/unparsable: other layers report that
            self.modules[rel] = tree
            self._collect(tree, rel)
        # __init__ bodies first: they teach element types
        # (``self._slots = [_Slot(i) ...]``) the other summaries consume
        fns = sorted(self.functions.values(),
                     key=lambda f: f.name != "__init__")
        for fn in fns:
            self._summarize(fn)
        self._propagate()

    # -- symbol table ---------------------------------------------------
    def _collect(self, tree: ast.Module, rel: str) -> None:
        def add_fn(node, cls: Optional[str], prefix: str) -> None:
            qual = f"{rel}:{prefix}{node.name}"
            self.functions[qual] = FunctionInfo(
                qual=qual, name=node.name, cls=cls, path=rel, node=node)
            self._by_name.setdefault(node.name, []).append(qual)
            if cls:
                self._methods.setdefault(cls, {}).setdefault(
                    node.name, qual)
            for sub in ast.iter_child_nodes(node):
                walk_defs(sub, cls, f"{prefix}{node.name}.")

        def walk_defs(node, cls: Optional[str], prefix: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                add_fn(node, cls, prefix)
            elif isinstance(node, ast.ClassDef):
                self._collect_class(node, rel)
                for sub in ast.iter_child_nodes(node):
                    walk_defs(sub, node.name, f"{node.name}.")
            else:
                for sub in ast.iter_child_nodes(node):
                    walk_defs(sub, cls, prefix)

        for node in tree.body:
            walk_defs(node, None, "")

    def _collect_class(self, node: ast.ClassDef, rel: str) -> None:
        # same-name classes across modules merge (over-approximation;
        # none exist in the gated tree today)
        ci = self.classes.setdefault(
            node.name, ClassInfo(node.name, rel, node.lineno))
        init_ids = self._init_node_ids(node)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and is_bounded_join(sub):
                ci.has_bounded_join = True
            if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (sub.targets if isinstance(sub, ast.Assign)
                       else [sub.target])
            value = sub.value
            in_init = id(sub) in init_ids
            for tgt in targets:
                chain = attr_chain(tgt)
                if len(chain) != 2 or chain[0] != "self":
                    continue
                attr = chain[1]
                if value is not None:
                    if _ctor_name(value, LOCK_CTORS):
                        ci.lock_attrs.add(attr)
                    if _ctor_name(value, EVENT_CTORS):
                        ci.event_attrs.add(attr)
                    if isinstance(value, ast.Call):
                        c = attr_chain(value.func)
                        if c and (c[-1][:1].isupper()
                                  or c[-1][:1] == "_"):
                            ci.attr_types.setdefault(attr, c[-1])
                if in_init:
                    ci.init_attrs.add(attr)
                if isinstance(sub, ast.AnnAssign):
                    t = _ann_class(sub.annotation)
                    if t and (t[:1].isupper() or t[:1] == "_"):
                        ci.attr_types.setdefault(attr, t)

    @staticmethod
    def _init_node_ids(cls_node: ast.ClassDef) -> Set[int]:
        """Node ids inside the class's ``__init__`` bodies — one walk
        per class, not one per assignment (the old per-statement
        enclosing-def scan was quadratic in class size)."""
        ids: Set[int] = set()
        for fn in cls_node.body:
            if (isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and fn.name == "__init__"):
                ids.update(id(sub) for sub in ast.walk(fn))
        return ids

    # -- id resolution --------------------------------------------------
    def _attr_owner(self, attr: str, module: str, *,
                    locks_only: bool = False) -> Optional[str]:
        """Class owning ``attr``: unique in ``module`` first, then
        unique project-wide; None on ambiguity (drop, don't guess)."""
        pool = [(n, c) for n, c in self.classes.items()
                if attr in (c.lock_attrs if locks_only else c.init_attrs)]
        same = [n for n, c in pool if c.path == module]
        if len(same) == 1:
            return same[0]
        if len(pool) == 1:
            return pool[0][0]
        return None

    def _expr_class(self, expr: ast.AST, fn: FunctionInfo,
                    local_types: Dict[str, str]) -> Optional[str]:
        """Best-effort class of an expression (``self``, typed locals,
        attr chains walked through recorded attribute types)."""
        chain = attr_chain(expr)
        if not chain:
            return None
        if chain[0] == "self" and fn.cls:
            base: Optional[str] = fn.cls
        else:
            base = local_types.get(chain[0])
        for attr in chain[1:]:
            if base is None or base not in self.classes:
                return None
            base = self.classes[base].attr_types.get(attr)
        return base if base in self.classes else None

    def lock_id(self, expr: ast.AST, fn: FunctionInfo,
                local_locks: Set[str],
                local_types: Dict[str, str]) -> Optional[str]:
        """Canonical lock identity of an acquired expression, or None
        when it is not a recognizable lock."""
        chain = attr_chain(expr)
        if not chain:
            return None
        if len(chain) == 1:
            if chain[0] in local_locks:
                return f"{fn.qual}.{chain[0]}"
            return None
        attr = chain[-1]
        base_cls = (self._expr_class(expr.value, fn, local_types)
                    if isinstance(expr, ast.Attribute) else None)
        if base_cls and attr in self.classes[base_cls].lock_attrs:
            return f"{base_cls}.{attr}"
        owner = self._attr_owner(attr, fn.path, locks_only=True)
        return f"{owner}.{attr}" if owner else None

    def attr_id(self, target: ast.AST, fn: FunctionInfo,
                local_types: Dict[str, str]) -> Optional[str]:
        """Canonical shared-attribute identity of a write target
        (``<Class>.<attr>`` for attributes assigned in that class'
        ``__init__``); None for locals, subscripts, ambiguity."""
        if not isinstance(target, ast.Attribute):
            return None
        chain = attr_chain(target)
        if not chain:
            return None
        attr = chain[-1]
        base_cls = self._expr_class(target.value, fn, local_types)
        if base_cls:
            if attr in self.classes[base_cls].init_attrs:
                return f"{base_cls}.{attr}"
            return None  # known class, attr not shared via __init__
        owner = self._attr_owner(attr, fn.path)
        return f"{owner}.{attr}" if owner else None

    def resolve_call(self, call: ast.Call, fn: FunctionInfo,
                     local_types: Dict[str, str]) -> Optional[str]:
        """Callee qual for a call, or None when ambiguous/external."""
        chain = attr_chain(call.func)
        if not chain:
            return None
        name = chain[-1]
        if len(chain) == 1:
            if name in self.classes:   # Cls(...) -> Cls.__init__
                return self._methods.get(name, {}).get("__init__")
            return self._unique(name, fn.path)
        base_cls = (self._expr_class(call.func.value, fn, local_types)
                    if isinstance(call.func, ast.Attribute) else None)
        if base_cls:
            return self._methods.get(base_cls, {}).get(name)
        return self._unique(name, fn.path, methods_only=True)

    def _unique(self, name: str, module: str,
                methods_only: bool = False) -> Optional[str]:
        quals = [q for q in self._by_name.get(name, ())
                 if not methods_only or self.functions[q].cls]
        same = [q for q in quals if self.functions[q].path == module]
        if len(same) == 1:
            return same[0]
        if len(quals) == 1:
            return quals[0]
        return None

    def resolve_callable_ref(self, expr: ast.AST, fn: FunctionInfo
                             ) -> Optional[str]:
        """A *reference* to a function (Thread ``target=``, callback)."""
        chain = attr_chain(expr)
        if not chain:
            return None
        name = chain[-1]
        if chain[0] == "self" and fn.cls and len(chain) == 2:
            return self._methods.get(fn.cls, {}).get(name)
        if len(chain) == 1:
            return self._unique(name, fn.path)
        return None

    # -- per-function summaries ----------------------------------------
    def _summarize(self, fn: FunctionInfo) -> None:
        local_locks: Set[str] = set()
        local_types: Dict[str, str] = {}
        node = fn.node
        args = getattr(node, "args", None)
        if args is not None:
            for a in list(args.args) + list(args.kwonlyargs):
                t = _ann_class(a.annotation)
                if t and t in self.classes:
                    local_types[a.arg] = t

        def scan_node(expr: ast.AST, held: frozenset,
                      stmt: ast.AST) -> None:
            """Record calls / acquire events / thread starts / callback
            escapes inside one header or simple statement."""
            for sub in _walk_no_defs(expr):
                if not isinstance(sub, ast.Call):
                    continue
                chain = attr_chain(sub.func)
                if chain and chain[-1] in THREAD_CTORS and len(chain) <= 2:
                    self._record_thread(fn, stmt, sub)
                    continue
                callee = self.resolve_call(sub, fn, local_types)
                if callee:
                    fn.calls.append((callee, sub.lineno, held))
                if chain and len(chain) >= 2 and chain[-1] == "acquire":
                    lid = self.lock_id(sub.func.value, fn, local_locks,
                                       local_types)
                    if lid:
                        fn.acquires.append((lid, sub.lineno, held))
                for arg in list(sub.args) + [kw.value
                                             for kw in sub.keywords]:
                    ref = self.resolve_callable_ref(arg, fn)
                    if ref and ref in self.functions:
                        self.functions[ref].escapes = True

        def lock_delta(scope: ast.AST) -> Tuple[Set[str], Set[str]]:
            acq: Set[str] = set()
            rel: Set[str] = set()
            for sub in _walk_no_defs(scope):
                if not isinstance(sub, ast.Call):
                    continue
                chain = attr_chain(sub.func)
                if chain and len(chain) >= 2 and \
                        chain[-1] in ("acquire", "release"):
                    lid = self.lock_id(sub.func.value, fn, local_locks,
                                       local_types)
                    if lid:
                        (acq if chain[-1] == "acquire" else rel).add(lid)
            return acq, rel

        def learn_locals(stmt: ast.AST) -> None:
            if isinstance(stmt, ast.Assign) and stmt.value is not None:
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        if _contains_ctor(stmt.value, LOCK_CTORS):
                            local_locks.add(tgt.id)
                        cls = (_ctor_name(stmt.value, set(self.classes))
                               or self._expr_class(stmt.value, fn,
                                                   local_types))
                        if cls:
                            local_types[tgt.id] = cls
                    elif isinstance(tgt, ast.Tuple) and \
                            isinstance(stmt.value, ast.Tuple):
                        for el, val in zip(tgt.elts, stmt.value.elts):
                            if isinstance(el, ast.Name):
                                cls = self._expr_class(val, fn,
                                                       local_types)
                                if cls:
                                    local_types[el.id] = cls
            if isinstance(stmt, ast.For) and isinstance(stmt.target,
                                                        ast.Name):
                chain = attr_chain(stmt.iter)
                if chain and chain[0] == "self" and fn.cls \
                        and len(chain) == 2:
                    ci = self.classes.get(fn.cls)
                    elem = ci.attr_elem_types.get(chain[1]) if ci else None
                    if elem:
                        local_types[stmt.target.id] = elem

        def record_writes(stmt: ast.AST, held: frozenset) -> None:
            targets: List[ast.AST] = []
            if isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    targets += (list(tgt.elts)
                                if isinstance(tgt, ast.Tuple) else [tgt])
            elif isinstance(stmt, ast.AugAssign):
                targets = [stmt.target]
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                # a bare annotation (``self.x: int``) writes nothing
                targets = [stmt.target]
            for tgt in targets:
                aid = self.attr_id(tgt, fn, local_types)
                if aid:
                    fn.writes.append((aid, tgt.lineno, held))
            # list-of-instances element types out of __init__ bodies
            if (fn.name == "__init__" and fn.cls
                    and isinstance(stmt, ast.Assign)):
                for tgt in stmt.targets:
                    chain = attr_chain(tgt)
                    if len(chain) == 2 and chain[0] == "self":
                        elem = _elem_ctor(stmt.value, set(self.classes))
                        if elem:
                            self.classes[fn.cls].attr_elem_types[
                                chain[1]] = elem

        def walk_body(stmts: Sequence[ast.AST], held: frozenset) -> None:
            cur = set(held)
            for stmt in stmts:
                learn_locals(stmt)
                snap = frozenset(cur)
                if isinstance(stmt, _DEFS):
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    added: Set[str] = set()
                    for item in stmt.items:
                        scan_node(item.context_expr, snap, stmt)
                        lid = self.lock_id(item.context_expr, fn,
                                           local_locks, local_types)
                        if lid:
                            fn.acquires.append(
                                (lid, item.context_expr.lineno,
                                 frozenset(cur | added)))
                            added.add(lid)
                    walk_body(stmt.body, frozenset(cur | added))
                elif isinstance(stmt, (ast.If, ast.While)):
                    scan_node(stmt.test, snap, stmt)
                    acq, rel = lock_delta(stmt.test)
                    inner = frozenset(cur | acq)
                    walk_body(stmt.body, inner)
                    walk_body(stmt.orelse, inner)
                    cur |= acq
                    cur -= rel
                elif isinstance(stmt, ast.For):
                    scan_node(stmt.iter, snap, stmt)
                    walk_body(stmt.body, snap)
                    walk_body(stmt.orelse, snap)
                elif isinstance(stmt, ast.Try):
                    walk_body(stmt.body, snap)
                    for h in stmt.handlers:
                        walk_body(h.body, snap)
                    walk_body(stmt.orelse, snap)
                    walk_body(stmt.finalbody, snap)
                    # the acquire -> try/finally: release() idiom: the
                    # finally's release applies to everything after
                    acq, rel = lock_delta(ast.Module(
                        body=list(stmt.finalbody), type_ignores=[]))
                    cur |= acq
                    cur -= rel
                else:
                    record_writes(stmt, snap)
                    scan_node(stmt, snap, stmt)
                    acq, rel = lock_delta(stmt)
                    cur |= acq
                    cur -= rel

        walk_body(getattr(node, "body", []), frozenset())

    def _record_thread(self, fn: FunctionInfo, stmt: ast.AST,
                       call: ast.Call) -> None:
        target_qual = None
        for kw in call.keywords:
            if kw.arg == "target":
                target_qual = self.resolve_callable_ref(kw.value, fn)
        retained = False
        assigned: Optional[str] = None
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    retained = True
                elif isinstance(tgt, ast.Name):
                    assigned = tgt.id
        if assigned:
            # a local Thread var later stored on an attribute or
            # appended to a container is retained too
            for sub in _walk_no_defs(fn.node):
                if isinstance(sub, ast.Assign) and \
                        isinstance(sub.value, ast.Name) and \
                        sub.value.id == assigned and any(
                            isinstance(t, (ast.Attribute, ast.Subscript))
                            for t in sub.targets):
                    retained = True
                if isinstance(sub, ast.Call):
                    chain = attr_chain(sub.func)
                    if chain and chain[-1] in ("append", "add") and any(
                            isinstance(a, ast.Name) and a.id == assigned
                            for a in sub.args):
                        retained = True
        fn.thread_starts.append(ThreadStart(
            site_qual=fn.qual, lineno=call.lineno,
            target_qual=target_qual, retained=retained))

    # -- interprocedural propagation ------------------------------------
    def _propagate(self) -> None:
        callers: Dict[str, List[Tuple[str, frozenset]]] = {}
        for fn in self.functions.values():
            for callee, _ln, held in fn.calls:
                callers.setdefault(callee, []).append((fn.qual, held))

        # transitive acquires: fixpoint union along call edges
        for fn in self.functions.values():
            fn.trans_acquires = frozenset(l for l, _, _ in fn.acquires)
        changed, guard = True, 0
        while changed and guard < len(self.functions) + 8:
            changed, guard = False, guard + 1
            for fn in self.functions.values():
                acc = set(fn.trans_acquires)
                for callee, _ln, _h in fn.calls:
                    cf = self.functions.get(callee)
                    if cf:
                        acc |= cf.trans_acquires
                if frozenset(acc) != fn.trans_acquires:
                    fn.trans_acquires = frozenset(acc)
                    changed = True

        # entry_held: intersection over known call sites; thread and
        # callback roots (and caller-less functions) enter with nothing
        roots = {ts.target_qual
                 for f in self.functions.values()
                 for ts in f.thread_starts if ts.target_qual}
        roots |= {f.qual for f in self.functions.values() if f.escapes}
        entry: Dict[str, Optional[frozenset]] = {
            q: (frozenset() if q in roots or q not in callers else None)
            for q in self.functions}
        changed, guard = True, 0
        while changed and guard < len(self.functions) + 8:
            changed, guard = False, guard + 1
            for q in self.functions:
                if q in roots or q not in callers:
                    continue
                metas = [entry[cq] | held
                         for cq, held in callers[q]
                         if entry.get(cq) is not None]
                if not metas:
                    continue
                new = frozenset.intersection(*metas)
                if entry[q] is None or new != entry[q]:
                    entry[q] = new
                    changed = True
        for q, fn in self.functions.items():
            fn.entry_held = entry[q] or frozenset()

        # thread reachability: BFS from thread targets + callbacks
        work = list(roots)
        seen: Set[str] = set()
        while work:
            q = work.pop()
            if q in seen or q not in self.functions:
                continue
            seen.add(q)
            self.functions[q].thread_reachable = True
            work.extend(callee for callee, _ln, _h
                        in self.functions[q].calls if callee not in seen)

    # -- derived views the passes consume -------------------------------
    def effective_held(self, fn: FunctionInfo,
                       held: frozenset) -> frozenset:
        return held | fn.entry_held

    def lock_order_edges(self) -> Dict[Tuple[str, str],
                                       List[Tuple[str, int]]]:
        """(held, acquired) -> [(qual, line)] — direct acquisitions plus
        call sites whose callee transitively acquires."""
        edges: Dict[Tuple[str, str], List[Tuple[str, int]]] = {}
        for fn in self.functions.values():
            for lock, ln, held in fn.acquires:
                for l in self.effective_held(fn, held):
                    if l != lock:
                        edges.setdefault((l, lock), []).append(
                            (fn.qual, ln))
            for callee, ln, held in fn.calls:
                cf = self.functions.get(callee)
                if cf is None:
                    continue
                for l in self.effective_held(fn, held):
                    for m in cf.trans_acquires:
                        if m != l:
                            edges.setdefault((l, m), []).append(
                                (fn.qual, ln))
        return edges

    def rel_loc(self, qual: str, lineno: int) -> str:
        """``relpath:Class.method:line`` — the finding location form."""
        path, _, name = qual.partition(":")
        return f"{path}:{name}:{lineno}"
