"""Pool passes (pass family *f* of docs/ANALYSIS.md): worker-process
lifecycle hazards.

The worker-pool serving plane (serve/pool.py, serve/worker.py) spawns
and supervises CHILD PROCESSES, which fail in two ways a long-lived
in-process plane cannot: a child that is never reaped with a bound
(a ``wait()``/``join()`` that can block forever, or no reap at all —
zombies and leaked workers accumulate across a server's lifetime, and
tier-1 test runs leak processes), and a respawn loop without backoff
or an attempt bound (a crash-looping worker converts one bad spec into
an infinite spawn storm that starves the machine the service runs on).
The pool's own disciplines — ``terminate → wait(timeout) → kill``
escalation, exponential-backoff respawns with a per-slot lifetime
bound, per-spec quarantine — exist for exactly these; this pass family
is the gate that keeps future pool code on them.

AST lints over the pool modules and the serve bench tool:

* ``QSM-POOL-REAP`` (error) — a ``subprocess.Popen(...)`` /
  ``multiprocessing.Process(...)`` spawn inside a scope (the enclosing
  class, else the module) with NO bounded reap anywhere in that scope:
  no ``.wait(timeout=...)``/``.wait(N)`` and no ``.join(N)`` carrying
  a bound.  An unreaped worker is a leak; an unbounded ``wait()`` on a
  wedged worker is the QSM-RES-SUBPROC hazard at the pool level.
  Sanctioned form: spawn and reap in the same class, every
  wait/join bounded, kill escalation after the bound.
* ``QSM-POOL-RESPAWN`` (error) — a constant-``True`` ``while`` loop
  that spawns a worker with no ``sleep``-based backoff inside the
  loop: a worker that dies instantly makes this a spawn storm.
  Sanctioned forms: gate the loop on a stop flag with backoff sleeps
  (serve/pool.py ``_supervise`` is the model), or bound attempts with
  a ``for``/counter instead of ``while True``.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from .astutil import attr_chain, parse_module
from .findings import ERROR, Finding

_SPAWN_CALLS = {"Popen", "Process"}
_REAP_CALLS = {"wait", "join"}


def _is_const_true(test: ast.AST) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


def _is_spawn(node: ast.Call) -> bool:
    chain = attr_chain(node.func)
    # Popen / subprocess.Popen / mp.Process — one attribute of module
    # depth at most, so e.g. obj.factory.Process(...) stays out
    return bool(chain) and chain[-1] in _SPAWN_CALLS and len(chain) <= 2


def _is_bounded_reap(node: ast.Call) -> bool:
    """``x.wait(5)`` / ``x.wait(timeout=...)`` / ``t.join(2.0)`` — a
    reap (or join) call that carries ANY bound expression.  A bare
    ``wait()``/``join()`` is unbounded and does not count."""
    chain = attr_chain(node.func)
    if not chain or chain[-1] not in _REAP_CALLS:
        return False
    if len(chain) < 2:  # plain wait()/join() builtins are not reaps
        return False
    if any(kw.arg == "timeout" for kw in node.keywords):
        return True
    return bool(node.args)


def _scope_map(tree: ast.Module) -> dict:
    """node id -> innermost enclosing ClassDef (or None for module
    scope).  The REAP rule is scoped per class: a pool class owns its
    workers' whole lifecycle, so the spawn and the bounded reap must
    live together — while a *different* class' reap says nothing about
    this one's spawns."""
    owner: dict = {}
    for cls in ast.walk(tree):
        if isinstance(cls, ast.ClassDef):
            for sub in ast.walk(cls):
                owner[id(sub)] = cls  # innermost wins (visited last)
    return owner


def _function_map(tree: ast.Module) -> dict:
    owner: dict = {}
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(fn):
                owner[id(sub)] = fn.name  # innermost wins
    return owner


def check_pool_file(path: str, root: Optional[str] = None
                    ) -> List[Finding]:
    tree = parse_module(path)
    relpath = path
    if root:
        try:
            relpath = os.path.relpath(path, root)
        except ValueError:
            pass
    scope_of = _scope_map(tree)
    fn_of = _function_map(tree)
    out: List[Finding] = []

    # collect spawns and bounded reaps per scope (class or module)
    spawns: dict = {}
    reaped: set = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        scope = scope_of.get(id(node))
        scope_key = id(scope) if scope is not None else None
        if _is_spawn(node):
            spawns.setdefault(scope_key, []).append(node)
        elif _is_bounded_reap(node):
            reaped.add(scope_key)

    for scope_key, nodes in spawns.items():
        if scope_key in reaped:
            continue
        for node in nodes:
            name = fn_of.get(id(node), "<module>")
            out.append(Finding(
                ERROR, "QSM-POOL-REAP",
                f"{relpath}:{name}:{node.lineno}",
                "worker spawned with no bounded reap path in its scope "
                "— no wait(timeout=)/join(N) anywhere in the owning "
                "class: leaked or zombie workers accumulate for the "
                "server's whole lifetime",
                "reap where you spawn: terminate() -> wait(timeout=...) "
                "-> kill() escalation (serve/pool.py stop/_shed is the "
                "model)"))

    # respawn storms: while-True loops that spawn without backoff
    for node in ast.walk(tree):
        if not isinstance(node, ast.While) or not _is_const_true(node.test):
            continue
        spawn = None
        has_sleep = False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                if _is_spawn(sub):
                    spawn = spawn or sub
                chain = attr_chain(sub.func)
                if chain and chain[-1] == "sleep":
                    has_sleep = True
        if spawn is None or has_sleep:
            continue
        name = fn_of.get(id(node), "<module>")
        out.append(Finding(
            ERROR, "QSM-POOL-RESPAWN",
            f"{relpath}:{name}:{node.lineno}",
            "while-True respawn loop with no backoff sleep — a worker "
            "that dies instantly turns this into a spawn storm that "
            "starves the host",
            "gate the loop on a stop flag with exponential-backoff "
            "sleeps and a lifetime attempt bound per slot "
            "(serve/pool.py _supervise), or bound attempts with a for "
            "loop"))
    return out
