"""Race passes (pass family *g* of docs/ANALYSIS.md): interprocedural
lock/thread hazards across the serving stack.

PRs 4–5 made qsm_tpu a long-lived threaded service: CheckServer
connection threads, the batcher's dispatcher threads, the pool
supervisor's heartbeat/respawn machinery and a shared verdict bank all
coordinate through locks.  A wedge or a torn verdict in that world
comes from a lock-order cycle or an unguarded shared write — defects
no single-module pattern matcher can see, because the two halves of
the hazard live in different functions (often different files).  This
family runs on the whole-program :class:`~qsm_tpu.analysis.callgraph.
Project` (symbol table + call graph + propagated lock summaries) over
the serve, resilience and tools planes:

* ``QSM-RACE-ORDER`` (error) — a cycle in the lock-order graph: lock
  ``B`` acquired while holding ``A`` on one path and ``A`` while
  holding ``B`` on another (directly or through calls).  Two threads
  interleaving those paths deadlock; on this stack that is a wedged
  server, not a crash.  Sanctioned form: one global acquisition order
  (document it on the lock's docstring), or never hold two locks.
* ``QSM-RACE-UNGUARDED`` (error) — an attribute that has lock-guarded
  writes elsewhere is written with NO lock held on a path reachable
  from a thread target or escaped callback.  A mixed discipline means
  the guard is load-bearing on some paths and absent on others — the
  torn-counter/torn-state shape.  ``__init__`` writes are exempt
  (pre-publication).
* ``QSM-THREAD-LIFECYCLE`` (error) — a thread started whose target
  contains a loop that never consults a stop signal (no ``Event.
  is_set()/wait()``, no stop-flag read, no deadline in the loop test),
  or a *retained* thread (stored on an attribute or container) whose
  owning scope has no bounded ``join(N)``.  Either way teardown can't
  complete deterministically — the hang shows up in tier-1 test
  teardown and every server restart.  Un-retained daemon threads with
  stop-gated loops (the connection-handler idiom) are sanctioned.
* ``QSM-RES-LEAK`` (error) — an fd/pipe/socket acquired (``os.pipe``/
  ``os.dup``/``os.open``/``os.fdopen``/``socket.socket``/bare
  ``open``) outside a ``with``, never closed in the acquiring
  function and never handed off (returned, stored, passed along).  A
  long-lived server leaks these until accept() fails with EMFILE.

The family's scan set is wider than any other (serve + resilience +
tools) because the hazards cross module boundaries; the engine's
declarative family registry (engine.py) carries that without
special-casing.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .astutil import attr_chain
from .callgraph import (FunctionInfo, Project, _walk_no_defs,
                        is_bounded_join)
from .findings import ERROR, Finding

# fd/socket acquisition calls the leak pass tracks: chains joined with
# '.'; single-component entries are builtins
_FD_ACQUIRE = {"os.pipe", "os.dup", "os.open", "os.fdopen",
               "socket.socket", "open"}
_TIME_CALLS = {"monotonic", "perf_counter", "time"}


def check_race_project(paths: Sequence[str],
                       root: Optional[str] = None) -> List[Finding]:
    """Run the whole family over one closed file set."""
    project = Project(paths, root=root)
    out: List[Finding] = []
    out += check_lock_order(project)
    out += check_unguarded_writes(project)
    out += check_thread_lifecycle(project)
    out += check_resource_leaks(project)
    return out


# ---------------------------------------------------------------------------
# QSM-RACE-ORDER
# ---------------------------------------------------------------------------

def check_lock_order(project: Project) -> List[Finding]:
    edges = project.lock_order_edges()
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    out: List[Finding] = []
    for scc in _lock_sccs(graph):
        # walk REAL edges through the SCC for the reported path: a
        # sorted node list is not a cycle, and indexing `edges` with a
        # synthesized pair would crash exactly when a 3+-lock deadlock
        # is found
        cycle = _cycle_path(graph, set(scc))
        pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
        sites = [f"{a} -> {b} at "
                 f"{project.rel_loc(*edges[(a, b)][0])}"
                 for a, b in pairs]
        first_qual, first_ln = edges[pairs[0]][0]
        out.append(Finding(
            ERROR, "QSM-RACE-ORDER",
            project.rel_loc(first_qual, first_ln),
            "lock-order cycle " + " -> ".join(cycle + [cycle[0]])
            + ": two threads interleaving these paths deadlock "
            "(" + "; ".join(sites) + ")",
            "pick ONE acquisition order for these locks and apply it "
            "on every path (or restructure so no path holds both)"))
    return out


def _cycle_path(graph: Dict[str, Set[str]], scc: Set[str]) -> List[str]:
    """One concrete cycle inside an SCC, following actual edges (every
    SCC node has an intra-SCC successor by definition)."""
    start = sorted(scc)[0]
    path = [start]
    seen = {start}
    node = start
    while True:
        nxts = [w for w in sorted(graph.get(node, ())) if w in scc]
        if start in nxts and len(path) > 1:
            return path
        unvisited = [w for w in nxts if w not in seen]
        node = unvisited[0] if unvisited else nxts[0]
        if node in seen:
            return path[path.index(node):]  # the loop we walked into
        path.append(node)
        seen.add(node)


def _lock_sccs(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Strongly connected components with |SCC| > 1 — one finding per
    deadlock-capable lock group (self-edges are excluded upstream:
    re-entrant patterns are a different defect class)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in sorted(graph.get(v, ())):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs


# ---------------------------------------------------------------------------
# QSM-RACE-UNGUARDED
# ---------------------------------------------------------------------------

def check_unguarded_writes(project: Project) -> List[Finding]:
    # attr id -> [(fn, line, effective_held)]
    sites: Dict[str, List[Tuple[FunctionInfo, int, frozenset]]] = {}
    for fn in project.functions.values():
        if fn.name == "__init__":
            continue  # pre-publication writes are the sanctioned form
        for attr, ln, held in fn.writes:
            sites.setdefault(attr, []).append(
                (fn, ln, project.effective_held(fn, held)))
    out: List[Finding] = []
    for attr in sorted(sites):
        rows = sites[attr]
        guarded = [r for r in rows if r[2]]
        if not guarded:
            continue  # no lock discipline to be inconsistent with
        # the guard is the lock most guarded writes agree on
        counts: Dict[str, int] = {}
        for _fn, _ln, held in guarded:
            for lock in held:
                counts[lock] = counts.get(lock, 0) + 1
        guard = sorted(counts, key=lambda k: (-counts[k], k))[0]
        guard_eg = next(project.rel_loc(fn.qual, ln)
                        for fn, ln, held in guarded if guard in held)
        for fn, ln, held in rows:
            if held or not fn.thread_reachable:
                continue
            out.append(Finding(
                ERROR, "QSM-RACE-UNGUARDED",
                project.rel_loc(fn.qual, ln),
                f"write to shared attribute {attr} with no lock held, "
                f"on a thread-reachable path — its other writes hold "
                f"{guard} (e.g. {guard_eg}); concurrent writers can "
                "tear or lose this update",
                f"hold {guard} here too (or move the write inside an "
                "existing guarded region)"))
    return out


# ---------------------------------------------------------------------------
# QSM-THREAD-LIFECYCLE
# ---------------------------------------------------------------------------

def _consults_stop(node: ast.AST, project: Project,
                   fn: FunctionInfo) -> bool:
    ci = project.classes.get(fn.cls) if fn.cls else None
    for sub in _walk_no_defs(node):
        if isinstance(sub, ast.Call):
            chain = attr_chain(sub.func)
            if chain and chain[-1] in ("is_set", "wait"):
                return True
        name = None
        if isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Name):
            name = sub.id
        if name is not None:
            low = name.lower()
            if "stop" in low or "shutdown" in low:
                return True
            if ci and name in ci.event_attrs:
                return True
    return False


def _time_bounded(test: ast.AST) -> bool:
    for sub in _walk_no_defs(test):
        if isinstance(sub, ast.Call):
            chain = attr_chain(sub.func)
            if chain and chain[-1] in _TIME_CALLS:
                return True
    return False


def _unstoppable_loop(target: FunctionInfo,
                      project: Project) -> Optional[int]:
    """Line of a ``while`` in the target that no stop signal (its own
    or an enclosing loop's) can exit, or None."""

    def visit(node: ast.AST, ancestor_ok: bool) -> Optional[int]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(child, ast.While):
                ok = (ancestor_ok
                      or _consults_stop(child, project, target)
                      or _time_bounded(child.test))
                if not ok:
                    return child.lineno
                hit = visit(child, True)
            else:
                hit = visit(child, ancestor_ok)
            if hit is not None:
                return hit
        return None

    return visit(target.node, False)


def _module_has_bounded_join(project: Project, path: str) -> bool:
    tree = project.modules.get(path)
    if tree is None:
        return False
    return any(isinstance(sub, ast.Call) and is_bounded_join(sub)
               for sub in ast.walk(tree))


def check_thread_lifecycle(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for fn in project.functions.values():
        for ts in fn.thread_starts:
            problems: List[str] = []
            target = (project.functions.get(ts.target_qual)
                      if ts.target_qual else None)
            if target is not None:
                ln = _unstoppable_loop(target, project)
                if ln is not None:
                    problems.append(
                        f"target {target.name}'s loop (line {ln}) never "
                        "consults a stop flag or deadline — the thread "
                        "cannot be told to exit")
            if ts.retained:
                scope_ok = (project.classes[fn.cls].has_bounded_join
                            if fn.cls and fn.cls in project.classes
                            else _module_has_bounded_join(project,
                                                          fn.path))
                if not scope_ok:
                    problems.append(
                        "the Thread object is retained but its owning "
                        "scope has no bounded join(N) — teardown cannot "
                        "complete deterministically")
            if problems:
                out.append(Finding(
                    ERROR, "QSM-THREAD-LIFECYCLE",
                    project.rel_loc(ts.site_qual, ts.lineno),
                    "thread started without a teardown path: "
                    + "; ".join(problems),
                    "gate the target loop on a threading.Event (stop "
                    "flag or deadline) and join retained threads with "
                    "a bound on the stop path (serve/batcher.py "
                    "start/stop is the model)"))
    return out


# ---------------------------------------------------------------------------
# QSM-RES-LEAK
# ---------------------------------------------------------------------------

def _is_fd_acquire(call: ast.Call) -> bool:
    chain = attr_chain(call.func)
    return bool(chain) and ".".join(chain) in _FD_ACQUIRE


def _class_closes_attr(project: Project, cls: Optional[str],
                       attr: str) -> bool:
    if not cls:
        return False
    for other in project.functions.values():
        if other.cls != cls:
            continue
        for sub in _walk_no_defs(other.node):
            if isinstance(sub, ast.Call):
                chain = attr_chain(sub.func)
                if chain and chain[-1] == "close" and attr in chain:
                    return True
                # handed to a helper (``self._close_pipes(proc)``)
                for arg in sub.args:
                    achain = attr_chain(arg)
                    if achain and attr in achain:
                        return True
    return False


def check_resource_leaks(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for fn in project.functions.values():
        out += _leaks_in(fn, project)
    return out


def _leaks_in(fn: FunctionInfo, project: Project) -> List[Finding]:
    node = fn.node
    with_items: Set[int] = set()
    consumed: Set[int] = set()
    for sub in _walk_no_defs(node):
        if isinstance(sub, (ast.With, ast.AsyncWith)):
            for item in sub.items:
                for c in ast.walk(item.context_expr):
                    with_items.add(id(c))
        if isinstance(sub, ast.Call):
            # an acquisition nested inside another expression is
            # consumed by it (``os.fdopen(os.dup(0))``)
            for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
                for c in ast.walk(arg):
                    consumed.add(id(c))

    # name -> closed/escaped facts, gathered once over the function
    closes: Set[str] = set()
    escapes: Set[str] = set()
    for sub in _walk_no_defs(node):
        if isinstance(sub, ast.Call):
            chain = attr_chain(sub.func)
            if chain and chain[-1] == "close":
                closes.update(chain[:-1])
            for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
                for c in ast.walk(arg):
                    nchain = attr_chain(c)
                    if nchain:
                        escapes.add(nchain[0])
        elif isinstance(sub, ast.Return) and sub.value is not None:
            for c in ast.walk(sub.value):
                nchain = attr_chain(c)
                if nchain:
                    escapes.add(nchain[0])
        elif isinstance(sub, ast.Assign):
            stores = any(isinstance(t, (ast.Attribute, ast.Subscript))
                         for t in sub.targets)
            if stores:
                for c in ast.walk(sub.value):
                    nchain = attr_chain(c)
                    if nchain:
                        escapes.add(nchain[0])

    out: List[Finding] = []
    for sub in _walk_no_defs(node):
        if not (isinstance(sub, ast.Call) and _is_fd_acquire(sub)):
            continue
        if id(sub) in with_items or id(sub) in consumed:
            continue
        stmt = _enclosing_assign(node, sub)
        if stmt is None:
            # acquired and dropped on the floor (bare expression)
            out.append(_leak_finding(fn, project, sub.lineno))
            continue
        names: List[str] = []
        attr_target: Optional[str] = None
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                names.append(tgt.id)
            elif isinstance(tgt, ast.Tuple):
                names += [e.id for e in tgt.elts
                          if isinstance(e, ast.Name)]
            elif isinstance(tgt, ast.Attribute):
                chain = attr_chain(tgt)
                if len(chain) == 2 and chain[0] == "self":
                    attr_target = chain[1]
        if attr_target is not None:
            if not _class_closes_attr(project, fn.cls, attr_target):
                out.append(_leak_finding(fn, project, sub.lineno))
            continue
        if not names:
            continue
        if any(n in closes or n in escapes for n in names):
            continue
        out.append(_leak_finding(fn, project, sub.lineno))
    return out


def _enclosing_assign(fn_node: ast.AST, call: ast.Call):
    """The Assign/AnnAssign statement whose value is this acquisition
    (an annotated ``s: socket.socket = socket.socket()`` binds a name
    just like a plain assign)."""
    for sub in _walk_no_defs(fn_node):
        if isinstance(sub, ast.Assign) and sub.value is call:
            return sub
        if isinstance(sub, ast.AnnAssign) and sub.value is call:
            return sub
    return None


def _leak_finding(fn: FunctionInfo, project: Project,
                  lineno: int) -> Finding:
    return Finding(
        ERROR, "QSM-RES-LEAK",
        project.rel_loc(fn.qual, lineno),
        "fd/socket acquired here is never closed in this function and "
        "never handed off (not returned, stored, or passed along) — a "
        "long-lived server leaks descriptors until accept() fails "
        "with EMFILE",
        "close on every exit (with-statement or try/finally), or hand "
        "the resource to an owner that closes it")
