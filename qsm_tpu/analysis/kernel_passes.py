"""Kernel trace-hazard passes (pass family *b* of docs/ANALYSIS.md).

The device engines (ops/jax_kernel.py, ops/pallas_kernel.py and the
combinators ops/segdc.py, ops/rootsplit.py, ops/pcomp.py) burn windows
in ways the test suite's tiny CPU corpora never exercise: a kernel that
silently retraces per batch pays a full first-compile inside the healing
window, a weak-type promotion recompiles every executable at double
width, a host transfer inside a traced loop body either fails the trace
on-chip or serializes the lockstep loop, and a Pallas table spec past
the VMEM envelope fails allocation only on the real Mosaic stack.

* ``QSM-KERN-DTYPE``     — abstract evaluation of ``step_jax``: the new
  state must stay int32 and ``ok`` must be bool; any float/int64/
  weak-type output is flagged (no device run needed — ``jax.eval_shape``).
* ``QSM-KERN-HOST-XFER`` — AST lint: no ``.item()`` / ``np.*`` /
  ``float()/int()`` on traced values inside while_loop/fori_loop/scan/
  pallas_call bodies.
* ``QSM-KERN-RETRACE``   — dynamic jit-cache growth across same-bucket
  calls: a warmed backend re-checking a same-shaped corpus must not
  compile anything new.
* ``QSM-KERN-VMEM``      — static VMEM-envelope estimator for the Pallas
  block layout, checked against ``MAX_PALLAS_STATES`` and every
  registry table spec (the chip-free twin of the Mosaic allocation
  failure ADVICE r5 finding 2 warned about).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence

import numpy as np

from ..core.spec import Spec
from .astutil import (attr_chain, iter_flagged_bodies, parse_module,
                      traced_function_names)
from .findings import ERROR, WARNING, Finding

# Per-core VMEM on current TPU generations is ~16 MB (Pallas guide);
# leave headroom for Mosaic's own scratch/regalloc — the estimator gates
# at 75% of the physical envelope.
VMEM_BYTES_PHYSICAL = 16 * 1024 * 1024
VMEM_BUDGET_BYTES = int(VMEM_BYTES_PHYSICAL * 0.75)

_HOST_CALL_ROOTS = {"np", "numpy"}
_HOST_CASTS = {"float", "int", "bool"}


# -- QSM-KERN-DTYPE ---------------------------------------------------------

def check_step_dtypes(spec: Spec, location: str) -> List[Finding]:
    """Abstract-evaluate ``step_jax`` with the kernel's exact input types
    (int32 state vector, int32 scalars) and flag promotions."""
    import jax
    import jax.numpy as jnp

    out: List[Finding] = []
    sds = jax.ShapeDtypeStruct
    try:
        ns, ok = jax.eval_shape(
            spec.step_jax,
            sds((spec.STATE_DIM,), jnp.int32),
            sds((), jnp.int32), sds((), jnp.int32), sds((), jnp.int32))
    except Exception as e:  # noqa: BLE001 — a step_jax that cannot even
        return [Finding(                  # trace is itself the finding
            ERROR, "QSM-KERN-DTYPE", location,
            f"step_jax fails abstract evaluation with int32 inputs: "
            f"{type(e).__name__}: {e}"[:300],
            "the kernel vmaps step_jax over int32 arrays; it must "
            "trace under exactly these types")]
    if np.dtype(ns.dtype) != np.int32:
        out.append(Finding(
            ERROR, "QSM-KERN-DTYPE", location,
            f"step_jax returns state dtype {ns.dtype} (want int32)",
            "a promoted state dtype recompiles every kernel executable "
            "and doubles the carry's HBM/VMEM footprint"))
    if ns.shape != (spec.STATE_DIM,):
        out.append(Finding(
            ERROR, "QSM-KERN-DTYPE", location,
            f"step_jax returns state shape {ns.shape} "
            f"(want ({spec.STATE_DIM},))",
            "the DFS carry stacks states at STATE_DIM width"))
    if np.dtype(ok.dtype) != np.bool_:
        out.append(Finding(
            ERROR, "QSM-KERN-DTYPE", location,
            f"step_jax returns ok dtype {ok.dtype} (want bool)",
            "the candidate mask ANDs ok with bool masks; a non-bool ok "
            "promotes the whole mask arithmetic"))
    for name, res in (("state", ns), ("ok", ok)):
        if getattr(res, "weak_type", False):
            out.append(Finding(
                WARNING, "QSM-KERN-DTYPE", location,
                f"step_jax {name} output is weakly typed",
                "weak types re-promote at use sites; anchor with an "
                "explicit astype"))
    return out


# -- QSM-KERN-HOST-XFER -----------------------------------------------------

def check_host_transfers(path: str, root: Optional[str] = None
                         ) -> List[Finding]:
    """AST lint over one engine module: host-device transfers inside
    traced loop bodies (``.item()``, ``np.*``, ``float()/int()``).

    Locations are function-qualified (``path:funcname:line``) so a
    whitelist entry can pin the one reviewed function
    (``...pallas_kernel.py:_i32``) instead of the whole file — a
    file-wide prefix would silently accept FUTURE transfers anywhere in
    the module the gate most exists to protect."""
    import os

    tree = parse_module(path)
    flagged = traced_function_names(tree)
    relpath = path
    if root:
        try:
            relpath = os.path.relpath(path, root)
        except ValueError:
            pass
    out: List[Finding] = []
    for fn_name, node in iter_flagged_bodies(tree, flagged):
        if not isinstance(node, ast.Call):
            continue
        loc = f"{relpath}:{fn_name}:{getattr(node, 'lineno', 0)}"
        chain = attr_chain(node.func)
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "item":
            out.append(Finding(
                ERROR, "QSM-KERN-HOST-XFER", loc,
                f".item() inside traced loop body {fn_name!r}",
                "a concretization inside a traced body either fails "
                "the trace or forces a device sync per iteration"))
        elif chain and chain[0] in _HOST_CALL_ROOTS:
            out.append(Finding(
                ERROR, "QSM-KERN-HOST-XFER", loc,
                f"host numpy call {'.'.join(chain)} inside traced "
                f"loop body {fn_name!r}",
                "use jax.numpy inside traced bodies; np.* materializes "
                "on host"))
        elif (isinstance(node.func, ast.Name)
              and node.func.id in _HOST_CASTS
              and node.args
              and not isinstance(node.args[0], ast.Constant)):
            out.append(Finding(
                WARNING, "QSM-KERN-HOST-XFER", loc,
                f"python {node.func.id}() cast inside traced loop body "
                f"{fn_name!r}",
                "casting a traced value concretizes it; use "
                "jnp astype/where"))
    return out


# -- QSM-KERN-RETRACE -------------------------------------------------------

def jit_cache_entries(backend) -> int:
    """Compiled-executable count of a JaxTPU-style backend: distinct
    jitted callables plus, where jax exposes it, each callable's own
    trace-cache size (a retrace shows up in one or the other)."""
    total = 0
    for store in ("_compiled", "_pallas_fns", "_table_fns"):
        fns = getattr(backend, store, None)
        if not isinstance(fns, dict):
            continue
        total += len(fns)
        for fn in fns.values():
            size = getattr(fn, "_cache_size", None)
            if callable(size):
                try:
                    total += int(size())
                except Exception:  # noqa: BLE001 — introspection only
                    pass
    return total


def check_retracing(spec: Spec, backend, corpora: Sequence,
                    location: str) -> List[Finding]:
    """Warm ``backend`` on ``corpora[0]`` then re-check same-bucket
    corpora; any jit cache-entry growth after the warm call is a
    retrace.  ``corpora`` must all bucket to the same (n_ops, batch)
    shapes — the caller guarantees it (engine.py builds them)."""
    backend.check_histories(spec, corpora[0])  # warm: compiles are legal
    warm = jit_cache_entries(backend)
    for i, corpus in enumerate(corpora):
        backend.check_histories(spec, corpus)
        now = jit_cache_entries(backend)
        if now > warm:
            return [Finding(
                ERROR, "QSM-KERN-RETRACE", location,
                f"jit cache grew {warm} -> {now} entries on warmed "
                f"same-bucket call #{i + 1} "
                f"(backend {type(backend).__name__})",
                "a per-call compile inside a healing window costs "
                "20-40 s on-chip; key compiled fns on shapes/buckets "
                "only, never per-call state")]
    return []


# -- QSM-KERN-VMEM ----------------------------------------------------------

def pallas_vmem_bytes(n_ops: int, state_bound: int, lanes: int,
                      cache_slots: int) -> int:
    """Static VMEM estimate (bytes) of one Pallas block of the scalar-
    table search kernel (ops/pallas_kernel.py build_pallas_chunk): every
    in_spec block plus every out_spec block, all int32, lane-minor
    ``[rows, lanes]`` layouts with the step tables ``[S, N, L]``.  This
    is the chip-free twin of the Mosaic VMEM allocation: if the estimate
    exceeds the envelope, the first real-chip launch would fail after
    compile — inside the window."""
    N, S, L = n_ops, state_bound, lanes
    CS = max(cache_slots, 1)
    in_rows = (
        2 * S * N      # nxt/ok step tables
        + N            # prec_word
        + N            # valid
        + 1            # nreq
        + N            # taken
        + (N + 1)      # chosen
        + (N + 1)      # states
        + 3            # dsi (depth/status/iters)
        + 3 * CS       # cache key0/key1/occupancy planes
    )
    out_rows = (
        N + 2 * (N + 1) + 3 + 3 * CS
    )
    return 4 * L * (in_rows + out_rows)


def check_pallas_vmem(specs_with_locations, location_consts: str
                      ) -> List[Finding]:
    """Estimate the Pallas VMEM footprint for the kernel's own ceiling
    (``MAX_PALLAS_STATES``) and for every table spec that PallasTPU
    would accept; flag anything over the envelope."""
    from ..ops.pallas_kernel import (MAX_PALLAS_OPS, MAX_PALLAS_STATES,
                                     PallasTPU)

    lanes = PallasTPU.LANES
    slots = PallasTPU.PALLAS_CACHE_SLOTS
    out: List[Finding] = []
    ceiling = pallas_vmem_bytes(MAX_PALLAS_OPS, MAX_PALLAS_STATES,
                                lanes, slots)
    if ceiling > VMEM_BUDGET_BYTES:
        out.append(Finding(
            ERROR, "QSM-KERN-VMEM", location_consts,
            f"MAX_PALLAS_STATES={MAX_PALLAS_STATES} admits blocks of "
            f"~{ceiling / 2**20:.1f} MiB VMEM "
            f"(> budget {VMEM_BUDGET_BYTES / 2**20:.1f} MiB)",
            "lower MAX_PALLAS_STATES or shrink LANES/"
            "PALLAS_CACHE_SLOTS — a too-large admitted table fails "
            "VMEM allocation on the real chip only"))
    from ..ops.scalarize import scalar_shadow

    for spec, loc in specs_with_locations:
        # derive the bound the way PallasTPU itself does: through the
        # scalarized shadow when one exists (a vector spec whose shadow
        # bound fits MAX_PALLAS_STATES IS accepted by the constructor,
        # so skipping it here would reintroduce the fail-on-chip gap)
        kspec = scalar_shadow(spec) or spec
        bound = (kspec.scalar_state_bound(MAX_PALLAS_OPS)
                 if kspec.STATE_DIM == 1 else None)
        if bound is None or bound > MAX_PALLAS_STATES:
            continue  # PallasTPU refuses these at construction
        est = pallas_vmem_bytes(MAX_PALLAS_OPS, bound, lanes, slots)
        if est > VMEM_BUDGET_BYTES:
            out.append(Finding(
                ERROR, "QSM-KERN-VMEM", loc,
                f"Pallas block for state bound {bound} estimates "
                f"~{est / 2**20:.1f} MiB VMEM "
                f"(> budget {VMEM_BUDGET_BYTES / 2**20:.1f} MiB)",
                "this spec would pass PallasTPU's constructor gate but "
                "fail VMEM allocation on-chip"))
    return out
