"""Shrink passes (pass family *h* of docs/ANALYSIS.md): frontier bounds.

The shrink plane's one structural promise is that every frontier is
FINITE and every greedy loop bounded: candidate generation is a bounded
sweep over one history's ops, the round loop carries an explicit cap
behind its lexicographic termination measure, and a truncated frontier
says so in ``why`` (qsm_tpu/shrink/frontier.py module docstring).  An
unbounded frontier generator is the failure mode that turns one shrink
request into a runaway CPU burn inside the serving plane — the shrink
verb shares the micro-batcher with paying traffic, so the burn starves
every client, not just the requester.

* ``QSM-SHRINK-UNBOUNDED`` (error) — a constant-true ``while`` loop
  that GROWS a frontier (``yield``, ``.append()``, ``.extend()``,
  ``.add()``) with no ``break`` at all, or with no comparison anywhere
  in the loop body (nothing that could be a size/round cap): frontier
  generation with no round or size bound.  Sanctioned forms: iterate
  the ops (a ``for`` over a bounded sequence), or gate the loop /
  ``break`` on an explicit cap comparison.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from .astutil import attr_chain, parse_module
from .findings import ERROR, Finding

_GROW_CALLS = {"append", "extend", "add"}


def _is_const_true(test: ast.AST) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


def _grows_frontier(node: ast.While) -> Optional[str]:
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Yield, ast.YieldFrom)):
            return "yield"
        if isinstance(sub, ast.Call):
            chain = attr_chain(sub.func)
            if chain and chain[-1] in _GROW_CALLS:
                return f".{chain[-1]}()"
    return None


def _has_bounded_break(node: ast.While) -> bool:
    has_break = any(isinstance(sub, ast.Break) for sub in ast.walk(node))
    if not has_break:
        return False
    # a break with no comparison anywhere in the loop cannot be a
    # size/round cap (it is some other control flow); require at least
    # one Compare so "while True: ... if len(out) >= cap: break" passes
    # and a compare-free grow loop does not
    return any(isinstance(sub, ast.Compare) for sub in ast.walk(node))


def check_shrink_file(path: str, root: Optional[str] = None
                      ) -> List[Finding]:
    tree = parse_module(path)
    relpath = path
    if root:
        try:
            relpath = os.path.relpath(path, root)
        except ValueError:
            pass
    owner: dict = {}
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(fn):
                owner[id(sub)] = fn  # innermost wins (visited last)
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.While):
            continue
        if not _is_const_true(node.test):
            continue  # a real test IS the bound (rounds < cap, etc.)
        grow = _grows_frontier(node)
        if grow is None:
            continue
        if _has_bounded_break(node):
            continue
        fn = owner.get(id(node))
        name = fn.name if fn is not None else "<module>"
        out.append(Finding(
            ERROR, "QSM-SHRINK-UNBOUNDED",
            f"{relpath}:{name}:{node.lineno}",
            f"while-True loop grows a frontier ({grow}) with no "
            "round/size cap — one shrink request becomes an unbounded "
            "CPU burn on lanes shared with paying traffic",
            "iterate the history's ops (bounded), or gate the loop/"
            "break on an explicit cap (shrink/frontier.py "
            "shrink_frontier's max_lanes is the model)"))
    return out
