"""Obs passes (pass family *i* of docs/ANALYSIS.md): trace-plane
discipline.

The observability plane (qsm_tpu/obs) has two structural promises the
rest of the stack leans on: every span CLOSES (an entered-never-exited
span leaves a hole in the causal tree and, worse, leaks whatever the
span body holds open), and every metric identity is BOUNDED (a metric
or label minted from per-request data — a history fingerprint, a cache
key — grows the registry without bound and turns the ``/metrics``
scrape into an allocation bomb; Prometheus calls this a cardinality
explosion).  The live code keeps both by construction; this family is
the gate that keeps future obs/serve/resilience code on them.

* ``QSM-OBS-SPAN`` (error) — a ``*.span(...)`` call used neither as a
  ``with`` context expression nor immediately returned (a delegating
  wrapper): a span opened by hand has no exception-safe close, so a
  raise between open and close orphans it.  Sanctioned forms: ``with
  tracer.span(...) as sp:`` (the close is the ``__exit__``), or
  ``return tracer.span(...)`` (the caller's ``with`` owns it).
* ``QSM-OBS-CARDINALITY`` (error) — a metric registration
  (``counter``/``gauge``/``histogram``) whose NAME argument, or a
  metric write (``inc``/``set``/``observe``/``event``/``emit``) whose
  label/attr value, is built dynamically from runtime data (f-string,
  string concatenation/``%``, ``.format()``): metric identity must
  come from a bounded vocabulary (worker ids, flush reasons, verdict
  names), never from per-request values.  ``str(wid)``-style casts of
  bounded values are fine — the rule flags string SYNTHESIS, the
  static marker of identity-from-data.

Span-event ATTRS are exempt from the cardinality rule's name check:
attrs ride the trace log (per-request by design), not the metric
registry.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from .astutil import attr_chain, parse_module
from .findings import ERROR, Finding

_METRIC_REGISTER = {"counter", "gauge", "histogram"}
_METRIC_WRITE = {"inc", "set", "observe"}


def _relpath(path: str, root: Optional[str]) -> str:
    if root:
        try:
            return os.path.relpath(path, root)
        except ValueError:
            pass
    return path


def _enclosing_function_map(tree: ast.Module) -> dict:
    owner: dict = {}
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(fn):
                owner[id(sub)] = fn  # innermost wins (visited last)
    return owner


def _is_dynamic_str(node: ast.AST) -> bool:
    """String synthesis: f-string, concat/%-format over strings, or a
    ``.format()`` call — the static marker of identity built from
    runtime data."""
    if isinstance(node, ast.JoinedStr):
        return any(isinstance(v, ast.FormattedValue) for v in node.values)
    if isinstance(node, ast.BinOp) and isinstance(node.op,
                                                  (ast.Add, ast.Mod)):
        return (_is_str_like(node.left) or _is_str_like(node.right))
    if isinstance(node, ast.Call):
        chain = attr_chain(node.func)
        return bool(chain) and chain[-1] == "format"
    return False


def _is_str_like(node: ast.AST) -> bool:
    return (isinstance(node, ast.Constant) and isinstance(node.value, str)
            ) or isinstance(node, ast.JoinedStr)


def check_obs_file(path: str, root: Optional[str] = None
                   ) -> List[Finding]:
    tree = parse_module(path)
    relpath = _relpath(path, root)
    owner = _enclosing_function_map(tree)

    # every span(...) call sanctioned by position: a `with` item's
    # context expression, or the value of a `return`
    sanctioned_spans = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                sanctioned_spans.add(id(item.context_expr))
        elif isinstance(node, ast.Return) and node.value is not None:
            sanctioned_spans.add(id(node.value))

    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if not chain:
            continue
        tail = chain[-1]
        fn = owner.get(id(node))
        where = f"{relpath}:{fn.name if fn else '<module>'}:{node.lineno}"
        if tail == "span" and len(chain) > 1:
            if id(node) not in sanctioned_spans:
                out.append(Finding(
                    ERROR, "QSM-OBS-SPAN", where,
                    "span opened outside a with-statement (and not "
                    "returned to a caller's with): a raise between "
                    "open and close orphans it — the causal tree "
                    "loses the stage and its duration",
                    "use `with tracer.span(...) as sp:` (exception-"
                    "safe close), or return the span for the caller's "
                    "with to own"))
        if tail in _METRIC_REGISTER and len(chain) > 1 and node.args:
            if _is_dynamic_str(node.args[0]):
                out.append(Finding(
                    ERROR, "QSM-OBS-CARDINALITY", where,
                    f"metric name passed to .{tail}() is synthesized "
                    "from runtime data: every distinct value mints a "
                    "new time series — unbounded registry growth "
                    "(cardinality explosion)",
                    "name metrics from a fixed vocabulary and carry "
                    "variability in BOUNDED labels (wid, flush "
                    "reason, verdict) or in span attrs, never in the "
                    "metric name"))
        if tail in _METRIC_WRITE and len(chain) > 1:
            for kw in node.keywords:
                if kw.arg is not None and _is_dynamic_str(kw.value):
                    out.append(Finding(
                        ERROR, "QSM-OBS-CARDINALITY", where,
                        f"label {kw.arg!r} of .{tail}() is synthesized "
                        "from runtime data: every distinct value mints "
                        "a new time series — unbounded registry growth",
                        "label metrics with bounded values only "
                        "(worker id, flush reason, verdict name); "
                        "per-request identity belongs in span attrs "
                        "on the trace log"))
    return out
