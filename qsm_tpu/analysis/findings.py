"""Structured findings + whitelist for the ``qsmlint`` static analyzer.

Every pass emits :class:`Finding` records — ``{severity, rule_id,
location, message, fix_hint}`` — never prints.  Rendering (text for
humans, JSON for probe_watcher/CI archival) and whitelist filtering live
here so the pass modules stay pure.

Severity policy (docs/ANALYSIS.md):

* ``error``   — would waste a live TPU window or produce a wrong verdict
  (spec parity divergence, state-bound violation, VMEM over-envelope,
  per-call retracing, nondeterminism feeding scheduler decisions).
  Non-whitelisted errors fail ``python -m qsm_tpu lint`` (exit 1) and
  block the probe_watcher seize sequence.
* ``warning`` — suspicious but often legitimate (wall-clock reads in the
  scheduler plane used only for timing stats).  Reported, never fatal.
* ``info``    — notes (passes that ran vacuously, sampled coverage).

Whitelist format (default file: ``<repo>/.qsmlint``): one accepted
finding per line, ``RULE_ID location-prefix`` with ``#`` comments —

    # timing stats, not delivery decisions
    QSM-DET-TIME qsm_tpu/sched/pool.py

A finding is whitelisted when its ``rule_id`` matches exactly and its
``location`` starts with the given prefix (``*`` matches any location).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Tuple

ERROR = "error"
WARNING = "warning"
INFO = "info"
SEVERITIES = (ERROR, WARNING, INFO)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer finding; ``location`` is ``path:line`` for AST passes
    and ``model:<family>`` / ``spec:<name>`` for semantic passes."""

    severity: str
    rule_id: str
    location: str
    message: str
    fix_hint: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Finding":
        return cls(severity=d["severity"], rule_id=d["rule_id"],
                   location=d["location"], message=d["message"],
                   fix_hint=d.get("fix_hint", ""))


class Whitelist:
    """Accepted findings: exact rule_id + location prefix per entry."""

    def __init__(self, entries: Sequence[Tuple[str, str]] = (),
                 path: Optional[str] = None):
        self.entries = list(entries)
        self.path = path

    @classmethod
    def load(cls, path: str) -> "Whitelist":
        entries = []
        with open(path) as f:
            for ln in f:
                ln = ln.split("#", 1)[0].strip()
                if not ln:
                    continue
                parts = ln.split(None, 1)
                rule = parts[0]
                prefix = parts[1].strip() if len(parts) > 1 else "*"
                entries.append((rule, prefix))
        return cls(entries, path=path)

    def allows(self, finding: Finding) -> bool:
        for rule, prefix in self.entries:
            if finding.rule_id != rule:
                continue
            if prefix == "*" or finding.location.startswith(prefix):
                return True
        return False


def split_whitelisted(findings: Sequence[Finding],
                      whitelist: Optional[Whitelist]
                      ) -> Tuple[List[Finding], List[Finding]]:
    """(kept, whitelisted); severity ordering is preserved within each."""
    if whitelist is None:
        return list(findings), []
    kept, allowed = [], []
    for f in findings:
        (allowed if whitelist.allows(f) else kept).append(f)
    return kept, allowed


def sort_findings(findings: Sequence[Finding]) -> List[Finding]:
    rank = {s: i for i, s in enumerate(SEVERITIES)}
    return sorted(findings,
                  key=lambda f: (rank.get(f.severity, len(SEVERITIES)),
                                 f.rule_id, f.location))


def render_text(findings: Sequence[Finding],
                whitelisted: Sequence[Finding] = ()) -> str:
    """Human rendering: one line per finding plus a summary tail."""
    lines = []
    for f in sort_findings(findings):
        lines.append(f"{f.severity.upper():7s} {f.rule_id}  {f.location}")
        lines.append(f"        {f.message}")
        if f.fix_hint:
            lines.append(f"        fix: {f.fix_hint}")
    n_err = sum(1 for f in findings if f.severity == ERROR)
    n_warn = sum(1 for f in findings if f.severity == WARNING)
    lines.append(f"qsmlint: {n_err} error(s), {n_warn} warning(s), "
                 f"{len(findings) - n_err - n_warn} info, "
                 f"{len(whitelisted)} whitelisted")
    return "\n".join(lines)


def render_json(findings: Sequence[Finding],
                whitelisted: Sequence[Finding] = (),
                meta: Optional[Dict] = None) -> str:
    """One JSON document — the ``--json`` / probe_watcher archive form."""
    doc = {
        "tool": "qsmlint",
        "errors": sum(1 for f in findings if f.severity == ERROR),
        "warnings": sum(1 for f in findings if f.severity == WARNING),
        "findings": [f.to_dict() for f in sort_findings(findings)],
        "whitelisted": [f.to_dict() for f in sort_findings(whitelisted)],
    }
    if meta:
        doc.update(meta)
    return json.dumps(doc)


# SARIF 2.1.0 (``--sarif PATH``): the interchange form CI diff viewers
# annotate pull requests from.  Deterministic output — sorted keys, no
# timestamps — so a golden-file test can pin the exact document.

_SARIF_LEVEL = {ERROR: "error", WARNING: "warning", INFO: "note"}
_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                 "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def _sarif_location(location: str) -> dict:
    """``path:Class.fn:line`` / ``path:line`` → uri + startLine;
    semantic locations (``model:cas``) become a bare uri."""
    parts = location.split(":")
    uri, line = parts[0], None
    if len(parts) > 1 and parts[-1].isdigit():
        line = int(parts[-1])
    if "/" not in uri and not uri.endswith(".py"):
        uri, line = location, None  # model:/fixture: pseudo-locations
    phys: Dict = {"artifactLocation": {"uri": uri}}
    if line is not None:
        phys["region"] = {"startLine": line}
    return {"physicalLocation": phys}


def _sarif_result(f: Finding, suppressed: bool) -> dict:
    text = f.message if not f.fix_hint else f"{f.message} (fix: {f.fix_hint})"
    res: Dict = {
        "ruleId": f.rule_id,
        "level": _SARIF_LEVEL.get(f.severity, "none"),
        "message": {"text": text},
        "locations": [_sarif_location(f.location)],
    }
    if suppressed:
        res["suppressions"] = [{
            "kind": "external",
            "justification": "accepted via the reviewed .qsmlint "
                             "whitelist (docs/ANALYSIS.md)"}]
    return res


def render_sarif(findings: Sequence[Finding],
                 whitelisted: Sequence[Finding] = (),
                 meta: Optional[Dict] = None) -> str:
    """One SARIF 2.1.0 document; whitelisted findings ride along as
    suppressed results so the CI viewer shows the reviewed claims too."""
    rules = sorted({f.rule_id
                    for f in list(findings) + list(whitelisted)})
    results = ([_sarif_result(f, False) for f in sort_findings(findings)]
               + [_sarif_result(f, True)
                  for f in sort_findings(whitelisted)])
    # no informationUri: SARIF 2.1.0 requires it to be an ABSOLUTE
    # URI and this repo has no canonical URL; the rule docs pointer
    # rides each rule's helpUri-free id (docs/ANALYSIS.md)
    driver: Dict = {
        "name": "qsmlint",
        "rules": [{"id": r} for r in rules],
    }
    if meta and meta.get("version"):
        driver["version"] = str(meta["version"])
    doc = {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{"tool": {"driver": driver}, "results": results}],
    }
    return json.dumps(doc, indent=2, sort_keys=True)
