"""Device-work-queue passes (pass family *o* of docs/ANALYSIS.md):
window-arbitrage discipline.

The devq plane (qsm_tpu/devq, docs/WINDOWS.md) exists because device
windows are RARE: every plane banks device-worthy work into a
persistent queue, and a seized window drains it.  Two structural
promises make that safe, and both are statically checkable:

* ``QSM-DEVQ-UNBOUNDED`` (error) — the queue is fed by EVERY plane,
  forever, so a class in the devq scan set whose instance-attribute
  container GROWS (``self.X.append/extend/add/insert``, or
  ``heapq.heappush(self.X, …)``) with no cap comparison or eviction
  anywhere in the class is the one-quiet-week-to-OOM pathology the
  monitor plane's family (k) gates, recurring at the fleet's shared
  choke point.  The structural scan IS family (k)'s
  (monitor_passes.py ``_scan_class`` — one definition of "bounded",
  three planes held to it), with family (m)'s ownership refinement:
  growth is only attributed to attributes the class itself owns as
  raw container literals; ``self.log.append(…)`` where ``log`` is a
  ``SegmentedLog()`` is delegation, gated at the delegate.

* ``QSM-DEVQ-DRAIN`` (error) — a window can close at ANY moment (the
  chip is snatched back), so every ``while`` loop inside a function
  whose name contains ``drain`` must consult the window deadline
  INSIDE the loop — a mention of ``deadline`` / ``remaining`` /
  ``window_end`` / ``time_left`` in the loop test or body.  A drain
  loop without one runs until the queue empties, wedging the process
  on a chip it no longer owns — the exact hang the probe layer exists
  to prevent, reintroduced one layer up.  ``for`` loops are exempt:
  iteration over a materialized collection is bounded by
  construction; the hazard is open-ended re-polling.

Scan set: qsm_tpu/devq/ + tools/window_drain.py + tools/bench_devq.py.
(monitor/session.py also has a ``_drain`` — its reorder buffer flush,
a different plane's discipline — and is deliberately NOT in this scan
set.)
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from .astutil import parse_module
from .findings import ERROR, Finding
from .gen_passes import _raw_container_attrs
from .monitor_passes import _scan_class

#: Identifier substrings that count as "the loop consulted the window
#: deadline".  Matched against Name ids and Attribute attrs, so both
#: ``remaining = self._remaining_s()`` and ``now() < self.window_end``
#: satisfy the discipline.
_DEADLINE_HINTS = ("deadline", "remaining", "window_end", "time_left")


def _mentions_deadline(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name is not None:
            low = name.lower()
            if any(h in low for h in _DEADLINE_HINTS):
                return True
    return False


def check_devq_file(path: str, root: Optional[str] = None
                    ) -> List[Finding]:
    tree = parse_module(path)
    relpath = path
    if root:
        try:
            relpath = os.path.relpath(path, root)
        except ValueError:
            pass
    out: List[Finding] = []
    # --- QSM-DEVQ-UNBOUNDED: family (k)'s class scan, family (m)'s
    # ownership refinement, this plane's rule id -----------------------
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        scan = _scan_class(cls)
        owned = _raw_container_attrs(cls)
        for attr, (fn_name, lineno, how) in sorted(scan.grows.items()):
            if attr in scan.disciplined or attr not in owned:
                continue
            out.append(Finding(
                ERROR, "QSM-DEVQ-UNBOUNDED",
                f"{relpath}:{cls.name}.{fn_name}:{lineno}",
                f"device-work accumulator self.{attr} grows ({how}) "
                "with no cap comparison or eviction anywhere in the "
                "class — every plane on every fleet node feeds this "
                "container, so it grows until the node OOMs",
                "compare its size against an explicit bound before "
                "growing or evict past the cap (queue.py "
                "DeviceWorkQueue._evict_over_cap is the model; done "
                "tombstones make eviction safe to re-bank)"))
    # --- QSM-DEVQ-DRAIN: while-loops in drain functions must consult
    # the window deadline ----------------------------------------------
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if "drain" not in fn.name.lower():
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.While):
                continue
            if _mentions_deadline(node):
                continue
            out.append(Finding(
                ERROR, "QSM-DEVQ-DRAIN",
                f"{relpath}:{fn.name}:{node.lineno}",
                f"drain loop in {fn.name}() never consults the window "
                "deadline — a snatched-away chip leaves it running "
                "until the queue empties, wedging the drainer on a "
                "device it no longer owns",
                "consult the remaining window time inside the loop "
                "(drain.py DrainScheduler.drain's `remaining = "
                "self._remaining_s()` break is the model) and degrade "
                "to the host ladder past the deadline"))
    return out
