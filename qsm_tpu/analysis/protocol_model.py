"""Protocol IR extraction — the wire contract as a checked artifact.

The serve protocol is hand-synced across six layers: ops are declared
in ``serve/protocol.py``, dispatched in ``serve/server.py``, forwarded
and re-dispatched in ``fleet/router.py``, called from
``serve/client.py``/``fleet/gossip.py``/``utils/cli.py`` — and the
invariants that keep the fleet honest (one ``_send`` egress stamps
``node``/``term``; every retried op is idempotent; SHED is never a
verdict) lived only in prose.  This module extracts the whole-program
protocol IR statically, on top of the :mod:`callgraph` symbol table:

* every op name, with the contract declared in ``serve/protocol.py``
  (``OPS`` / ``IDEMPOTENT_OPS`` / ``*_ENVELOPE`` — parsed from the
  AST, never imported);
* every SEND SITE: a dict literal carrying a constant ``"op"`` key,
  its request keys (literal keys, resolved ``**`` splats, later
  ``req["k"] = ...`` writes in the same function), and whether the
  doc flows into a RETRYING call path (``CheckClient._round_trip``
  failover, ``NodeLink.request`` fresh-socket retry, router
  re-dispatch loops);
* every HANDLER: classes defining both ``_send`` and ``_handle`` are
  egress classes; ``_handle``'s dispatch chain is walked branch-aware
  (``op == "x"`` / ``op in (...) + self._SESSION_OPS``), helper calls
  that receive the request dict propagate the op set, and every doc
  reaching the class egress (directly or through send-forwarding
  wrappers like ``_respond``) contributes its resolved response keys;
* every CONSUMER READ at an op-knowable site (``doc = client.check(
  ...)``; ``resp = link.request({"op": ...})``) — deliberately
  under-approximated: reads through the generic transport are not
  attributed to an op.

The IR is emitted deterministically (sorted keys, repo-relative
paths, no timestamps) as the committed ``PROTOCOL.json`` plus a
rendered ``docs/PROTOCOL.md`` (``make protocol``); lint family (l)
(:mod:`protocol_passes`) checks conformance and gates drift.

Known IR limits (docs/ANALYSIS.md §l): dynamically computed op names
are invisible; a ``{**req, ...}`` splat without a constant ``"op"``
key is recorded as a request *forward*, not a send site; response
docs built from unresolvable calls mark the op ``dynamic_response``
and the field pass stands down for that direction.  CPU-only, pure
AST — no qsm_tpu imports, no JAX.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .astutil import attr_chain
from .callgraph import (FunctionInfo, Project, _ann_class, _ctor_name,
                        _walk_no_defs)

PROTOCOL_SOURCE = "qsm_tpu/serve/protocol.py"
PROTOCOL_ARTIFACT = "PROTOCOL.json"

# Envelope fallbacks for sub-programs (fixtures) that carry no
# declarations of their own: the envelope is part of the wire grammar,
# not of any one op.
_DEFAULT_REQUEST_ENVELOPE = ("op", "id", "trace", "parent",
                             "deadline_s")
_DEFAULT_RESPONSE_ENVELOPE = ("ok", "id", "error", "node", "term",
                              "trace", "flight", "shed", "reason",
                              "router")

_CONTRACT_NAMES = ("OPS", "IDEMPOTENT_OPS", "REQUEST_ENVELOPE",
                   "RESPONSE_ENVELOPE")

# resolution bounds: alternatives per doc expression before collapse,
# call-resolution depth guard (cycles collapse to dynamic)
_MAX_ALTS = 6


def _const_strings(expr: ast.AST,
                   env: Dict[str, List[str]]) -> Optional[List[str]]:
    """Evaluate a literal string-collection expression: tuples/lists/
    sets of constants, ``Name``/``self.ATTR`` lookups into ``env``,
    ``tuple()/frozenset()/set()/sorted()`` wrapping, ``+`` concat and
    ``-`` difference.  None when not statically evaluable."""
    if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
        out: List[str] = []
        for e in expr.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return out
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [expr.value]
    if isinstance(expr, ast.Name):
        return env.get(expr.id)
    if isinstance(expr, ast.Attribute):        # self._SESSION_OPS
        return env.get(expr.attr)
    if isinstance(expr, ast.Call):
        chain = attr_chain(expr.func)
        if (chain and len(expr.args) == 1
                and chain[-1] in ("tuple", "frozenset", "set",
                                  "sorted", "list")):
            return _const_strings(expr.args[0], env)
        return None
    if isinstance(expr, ast.BinOp):
        left = _const_strings(expr.left, env)
        right = _const_strings(expr.right, env)
        if left is None or right is None:
            return None
        if isinstance(expr.op, ast.Add):
            return left + right
        if isinstance(expr.op, ast.Sub):
            return [x for x in left if x not in right]
        return None
    return None


class Contract:
    """The declared contract: module-level tuples in
    ``serve/protocol.py`` plus class-level ``OPS``/``IDEMPOTENT_OPS``
    declarations (fixture sub-programs declare their vocabulary on the
    stub classes themselves)."""

    def __init__(self) -> None:
        self.ops: Set[str] = set()
        self.idempotent: Set[str] = set()
        self.request_envelope: Set[str] = set(_DEFAULT_REQUEST_ENVELOPE)
        self.response_envelope: Set[str] = set(
            _DEFAULT_RESPONSE_ENVELOPE)
        self.source: Optional[str] = None    # module declaring OPS
        self.declared = False

    @staticmethod
    def _scan_body(body: Sequence[ast.stmt],
                   env: Dict[str, List[str]]) -> Dict[str, List[str]]:
        found: Dict[str, List[str]] = {}
        for stmt in body:
            if not (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                continue
            name = stmt.targets[0].id
            vals = _const_strings(stmt.value, env)
            if vals is not None:
                env[name] = vals
                if name in _CONTRACT_NAMES:
                    found[name] = vals
        return found

    def absorb(self, found: Dict[str, List[str]], rel: str,
               module_level: bool) -> None:
        if "OPS" in found:
            self.ops.update(found["OPS"])
            self.declared = True
            if module_level and self.source is None:
                self.source = rel
        if "IDEMPOTENT_OPS" in found:
            self.idempotent.update(found["IDEMPOTENT_OPS"])
            self.declared = True
        if module_level and "REQUEST_ENVELOPE" in found:
            self.request_envelope.update(found["REQUEST_ENVELOPE"])
        if module_level and "RESPONSE_ENVELOPE" in found:
            self.response_envelope.update(found["RESPONSE_ENVELOPE"])


class SendSite:
    def __init__(self, op: str, qual: str, line: int, path_kind: str):
        self.op = op
        self.qual = qual
        self.line = line
        self.path_kind = path_kind
        self.request_keys: Set[str] = set()
        self.dynamic_request = False
        self.retried = False
        self.retry_via: Set[str] = set()
        self.forwards_request = False   # {**req, ...} re-dispatch


class Handler:
    def __init__(self, cls: str, role: str, path: str):
        self.cls = cls
        self.role = role
        self.path = path
        self.request_keys_read: Set[str] = set()
        self.response_keys_written: Set[str] = set()
        self.response_alts: List[Tuple[Set[str], bool, bool]] = []
        self.dynamic_response = False
        self.ha_gated = False
        self.forwards_request = False


def _path_kind(rel: str) -> str:
    base = os.path.basename(rel)
    if base == "client.py":
        return "client"
    if base == "router.py":
        return "router"
    if base == "cli.py":
        return "cli"
    if base == "fixtures.py":
        return "fixture"
    if base in ("gossip.py", "membership.py"):
        return "fleet"
    return os.path.splitext(base)[0]


class ProtocolModel:
    """The extracted whole-program protocol IR over one file set."""

    def __init__(self, files: Sequence[str],
                 root: Optional[str] = None):
        self.project = Project(
            [f for f in files if f.endswith(".py")], root=root)
        self.contract = Contract()
        self.send_sites: List[SendSite] = []
        # (op, class) -> Handler
        self.handlers: Dict[Tuple[str, str], Handler] = {}
        # op -> [(key, qual, line)]
        self.consumer_reads: Dict[str, List[Tuple[str, str, int]]] = {}
        self.egress: Dict[str, dict] = {}        # class -> egress info
        self.egress_violations: List[Tuple[str, int, str]] = []
        # op -> ops it was co-attributed with (multi-op dispatch
        # branches and shared helper tails): the field pass checks
        # request keys against the GROUP's sender union, since
        # whole-helper aggregation cannot split reads per op
        self.co_dispatched: Dict[str, Set[str]] = {}
        self._module_env: Dict[str, Dict[str, List[str]]] = {}
        self._class_env: Dict[str, Dict[str, List[str]]] = {}
        self._fn_assigns: Dict[str, Dict[str, list]] = {}
        self._fn_subwrites: Dict[str, Dict[str, List[Optional[str]]]] = {}
        self._fn_types: Dict[str, Dict[str, str]] = {}
        self._ret_cache: Dict[str, List[Tuple[Set[str], bool, bool]]] = {}
        # hot-path memos — the forwarding/retrying fixpoints revisit
        # every function per round, so call lists, resent-name sets
        # and call resolutions are computed once per function/node
        self._fn_calls: Dict[str, List[ast.Call]] = {}
        self._fn_resent: Dict[str, Set[str]] = {}
        self._resolve_memo: Dict[int, Optional[str]] = {}
        self._extract()

    # -- contract -----------------------------------------------------
    def _read_contract(self) -> None:
        for rel in sorted(self.project.modules):
            tree = self.project.modules[rel]
            env: Dict[str, List[str]] = {}
            found = Contract._scan_body(tree.body, env)
            self._module_env[rel] = env
            self.contract.absorb(found, rel, module_level=True)
            for stmt in tree.body:
                if not isinstance(stmt, ast.ClassDef):
                    continue
                cls_env = dict(env)
                cls_found = Contract._scan_body(stmt.body, cls_env)
                self._class_env[stmt.name] = cls_env
                self.contract.absorb(cls_found, rel,
                                     module_level=False)

    # -- per-function facts -------------------------------------------
    def _assigns(self, fn: FunctionInfo) -> Dict[str, list]:
        got = self._fn_assigns.get(fn.qual)
        if got is None:
            got = {}
            subs: Dict[str, List[Optional[str]]] = {}
            for n in _walk_no_defs(fn.node):
                if (isinstance(n, ast.Assign) and len(n.targets) == 1):
                    t = n.targets[0]
                    if isinstance(t, ast.Name):
                        got.setdefault(t.id, []).append(n.value)
                    elif (isinstance(t, ast.Subscript)
                          and isinstance(t.value, ast.Name)):
                        key = (t.slice.value
                               if isinstance(t.slice, ast.Constant)
                               and isinstance(t.slice.value, str)
                               else None)
                        subs.setdefault(t.value.id, []).append(key)
            self._fn_assigns[fn.qual] = got
            self._fn_subwrites[fn.qual] = subs
        return got

    def _subwrites(self, fn: FunctionInfo
                   ) -> Dict[str, List[Optional[str]]]:
        self._assigns(fn)
        return self._fn_subwrites[fn.qual]

    def _local_types(self, fn: FunctionInfo) -> Dict[str, str]:
        got = self._fn_types.get(fn.qual)
        if got is not None:
            return got
        types: Dict[str, str] = {}
        classes = set(self.project.classes)
        node = fn.node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            for arg in (list(a.posonlyargs) + list(a.args)
                        + list(a.kwonlyargs)):
                cls = _ann_class(arg.annotation)
                if cls in classes:
                    types[arg.arg] = cls
        for n in _walk_no_defs(fn.node):
            if (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)):
                name = _ctor_name(n.value, classes)
                if name:
                    types[n.targets[0].id] = name
            elif isinstance(n, ast.With):
                for item in n.items:
                    if (item.optional_vars is not None
                            and isinstance(item.optional_vars,
                                           ast.Name)):
                        name = _ctor_name(item.context_expr, classes)
                        if name:
                            types[item.optional_vars.id] = name
        self._fn_types[fn.qual] = types
        return types

    def _resolve(self, call: ast.Call,
                 fn: FunctionInfo) -> Optional[str]:
        """``Project.resolve_call`` plus a method-name fallback for
        chains ``attr_chain`` cannot root (``self.links[nid].request``
        — Subscript bases).  Memoised by call-node identity: the same
        AST nodes are revisited every fixpoint round."""
        key = id(call)
        if key in self._resolve_memo:
            return self._resolve_memo[key]
        got = self.project.resolve_call(call, fn,
                                        self._local_types(fn))
        if got is None and isinstance(call.func, ast.Attribute):
            got = self.project._unique(call.func.attr, fn.path,
                                       methods_only=True)
        self._resolve_memo[key] = got
        return got

    # -- doc resolution -----------------------------------------------
    def _resolve_doc(self, expr: ast.AST, fn: FunctionInfo,
                     visiting: Set[str]
                     ) -> List[Tuple[Set[str], bool, bool]]:
        """Alternatives for a response/request doc expression:
        ``(keys, dynamic, merged)`` triples.  ``dynamic`` = some key
        source could not be resolved; ``merged`` = alternatives were
        collapsed (the SHED pass must not trust the combination)."""
        if isinstance(expr, ast.Dict):
            alts: List[Tuple[Set[str], bool, bool]] = [(set(), False,
                                                        False)]
            for key, value in zip(expr.keys, expr.values):
                if key is None:                       # ** splat
                    subs = self._resolve_doc(value, fn, visiting)
                    alts = self._cross(alts, subs)
                elif (isinstance(key, ast.Constant)
                        and isinstance(key.value, str)):
                    for keys, _dyn, _m in alts:
                        keys.add(key.value)
                else:                                 # computed key
                    alts = [(k, True, m) for k, _d, m in alts]
            return alts
        if isinstance(expr, ast.Name):
            values = self._assigns(fn).get(expr.id)
            if not values:
                return [(set(), True, False)]
            alts = []
            for v in values:
                alts.extend(self._resolve_doc(v, fn, visiting))
            alts = self._cap(alts)
            for key in self._subwrites(fn).get(expr.id, ()):
                if key is None:
                    alts = [(k, True, m) for k, _d, m in alts]
                else:
                    for keys, _d, _m in alts:
                        keys.add(key)
            return alts
        if isinstance(expr, ast.Call):
            callee = self._resolve(expr, fn)
            if callee is None or callee in visiting:
                return [(set(), True, False)]
            return self._returns_of(callee, visiting)
        if isinstance(expr, ast.IfExp):
            return self._cap(
                self._resolve_doc(expr.body, fn, visiting)
                + self._resolve_doc(expr.orelse, fn, visiting))
        return [(set(), True, False)]

    @staticmethod
    def _cap(alts: List[Tuple[Set[str], bool, bool]]
             ) -> List[Tuple[Set[str], bool, bool]]:
        if len(alts) <= _MAX_ALTS:
            return alts
        union: Set[str] = set()
        dyn = False
        for keys, d, _m in alts:
            union |= keys
            dyn = dyn or d
        return [(union, dyn, True)]

    @classmethod
    def _cross(cls, alts, subs):
        out = []
        for keys, dyn, m in alts:
            for skeys, sdyn, sm in subs:
                out.append((set(keys) | skeys, dyn or sdyn, m or sm))
        return cls._cap(out)

    def _returns_of(self, qual: str, visiting: Set[str]
                    ) -> List[Tuple[Set[str], bool, bool]]:
        got = self._ret_cache.get(qual)
        if got is not None:
            return got
        fn = self.project.functions.get(qual)
        if fn is None:
            return [(set(), True, False)]
        visiting = visiting | {qual}
        alts: List[Tuple[Set[str], bool, bool]] = []
        for n in _walk_no_defs(fn.node):
            if isinstance(n, ast.Return) and n.value is not None:
                if (isinstance(n.value, ast.Constant)
                        and n.value.value is None):
                    continue
                alts.extend(self._resolve_doc(n.value, fn, visiting))
        if not alts:
            alts = [(set(), True, False)]
        alts = self._cap(alts)
        self._ret_cache[qual] = alts
        return alts

    # -- egress classes and send-forwarding ---------------------------
    def _find_egress(self) -> None:
        methods = self.project._methods
        for cls, named in methods.items():
            if "_send" not in named or "_handle" not in named:
                continue
            send_fn = self.project.functions[named["_send"]]
            stamps: Set[str] = set()
            for n in _walk_no_defs(send_fn.node):
                if isinstance(n, ast.Dict) and None in n.keys:
                    for key in n.keys:
                        if (isinstance(key, ast.Constant)
                                and isinstance(key.value, str)):
                            stamps.add(key.value)
                elif (isinstance(n, ast.Assign) and len(n.targets) == 1
                        and isinstance(n.targets[0], ast.Subscript)):
                    s = n.targets[0].slice
                    if (isinstance(s, ast.Constant)
                            and isinstance(s.value, str)):
                        stamps.add(s.value)
            info = self.project.classes.get(cls)
            role = ("router" if "_active_now" in named else "node")
            self.egress[cls] = {
                "path": info.path if info else send_fn.path,
                "method": "_send", "role": role,
                "stamps": sorted(stamps),
            }

    def _forwarding_params(self) -> Dict[str, Set[int]]:
        """Fixpoint: qual -> parameter positions (0-based, ``self``
        included) whose value reaches an egress (``_send``/
        ``send_doc``).  Seeds: every egress class ``_send`` doc param;
        ``send_doc``'s own doc param."""
        fwd: Dict[str, Set[int]] = {}
        for cls in self.egress:
            qual = self.project._methods[cls]["_send"]
            fn = self.project.functions[qual]
            n = len(self._param_names(fn))
            fwd[qual] = {i for i in range(2, n)} or {n - 1}
        for qual, fn in self.project.functions.items():
            if fn.name == "send_doc" and fn.cls is None:
                fwd[qual] = {1}
        changed = True
        while changed:
            changed = False
            for qual, fn in self.project.functions.items():
                params = self._param_names(fn)
                if not params:
                    continue
                mine = fwd.setdefault(qual, set())
                for call in self._calls_in(fn):
                    for pos in self._sent_positions(call, fn, fwd):
                        arg = self._arg_at(call, fn, pos)
                        if (isinstance(arg, ast.Name)
                                and arg.id in params):
                            idx = params.index(arg.id)
                            if idx not in mine:
                                mine.add(idx)
                                changed = True
        return fwd

    @staticmethod
    def _param_names(fn: FunctionInfo) -> List[str]:
        node = fn.node
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            return []
        a = node.args
        return [x.arg for x in
                list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]

    def _calls_in(self, fn: FunctionInfo) -> List[ast.Call]:
        got = self._fn_calls.get(fn.qual)
        if got is None:
            got = [n for n in _walk_no_defs(fn.node)
                   if isinstance(n, ast.Call)]
            self._fn_calls[fn.qual] = got
        return got

    def _sent_positions(self, call: ast.Call, fn: FunctionInfo,
                        fwd: Dict[str, Set[int]]) -> Set[int]:
        """Positions (in the CALLEE's parameter list) of this call's
        doc-forwarding parameters."""
        chain = attr_chain(call.func)
        name = chain[-1] if chain else (
            call.func.attr if isinstance(call.func, ast.Attribute)
            else None)
        if name == "send_doc":
            return {1}
        callee = self._resolve(call, fn)
        if callee is None:
            return set()
        return fwd.get(callee, set())

    def _arg_at(self, call: ast.Call, fn: FunctionInfo,
                pos: int) -> Optional[ast.AST]:
        """The argument expression landing in callee parameter
        ``pos``.  Bound-method calls (``self.m(...)``, ``obj.m(...)``)
        shift positional args by one for the bound ``self``; calls to
        plain functions (``send_doc(sock, doc)``) do not."""
        offset = 1 if isinstance(call.func, ast.Attribute) else 0
        idx = pos - offset
        if 0 <= idx < len(call.args):
            return call.args[idx]
        return None

    # -- send sites ---------------------------------------------------
    def _op_dicts(self, fn: FunctionInfo
                  ) -> List[Tuple[str, ast.Dict]]:
        out = []
        for n in _walk_no_defs(fn.node):
            if not isinstance(n, ast.Dict):
                continue
            for key, value in zip(n.keys, n.values):
                if (isinstance(key, ast.Constant) and key.value == "op"
                        and isinstance(value, ast.Constant)
                        and isinstance(value.value, str)):
                    out.append((value.value, n))
                    break
        return out

    def _collect_send_sites(self) -> None:
        for qual in sorted(self.project.functions):
            fn = self.project.functions[qual]
            resent = self._resent_names(fn)
            assigns = self._assigns(fn)
            for op, node in self._op_dicts(fn):
                site = SendSite(op, qual, node.lineno,
                                _path_kind(fn.path))
                for keys, dyn, _m in self._cap(
                        self._resolve_doc(node, fn, set())):
                    site.request_keys |= keys
                    site.dynamic_request |= dyn
                var = None
                for name, values in assigns.items():
                    if node in values:
                        var = name
                        break
                if var is not None:
                    for key in self._subwrites(fn).get(var, ()):
                        if key is None:
                            site.dynamic_request = True
                        else:
                            site.request_keys.add(key)
                self._mark_retries(site, node, var, fn, resent)
                self.send_sites.append(site)

    # -- retry analysis -----------------------------------------------
    def _resent_names(self, fn: FunctionInfo) -> Set[str]:
        """Names re-sent across attempts inside ``fn``: call args in a
        try body whose except continues a surrounding loop, and call
        args of an except-handler call that re-invokes the try body's
        callee (the NodeLink fresh-socket shape)."""
        cached = self._fn_resent.get(fn.qual)
        if cached is not None:
            return cached
        resent: Set[str] = set()
        for loop in _walk_no_defs(fn.node):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for t in ast.walk(loop):
                if not isinstance(t, ast.Try):
                    continue
                retries = any(
                    isinstance(x, ast.Continue)
                    for h in t.handlers for x in ast.walk(h))
                if not retries:
                    continue
                for n in t.body:
                    for c in ast.walk(n):
                        if isinstance(c, ast.Call):
                            resent.update(
                                a.id for a in c.args
                                if isinstance(a, ast.Name))
        for t in _walk_no_defs(fn.node):
            if not isinstance(t, ast.Try):
                continue
            tried = {attr_chain(c.func)[-1]
                     for n in t.body for c in ast.walk(n)
                     if isinstance(c, ast.Call)
                     and attr_chain(c.func)}
            for h in t.handlers:
                for c in ast.walk(h):
                    if (isinstance(c, ast.Call) and attr_chain(c.func)
                            and attr_chain(c.func)[-1] in tried):
                        resent.update(a.id for a in c.args
                                      if isinstance(a, ast.Name))
        self._fn_resent[fn.qual] = resent
        return resent

    def _retrying_params(self) -> Dict[str, Set[str]]:
        """qual -> request-doc parameter names that end up re-sent,
        transitively (``CheckClient.check`` passes ``req`` into
        ``_round_trip`` whose ``req`` retries)."""
        direct: Dict[str, Set[str]] = {}
        for qual, fn in self.project.functions.items():
            params = set(self._param_names(fn))
            hits = self._resent_names(fn) & params
            if hits:
                direct[qual] = hits
        changed = True
        while changed:
            changed = False
            for qual, fn in self.project.functions.items():
                params = self._param_names(fn)
                if not params:
                    continue
                mine = direct.setdefault(qual, set())
                for call in self._calls_in(fn):
                    callee = self._resolve(call, fn)
                    if callee is None or callee not in direct:
                        continue
                    cps = self._param_names(
                        self.project.functions[callee])
                    for pname in direct[callee]:
                        if pname not in cps:
                            continue
                        arg = self._arg_at(call, fn,
                                           cps.index(pname))
                        if (isinstance(arg, ast.Name)
                                and arg.id in params
                                and arg.id not in mine):
                            mine.add(arg.id)
                            changed = True
        return {q: s for q, s in direct.items() if s}

    def _mark_retries(self, site: SendSite, node: ast.Dict,
                      var: Optional[str], fn: FunctionInfo,
                      resent: Set[str]) -> None:
        if var is not None and var in resent:
            site.retried = True
            site.retry_via.add(fn.qual)
        retrying = self._retrying
        for call in self._calls_in(fn):
            carries = (node in call.args
                       or (var is not None
                           and any(isinstance(a, ast.Name)
                                   and a.id == var
                                   for a in call.args)))
            if not carries:
                continue
            callee = self._resolve(call, fn)
            if callee is None or callee not in retrying:
                continue
            cps = self._param_names(self.project.functions[callee])
            for pname in retrying[callee]:
                if pname not in cps:
                    continue
                arg = self._arg_at(call, fn, cps.index(pname))
                hit = (arg is node
                       or (isinstance(arg, ast.Name)
                           and var is not None and arg.id == var))
                if hit:
                    site.retried = True
                    site.retry_via.add(callee)

    # -- handler extraction -------------------------------------------
    def _handler(self, cls: str, op: str) -> Handler:
        h = self.handlers.get((op, cls))
        if h is None:
            info = self.egress[cls]
            h = Handler(cls, info["role"], info["path"])
            self.handlers[(op, cls)] = h
        return h

    def _branch_ops(self, test: ast.AST, op_var: str,
                    env: Dict[str, List[str]]
                    ) -> Tuple[Optional[List[str]], bool]:
        """Op set named by a dispatch test, plus whether the branch is
        the router HA gate (an ``_active_now`` clause rides along)."""
        if isinstance(test, ast.BoolOp) and isinstance(test.op,
                                                      ast.And):
            ops = None
            ha = False
            for clause in test.values:
                got, _ = self._branch_ops(clause, op_var, env)
                if got is not None and ops is None:
                    ops = got
                if any(isinstance(n, ast.Call)
                       and attr_chain(n.func)
                       and attr_chain(n.func)[-1] == "_active_now"
                       for n in ast.walk(clause)):
                    ha = True
            return ops, ha
        if isinstance(test, ast.Compare) and len(test.ops) == 1:
            left, cmp = test.left, test.ops[0]
            if not (isinstance(left, ast.Name) and left.id == op_var):
                return None, False
            target = test.comparators[0]
            if isinstance(cmp, ast.Eq):
                if (isinstance(target, ast.Constant)
                        and isinstance(target.value, str)):
                    return [target.value], False
            elif isinstance(cmp, ast.In):
                vals = _const_strings(target, env)
                if vals is not None:
                    return vals, False
        return None, False

    def _extract_handlers(self) -> None:
        for cls in sorted(self.egress):
            named = self.project._methods[cls]
            handle_fn = self.project.functions[named["_handle"]]
            env = self._class_env.get(cls, {})
            op_var, req_var = self._dispatch_vars(handle_fn)
            if req_var is None:
                continue
            self._pending: List[Tuple[str, List[str], str]] = []
            served: Set[str] = set()
            self._walk_dispatch(handle_fn, handle_fn.node.body,
                                op_var, req_var, env, cls, served)
            # root-level request reads (before/outside the chain)
            # apply to every op this class serves
            if served:
                root_reads = self._request_reads(
                    handle_fn, req_var, outside_dispatch=True,
                    op_var=op_var, env=env)
                for op in served:
                    self._handler(cls, op).request_keys_read |= \
                        root_reads
            # propagate into helpers that received the request dict
            seen: Dict[str, Set[str]] = {}
            while self._pending:
                qual, ops, param = self._pending.pop()
                done = seen.setdefault(f"{qual}:{param}", set())
                new = [o for o in ops if o not in done]
                if not new:
                    continue
                done.update(new)
                self._absorb_helper(cls, qual, new, param)

    def _dispatch_vars(self, fn: FunctionInfo
                       ) -> Tuple[Optional[str], Optional[str]]:
        """(op variable, request variable) of a ``_handle``: the
        ``op = req.get("op", ...)`` assignment, or an ``op``/``req``
        parameter pair."""
        params = self._param_names(fn)
        op_var = "op" if "op" in params else None
        req_var = None
        for cand in ("req", "request", "doc", "msg"):
            if cand in params:
                req_var = cand
                break
        for n in _walk_no_defs(fn.node):
            if (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and isinstance(n.value, ast.Call)
                    and isinstance(n.value.func, ast.Attribute)
                    and n.value.func.attr == "get"
                    and isinstance(n.value.func.value, ast.Name)
                    and n.value.args
                    and isinstance(n.value.args[0], ast.Constant)
                    and n.value.args[0].value == "op"):
                op_var = n.targets[0].id
                req_var = n.value.func.value.id
        return op_var, req_var

    def _walk_dispatch(self, fn: FunctionInfo, stmts, op_var, req_var,
                       env, cls: str, served: Set[str],
                       ops_ctx: Optional[List[str]] = None,
                       restrict: Optional[Set[str]] = None) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.If) and op_var is not None:
                ops, ha = self._branch_ops(stmt.test, op_var, env)
                if ops is not None and restrict is not None:
                    ops = [o for o in ops if o in restrict]
                if ops is not None:
                    served.update(ops)
                    for op in ops:
                        h = self._handler(cls, op)
                        if ha:
                            h.ha_gated = True
                    self._walk_dispatch(fn, stmt.body, op_var,
                                        req_var, env, cls, served,
                                        ops, restrict)
                else:
                    if ops_ctx:
                        self._absorb_stmt(fn, stmt.test, ops_ctx,
                                          req_var, cls)
                    self._walk_dispatch(fn, stmt.body, op_var,
                                        req_var, env, cls, served,
                                        ops_ctx, restrict)
                self._walk_dispatch(fn, stmt.orelse, op_var, req_var,
                                    env, cls, served, ops_ctx,
                                    restrict)
                continue
            if isinstance(stmt, (ast.Try,)):
                for blk in (stmt.body, stmt.orelse, stmt.finalbody):
                    self._walk_dispatch(fn, blk, op_var, req_var, env,
                                        cls, served, ops_ctx, restrict)
                for h in stmt.handlers:
                    self._walk_dispatch(fn, h.body, op_var, req_var,
                                        env, cls, served, ops_ctx,
                                        restrict)
                continue
            if isinstance(stmt, (ast.With, ast.For, ast.While)):
                if ops_ctx:
                    for sub in (getattr(stmt, "test", None),
                                getattr(stmt, "iter", None)):
                        if sub is not None:
                            self._absorb_stmt(fn, sub, ops_ctx,
                                              req_var, cls)
                self._walk_dispatch(fn, stmt.body, op_var, req_var,
                                    env, cls, served, ops_ctx,
                                    restrict)
                if hasattr(stmt, "orelse"):
                    self._walk_dispatch(fn, stmt.orelse, op_var,
                                        req_var, env, cls, served,
                                        ops_ctx, restrict)
                continue
            if ops_ctx:
                self._absorb_stmt(fn, stmt, ops_ctx, req_var, cls)

    def _absorb_stmt(self, fn: FunctionInfo, stmt: ast.AST,
                     ops: List[str], req_var: str, cls: str) -> None:
        """One statement inside an op-attributed dispatch branch:
        request reads, egress docs, helper propagation."""
        if len(ops) > 1:
            for op in ops:
                self.co_dispatched.setdefault(op, set()).update(ops)
        reads, forwards = self._reads_in(stmt, req_var)
        for op in ops:
            h = self._handler(cls, op)
            h.request_keys_read |= reads
            h.forwards_request |= forwards
        for call in (n for n in ast.walk(stmt)
                     if isinstance(n, ast.Call)):
            if any(isinstance(a, ast.Name) and a.id == req_var
                   for a in call.args):
                callee = self._resolve(call, fn)
                if callee is not None:
                    cps = self._param_names(
                        self.project.functions[callee])
                    for i, a in enumerate(call.args):
                        if (isinstance(a, ast.Name)
                                and a.id == req_var):
                            offset = (1 if isinstance(call.func,
                                                      ast.Attribute)
                                      else 0)
                            if i + offset < len(cps):
                                self._pending.append(
                                    (callee, list(ops),
                                     cps[i + offset]))
        self._absorb_egress(fn, stmt, ops, cls)

    def _absorb_egress(self, fn: FunctionInfo, stmt: ast.AST,
                       ops: List[str], cls: str) -> None:
        fwd = self._fwd
        for call in (n for n in ast.walk(stmt)
                     if isinstance(n, ast.Call)):
            for pos in self._sent_positions(call, fn, fwd):
                arg = self._arg_at(call, fn, pos)
                if arg is None:
                    continue
                alts = self._cap(self._resolve_doc(arg, fn, set()))
                for op in ops:
                    h = self._handler(cls, op)
                    h.response_alts.extend(alts)
                    for keys, dyn, _m in alts:
                        h.response_keys_written |= keys
                        h.dynamic_response |= dyn

    def _absorb_helper(self, cls: str, qual: str, ops: List[str],
                       param: str) -> None:
        """Attribution for a helper that received the request dict
        (``_handle_check(conn, req)``, ``_handle_obs(conn, op, req)``
        …).  A helper that also receives the ``op`` variable gets the
        same branch-aware walk as the dispatch root — its internal
        ``if op == ...`` chain refines which of ``ops`` each read and
        response belongs to; statements outside any op test (shared
        preambles and tails) attribute to the whole passed set.
        Helpers without an ``op`` parameter aggregate whole-function.

        A helper may also RETURN the response doc to a caller that
        sends it; that flow is covered by the caller's own egress-site
        resolution (``doc = self._route_session(...)`` then
        ``self._respond(conn, doc)``), not here."""
        fn = self.project.functions.get(qual)
        if fn is None:
            return
        env = self._class_env.get(fn.cls or "",
                                  self._class_env.get(cls, {}))
        op_var = "op" if "op" in self._param_names(fn) else None
        self._walk_dispatch(fn, fn.node.body, op_var, param, env,
                            cls, set(), ops_ctx=list(ops),
                            restrict=set(ops))

    def _reads_in(self, stmt: ast.AST,
                  req_var: str) -> Tuple[Set[str], bool]:
        reads: Set[str] = set()
        forwards = False
        for n in ast.walk(stmt):
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "get"
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == req_var
                    and n.args
                    and isinstance(n.args[0], ast.Constant)
                    and isinstance(n.args[0].value, str)):
                reads.add(n.args[0].value)
            elif (isinstance(n, ast.Subscript)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == req_var
                    and isinstance(n.slice, ast.Constant)
                    and isinstance(n.slice.value, str)
                    and isinstance(getattr(n, "ctx", None), ast.Load)):
                reads.add(n.slice.value)
            elif (isinstance(n, ast.Compare) and len(n.ops) == 1
                    and isinstance(n.ops[0], (ast.In, ast.NotIn))
                    and isinstance(n.left, ast.Constant)
                    and isinstance(n.left.value, str)
                    and isinstance(n.comparators[0], ast.Name)
                    and n.comparators[0].id == req_var):
                reads.add(n.left.value)
            elif isinstance(n, ast.Dict):
                for key, value in zip(n.keys, n.values):
                    if (key is None and isinstance(value, ast.Name)
                            and value.id == req_var):
                        forwards = True
        return reads, forwards

    def _request_reads(self, fn: FunctionInfo, req_var: str, *,
                       outside_dispatch: bool, op_var: Optional[str],
                       env: Dict[str, List[str]]) -> Set[str]:
        """Request reads at the dispatch root outside any op branch
        (the ``op = req.get("op")`` preamble)."""
        reads: Set[str] = set()
        for stmt in fn.node.body:
            if isinstance(stmt, ast.If) and op_var is not None:
                ops, _ = self._branch_ops(stmt.test, op_var, env)
                if ops is not None:
                    continue
            got, _ = self._reads_in(stmt, req_var)
            reads |= got
        return reads

    # -- egress discipline --------------------------------------------
    def _collect_egress_violations(self) -> None:
        """Raw ``send_doc``/``sendall`` calls inside an egress class
        but outside its ``_send`` — responses that would skip the
        node/term stamping."""
        for qual in sorted(self.project.functions):
            fn = self.project.functions[qual]
            if fn.cls not in self.egress or fn.name == "_send":
                continue
            for call in self._calls_in(fn):
                chain = attr_chain(call.func)
                name = chain[-1] if chain else (
                    call.func.attr
                    if isinstance(call.func, ast.Attribute) else None)
                if name in ("send_doc", "sendall"):
                    self.egress_violations.append(
                        (qual, call.lineno, name))

    # -- consumer reads -----------------------------------------------
    def _op_of_function(self) -> Dict[str, Set[str]]:
        # fn qual -> ops whose *transport response* the function hands
        # back.  Qualifies only when some ``return`` value is a call
        # carrying the op doc (inline dict, or a local bound to one):
        # ``return self._round_trip(req)`` counts; a helper that sends
        # an op but returns a value derived *from* the response (e.g.
        # one element of ``resp["covers"]``) does not.
        ops: Dict[str, Set[str]] = {}
        by_qual: Dict[str, Set[str]] = {}
        for site in self.send_sites:
            by_qual.setdefault(site.qual, set()).add(site.op)
        for qual, sent in by_qual.items():
            fn = self.project.functions.get(qual)
            if fn is None:
                continue
            assigns = self._assigns(fn)
            op_dict_ids = {id(node)
                           for _op, node in self._op_dicts(fn)}

            def _carries(call: ast.Call) -> bool:
                vals = list(call.args)
                vals += [kw.value for kw in call.keywords
                         if kw.arg is not None]
                for a in vals:
                    if id(a) in op_dict_ids:
                        return True
                    if (isinstance(a, ast.Name) and a.id in assigns
                            and any(id(x) in op_dict_ids
                                    for x in assigns[a.id])):
                        return True
                return False

            for n in _walk_no_defs(fn.node):
                if (isinstance(n, ast.Return)
                        and isinstance(n.value, ast.Call)
                        and _carries(n.value)):
                    ops[qual] = set(sent)
                    break
        return ops

    def _collect_consumer_reads(self) -> None:
        op_of = self._op_of_function()
        for qual in sorted(self.project.functions):
            fn = self.project.functions[qual]
            assigns = self._assigns(fn)
            op_dicts = {id(node): op
                        for op, node in self._op_dicts(fn)}
            # var -> ops it answers for
            resp_vars: Dict[str, Set[str]] = {}
            for name, values in assigns.items():
                for v in values:
                    if not isinstance(v, ast.Call):
                        continue
                    callee = self._resolve(v, fn)
                    if callee in op_of and len(op_of[callee]) == 1:
                        resp_vars.setdefault(name, set()).update(
                            op_of[callee])
                        continue
                    for a in v.args:
                        if id(a) in op_dicts:
                            resp_vars.setdefault(name, set()).add(
                                op_dicts[id(a)])
                        elif (isinstance(a, ast.Name)
                                and a.id in assigns
                                and any(id(x) in op_dicts
                                        for x in assigns[a.id])):
                            for x in assigns[a.id]:
                                if id(x) in op_dicts:
                                    resp_vars.setdefault(
                                        name, set()).add(
                                        op_dicts[id(x)])
            if not resp_vars:
                continue
            for var, ops in resp_vars.items():
                for n in _walk_no_defs(fn.node):
                    key = None
                    line = getattr(n, "lineno", 0)
                    if (isinstance(n, ast.Call)
                            and isinstance(n.func, ast.Attribute)
                            and n.func.attr == "get"
                            and isinstance(n.func.value, ast.Name)
                            and n.func.value.id == var
                            and n.args
                            and isinstance(n.args[0], ast.Constant)
                            and isinstance(n.args[0].value, str)):
                        key = n.args[0].value
                    elif (isinstance(n, ast.Subscript)
                            and isinstance(n.value, ast.Name)
                            and n.value.id == var
                            and isinstance(n.slice, ast.Constant)
                            and isinstance(n.slice.value, str)):
                        key = n.slice.value
                    elif (isinstance(n, ast.Compare)
                            and len(n.ops) == 1
                            and isinstance(n.ops[0],
                                           (ast.In, ast.NotIn))
                            and isinstance(n.left, ast.Constant)
                            and isinstance(n.left.value, str)
                            and isinstance(n.comparators[0], ast.Name)
                            and n.comparators[0].id == var):
                        key = n.left.value
                    if key is None:
                        continue
                    for op in ops:
                        self.consumer_reads.setdefault(op, []).append(
                            (key, qual, line))

    # -- driver -------------------------------------------------------
    def _extract(self) -> None:
        self._read_contract()
        self._find_egress()
        self._fwd = self._forwarding_params()
        self._retrying = self._retrying_params()
        self._collect_send_sites()
        self._extract_handlers()
        self._collect_egress_violations()
        self._collect_consumer_reads()

    # -- rendering ----------------------------------------------------
    def ops_seen(self) -> List[str]:
        seen = set(self.contract.ops)
        seen.update(s.op for s in self.send_sites)
        seen.update(op for op, _cls in self.handlers)
        return sorted(seen)

    def to_doc(self) -> dict:
        ops_doc: Dict[str, dict] = {}
        for op in self.ops_seen():
            handlers = []
            for (hop, cls), h in sorted(self.handlers.items()):
                if hop != op:
                    continue
                handlers.append({
                    "class": cls, "role": h.role, "path": h.path,
                    "ha_gated": h.ha_gated,
                    "forwards_request": h.forwards_request,
                    "dynamic_response": h.dynamic_response,
                    "request_keys_read": sorted(h.request_keys_read),
                    "response_keys_written": sorted(
                        h.response_keys_written),
                })
            callers = []
            for s in sorted((s for s in self.send_sites
                             if s.op == op),
                            key=lambda s: (s.qual, s.line)):
                callers.append({
                    "qual": s.qual, "line": s.line,
                    "path_kind": s.path_kind,
                    "request_keys": sorted(s.request_keys),
                    "dynamic_request": s.dynamic_request,
                    "retried": s.retried,
                    "retry_via": sorted(s.retry_via),
                })
            reads = sorted(set(self.consumer_reads.get(op, ())))
            ops_doc[op] = {
                "declared": op in self.contract.ops,
                "idempotent": op in self.contract.idempotent,
                "handlers": handlers,
                "callers": callers,
                "consumer_reads": [
                    {"key": k, "qual": q, "line": ln}
                    for k, q, ln in reads],
            }
        return {
            "artifact": "PROTOCOL",
            "version": 1,
            "contract": {
                "source": self.contract.source,
                "ops": sorted(self.contract.ops),
                "idempotent_ops": sorted(self.contract.idempotent),
                "request_envelope": sorted(
                    self.contract.request_envelope),
                "response_envelope": sorted(
                    self.contract.response_envelope),
            },
            "egress": {cls: dict(info, stamps=sorted(info["stamps"]))
                       for cls, info in sorted(self.egress.items())},
            "ops": ops_doc,
            "summary": self.summary(),
        }

    def summary(self) -> dict:
        ops = self.ops_seen()
        handled = sorted({op for op, _cls in self.handlers})
        called = sorted({s.op for s in self.send_sites})
        retried = sorted({s.op for s in self.send_sites if s.retried})
        dynamic = sorted({op for (op, _c), h in self.handlers.items()
                          if h.dynamic_response})
        return {
            "ops": len(ops),
            "handled_ops": len([o for o in ops if o in handled]),
            "called_ops": len([o for o in ops if o in called]),
            "idempotent_ops": len(self.contract.idempotent),
            "retried_ops": retried,
            "dynamic_response_ops": dynamic,
            "handlers": len(self.handlers),
            "send_sites": len(self.send_sites),
        }


def render_protocol_json(model: ProtocolModel) -> str:
    return json.dumps(model.to_doc(), indent=2, sort_keys=True) + "\n"


def render_protocol_md(model: ProtocolModel) -> str:
    doc = model.to_doc()
    lines = [
        "# The wire contract",
        "",
        "Generated by static extraction "
        "(`python -m qsm_tpu.analysis.protocol_model`, "
        "`make protocol`) — do not edit by hand; lint family (l) "
        "fails the gate when this drifts from the tree "
        "(QSM-PROTO-DRIFT).  Source of truth for the op vocabulary: "
        f"`{doc['contract']['source']}`.",
        "",
        "| Op | Idempotent | Handlers | Callers | Retried via |",
        "|---|---|---|---|---|",
    ]
    for op, entry in sorted(doc["ops"].items()):
        handlers = ", ".join(
            "{}[{}{}]".format(h["class"], h["role"],
                              ", ha-gated" if h["ha_gated"] else "")
            for h in entry["handlers"]) or "—"
        kinds: Dict[str, int] = {}
        vias: Set[str] = set()
        for c in entry["callers"]:
            kinds[c["path_kind"]] = kinds.get(c["path_kind"], 0) + 1
            if c["retried"]:
                vias.update(v.split(":")[-1] for v in c["retry_via"])
        callers = ", ".join(f"{k}×{n}" if n > 1 else k
                            for k, n in sorted(kinds.items())) or "—"
        idem = "yes" if entry["idempotent"] else "**no**"
        lines.append("| `{}` | {} | {} | {} | {} |".format(
            op, idem, handlers, callers,
            ", ".join(f"`{v}`" for v in sorted(vias)) or "—"))
    lines += [
        "",
        "## Invariants the lint family (l) enforces",
        "",
        "* **One egress** — every handler response leaves through its "
        "class's single `_send`, which stamps "
        + "; ".join(
            "`{}` → {}".format(
                cls, ", ".join(f"`{s}`" for s in info["stamps"]))
            for cls, info in sorted(doc["egress"].items()))
        + " (QSM-PROTO-EGRESS).",
        "* **Retry ⇒ idempotent** — every op reachable from a "
        "retrying call path (`CheckClient._round_trip` failover, "
        "`NodeLink.request` fresh-socket retry, router re-dispatch) "
        "must be in `IDEMPOTENT_OPS` in `serve/protocol.py`; "
        "`shutdown` is deliberately absent and rides a single "
        "non-retrying attempt (QSM-PROTO-RETRY-IDEMPOTENT).",
        "* **SHED is never a verdict** — an admission/HA shed doc "
        "(`shed: true`) never carries verdict/witness keys "
        "(QSM-PROTO-SHED).",
        "* **Fields are load-bearing** — a response key some consumer "
        "reads must be written by a handler of that op; a request key "
        "a handler reads must be set by some sender "
        "(QSM-PROTO-FIELDS).",
        "",
    ]
    return "\n".join(lines)


def default_files(root: str) -> List[str]:
    from .engine import DEFAULT_PROTOCOL_FILES
    return [os.path.join(root, rel) for rel in DEFAULT_PROTOCOL_FILES]


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    ap = argparse.ArgumentParser(
        description="extract the wire-contract IR -> PROTOCOL.json "
                    "+ docs/PROTOCOL.md")
    ap.add_argument("--root", default=repo)
    ap.add_argument("--json-out", default=None,
                    help="default: <root>/PROTOCOL.json")
    ap.add_argument("--md-out", default=None,
                    help="default: <root>/docs/PROTOCOL.md")
    args = ap.parse_args(argv)
    model = ProtocolModel(default_files(args.root), root=args.root)
    json_out = args.json_out or os.path.join(args.root,
                                             PROTOCOL_ARTIFACT)
    md_out = args.md_out or os.path.join(args.root, "docs",
                                         "PROTOCOL.md")
    with open(json_out, "w") as f:
        f.write(render_protocol_json(model))
    with open(md_out, "w") as f:
        f.write(render_protocol_md(model))
    s = model.summary()
    print(f"{s['ops']} op(s), {s['handlers']} handler(s), "
          f"{s['send_sites']} send site(s) -> {json_out} + {md_out}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
