"""``CppOracle`` — the native host checker (LineariseBackend).

Routing per history:

* scalar-state specs with a declared state bound → the C++ DFS driven by
  the dense domain step table (wg.cpp kind 0);
* vector-state specs that declare a built-in C++ kernel
  (``Spec.native_kernel``: queue, kv, stack) → the same DFS with the
  native step function (kinds 1-3), total in the response like
  ``step_py``;
* everything else — unknown specs, out-of-domain args, histories over
  128 ops (the encoder's largest bucket; the C++ search picks a 64- or
  128-bit taken mask per history), missing toolchain — falls back to the
  Python oracle, so verdicts are always available and always exact.

For the TABLE path, out-of-domain RESPONSES also route to the fallback:
the table only covers declared domains, and staying exact for arbitrary
future specs beats assuming every out-of-domain response fails.  Native
vector kernels evaluate the response directly, so they take any response
value, exactly as the Python oracle does.
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Sequence

import numpy as np

from ..core.history import History
from ..core.spec import Spec, compile_step_table
from ..ops.backend import Verdict
from ..ops.wing_gong_cpu import WingGongCPU

# public: the native checker's coverage cap (an unsigned __int128 taken
# mask — the encoder's largest long-context bucket) — consumers
# (bench.py's sweep caps) must derive from this, not hardcode
NATIVE_MAX_OPS = 128
_MAX_OPS = NATIVE_MAX_OPS
_MAX_STATE = 64  # wg.cpp MAX_STATE


def _blockers2(prec: np.ndarray) -> np.ndarray:
    """Per-op precedence blockers as [n][2] little-endian uint64 words
    (the wg.cpp mask wire format): word w of blockers2[j] has bit b set
    iff op ``64*w + b`` strictly precedes op j.  Fully vectorized — the
    per-edge Python loop was 80% of the whole native check wall-clock."""
    n = prec.shape[0]
    # 2 words cover exactly NATIVE_MAX_OPS predecessors; a cap/routing
    # drift past that would silently drop precedence bits (admitting
    # illegal linearizations) — fail loudly instead.
    assert n <= _MAX_OPS, f"_blockers2: n={n} exceeds {_MAX_OPS}-op mask"
    out = np.zeros((n, 2), np.uint64)
    idx = np.arange(n)
    word = idx >> 6                              # word of predecessor i
    bit = np.uint64(1) << np.uint64(idx & 63)
    for w in range(2):
        rows = word == w
        if rows.any():
            contrib = np.where(prec[rows], bit[rows, None], np.uint64(0))
            out[:, w] = np.bitwise_or.reduce(contrib, axis=0)
    return out


class CppOracle:
    """Batched native Wing–Gong checker."""

    name = "cpp_oracle"

    def __init__(self, spec: Spec, node_budget: int = 50_000_000,
                 memo: bool = True,
                 fallback: Optional[WingGongCPU] = None):
        from . import get_lib

        self.spec = spec
        self.node_budget = node_budget
        self.memo = memo
        self.fallback = fallback or WingGongCPU(node_budget=node_budget,
                                                memo=memo)
        self._lib = get_lib()
        self._tables = {}  # state bound -> (trans, ok)
        self._vector_kernel = spec.native_kernel()
        if (self._vector_kernel is not None
                and spec.STATE_DIM > _MAX_STATE):
            self._vector_kernel = None  # larger than the C++ state cap
        self.nodes_explored = 0
        self.native_histories = 0
        self.fallback_histories = 0

    # ------------------------------------------------------------------
    def _uses_table(self) -> bool:
        return (self.spec.STATE_DIM == 1
                and self.spec.scalar_state_bound(1) is not None)

    def _ops_in_domain(self, ops) -> bool:
        """ONE copy of the native-routing domain rules (used by history
        routing and by end_states): args always in the declared domains;
        responses too on the table path (module docstring)."""
        table = self._uses_table()
        for o in ops:
            if not (0 <= o.cmd < self.spec.n_cmds
                    and 0 <= o.arg < self.spec.CMDS[o.cmd].n_args):
                return False  # out-of-domain arg: step contract undefined
            if table and not o.is_pending and not (
                    0 <= o.resp < self.spec.CMDS[o.cmd].n_resps):
                return False  # table path: stay exact
        return True

    def _dispatch(self, max_len: int, max_start: Optional[int] = None):
        """ONE copy of the table-vs-kernel selection: (kind, p0, p1,
        elem_bits, trans, ok, S, C, A, R) for the C++ calls, or None when
        this spec has no native route.  ``max_start`` sizes the scalar
        table past the from-initial bound when searches begin at frontier
        states (growth heuristic: +1 per step covers the counter/ticket
        family; anything that still escapes hits the in-kernel OOB guard
        and defers honestly — never a misread)."""
        if self._uses_table():
            bound = self.spec.scalar_state_bound(max(max_len, 1))
            if max_start is not None:
                bound = max(bound, max_start + 1 + max_len)
            trans, ok = self._table(bound)
            S, C, A, R = trans.shape
            return (0, 0, 0, 0, trans, ok, S, C, A, R)
        if self._vector_kernel is not None:
            kind, p0, p1 = self._vector_kernel
            return (kind, p0, p1, self._elem_bits(kind, p0, p1),
                    None, None, 0, 0, 0, 0)
        return None

    def can_enumerate(self) -> bool:
        """True when this spec has a native route at all (step table or
        vector kernel, library loaded) — i.e., :meth:`end_states` can
        ever answer non-None.  Callers choosing a middle-segment
        enumerator (ops/segdc.py::default_middle_oracle) must probe this:
        a CppOracle that always falls back is strictly worse than the
        memoised Python oracle it would displace."""
        return self._lib is not None and self._dispatch(1) is not None

    def _native_ok(self, h: History) -> bool:
        if self._lib is None or len(h) > _MAX_OPS:
            return False
        if not self._uses_table() and self._vector_kernel is None:
            return False
        return self._ops_in_domain(h.ops)

    def _table(self, bound: int):
        # any cached table with rows >= bound serves (the kernel reads S
        # from the table itself); compile rounded up so a frontier that
        # grows by one per segment doesn't recompile per segment
        usable = [b for b in self._tables if b >= bound]
        if usable:
            return self._tables[min(usable)]
        bound = -(-bound // 32) * 32
        trans, ok = compile_step_table(self.spec, bound)
        # NOT clipped: a successor beyond the table is caught by the
        # in-kernel state_oob guard and deferred honestly — clipping
        # would silently misread a wrong row instead
        trans = np.ascontiguousarray(trans, np.int32)
        ok = np.ascontiguousarray(ok, np.uint8)
        self._tables[bound] = (trans, ok)
        return self._tables[bound]

    # ------------------------------------------------------------------
    def check_histories(self, spec: Spec, histories: Sequence[History],
                        init_states: Optional[Sequence] = None
                        ) -> np.ndarray:
        assert spec is self.spec, "CppOracle is bound to one spec"
        out = np.empty(len(histories), np.int8)
        native_idx: List[int] = []
        fb_idx: List[int] = []
        for i, h in enumerate(histories):
            (native_idx if self._native_ok(h) else fb_idx).append(i)

        if native_idx:
            self._run_native(histories, native_idx, init_states, out)
            self.native_histories += len(native_idx)
        for i in fb_idx:
            init = None if init_states is None else init_states[i]
            if init is None:
                out[i] = self.fallback.check_histories(
                    spec, [histories[i]])[0]
            else:
                out[i] = int(self.fallback.check_from(
                    spec, histories[i], np.asarray(init)))
            self.fallback_histories += 1
        return out

    def check_from(self, spec: Spec, history: History, init_state) -> Verdict:
        v = self.check_histories(spec, [history], init_states=[init_state])
        return Verdict(int(v[0]))

    def search_stats(self):
        """Host-search cost record (qsm_tpu/search/stats.py): native C++
        nodes plus whatever the Python fallback spent on out-of-domain
        histories, one honest sum — the fastest host denominator the
        device's iters-per-history is judged against."""
        from ..search.stats import SearchStats, collect_search_stats

        st = SearchStats(
            engine=self.name,
            histories=self.native_histories + self.fallback_histories,
            nodes_explored=self.nodes_explored,
        )
        return st.absorb(collect_search_stats(self.fallback))

    def check_witness(self, spec: Spec, history: History):
        """(verdict, witness) — delegated to the Python oracle: witness
        extraction is a debugging/audit path, and the fallback shares
        this backend's candidate order and budget config, so the verdict
        agrees with :meth:`check_histories` wherever both decide."""
        return self.fallback.check_witness(spec, history)

    # ------------------------------------------------------------------
    def end_states(self, spec: Spec, ops, starts, budget=None,
                   node_budget: Optional[int] = None,
                   max_out: int = 4096):
        """Distinct model states reachable by SOME complete linearization
        of ``ops`` (a pending-free segment) from any state in ``starts`` —
        the native counterpart of ops/segdc.py::_end_states for its
        middle-segment frontier threading.  Returns a set of int tuples,
        or None when this spec/segment can't run natively or the budget /
        output cap is hit (the caller then uses the Python path).

        ``budget``: a shared object with a ``left`` counter (SegDC's
        per-history node budget); nodes consumed natively are charged
        against it — including on failure, so a Python fallback resumes
        with the true remainder instead of double-spending."""
        assert spec is self.spec
        n = len(ops)
        if (self._lib is None or n == 0 or n > _MAX_OPS
                or any(o.is_pending for o in ops)
                or not self._ops_in_domain(ops)):
            return None
        starts = sorted({tuple(int(v) for v in s) for s in starts})
        disp = self._dispatch(n, max_start=(max(s[0] for s in starts)
                                            if self._uses_table() else None))
        if disp is None:
            return None
        kind, p0, p1, elem_bits, trans, ok, S, C, A, R = disp

        dim = spec.STATE_DIM
        # one construction site for precedence: the same History-based
        # vectorized path _run_native uses
        seg = History(sorted(ops, key=lambda o: o.invoke_time))
        cmd = np.asarray([o.cmd for o in seg.ops], np.int32)
        arg = np.asarray([o.arg for o in seg.ops], np.int32)
        resp = np.asarray([o.resp for o in seg.ops], np.int32)
        blockers = _blockers2(seg.precedes_matrix().astype(bool))
        inits = np.asarray(starts, np.int32).reshape(len(starts), dim)
        out = np.empty((max_out, dim), np.int32)
        if node_budget is None:
            node_budget = (budget.left if budget is not None
                           else self.node_budget)
        if node_budget <= 0:
            return None

        def p(a, ty):
            return (None if a is None
                    else a.ctypes.data_as(ctypes.POINTER(ty)))

        nodes_used = ctypes.c_longlong(0)
        got = self._lib.wg_end_states(
            n, p(cmd, ctypes.c_int32), p(arg, ctypes.c_int32),
            p(resp, ctypes.c_int32), p(blockers, ctypes.c_uint64),
            kind, dim, p0, p1, elem_bits,
            p(trans, ctypes.c_int32), p(ok, ctypes.c_uint8),
            S, C, A, R,
            p(inits, ctypes.c_int32), len(starts),
            node_budget, p(out, ctypes.c_int32), max_out,
            ctypes.byref(nodes_used))
        self.nodes_explored += int(nodes_used.value)
        if budget is not None:
            budget.left -= int(nodes_used.value)
        if got < 0:
            return None  # -1 budget, -2 output cap, -3 table escape
        return {tuple(int(v) for v in out[i]) for i in range(int(got))}

    # ------------------------------------------------------------------
    def _elem_bits(self, kind: int, p0: int, p1: int) -> int:
        """Bit width bounding any state element of a native vector kernel
        (lets the C++ memo pack the state into one 64-bit word instead of
        allocating a string key per DFS node).  0 = unknown, use strings."""
        if kind in (1, 3):  # queue/stack: [length <= cap, slots < n_values]
            return max(p0, p1 - 1).bit_length() or 1
        if kind == 2:    # kv: values < n_values
            return max(1, (p1 - 1).bit_length())
        return 0

    def _run_native(self, histories, idx, init_states, out) -> None:
        spec = self.spec
        dim = spec.STATE_DIM
        max_len = max(len(histories[i]) for i in idx)
        max_start = None
        if self._uses_table() and init_states is not None:
            # searches may start at frontier states past the from-initial
            # bound (SegDC's route) — size the table to cover them; any
            # still-escaping state hits the in-kernel OOB guard
            max_start = max(
                (int(np.asarray(init_states[i])[0])
                 for i in idx if init_states[i] is not None),
                default=0)
        kind, p0, p1, elem_bits, trans, ok, S, C, A, R = self._dispatch(
            max_len, max_start=max_start)

        total = sum(len(histories[i]) for i in idx)
        offsets = np.zeros(len(idx) + 1, np.int64)
        cmd = np.empty(total, np.int32)
        arg = np.empty(total, np.int32)
        resp = np.empty(total, np.int32)
        pending = np.empty(total, np.uint8)
        blockers = np.empty((total, 2), np.uint64)
        inits = np.empty((len(idx), dim), np.int32)
        default_init = np.asarray(spec.initial_state(), np.int32)
        pos = 0
        for k, i in enumerate(idx):
            h = histories[i]
            n = len(h)
            offsets[k + 1] = pos + n
            blockers[pos:pos + n] = _blockers2(
                h.precedes_matrix().astype(bool))
            for j, o in enumerate(h.ops):
                cmd[pos + j] = o.cmd
                arg[pos + j] = o.arg
                resp[pos + j] = 0 if o.is_pending else o.resp
                pending[pos + j] = 1 if o.is_pending else 0
            inits[k] = (default_init if init_states is None
                        or init_states[i] is None
                        else np.asarray(init_states[i], np.int32))
            pos += n

        n_resps = np.asarray([c.n_resps for c in spec.CMDS], np.int32)
        verdicts = np.empty(len(idx), np.int32)

        def p(a, ty):
            return (None if a is None
                    else a.ctypes.data_as(ctypes.POINTER(ty)))

        nodes = self._lib.wg_check_batch(
            len(idx), p(offsets, ctypes.c_int64),
            p(cmd, ctypes.c_int32), p(arg, ctypes.c_int32),
            p(resp, ctypes.c_int32), p(pending, ctypes.c_uint8),
            p(blockers, ctypes.c_uint64),
            kind, dim, p0, p1, elem_bits,
            p(trans, ctypes.c_int32), p(ok, ctypes.c_uint8),
            S, C, A, R, p(n_resps, ctypes.c_int32),
            p(inits, ctypes.c_int32),
            self.node_budget, 1 if self.memo else 0,
            p(verdicts, ctypes.c_int32))
        self.nodes_explored += int(nodes)
        for k, i in enumerate(idx):
            out[i] = verdicts[k]
