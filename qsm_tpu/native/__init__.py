"""Native (C++) host lineariser — build, bind, route.

The reference is pure Haskell with no native components (SURVEY.md §2a);
this module is OUR framework's native runtime piece, the designated C++
fast path for the host-side hot loop the survey anticipated.  The TPU
kernel (ops/jax_kernel.py) remains the accelerator path; this is the host
checker plane: a drop-in ``LineariseBackend`` that decides scalar-state
specs 1-2 orders of magnitude faster than the pure-Python oracle, used
anywhere the oracle is hot (BUDGET_EXCEEDED resolution, SegDC final
segments on hosts without a chip, parity sweeps).

Build story: ``g++ -O2 -shared -fPIC`` at first use, cached next to the
source keyed by a source hash; ctypes bindings (pybind11 is not in the
image — the build instructions name ctypes as the binding path).  When no
toolchain is available the router reports unavailability and callers fall
back to the Python oracle — never a hard failure.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
import tempfile
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "wg.cpp")

_lib = None
_lib_error: Optional[str] = None


def _build_lib_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(tempfile.gettempdir(),
                        f"qsm_wg_{digest}_{sys.version_info[0]}.so")


def native_available() -> bool:
    return get_lib() is not None


def native_status() -> str:
    """Availability WITHOUT triggering the (up to 2-minute) first-use
    compile: "loaded", "built" (cached .so present, not yet dlopened),
    "failed: ..." or "unbuilt".  Metadata commands (the ``list`` CLI)
    use this; checkers that actually need the library call
    :func:`native_available`/:func:`get_lib`, which do compile."""
    if _lib is not None:
        return "loaded"
    if _lib_error is not None:
        return f"failed: {_lib_error}"
    if os.path.exists(_build_lib_path()):
        return "built"
    return "unbuilt"


def native_error() -> Optional[str]:
    get_lib()
    return _lib_error


def get_lib():
    """Compile (once) and load the native lineariser; None if impossible."""
    global _lib, _lib_error
    if _lib is not None or _lib_error is not None:
        return _lib
    so_path = _build_lib_path()
    if not os.path.exists(so_path):
        # process-unique temp output: concurrent first-use compiles (e.g.
        # the watcher's window bench racing an operator CLI run) must not
        # interleave writes into one .tmp and install a corrupt library
        tmp = f"{so_path}.{os.getpid()}.tmp"
        try:
            r = subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                 "-o", tmp, _SRC],
                capture_output=True, text=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired) as e:
            _lib_error = f"g++ unavailable: {e!r}"
            return None
        if r.returncode != 0:
            _lib_error = f"compile failed: {r.stderr[-400:]}"
            return None
        os.replace(tmp, so_path)
    try:
        lib = ctypes.CDLL(so_path)
    except OSError as e:
        _lib_error = f"dlopen failed: {e!r}"
        return None
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.wg_check_batch.restype = ctypes.c_longlong
    lib.wg_check_batch.argtypes = [
        ctypes.c_int, i64p,                 # n_hist, offsets
        i32p, i32p, i32p, u8p, u64p,        # cmd, arg, resp, pending, blockers
        ctypes.c_int, ctypes.c_int,         # kind, state_dim
        ctypes.c_int32, ctypes.c_int32,     # p0, p1
        ctypes.c_int,                       # elem_bits (0 = string keys)
        i32p, u8p,                          # trans, ok (kind 0; else None)
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,  # S C A R
        i32p,                               # n_resps
        i32p, ctypes.c_longlong, ctypes.c_int,  # init_states, budget, memo
        i32p,                               # out_verdicts
    ]
    lib.wg_end_states.restype = ctypes.c_longlong
    lib.wg_end_states.argtypes = [
        ctypes.c_int,                       # n (segment ops)
        i32p, i32p, i32p, u64p,             # cmd, arg, resp, blockers
        ctypes.c_int, ctypes.c_int,         # kind, state_dim
        ctypes.c_int32, ctypes.c_int32,     # p0, p1
        ctypes.c_int,                       # elem_bits
        i32p, u8p,                          # trans, ok
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,  # S C A R
        i32p, ctypes.c_int,                 # init_states, n_inits
        ctypes.c_longlong,                  # node budget
        i32p, ctypes.c_int,                 # out_states, max_out
        ctypes.POINTER(ctypes.c_longlong),  # nodes_used (out)
    ]
    _lib = lib
    return _lib


from .oracle import NATIVE_MAX_OPS, CppOracle  # noqa: E402  (needs get_lib)

__all__ = ["CppOracle", "NATIVE_MAX_OPS", "get_lib", "native_available",
           "native_status",
           "native_error"]
