// Native Wing–Gong lineariser — the C++ fast path of the host checker
// plane (SURVEY.md §2a names a C++ extension as the designated fallback for
// host-side hot loops; the reference itself is pure Haskell with no native
// code, so this is OUR runtime component, not a port).
//
// Faithful to qsm_tpu/ops/wing_gong_cpu.py::WingGongCPU._check: identical
// candidate order (ops ascending, responses ascending), identical node
// budget accounting (one unit per step evaluation), identical memo
// semantics (configurations (taken-set, state) proven non-linearizable-
// from), identical pending-op treatment (a pending op may linearise with
// ANY response in its command's domain, or never).  Verdict codes match
// ops/backend.py::Verdict: 0 VIOLATION, 1 LINEARIZABLE, 2 BUDGET_EXCEEDED.
//
// Spec dispatch (SpecDesc.kind):
//   0 — scalar-state spec via the dense [S][C][A][R] domain table compiled
//       by core/spec.py::compile_step_table;
//   1 — bounded FIFO queue (models/queue.py semantics reimplemented:
//       state = [length, slot0..slotC-1], params = capacity, n_values);
//   2 — multi-key KV map (models/kv.py: state = value per key,
//       params = n_keys, n_values);
//   3 — bounded LIFO stack (models/stack.py: state = [length, slots...],
//       params = capacity, n_values; top is slots[length-1]).
// Vector kinds evaluate the step directly (total in the response, exactly
// like step_py), so only ARG domains need host-side routing; parity with
// the Python oracle is pinned by tests/test_native.py.
//
// Histories are capped at 128 ops (the encoder's largest long-context
// bucket): the taken set is an unsigned __int128 (GCC builtin; this
// library is built with g++ per native/__init__.py) and precedence is a
// per-op blocker bitmask of the same width, passed from Python as
// little-endian (lo, hi) uint64 pairs.

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_set>
#include <utility>
#include <vector>

namespace {

constexpr int MAX_STATE = 64;  // state vector length cap (router enforces)

using Mask = unsigned __int128;  // widest taken/blocker bitmask, ops <= 128

struct SpecDesc {
    int kind;        // 0 table, 1 queue, 2 kv, 3 stack
    int state_dim;
    int32_t p0, p1;  // queue: capacity, n_values; kv: n_keys, n_values
    const int32_t* trans;  // kind 0 only: [S][C][A][R]
    const uint8_t* ok;
    int S, C, A, R;
    // bit width of any state element (caller-provided domain bound).  When
    // state_dim * elem_bits <= 64 the whole vector packs into the fast
    // pair key — no per-node heap allocation on the memo path.
    int elem_bits;
};

// A kind-0 state outside the table: the caller must defer (never index).
static inline bool state_oob(const SpecDesc& sp, const int32_t* s) {
    return sp.kind == 0 && (s[0] < 0 || s[0] >= sp.S);
}

// Caller-supplied START states can be arbitrary (check_from / frontier
// threading); the step kernels preserve validity, so one root check per
// search restores the "never a misread" contract for vector kinds too:
// a queue length outside [0, cap] would index past the stack buffer, and
// any element past elem_bits would alias packed memo keys (an aliased
// "proven failed" entry is a WRONG verdict, not just a lost prune).
static inline bool start_state_invalid(const SpecDesc& sp,
                                       const int32_t* s) {
    switch (sp.kind) {
        case 0:
            return s[0] < 0 || s[0] >= sp.S;
        case 1:
        case 3: {  // queue/stack share the [length, slots...] layout
            if (s[0] < 0 || s[0] > sp.p0) return true;       // length
            for (int i = 1; i <= sp.p0; ++i)                 // slots
                if (s[i] < 0 || s[i] >= sp.p1) return true;
            return false;
        }
        case 2: {
            for (int i = 0; i < sp.state_dim; ++i)           // values
                if (s[i] < 0 || s[i] >= sp.p1) return true;
            return false;
        }
    }
    return true;
}

// step: writes the successor state into out[], returns the postcondition.
// Kind-0 callers must have checked state_oob() first.
static inline bool do_step(const SpecDesc& sp, const int32_t* s,
                           int32_t* out, int cmd, int arg, int resp) {
    switch (sp.kind) {
        case 0: {
            const int idx = ((s[0] * sp.C + cmd) * sp.A + arg) * sp.R + resp;
            out[0] = sp.trans[idx];
            return sp.ok[idx] != 0;
        }
        case 1: {  // bounded FIFO queue: s = [length, slots...]
            const int cap = sp.p0, n_values = sp.p1;
            const int length = s[0];
            std::memcpy(out, s, sizeof(int32_t) * (1 + cap));
            if (cmd == 0) {                       // ENQ(arg)
                if (length == cap) return resp == 1;   // FULL
                out[1 + length] = arg;
                out[0] = length + 1;
                return resp == 0;                      // OK
            }
            if (length == 0) return resp == n_values;  // DEQ on empty
            const int head = s[1];
            for (int i = 0; i < cap - 1; ++i) out[1 + i] = s[2 + i];
            out[cap] = 0;  // canonical form: vacated tail slot zeroed
            out[0] = length - 1;
            return resp == head;
        }
        case 2: {  // kv map: s = value per key
            const int n_values = sp.p1;
            std::memcpy(out, s, sizeof(int32_t) * sp.state_dim);
            if (cmd == 0) return resp == s[arg];       // GET(key)
            out[arg / n_values] = arg % n_values;      // PUT packs k*V+v
            return resp == 0;
        }
        case 3: {  // bounded LIFO stack: s = [length, slots...]
            const int cap = sp.p0, n_values = sp.p1;
            const int length = s[0];
            std::memcpy(out, s, sizeof(int32_t) * (1 + cap));
            if (cmd == 0) {                       // PUSH(arg)
                if (length == cap) return resp == 1;   // FULL
                out[1 + length] = arg;
                out[0] = length + 1;
                return resp == 0;                      // OK
            }
            if (length == 0) return resp == n_values;  // POP on empty
            out[length] = 0;  // canonical form: vacated top zeroed
            out[0] = length - 1;
            return resp == s[length];                  // top = slots[len-1]
        }
    }
    return false;
}

// Exact memo keys.  Scalar states use a (taken, state) pair set — the hot
// path; vector states serialize taken + the raw state bytes into a string
// set.  Both are exact (full-key storage), collisions impossible.
//
// The search is TEMPLATED on the mask type M: uint64_t for histories of
// <= 64 ops (the common case — one-word bit ops, 16-byte keys), Mask
// (unsigned __int128) for the 96/128-op long-context buckets.  Measured:
// running everything at 128 bits costs ~2x on the <= 64-op bench corpus
// (bigger hash keys dominate), so the width is chosen per history.

static inline uint64_t mix64(uint64_t x) {
    x += 0x9E3779B97F4A7C15ull;
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
    x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
    return x ^ (x >> 31);
}

template <typename M>
struct KeyHashT {
    size_t operator()(const std::pair<M, uint64_t>& k) const {
        uint64_t h = mix64(static_cast<uint64_t>(k.first));
        if (sizeof(M) > 8)
            h ^= mix64(static_cast<uint64_t>(
                     static_cast<Mask>(k.first) >> 64))
                 * 0xC2B2AE3D27D4EB4Full;
        return h ^ (mix64(k.second) * 0x9E3779B9ull);
    }
};

template <typename M>
struct CtxT {
    using Key = std::pair<M, uint64_t>;
    int n;
    const int32_t* cmd;
    const int32_t* arg;
    const int32_t* resp;
    const uint8_t* pending;
    const M* blockers;
    SpecDesc sp;
    const int32_t* n_resps;  // per command
    int n_required;
    long long budget;
    long long nodes;
    bool use_memo;
    std::unordered_set<Key, KeyHashT<M>>* seen;      // state_dim == 1
    std::unordered_set<std::string>* seen_vec;       // state_dim  > 1
};

template <typename M>
static inline std::pair<M, uint64_t> key_of(M taken, int state) {
    return {taken, static_cast<uint64_t>(static_cast<uint32_t>(state))};
}

// Packs a small-domain state vector into the pair key's second word; the
// caller guarantees every element fits elem_bits (spec domain bound).
template <typename M>
static inline std::pair<M, uint64_t> key_packed(
    M taken, const int32_t* state, int dim, int elem_bits) {
    uint64_t packed = 0;
    for (int i = 0; i < dim; ++i)
        packed |= static_cast<uint64_t>(static_cast<uint32_t>(state[i]))
                  << (i * elem_bits);
    return {taken, packed};
}

template <typename M>
static std::string vec_key(M taken, const int32_t* state, int dim) {
    std::string k(sizeof(taken) + sizeof(int32_t) * dim, '\0');
    std::memcpy(&k[0], &taken, sizeof(taken));
    std::memcpy(&k[sizeof(taken)], state, sizeof(int32_t) * dim);
    return k;
}

// returns Verdict {0, 1, 2}
template <typename M>
static int dfs(CtxT<M>& c, M taken, const int32_t* state,
               int got_required) {
    if (got_required == c.n_required) return 1;
    if (c.budget <= 0) return 2;
    // a state beyond the domain table (non-initial start past the bound,
    // or a broken bound contract): defer honestly instead of misreading
    if (state_oob(c.sp, state)) return 2;
    const bool scalar = c.sp.state_dim == 1;
    const bool packed = !scalar
        && c.sp.elem_bits > 0
        && c.sp.state_dim * c.sp.elem_bits <= 64;
    std::pair<M, uint64_t> key{};
    std::string vkey;
    if (c.use_memo) {
        if (scalar) {
            key = key_of(taken, state[0]);
            if (c.seen->count(key)) return 0;
        } else if (packed) {
            key = key_packed(taken, state, c.sp.state_dim, c.sp.elem_bits);
            if (c.seen->count(key)) return 0;
        } else {
            vkey = vec_key(taken, state, c.sp.state_dim);
            if (c.seen_vec->count(vkey)) return 0;
        }
    }
    bool saw_budget = false;
    int32_t child[MAX_STATE];
    for (int j = 0; j < c.n; ++j) {
        if (taken >> j & 1) continue;
        if (c.blockers[j] & ~taken) continue;  // an untaken op precedes j
        const int cm = c.cmd[j], a = c.arg[j];
        const bool pend = c.pending[j];
        const int r_lo = pend ? 0 : c.resp[j];
        const int r_hi = pend ? c.n_resps[cm] : c.resp[j] + 1;
        for (int r = r_lo; r < r_hi; ++r) {
            --c.budget;
            ++c.nodes;
            if (c.budget <= 0) return 2;
            if (!do_step(c.sp, state, child, cm, a, r)) continue;
            const int sub = dfs(c, static_cast<M>(taken | (static_cast<M>(1) << j)), child,
                                got_required + (pend ? 0 : 1));
            if (sub == 1) return 1;
            if (sub == 2) saw_budget = true;
        }
    }
    if (saw_budget) return 2;
    if (c.use_memo) {
        if (scalar || packed) c.seen->insert(key);
        else c.seen_vec->insert(std::move(vkey));
    }
    return 0;
}

// blockers2 (lo, hi) uint64 pairs -> per-op masks at width M: the ONE
// wire-format decode, shared by both entry paths.
template <typename M>
static std::vector<M> widen_blockers(int n, const uint64_t* blockers2) {
    std::vector<M> out(n);
    for (int j = 0; j < n; ++j) {
        M b = static_cast<M>(blockers2[2 * j]);
        if (sizeof(M) > 8)
            b |= static_cast<M>(
                static_cast<Mask>(blockers2[2 * j + 1]) << 64);
        out[j] = b;
    }
    return out;
}

// one complete history decided at mask width M
template <typename M>
static int check_one(int n, const int32_t* cmd, const int32_t* arg,
                     const int32_t* resp, const uint8_t* pending,
                     const uint64_t* blockers2, const SpecDesc& sp,
                     const int32_t* n_resps, const int32_t* init,
                     long long node_budget, bool use_memo,
                     long long* nodes_out) {
    int n_required = 0;
    for (int j = 0; j < n; ++j)
        if (!pending[j]) ++n_required;
    const std::vector<M> blockers = widen_blockers<M>(n, blockers2);
    std::unordered_set<std::pair<M, uint64_t>, KeyHashT<M>> seen;
    std::unordered_set<std::string> seen_vec;
    CtxT<M> c{n, cmd, arg, resp, pending, blockers.data(), sp, n_resps,
              n_required, node_budget, 0, use_memo, &seen, &seen_vec};
    const int v = dfs(c, static_cast<M>(0), init, 0);
    *nodes_out = c.nodes;
    return v;
}

// --- end-state enumeration (decrease-and-conquer middle segments) -------
// Explores EVERY valid complete linearization of a (pending-free) segment
// from one start state, collecting the set of distinct reachable end
// states — the native counterpart of ops/segdc.py::_end_states, same
// budget accounting (one unit per step evaluation) and same visited-set
// pruning semantics.

template <typename M>
struct EndCtxT {
    using Key = std::pair<M, uint64_t>;
    int n;
    const int32_t* cmd;
    const int32_t* arg;
    const int32_t* resp;
    const M* blockers;
    SpecDesc sp;
    long long budget;
    long long nodes;
    bool overflow;   // hit max_out (distinct from budget exhaustion)
    bool oob;        // kind-0 state escaped the table (caller must defer)
    std::unordered_set<Key, KeyHashT<M>>* visited;  // packed/scalar states
    std::unordered_set<std::string>* visited_vec;   // string-key states
    std::unordered_set<std::string>* ends;          // distinct end states
    int32_t* out;       // [max_out][state_dim]
    int max_out;
};

template <typename M>
static bool end_dfs(EndCtxT<M>& c, M taken, const int32_t* state) {
    const int dim = c.sp.state_dim;
    const M full = (c.n == static_cast<int>(sizeof(M) * 8))
                       ? ~static_cast<M>(0)
                       : ((static_cast<M>(1) << c.n) - 1);
    if (taken == full) {
        std::string k(reinterpret_cast<const char*>(state),
                      sizeof(int32_t) * dim);
        if (c.ends->count(k)) return true;
        if (static_cast<int>(c.ends->size()) >= c.max_out) {
            c.overflow = true;
            return false;
        }
        std::memcpy(c.out + c.ends->size() * dim, state,
                    sizeof(int32_t) * dim);
        c.ends->insert(std::move(k));
        return true;
    }
    if (state_oob(c.sp, state)) {
        c.oob = true;
        return false;
    }
    const bool scalar = dim == 1;
    const bool packed = !scalar && c.sp.elem_bits > 0
                        && dim * c.sp.elem_bits <= 64;
    if (scalar || packed) {
        auto key = scalar ? key_of(taken, state[0])
                          : key_packed(taken, state, dim, c.sp.elem_bits);
        if (!c.visited->insert(key).second) return true;
    } else {
        if (!c.visited_vec->insert(vec_key(taken, state, dim)).second)
            return true;
    }
    int32_t child[MAX_STATE];
    for (int j = 0; j < c.n; ++j) {
        if (taken >> j & 1) continue;
        if (c.blockers[j] & ~taken) continue;
        --c.budget;
        ++c.nodes;
        if (c.budget <= 0) return false;
        if (!do_step(c.sp, state, child, c.cmd[j], c.arg[j], c.resp[j]))
            continue;
        if (!end_dfs(c, static_cast<M>(taken | (static_cast<M>(1) << j)),
                     child))
            return false;
    }
    return true;
}

// all starts of one segment enumerated at mask width M; returns rc
// (0 ok, -1 budget, -2 overflow, -3 oob/invalid-start) and charges
// *nodes_used
template <typename M>
static long long end_states_run(
    int n, const int32_t* cmd, const int32_t* arg, const int32_t* resp,
    const uint64_t* blockers2, const SpecDesc& sp,
    const int32_t* init_states, int n_inits, long long node_budget,
    int32_t* out_states, int max_out,
    std::unordered_set<std::string>* ends, long long* nodes_used) {
    const std::vector<M> blockers = widen_blockers<M>(n, blockers2);
    EndCtxT<M> c{n, cmd, arg, resp, blockers.data(), sp, node_budget, 0,
                 false, false, nullptr, nullptr, ends, out_states, max_out};
    long long rc = 0;
    for (int i = 0; i < n_inits && rc == 0; ++i) {
        // fresh visited set per start, exactly like the Python version
        std::unordered_set<std::pair<M, uint64_t>, KeyHashT<M>> visited;
        std::unordered_set<std::string> visited_vec;
        c.visited = &visited;
        c.visited_vec = &visited_vec;
        const int32_t* init = init_states + i * sp.state_dim;
        if (start_state_invalid(sp, init)) {
            rc = -3;  // caller falls back to the exact Python walk
        } else if (!end_dfs(c, static_cast<M>(0), init)) {
            rc = c.oob ? -3 : (c.overflow ? -2 : -1);
        }
    }
    *nodes_used = c.nodes;
    return rc;
}

}  // namespace

extern "C" {

// Enumerate reachable end states of a complete segment from n_inits start
// states.  Returns the count written to out_states; on failure returns
// -1 (budget exhausted), -2 (more than max_out distinct end states), or
// -3 (a scalar state escaped the domain table — caller must defer).
// *nodes_used always reports step evaluations consumed, so the caller can
// charge its shared budget before falling back to the exact Python walk.
// ``blockers2`` is [n][2] uint64 little-endian (lo, hi) words per op.
long long wg_end_states(
    int n, const int32_t* cmd, const int32_t* arg, const int32_t* resp,
    const uint64_t* blockers2,
    int kind, int state_dim, int32_t p0, int32_t p1, int elem_bits,
    const int32_t* trans, const uint8_t* ok,
    int S, int C, int A, int R,
    const int32_t* init_states, int n_inits,
    long long node_budget, int32_t* out_states, int max_out,
    long long* nodes_used) {
    SpecDesc sp{kind, state_dim, p0, p1, trans, ok, S, C, A, R, elem_bits};
    std::unordered_set<std::string> ends;
    const long long rc =
        (n <= 64)
            ? end_states_run<uint64_t>(n, cmd, arg, resp, blockers2, sp,
                                       init_states, n_inits, node_budget,
                                       out_states, max_out, &ends,
                                       nodes_used)
            : end_states_run<Mask>(n, cmd, arg, resp, blockers2, sp,
                                   init_states, n_inits, node_budget,
                                   out_states, max_out, &ends, nodes_used);
    return rc == 0 ? static_cast<long long>(ends.size()) : rc;
}

// Decide a batch: per-history op arrays are concatenated, offsets[i] is
// the start of history i's ops, offsets[n_hist] the total.  init_states
// is [n_hist][state_dim] (per-lane start states — the segmentation
// combinator's route).  kind/p0/p1 select the spec semantics; trans/ok
// carry the scalar domain table for kind 0 (pass null otherwise).
// ``blockers2`` is [total][2] uint64 little-endian (lo, hi) words per op.
// Returns total nodes explored; verdicts land in out_verdicts.
long long wg_check_batch(
    int n_hist, const int64_t* offsets,
    const int32_t* cmd, const int32_t* arg, const int32_t* resp,
    const uint8_t* pending, const uint64_t* blockers2,
    int kind, int state_dim, int32_t p0, int32_t p1, int elem_bits,
    const int32_t* trans, const uint8_t* ok,
    int S, int C, int A, int R, const int32_t* n_resps,
    const int32_t* init_states, long long node_budget, int use_memo,
    int32_t* out_verdicts) {
    SpecDesc sp{kind, state_dim, p0, p1, trans, ok, S, C, A, R, elem_bits};
    long long total = 0;
    for (int i = 0; i < n_hist; ++i) {
        const int64_t lo = offsets[i];
        const int n = static_cast<int>(offsets[i + 1] - lo);
        const int32_t* init = init_states + i * state_dim;
        long long nodes = 0;
        if (n == 0) {
            out_verdicts[i] = 1;
        } else if (start_state_invalid(sp, init)) {
            out_verdicts[i] = 2;  // defer: the Python oracle is exact here
        } else if (n <= 64) {     // per-history mask width (see above)
            out_verdicts[i] = check_one<uint64_t>(
                n, cmd + lo, arg + lo, resp + lo, pending + lo,
                blockers2 + 2 * lo, sp, n_resps, init, node_budget,
                use_memo != 0, &nodes);
        } else {
            out_verdicts[i] = check_one<Mask>(
                n, cmd + lo, arg + lo, resp + lo, pending + lo,
                blockers2 + 2 * lo, sp, n_resps, init, node_budget,
                use_memo != 0, &nodes);
        }
        total += nodes;
    }
    return total;
}

}  // extern "C"
