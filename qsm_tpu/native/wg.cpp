// Native Wing–Gong lineariser — the C++ fast path of the host checker
// plane (SURVEY.md §2a names a C++ extension as the designated fallback for
// host-side hot loops; the reference itself is pure Haskell with no native
// code, so this is OUR runtime component, not a port).
//
// Faithful to qsm_tpu/ops/wing_gong_cpu.py::WingGongCPU._check: identical
// candidate order (ops ascending, responses ascending), identical node
// budget accounting (one unit per step evaluation), identical memo
// semantics (configurations (taken-set, state) proven non-linearizable-
// from), identical pending-op treatment (a pending op may linearise with
// ANY response in its command's domain, or never).  Verdict codes match
// ops/backend.py::Verdict: 0 VIOLATION, 1 LINEARIZABLE, 2 BUDGET_EXCEEDED.
//
// Spec dispatch (SpecDesc.kind):
//   0 — scalar-state spec via the dense [S][C][A][R] domain table compiled
//       by core/spec.py::compile_step_table;
//   1 — bounded FIFO queue (models/queue.py semantics reimplemented:
//       state = [length, slot0..slotC-1], params = capacity, n_values);
//   2 — multi-key KV map (models/kv.py: state = value per key,
//       params = n_keys, n_values).
// Vector kinds evaluate the step directly (total in the response, exactly
// like step_py), so only ARG domains need host-side routing; parity with
// the Python oracle is pinned by tests/test_native.py.
//
// Histories are capped at 64 ops (the encoder's bucket cap), so the taken
// set is one uint64 and precedence is a per-op blocker bitmask.

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_set>
#include <utility>

namespace {

constexpr int MAX_STATE = 64;  // state vector length cap (router enforces)

struct SpecDesc {
    int kind;        // 0 table, 1 queue, 2 kv
    int state_dim;
    int32_t p0, p1;  // queue: capacity, n_values; kv: n_keys, n_values
    const int32_t* trans;  // kind 0 only: [S][C][A][R]
    const uint8_t* ok;
    int S, C, A, R;
    // bit width of any state element (caller-provided domain bound).  When
    // state_dim * elem_bits <= 64 the whole vector packs into the fast
    // pair key — no per-node heap allocation on the memo path.
    int elem_bits;
};

// step: writes the successor state into out[], returns the postcondition.
static inline bool do_step(const SpecDesc& sp, const int32_t* s,
                           int32_t* out, int cmd, int arg, int resp) {
    switch (sp.kind) {
        case 0: {
            const int idx = ((s[0] * sp.C + cmd) * sp.A + arg) * sp.R + resp;
            out[0] = sp.trans[idx];
            return sp.ok[idx] != 0;
        }
        case 1: {  // bounded FIFO queue: s = [length, slots...]
            const int cap = sp.p0, n_values = sp.p1;
            const int length = s[0];
            std::memcpy(out, s, sizeof(int32_t) * (1 + cap));
            if (cmd == 0) {                       // ENQ(arg)
                if (length == cap) return resp == 1;   // FULL
                out[1 + length] = arg;
                out[0] = length + 1;
                return resp == 0;                      // OK
            }
            if (length == 0) return resp == n_values;  // DEQ on empty
            const int head = s[1];
            for (int i = 0; i < cap - 1; ++i) out[1 + i] = s[2 + i];
            out[cap] = 0;  // canonical form: vacated tail slot zeroed
            out[0] = length - 1;
            return resp == head;
        }
        case 2: {  // kv map: s = value per key
            const int n_values = sp.p1;
            std::memcpy(out, s, sizeof(int32_t) * sp.state_dim);
            if (cmd == 0) return resp == s[arg];       // GET(key)
            out[arg / n_values] = arg % n_values;      // PUT packs k*V+v
            return resp == 0;
        }
    }
    return false;
}

// Exact memo keys.  Scalar states use a (taken, state) pair set — the hot
// path; vector states serialize taken + the raw state bytes into a string
// set.  Both are exact (full-key storage), collisions impossible.
using Key = std::pair<uint64_t, uint64_t>;

struct KeyHash {
    size_t operator()(const Key& k) const {
        auto mix = [](uint64_t x) {
            x += 0x9E3779B97F4A7C15ull;
            x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
            x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
            return x ^ (x >> 31);
        };
        return mix(k.first) ^ (mix(k.second) * 0x9E3779B9ull);
    }
};

struct Ctx {
    int n;
    const int32_t* cmd;
    const int32_t* arg;
    const int32_t* resp;
    const uint8_t* pending;
    const uint64_t* blockers;
    SpecDesc sp;
    const int32_t* n_resps;  // per command
    int n_required;
    long long budget;
    long long nodes;
    bool use_memo;
    std::unordered_set<Key, KeyHash>* seen;          // state_dim == 1
    std::unordered_set<std::string>* seen_vec;       // state_dim  > 1
};

static inline Key key_of(uint64_t taken, int state) {
    return {taken, static_cast<uint64_t>(static_cast<uint32_t>(state))};
}

// Packs a small-domain state vector into the pair key's second word; the
// caller guarantees every element fits elem_bits (spec domain bound).
static inline Key key_packed(uint64_t taken, const int32_t* state, int dim,
                             int elem_bits) {
    uint64_t packed = 0;
    for (int i = 0; i < dim; ++i)
        packed |= static_cast<uint64_t>(static_cast<uint32_t>(state[i]))
                  << (i * elem_bits);
    return {taken, packed};
}

static std::string vec_key(uint64_t taken, const int32_t* state, int dim) {
    std::string k(sizeof(taken) + sizeof(int32_t) * dim, '\0');
    std::memcpy(&k[0], &taken, sizeof(taken));
    std::memcpy(&k[sizeof(taken)], state, sizeof(int32_t) * dim);
    return k;
}

// returns Verdict {0, 1, 2}
static int dfs(Ctx& c, uint64_t taken, const int32_t* state,
               int got_required) {
    if (got_required == c.n_required) return 1;
    if (c.budget <= 0) return 2;
    const bool scalar = c.sp.state_dim == 1;
    const bool packed = !scalar
        && c.sp.elem_bits > 0
        && c.sp.state_dim * c.sp.elem_bits <= 64;
    Key key{};
    std::string vkey;
    if (c.use_memo) {
        if (scalar) {
            key = key_of(taken, state[0]);
            if (c.seen->count(key)) return 0;
        } else if (packed) {
            key = key_packed(taken, state, c.sp.state_dim, c.sp.elem_bits);
            if (c.seen->count(key)) return 0;
        } else {
            vkey = vec_key(taken, state, c.sp.state_dim);
            if (c.seen_vec->count(vkey)) return 0;
        }
    }
    bool saw_budget = false;
    int32_t child[MAX_STATE];
    for (int j = 0; j < c.n; ++j) {
        if (taken >> j & 1) continue;
        if (c.blockers[j] & ~taken) continue;  // an untaken op precedes j
        const int cm = c.cmd[j], a = c.arg[j];
        const bool pend = c.pending[j];
        const int r_lo = pend ? 0 : c.resp[j];
        const int r_hi = pend ? c.n_resps[cm] : c.resp[j] + 1;
        for (int r = r_lo; r < r_hi; ++r) {
            --c.budget;
            ++c.nodes;
            if (c.budget <= 0) return 2;
            if (!do_step(c.sp, state, child, cm, a, r)) continue;
            const int sub = dfs(c, taken | (1ull << j), child,
                                got_required + (pend ? 0 : 1));
            if (sub == 1) return 1;
            if (sub == 2) saw_budget = true;
        }
    }
    if (saw_budget) return 2;
    if (c.use_memo) {
        if (scalar || packed) c.seen->insert(key);
        else c.seen_vec->insert(std::move(vkey));
    }
    return 0;
}

}  // namespace

extern "C" {

// Decide a batch: per-history op arrays are concatenated, offsets[i] is
// the start of history i's ops, offsets[n_hist] the total.  init_states
// is [n_hist][state_dim] (per-lane start states — the segmentation
// combinator's route).  kind/p0/p1 select the spec semantics; trans/ok
// carry the scalar domain table for kind 0 (pass null otherwise).
// Returns total nodes explored; verdicts land in out_verdicts.
long long wg_check_batch(
    int n_hist, const int64_t* offsets,
    const int32_t* cmd, const int32_t* arg, const int32_t* resp,
    const uint8_t* pending, const uint64_t* blockers,
    int kind, int state_dim, int32_t p0, int32_t p1, int elem_bits,
    const int32_t* trans, const uint8_t* ok,
    int S, int C, int A, int R, const int32_t* n_resps,
    const int32_t* init_states, long long node_budget, int use_memo,
    int32_t* out_verdicts) {
    SpecDesc sp{kind, state_dim, p0, p1, trans, ok, S, C, A, R, elem_bits};
    long long total = 0;
    for (int i = 0; i < n_hist; ++i) {
        const int64_t lo = offsets[i];
        const int n = static_cast<int>(offsets[i + 1] - lo);
        int n_required = 0;
        for (int j = 0; j < n; ++j)
            if (!pending[lo + j]) ++n_required;
        std::unordered_set<Key, KeyHash> seen;
        std::unordered_set<std::string> seen_vec;
        Ctx c{n, cmd + lo, arg + lo, resp + lo, pending + lo,
              blockers + lo, sp, n_resps, n_required, node_budget, 0,
              use_memo != 0, &seen, &seen_vec};
        out_verdicts[i] =
            (n == 0) ? 1 : dfs(c, 0ull, init_states + i * state_dim, 0);
        total += c.nodes;
    }
    return total;
}

}  // extern "C"
