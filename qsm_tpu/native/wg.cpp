// Native Wing–Gong lineariser — the C++ fast path of the host checker
// plane (SURVEY.md §2a names a C++ extension as the designated fallback for
// host-side hot loops; the reference itself is pure Haskell with no native
// code, so this is OUR runtime component, not a port).
//
// Faithful to qsm_tpu/ops/wing_gong_cpu.py::WingGongCPU._check: identical
// candidate order (ops ascending, responses ascending), identical node
// budget accounting (one unit per step evaluation), identical memo
// semantics (configurations (taken-set, state) proven non-linearizable-
// from), identical pending-op treatment (a pending op may linearise with
// ANY response in its command's domain, or never).  Verdict codes match
// ops/backend.py::Verdict: 0 VIOLATION, 1 LINEARIZABLE, 2 BUDGET_EXCEEDED.
//
// Scope: scalar-state specs with a declared state bound (the step function
// arrives as the dense [S][C][A][R] domain table compiled by
// core/spec.py::compile_step_table).  Vector-state specs stay on the
// Python oracle — the Python side routes them (native/__init__.py).
//
// Histories are capped at 64 ops (the encoder's bucket cap), so the taken
// set is one uint64 and precedence is a per-op blocker bitmask.

#include <cstddef>
#include <cstdint>
#include <unordered_set>
#include <utility>

namespace {

// Exact memo key: the 64-op taken mask plus the scalar model state — an
// exact pair, no packing tricks, no collision risk.  (__int128 would pack
// both, but libstdc++'s hash-table traits reject it under -std=c++17.)
using Key = std::pair<uint64_t, uint64_t>;

struct KeyHash {
    size_t operator()(const Key& k) const {
        // splitmix64 over both halves
        auto mix = [](uint64_t x) {
            x += 0x9E3779B97F4A7C15ull;
            x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
            x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
            return x ^ (x >> 31);
        };
        return mix(k.first) ^ (mix(k.second) * 0x9E3779B9ull);
    }
};

struct Ctx {
    int n;
    const int32_t* cmd;
    const int32_t* arg;
    const int32_t* resp;
    const uint8_t* pending;
    const uint64_t* blockers;
    const int32_t* trans;   // [S][C][A][R]
    const uint8_t* ok;      // [S][C][A][R]
    int S, C, A, R;
    const int32_t* n_resps; // per command
    int n_required;
    long long budget;
    long long nodes;
    bool use_memo;
    std::unordered_set<Key, KeyHash>* seen;
};

static inline Key key_of(uint64_t taken, int state) {
    return {taken, static_cast<uint64_t>(static_cast<uint32_t>(state))};
}

static inline int step_idx(const Ctx& c, int s, int cm, int a, int r) {
    return ((s * c.C + cm) * c.A + a) * c.R + r;
}

// returns Verdict {0, 1, 2}
static int dfs(Ctx& c, uint64_t taken, int state, int got_required) {
    if (got_required == c.n_required) return 1;
    if (c.budget <= 0) return 2;
    Key key{};
    if (c.use_memo) {
        key = key_of(taken, state);
        if (c.seen->count(key)) return 0;
    }
    bool saw_budget = false;
    for (int j = 0; j < c.n; ++j) {
        if (taken >> j & 1) continue;
        if (c.blockers[j] & ~taken) continue;  // an untaken op precedes j
        const int cm = c.cmd[j], a = c.arg[j];
        const bool pend = c.pending[j];
        const int r_lo = pend ? 0 : c.resp[j];
        const int r_hi = pend ? c.n_resps[cm] : c.resp[j] + 1;
        for (int r = r_lo; r < r_hi; ++r) {
            --c.budget;
            ++c.nodes;
            if (c.budget <= 0) return 2;
            const int idx = step_idx(c, state, cm, a, r);
            if (!c.ok[idx]) continue;
            const int sub = dfs(c, taken | (1ull << j), c.trans[idx],
                                got_required + (pend ? 0 : 1));
            if (sub == 1) return 1;
            if (sub == 2) saw_budget = true;
        }
    }
    if (saw_budget) return 2;
    if (c.use_memo) c.seen->insert(key);
    return 0;
}

}  // namespace

extern "C" {

// Decide one history.  Returns nodes explored; verdict via out param.
long long wg_check(
    int n, const int32_t* cmd, const int32_t* arg, const int32_t* resp,
    const uint8_t* pending, const uint64_t* blockers,
    const int32_t* trans, const uint8_t* ok,
    int S, int C, int A, int R, const int32_t* n_resps,
    int init_state, long long node_budget, int use_memo,
    int32_t* out_verdict) {
    int n_required = 0;
    for (int j = 0; j < n; ++j)
        if (!pending[j]) ++n_required;
    std::unordered_set<Key, KeyHash> seen;
    Ctx c{n, cmd, arg, resp, pending, blockers, trans, ok,
          S, C, A, R, n_resps, n_required, node_budget, 0,
          use_memo != 0, &seen};
    *out_verdict = (n == 0) ? 1 : dfs(c, 0ull, init_state, 0);
    return c.nodes;
}

// Decide a batch: per-history arrays are concatenated, offsets[i] is the
// start of history i's ops, offsets[n_hist] the total.  init_states may
// carry one scalar per history (per-lane start states for the
// segmentation combinator).  Returns total nodes explored.
long long wg_check_batch(
    int n_hist, const int64_t* offsets,
    const int32_t* cmd, const int32_t* arg, const int32_t* resp,
    const uint8_t* pending, const uint64_t* blockers,
    const int32_t* trans, const uint8_t* ok,
    int S, int C, int A, int R, const int32_t* n_resps,
    const int32_t* init_states, long long node_budget, int use_memo,
    int32_t* out_verdicts) {
    long long total = 0;
    for (int i = 0; i < n_hist; ++i) {
        const int64_t lo = offsets[i];
        const int n = static_cast<int>(offsets[i + 1] - lo);
        total += wg_check(n, cmd + lo, arg + lo, resp + lo, pending + lo,
                          blockers + lo, trans, ok, S, C, A, R, n_resps,
                          init_states[i], node_budget, use_memo,
                          out_verdicts + i);
    }
    return total;
}

}  // extern "C"
