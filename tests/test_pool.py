"""Parallel execution plane (sched/pool.py): fanning schedule executions
over spawn-started worker processes must be invisible in the results —
histories, counterexamples, and stats bit-identical to the serial path —
because every history is a pure function of (SUT factory, program, seed,
faults)."""

import dataclasses

from qsm_tpu.core.property import PropertyConfig, prop_concurrent
from qsm_tpu.models.registry import SutFactory, make


CFG = PropertyConfig(n_trials=24, n_pids=4, max_ops=16, seed=11)


import pytest


@pytest.fixture(scope="module")
def serial_racy_result():
    """One serial baseline for every parity test in this module (the
    run is deterministic, so sharing it is free)."""
    spec, sut = make("cas", "racy")
    return prop_concurrent(spec, sut, CFG)


def test_pool_matches_serial_on_failure(serial_racy_result):
    serial = serial_racy_result
    spec2, sut2 = make("cas", "racy")
    pooled = prop_concurrent(
        spec2, sut2, dataclasses.replace(CFG, executor_workers=2),
        sut_factory=SutFactory("cas", "racy"))
    assert not serial.ok and not pooled.ok
    assert pooled.counterexample.trial == serial.counterexample.trial
    assert pooled.counterexample.trial_seed == serial.counterexample.trial_seed
    assert (pooled.counterexample.history.fingerprint()
            == serial.counterexample.history.fingerprint())
    assert (tuple(pooled.counterexample.program.ops)
            == tuple(serial.counterexample.program.ops))


def test_pool_matches_serial_on_pass_and_with_faults():
    from qsm_tpu.sched.scheduler import FaultPlan

    cfg = dataclasses.replace(
        CFG, n_trials=12,
        faults=FaultPlan(p_drop=0.1, p_duplicate=0.05))
    spec, sut = make("cas", "atomic")
    serial = prop_concurrent(spec, sut, cfg)
    spec2, sut2 = make("cas", "atomic")
    pooled = prop_concurrent(
        spec2, sut2, dataclasses.replace(cfg, executor_workers=2),
        sut_factory=SutFactory("cas", "atomic"))
    assert serial.ok and pooled.ok
    assert pooled.histories_checked == serial.histories_checked
    assert pooled.distinct_histories == serial.distinct_histories
    assert pooled.undecided == serial.undecided


def test_pool_ignored_without_factory():
    spec, sut = make("register", "atomic")
    res = prop_concurrent(
        spec, sut,
        dataclasses.replace(CFG, n_trials=5, n_pids=2, max_ops=8,
                            executor_workers=4))  # no factory -> serial
    assert res.ok


def test_pool_with_tcp_transport_matches_serial(serial_racy_result):
    """Workers build their own loopback-TCP transports (PoolExecutor's
    transport spec); results must still be bit-identical to the serial
    in-memory run — the full transport × executor matrix holds."""
    serial = serial_racy_result
    spec2, sut2 = make("cas", "racy")
    pooled_tcp = prop_concurrent(
        spec2, sut2,
        dataclasses.replace(CFG, executor_workers=2, transport="tcp"),
        sut_factory=SutFactory("cas", "racy"))
    assert not serial.ok and not pooled_tcp.ok
    assert (pooled_tcp.counterexample.history.fingerprint()
            == serial.counterexample.history.fingerprint())
    assert pooled_tcp.counterexample.trial_seed == \
        serial.counterexample.trial_seed
