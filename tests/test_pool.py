"""Parallel execution plane (sched/pool.py): fanning schedule executions
over spawn-started worker processes must be invisible in the results —
histories, counterexamples, and stats bit-identical to the serial path —
because every history is a pure function of (SUT factory, program, seed,
faults)."""

import dataclasses

from qsm_tpu.core.property import PropertyConfig, prop_concurrent
from qsm_tpu.models.registry import SutFactory, make


CFG = PropertyConfig(n_trials=24, n_pids=4, max_ops=16, seed=11)


import pytest


@pytest.fixture(scope="module")
def serial_racy_result():
    """One serial baseline for every parity test in this module (the
    run is deterministic, so sharing it is free)."""
    spec, sut = make("cas", "racy")
    return prop_concurrent(spec, sut, CFG)


def test_pool_matches_serial_on_failure(serial_racy_result):
    serial = serial_racy_result
    spec2, sut2 = make("cas", "racy")
    pooled = prop_concurrent(
        spec2, sut2, dataclasses.replace(CFG, executor_workers=2),
        sut_factory=SutFactory("cas", "racy"))
    assert not serial.ok and not pooled.ok
    assert pooled.counterexample.trial == serial.counterexample.trial
    assert pooled.counterexample.trial_seed == serial.counterexample.trial_seed
    assert (pooled.counterexample.history.fingerprint()
            == serial.counterexample.history.fingerprint())
    assert (tuple(pooled.counterexample.program.ops)
            == tuple(serial.counterexample.program.ops))


def test_pool_matches_serial_on_pass_and_with_faults():
    from qsm_tpu.sched.scheduler import FaultPlan

    cfg = dataclasses.replace(
        CFG, n_trials=12,
        faults=FaultPlan(p_drop=0.1, p_duplicate=0.05))
    spec, sut = make("cas", "atomic")
    serial = prop_concurrent(spec, sut, cfg)
    spec2, sut2 = make("cas", "atomic")
    pooled = prop_concurrent(
        spec2, sut2, dataclasses.replace(cfg, executor_workers=2),
        sut_factory=SutFactory("cas", "atomic"))
    assert serial.ok and pooled.ok
    assert pooled.histories_checked == serial.histories_checked
    assert pooled.distinct_histories == serial.distinct_histories
    assert pooled.undecided == serial.undecided


def test_pool_worker_init_failure_fails_fast():
    """A sut_factory that crashes in the fresh worker interpreter must
    surface as an error, not an infinite respawn hang (regression: the
    pre-probe PoolExecutor wedged run_many forever)."""
    from qsm_tpu.sched.pool import PoolExecutor

    pool = PoolExecutor(_ExplodingFactory(), n_workers=1)
    # the probe can only detect the crash by TIMING OUT (the probe task
    # never runs when the initializer raises), so this whole duration is
    # always spent — keep it tiny
    pool.PROBE_TIMEOUT_S = 2.0
    try:
        with pytest.raises(RuntimeError, match="failed to initialize"):
            pool.run_many([(None, "s")], faults=None, max_steps=10)
    finally:
        pool.close()


class _ExplodingFactory:
    """Picklable, but construction fails in the worker."""

    def __call__(self):
        raise RuntimeError("boom in worker")


def test_pool_ignored_without_factory():
    spec, sut = make("register", "atomic")
    res = prop_concurrent(
        spec, sut,
        dataclasses.replace(CFG, n_trials=5, n_pids=2, max_ops=8,
                            executor_workers=4))  # no factory -> serial
    assert res.ok


def test_pool_with_tcp_transport_matches_serial(serial_racy_result):
    """Workers build their own loopback-TCP transports (PoolExecutor's
    transport spec); results must still be bit-identical to the serial
    in-memory run — the full transport × executor matrix holds."""
    serial = serial_racy_result
    spec2, sut2 = make("cas", "racy")
    pooled_tcp = prop_concurrent(
        spec2, sut2,
        dataclasses.replace(CFG, executor_workers=2, transport="tcp"),
        sut_factory=SutFactory("cas", "racy"))
    assert not serial.ok and not pooled_tcp.ok
    assert (pooled_tcp.counterexample.history.fingerprint()
            == serial.counterexample.history.fingerprint())
    assert pooled_tcp.counterexample.trial_seed == \
        serial.counterexample.trial_seed


def test_explore_pool_matches_serial():
    """Parallel tree enumeration (ExplorePool via explore_many
    workers>0) is bit-identical to the serial walk — trees are
    deterministic, fan-out changes wall-clock only (and on this 1-core
    image not even that; pool.py docstring records the measurement)."""
    from qsm_tpu.core.generator import generate_program
    from qsm_tpu.sched.systematic import explore_many

    spec, _ = make("set", "racy")
    progs = [generate_program(spec, seed=s, n_pids=2, max_ops=4)
             for s in range(4)]
    factory = SutFactory("set", "racy")
    serial = explore_many(factory, progs, spec, max_schedules=5_000)
    par = explore_many(factory, progs, spec, max_schedules=5_000,
                       workers=2)
    for a, b in zip(serial, par):
        assert (a.schedules_run, a.pruned_schedules,
                a.distinct_histories, a.exhausted,
                a.violations, a.undecided) == (
            b.schedules_run, b.pruned_schedules,
            b.distinct_histories, b.exhausted,
            b.violations, b.undecided)


def test_explore_workers_flag_needs_programs():
    from qsm_tpu.utils.cli import main

    import pytest

    with pytest.raises(SystemExit, match="workers"):
        main(["explore", "--model", "set", "--workers", "2"])
