"""Beyond the reference's scale: 96- and 128-op histories (the largest
BASELINE config is 64×16).  The device kernel and host oracles handle the
new buckets directly; the native C++ checker's 128-bit taken mask covers
the full bucket range natively; segmentation keeps the long-history cost
decomposed (SURVEY.md §5 long-context row)."""

import pytest

import numpy as np

from qsm_tpu import Verdict, WingGongCPU
from qsm_tpu.core.history import bucket_for
from qsm_tpu.models import CasSpec, AtomicCasSUT, RacyCasSUT, QueueSpec
from qsm_tpu.models.queue import AtomicQueueSUT, RacyTwoPhaseQueueSUT
from qsm_tpu.utils.corpus import build_corpus


def test_buckets_extend_past_reference_scale():
    assert bucket_for(65) == 96
    assert bucket_for(97) == 128


@pytest.mark.slow
def test_cas_96ops_device_parity():
    from qsm_tpu.ops.jax_kernel import JaxTPU

    spec = CasSpec()
    corpus = build_corpus(spec, (AtomicCasSUT, RacyCasSUT), n=24,
                          n_pids=8, max_ops=96, seed_base=1000,
                          seed_prefix="long")
    dev = JaxTPU(spec)
    got = dev.check_histories(spec, corpus)
    want = WingGongCPU(memo=True).check_histories(spec, corpus)
    decided = got != int(Verdict.BUDGET_EXCEEDED)
    np.testing.assert_array_equal(got[decided], np.asarray(want)[decided])
    assert decided.sum() >= 0.8 * len(corpus)
    assert (want == int(Verdict.VIOLATION)).any()


def test_cas_128ops_native_parity():
    """The top bucket: 128-op histories fill the whole __int128 mask."""
    from qsm_tpu.native import CppOracle

    spec = CasSpec()
    corpus = build_corpus(spec, (AtomicCasSUT, RacyCasSUT), n=16,
                          n_pids=8, max_ops=128, seed_base=2000,
                          seed_prefix="long128")
    assert any(len(h) > 96 for h in corpus)
    want = WingGongCPU(memo=True).check_histories(spec, corpus)
    cpp = CppOracle(spec)
    np.testing.assert_array_equal(cpp.check_histories(spec, corpus), want)
    assert cpp.native_histories == len(corpus)
    assert (want == int(Verdict.VIOLATION)).any()


@pytest.mark.slow
def test_cas_128ops_device_parity():
    """The device kernel at the 128-op bucket (4 taken-mask words in the
    packed precedence path) — decided verdicts must match the oracle."""
    from qsm_tpu.ops.jax_kernel import JaxTPU

    spec = CasSpec()
    corpus = build_corpus(spec, (AtomicCasSUT, RacyCasSUT), n=12,
                          n_pids=8, max_ops=128, seed_base=2000,
                          seed_prefix="long128")
    want = WingGongCPU(memo=True).check_histories(spec, corpus)
    got = JaxTPU(spec).check_histories(spec, corpus)
    decided = got != int(Verdict.BUDGET_EXCEEDED)
    np.testing.assert_array_equal(got[decided], np.asarray(want)[decided])
    assert decided.sum() >= 0.7 * len(corpus)
    assert (want == int(Verdict.VIOLATION)).any()  # not vacuous


def test_queue_96ops_segdc_and_native_fallback_parity():
    from qsm_tpu.native import CppOracle
    from qsm_tpu.ops.segdc import SegDC

    spec = QueueSpec()
    corpus = build_corpus(spec, (AtomicQueueSUT, RacyTwoPhaseQueueSUT),
                          n=24, n_pids=8, max_ops=96, seed_base=1000,
                          seed_prefix="long")
    assert any(len(h) > 64 for h in corpus)
    want = WingGongCPU(memo=True).check_histories(spec, corpus)

    seg = SegDC(spec)
    np.testing.assert_array_equal(seg.check_histories(spec, corpus), want)
    assert seg.segments_split > 0

    cpp = CppOracle(spec)
    np.testing.assert_array_equal(cpp.check_histories(spec, corpus), want)
    # the 128-bit taken mask decides >64-op histories NATIVELY now
    assert cpp.fallback_histories == 0
    assert cpp.native_histories == len(corpus)
