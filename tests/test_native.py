"""Native C++ lineariser: availability (the toolchain is baked into the
image — a compile failure must FAIL, not skip), parity with the Python
oracle incl. pending-op fault histories and budget semantics, fallback
routing for vector-state specs, and init-state starts (SegDC's route)."""

import pytest

import numpy as np

from qsm_tpu import Verdict, WingGongCPU
from qsm_tpu.models import CasSpec, AtomicCasSUT, RacyCasSUT, QueueSpec
from qsm_tpu.models.queue import AtomicQueueSUT, RacyTwoPhaseQueueSUT
from qsm_tpu.models.register import (AtomicRegisterSUT,
                                     RacyCachedRegisterSUT, RegisterSpec)
from qsm_tpu.native import CppOracle, native_available, native_error
from qsm_tpu.utils.corpus import build_corpus


def test_native_lib_builds():
    assert native_available(), native_error()


def test_parity_cas_corpus():
    spec = CasSpec()
    corpus = build_corpus(spec, (AtomicCasSUT, RacyCasSUT), n=96,
                          n_pids=8, max_ops=32, seed_base=1000,
                          seed_prefix="bench")
    cpp = CppOracle(spec)
    got = cpp.check_histories(spec, corpus)
    want = WingGongCPU(memo=True).check_histories(spec, corpus)
    np.testing.assert_array_equal(got, want)
    assert cpp.native_histories == len(corpus)  # no silent fallback
    assert (got == int(Verdict.VIOLATION)).any()
    assert (got == int(Verdict.LINEARIZABLE)).any()


def test_parity_register_with_pending_ops():
    """Fault-injected histories carry pending ops; the C++ search must
    complete/prune them exactly like the oracle."""
    from qsm_tpu import generate_program, run_concurrent
    from qsm_tpu.sched.scheduler import FaultPlan

    spec = RegisterSpec(n_values=5)
    hists = []
    for seed in range(48):
        prog = generate_program(spec, seed=seed, n_pids=2, max_ops=12)
        sut = (AtomicRegisterSUT if seed % 2 else RacyCachedRegisterSUT)()
        hists.append(run_concurrent(
            sut, prog, seed=f"n{seed}",
            faults=FaultPlan(p_drop=0.2, p_duplicate=0.1)))
    assert any(h.n_pending for h in hists), "fault corpus vacuous"
    cpp = CppOracle(spec)
    got = cpp.check_histories(spec, hists)
    want = WingGongCPU(memo=True).check_histories(spec, hists)
    np.testing.assert_array_equal(got, want)
    # out-of-domain responses route to the fallback BY DESIGN (exactness
    # for arbitrary specs); the bulk must still go native
    assert cpp.native_histories >= 0.8 * len(hists)
    assert cpp.native_histories + cpp.fallback_histories == len(hists)


def test_budget_semantics_match_oracle():
    """Same candidate order + same node accounting -> bit-identical
    verdicts INCLUDING which histories exceed a tiny budget."""
    spec = CasSpec()
    corpus = build_corpus(spec, (AtomicCasSUT, RacyCasSUT), n=48,
                          n_pids=8, max_ops=24, seed_base=7,
                          seed_prefix="budget")
    for budget in (50, 500, 5_000):
        cpp = CppOracle(spec, node_budget=budget, memo=False)
        py = WingGongCPU(node_budget=budget)
        np.testing.assert_array_equal(
            cpp.check_histories(spec, corpus),
            py.check_histories(spec, corpus), err_msg=f"budget={budget}")


def test_queue_native_kernel_parity():
    """Vector-state queue histories run the built-in C++ step kernel
    (wg.cpp kind 1) — full-size 48-op corpus, parity with the oracle."""
    spec = QueueSpec()
    corpus = build_corpus(spec, (AtomicQueueSUT, RacyTwoPhaseQueueSUT),
                          n=48, n_pids=8, max_ops=48, seed_base=1000,
                          seed_prefix="bench")
    cpp = CppOracle(spec)
    got = cpp.check_histories(spec, corpus)
    want = WingGongCPU(memo=True).check_histories(spec, corpus)
    np.testing.assert_array_equal(got, want)
    assert cpp.native_histories == len(corpus)
    assert (got == int(Verdict.VIOLATION)).any()
    assert (got == int(Verdict.LINEARIZABLE)).any()


@pytest.mark.slow
def test_kv_native_kernel_parity():
    """KV histories (wg.cpp kind 2) at full 16-pid/64-op size; note the
    UNdecomposed search — the native DFS handles it where the Python
    memo oracle is impractically slow, so parity is checked against
    P-compositionality + oracle."""
    from qsm_tpu.models import KvSpec
    from qsm_tpu.models.kv import AtomicKvSUT, StaleCacheKvSUT
    from qsm_tpu.ops.pcomp import PComp

    spec = KvSpec()
    corpus = build_corpus(spec, (AtomicKvSUT, StaleCacheKvSUT),
                          n=24, n_pids=16, max_ops=64, seed_base=1000,
                          seed_prefix="bench")
    cpp = CppOracle(spec, node_budget=20_000_000)
    got = cpp.check_histories(spec, corpus)
    want = PComp(spec).check_histories(spec, corpus)
    decided = got != int(Verdict.BUDGET_EXCEEDED)
    np.testing.assert_array_equal(got[decided],
                                  np.asarray(want)[decided])
    assert cpp.native_histories == len(corpus)
    assert decided.any()


def test_unknown_vector_spec_routes_to_fallback():
    """A vector-state spec WITHOUT a native kernel still gets exact
    verdicts via the Python fallback."""
    spec = QueueSpec()
    corpus = build_corpus(spec, (AtomicQueueSUT, RacyTwoPhaseQueueSUT),
                          n=16, n_pids=4, max_ops=16, seed_base=3,
                          seed_prefix="fb")
    cpp = CppOracle(spec)
    cpp._vector_kernel = None  # simulate a spec with no C++ kernel
    got = cpp.check_histories(spec, corpus)
    want = WingGongCPU(memo=True).check_histories(spec, corpus)
    np.testing.assert_array_equal(got, want)
    assert cpp.native_histories == 0
    assert cpp.fallback_histories == len(corpus)


def test_check_from_init_state():
    """SegDC's final-segment route: start the search from explicit model
    states; parity with the Python oracle's check_from."""
    from qsm_tpu import overlapping_history

    spec = RegisterSpec(n_values=5)
    READ, WRITE = 0, 1
    h = overlapping_history([(0, READ, 0, 3, 0, 1)])
    cpp = CppOracle(spec)
    py = WingGongCPU(memo=True)
    for s in range(5):
        state = np.asarray([s], np.int32)
        assert (cpp.check_from(spec, h, state)
                == py.check_from(spec, h, state)), s


def test_native_is_actually_fast():
    """Not a perf assertion in CI — just a sanity floor: the native path
    must decide the CAS bench corpus well under the Python oracle's time
    (catches accidentally routing everything to the fallback)."""
    import time

    spec = CasSpec()
    corpus = build_corpus(spec, (AtomicCasSUT, RacyCasSUT), n=96,
                          n_pids=8, max_ops=32, seed_base=1000,
                          seed_prefix="bench")
    cpp = CppOracle(spec)
    cpp.check_histories(spec, corpus)  # table build + lib load
    t0 = time.perf_counter()
    cpp.check_histories(spec, corpus)
    cpp_s = time.perf_counter() - t0
    py = WingGongCPU(memo=True)
    t0 = time.perf_counter()
    py.check_histories(spec, corpus)
    py_s = time.perf_counter() - t0
    assert cpp_s < py_s, (cpp_s, py_s)


def test_end_states_matches_python_enumeration():
    """Native middle-segment end-state enumeration == the Python walk,
    and segdc with a CppOracle oracle stays verdict-identical while
    actually using it."""
    from qsm_tpu.ops.segdc import SegDC, _Budget, _end_states, \
        split_at_quiescent_cuts

    spec = QueueSpec()
    corpus = build_corpus(spec, (AtomicQueueSUT, RacyTwoPhaseQueueSUT),
                          n=48, n_pids=8, max_ops=48, seed_base=1000,
                          seed_prefix="bench")
    cpp = CppOracle(spec)
    checked = 0
    for h in corpus:
        segs = split_at_quiescent_cuts(h)
        if len(segs) <= 1:
            continue
        frontier = {tuple(int(v) for v in spec.initial_state())}
        for seg in segs[:-1]:
            want = _end_states(spec, seg, frontier, _Budget(10_000_000))
            got = cpp.end_states(spec, seg, frontier)
            assert got == want, (len(seg), sorted(frontier))
            checked += 1
            frontier = want
    assert checked > 0, "corpus produced no middle segments"

    # the Python-walk reference must stay Python: SegDC's DEFAULT
    # middle oracle is now the native checker, so pin it explicitly
    host = SegDC(spec, oracle=WingGongCPU(memo=True))
    nat = SegDC(spec, make_inner=lambda s: cpp, oracle=cpp)
    got = nat.check_histories(spec, corpus)
    want = host.check_histories(spec, corpus)
    np.testing.assert_array_equal(got, want)
    assert nat.segments_native > 0


def test_frontier_start_past_segment_bound_is_exact():
    """Regression: a ticket-dispenser frontier state can exceed the FINAL
    segment's from-initial state bound (scalar_state_bound(n2) < start).
    The native table must cover frontier starts (and anything that still
    escapes defers via the in-kernel OOB guard) — never a misread."""
    from qsm_tpu import overlapping_history
    from qsm_tpu.models.counter import TicketSpec
    from qsm_tpu.ops.segdc import SegDC, split_at_quiescent_cuts

    spec = TicketSpec(n_tickets=32)
    TAKE = 0
    # segment 1: ten sequential ok TAKEs drive the state to 10;
    # segment 2 (after a quiescent cut): one TAKE expecting ticket 10
    ops = [(0, TAKE, 0, i, 2 * i, 2 * i + 1) for i in range(10)]
    good = overlapping_history(ops + [(1, TAKE, 0, 10, 100, 101)])
    bad = overlapping_history(ops + [(1, TAKE, 0, 3, 100, 101)])
    assert len(split_at_quiescent_cuts(good)) == 11

    cpp = CppOracle(spec)
    host = SegDC(spec, oracle=WingGongCPU(memo=True))
    nat = SegDC(spec, make_inner=lambda s: cpp, oracle=cpp)
    for h in (good, bad):
        want = host.check_histories(spec, [h])
        got = nat.check_histories(spec, [h])
        np.testing.assert_array_equal(got, want, err_msg=str(h))
    assert nat.segments_native > 0
    # direct end_states past the per-segment bound
    seg2 = split_at_quiescent_cuts(good)[-1]
    got = cpp.end_states(spec, seg2, {(10,)})
    assert got == {(11,)}
    # check_from with a start past scalar_state_bound(len(h)) stays exact
    h1 = overlapping_history([(0, TAKE, 0, 20, 0, 1)])
    assert cpp.check_from(spec, h1, np.asarray([20], np.int32)) == \
        WingGongCPU().check_from(spec, h1, np.asarray([20], np.int32))


def test_invalid_start_states_defer_never_corrupt():
    """Foreign/corrupt start states on vector kernels must defer (verdict
    BUDGET_EXCEEDED) rather than smash the stack buffer or alias packed
    memo keys."""
    from qsm_tpu import overlapping_history

    spec = QueueSpec()
    h = overlapping_history([(0, 0, 1, 0, 0, 1)])  # ENQ(1) -> OK
    cpp = CppOracle(spec)
    for bad in ([70, 0, 0, 0, 0], [-3, 0, 0, 0, 0], [1, 99, 0, 0, 0]):
        v = cpp.check_from(spec, h, np.asarray(bad, np.int32))
        assert v == Verdict.BUDGET_EXCEEDED, bad
        assert cpp.end_states(spec, h.ops, {tuple(bad)}) is None
    # valid starts still decide natively
    assert cpp.check_from(
        spec, h, np.asarray([0, 0, 0, 0, 0], np.int32)) \
        == Verdict.LINEARIZABLE


def test_randomized_cross_spec_parity_sweep():
    """Safety net across ALL five spec families at assorted sizes:
    native verdicts == Python oracle verdicts on runner-produced
    corpora (faults included for the scalar specs)."""
    from qsm_tpu import generate_program, run_concurrent
    from qsm_tpu.models.registry import make
    from qsm_tpu.sched.scheduler import FaultPlan

    for model, n_pids, max_ops, faults in (
            ("register", 2, 12, FaultPlan(p_drop=0.2)),
            ("ticket", 4, 24, None),
            ("cas", 8, 24, FaultPlan(p_duplicate=0.15)),
            ("queue", 6, 32, None),
            ("kv", 8, 32, None)):
        spec, _ = make(model, "atomic")
        hists = []
        for seed in range(20):
            impl = "atomic" if seed % 2 else "racy"
            _, sut = make(model, impl)
            prog = generate_program(spec, seed=seed * 7 + 1,
                                    n_pids=n_pids, max_ops=max_ops)
            hists.append(run_concurrent(sut, prog, seed=f"x{seed}",
                                        faults=faults))
        cpp = CppOracle(spec, node_budget=10_000_000)
        got = cpp.check_histories(spec, hists)
        want = WingGongCPU(memo=True,
                           node_budget=10_000_000).check_histories(
            spec, hists)
        decided = (got != 2) & (np.asarray(want) != 2)
        np.testing.assert_array_equal(
            got[decided], np.asarray(want)[decided], err_msg=model)
        # never vacuous: the native path must have decided real work
        assert cpp.native_histories > 0, model
        assert decided.any(), model
