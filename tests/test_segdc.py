"""Decrease-and-conquer segmentation (PAPERS.md:9; SURVEY.md §5 long-context
row): quiescent-cut splitting, frontier threading across ambiguous segment
end states, pending ops in the final segment, and full verdict parity with
the oracle on the queue-48 bench corpus."""

import numpy as np
import pytest

from qsm_tpu import Verdict, WingGongCPU, check_one, overlapping_history
from qsm_tpu.core.history import sequential_history
from qsm_tpu.models import QueueSpec
from qsm_tpu.models.queue import AtomicQueueSUT, RacyTwoPhaseQueueSUT
from qsm_tpu.models.register import (AtomicRegisterSUT,
                                     RacyCachedRegisterSUT, RegisterSpec)
from qsm_tpu.ops.segdc import SegDC, split_at_quiescent_cuts
from qsm_tpu.utils.corpus import build_corpus

RSPEC = RegisterSpec(n_values=5)
READ, WRITE = 0, 1


def test_split_at_quiescent_cuts():
    # two sequential ops cut; two overlapping ops don't
    h = sequential_history([(0, WRITE, 3, 0), (1, READ, 0, 3)])
    assert [len(s) for s in split_at_quiescent_cuts(h)] == [1, 1]
    h = overlapping_history([(0, WRITE, 3, 0, 0, 5), (1, READ, 0, 3, 1, 2)])
    assert [len(s) for s in split_at_quiescent_cuts(h)] == [2]
    # a pending op forbids all later cuts
    h = overlapping_history([(0, WRITE, 3, -1, 0, 1 << 30),
                             (1, READ, 0, 3, 5, 6), (1, READ, 0, 3, 8, 9)])
    assert [len(s) for s in split_at_quiescent_cuts(h)] == [3]


def test_frontier_threads_ambiguous_segment_state():
    """Two concurrent writes (1 and 2) leave an ambiguous end state; a later
    quiescent read of EITHER value must pass, of a third value must fail —
    exactly the frontier-set semantics."""
    seg1 = [(0, WRITE, 1, 0, 0, 10), (1, WRITE, 2, 0, 1, 9)]
    for read_val, expect in ((1, Verdict.LINEARIZABLE),
                             (2, Verdict.LINEARIZABLE),
                             (3, Verdict.VIOLATION)):
        h = overlapping_history(seg1 + [(0, READ, 0, read_val, 20, 21)])
        assert len(split_at_quiescent_cuts(h)) == 2
        backend = SegDC(RSPEC)
        got = backend.check_histories(RSPEC, [h])[0]
        assert got == int(expect), (read_val, got)
        assert backend.segments_split == 1
        # and the oracle agrees (exactness)
        assert check_one(WingGongCPU(), RSPEC, h) == expect


def test_pending_op_in_final_segment():
    """A pending write after a cut may or may not have taken effect; reads
    of both the old and the new value must pass."""
    base = [(0, WRITE, 1, 0, 0, 1)]
    pend = [(0, WRITE, 4, -1, 10, 1 << 30)]
    for read_val, expect in ((1, Verdict.LINEARIZABLE),
                             (4, Verdict.LINEARIZABLE),
                             (2, Verdict.VIOLATION)):
        h = overlapping_history(base + pend + [(1, READ, 0, read_val,
                                                20, 21)])
        assert len(split_at_quiescent_cuts(h)) >= 2
        got = SegDC(RSPEC).check_histories(RSPEC, [h])[0]
        assert got == int(expect), (read_val, got)


def test_queue48_corpus_parity_zero_undecided():
    """The round-1 verdict's done-criterion: the queue-48 bench corpus is
    decided with 0 BUDGET_EXCEEDED and verdicts equal to the oracle's."""
    spec = QueueSpec()
    corpus = build_corpus(spec, (AtomicQueueSUT, RacyTwoPhaseQueueSUT),
                          n=64, n_pids=8, max_ops=48, seed_base=1000,
                          seed_prefix="bench")
    backend = SegDC(spec)
    oracle = WingGongCPU(memo=True)
    got = backend.check_histories(spec, corpus)
    want = oracle.check_histories(spec, corpus)
    np.testing.assert_array_equal(got, want)
    assert int((got == int(Verdict.BUDGET_EXCEEDED)).sum()) == 0
    assert (got == int(Verdict.VIOLATION)).any()
    assert (got == int(Verdict.LINEARIZABLE)).any()


@pytest.mark.slow
def test_queue48_final_segments_decided_on_device_backend():
    """VERDICT round 2, "Next round" #6 done-criterion: ``segdc-tpu`` parity
    on the queue-48 corpus with SEGMENTS (not just uncut wholes) decided on
    the device backend — every (final segment × frontier state) pair goes
    through ``JaxTPU.check_histories(..., init_states=…)`` in one batch."""
    from qsm_tpu.ops.jax_kernel import JaxTPU

    spec = QueueSpec()
    corpus = build_corpus(spec, (AtomicQueueSUT, RacyTwoPhaseQueueSUT),
                          n=32, n_pids=8, max_ops=48, seed_base=1000,
                          seed_prefix="bench")
    backend = SegDC(spec, make_inner=lambda s: JaxTPU(s))
    assert backend.device_final  # auto-detected from JaxTPU's signature
    got = backend.check_histories(spec, corpus)
    want = WingGongCPU(memo=True).check_histories(spec, corpus)
    # wherever the device path decided, verdicts must equal the oracle's;
    # BUDGET_EXCEEDED is honest deferral (the property layer resolves it)
    decided = got != int(Verdict.BUDGET_EXCEEDED)
    np.testing.assert_array_equal(got[decided], np.asarray(want)[decided])
    assert backend.segments_split > 0
    assert backend.final_states_device > 0   # segments really hit the device
    assert backend.inner.device_histories > 0


def test_segdc_device_final_matches_oracle_final_on_register():
    """The batched device final-segment resolution and the host oracle
    final-segment loop agree verdict-for-verdict on a cut-heavy corpus."""
    from qsm_tpu.ops.jax_kernel import JaxTPU

    corpus = build_corpus(RSPEC, (lambda _s: AtomicRegisterSUT(),
                                  lambda _s: RacyCachedRegisterSUT()),
                          n=48, n_pids=2, max_ops=12, seed_base=5,
                          seed_prefix="segdc")
    host = SegDC(RSPEC)
    dev = SegDC(RSPEC, make_inner=lambda s: JaxTPU(s))
    np.testing.assert_array_equal(host.check_histories(RSPEC, corpus),
                                  dev.check_histories(RSPEC, corpus))
    assert dev.final_states_device > 0


def test_low_concurrency_register_corpus_parity():
    """2-pid histories cut often; segmented verdicts must equal the
    oracle's everywhere, including violations."""
    corpus = build_corpus(RSPEC, (lambda _s: AtomicRegisterSUT(),
                                  lambda _s: RacyCachedRegisterSUT()),
                          n=48, n_pids=2, max_ops=12, seed_base=5,
                          seed_prefix="segdc")
    backend = SegDC(RSPEC)
    got = backend.check_histories(RSPEC, corpus)
    want = WingGongCPU(memo=True).check_histories(RSPEC, corpus)
    np.testing.assert_array_equal(got, want)
    assert backend.segments_split > 0  # splitting actually happened


def test_device_final_segments_with_pending_ops():
    """Fault-injected histories put PENDING ops in final segments; the
    batched device-final path (init_states + host-side pending expansion)
    must agree with the host SegDC everywhere."""
    from qsm_tpu import generate_program, run_concurrent
    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.sched.scheduler import FaultPlan

    hists = []
    for seed in range(32):
        prog = generate_program(RSPEC, seed=seed, n_pids=2, max_ops=12)
        sut = (AtomicRegisterSUT if seed % 2 else RacyCachedRegisterSUT)()
        hists.append(run_concurrent(
            sut, prog, seed=f"sp{seed}",
            faults=FaultPlan(p_drop=0.25, p_duplicate=0.1)))
    assert any(h.n_pending for h in hists), "fault corpus vacuous"
    host = SegDC(RSPEC)
    dev = SegDC(RSPEC, make_inner=lambda s: JaxTPU(s))
    got = dev.check_histories(RSPEC, hists)
    want = host.check_histories(RSPEC, hists)
    np.testing.assert_array_equal(got, want)
    assert dev.final_states_device > 0
