"""Sequential property path (M1): spec + generator + sequential runner."""

import random

from qsm_tpu import ModelSUT, generate_program, run_sequential
from qsm_tpu.models.register import READ, WRITE, RegisterSpec

SPEC = RegisterSpec(n_values=5)


def test_model_sut_always_passes():
    for seed in range(25):
        prog = generate_program(SPEC, seed=seed, n_pids=1, max_ops=12)
        res = run_sequential(SPEC, ModelSUT(SPEC), prog)
        assert res.ok, (seed, res.failed_at)
        assert len(res.history) == len(prog)


class StuckRegister:
    """Broken SUT: writes are dropped."""

    def reset(self):
        self.value = 0

    def apply(self, cmd, arg):
        if cmd == READ:
            return self.value
        return 0  # ack but don't store


def test_broken_sut_fails():
    failed = False
    for seed in range(50):
        prog = generate_program(SPEC, seed=seed, n_pids=1, max_ops=12)
        res = run_sequential(SPEC, StuckRegister(), prog)
        if not res.ok:
            failed = True
            break
    assert failed, "write-dropping register was never caught"


def test_generation_deterministic():
    a = generate_program(SPEC, seed=7, n_pids=2, max_ops=12)
    b = generate_program(SPEC, seed=7, n_pids=2, max_ops=12)
    assert a == b


def test_generated_domains_in_range():
    rng = random.Random(0)
    for _ in range(20):
        seed = rng.randrange(1 << 30)
        prog = generate_program(SPEC, seed=seed, n_pids=3, max_ops=12)
        assert 1 <= len(prog) <= 12
        for op in prog.ops:
            assert 0 <= op.pid < 3
            sig = SPEC.CMDS[op.cmd]
            assert 0 <= op.arg < sig.n_args
