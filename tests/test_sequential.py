"""Sequential property path (M1): spec + generator + sequential runner."""

import random

from qsm_tpu import ModelSUT, generate_program, run_sequential
from qsm_tpu.models.register import READ, WRITE, RegisterSpec

SPEC = RegisterSpec(n_values=5)


def test_model_sut_always_passes():
    for seed in range(25):
        prog = generate_program(SPEC, seed=seed, n_pids=1, max_ops=12)
        res = run_sequential(SPEC, ModelSUT(SPEC), prog)
        assert res.ok, (seed, res.failed_at)
        assert len(res.history) == len(prog)


class StuckRegister:
    """Broken SUT: writes are dropped."""

    def reset(self):
        self.value = 0

    def apply(self, cmd, arg):
        if cmd == READ:
            return self.value
        return 0  # ack but don't store


def test_broken_sut_fails():
    failed = False
    for seed in range(50):
        prog = generate_program(SPEC, seed=seed, n_pids=1, max_ops=12)
        res = run_sequential(SPEC, StuckRegister(), prog)
        if not res.ok:
            failed = True
            break
    assert failed, "write-dropping register was never caught"


def test_generation_deterministic():
    a = generate_program(SPEC, seed=7, n_pids=2, max_ops=12)
    b = generate_program(SPEC, seed=7, n_pids=2, max_ops=12)
    assert a == b


def test_generated_domains_in_range():
    rng = random.Random(0)
    for _ in range(20):
        seed = rng.randrange(1 << 30)
        prog = generate_program(SPEC, seed=seed, n_pids=3, max_ops=12)
        assert 1 <= len(prog) <= 12
        for op in prog.ops:
            assert 0 <= op.pid < 3
            sig = SPEC.CMDS[op.cmd]
            assert 0 <= op.arg < sig.n_args


def test_prop_sequential_model_self_test_passes():
    from qsm_tpu import prop_sequential

    res = prop_sequential(SPEC, ModelSUT(SPEC), n_trials=50, max_ops=12,
                          seed=3)
    assert res.ok and res.trials_run == 50


def test_prop_sequential_finds_and_shrinks_bug():
    from qsm_tpu import prop_sequential

    class DropsSecondWrite:
        """Sequential SUT that ignores every second write — a bug the
        inline postcondition check must catch and shrink."""

        def reset(self):
            self.v = 0
            self.writes = 0

        def apply(self, cmd, arg):
            if cmd == 1:  # WRITE
                self.writes += 1
                if self.writes % 2 == 0:
                    return 0  # acked but dropped
                self.v = arg
                return 0
            return self.v  # READ

    res = prop_sequential(SPEC, DropsSecondWrite(), n_trials=200,
                          max_ops=12, seed=1)
    assert not res.ok
    assert res.counterexample is not None and res.history is not None
    assert res.failed_at is not None
    # shrinking got it small: a minimal exposure is write, write(x), read
    assert len(res.counterexample) <= 4, len(res.counterexample)
    # deterministic replay of the property
    from qsm_tpu import prop_sequential as ps2

    res2 = ps2(SPEC, DropsSecondWrite(), n_trials=200, max_ops=12, seed=1)
    assert res2.trial_seed == res.trial_seed
    assert tuple(res2.counterexample.ops) == tuple(res.counterexample.ops)
