"""P-compositional check plane (ISSUE 9): declared projections validate
at compile time, the planner decomposes exactly when the split buys
smaller buckets (and REFUSES with provenance when it must), decomposed
verdicts are bit-identical to the undecomposed host ladder across
engines and spec families, and every decomposed LINEARIZABLE history
carries a stitched whole-history witness that ``verify_witness`` replays
search-free (docs/PCOMP.md)."""

import dataclasses

import numpy as np
import pytest

from qsm_tpu import Verdict, WingGongCPU
from qsm_tpu.core.spec import CmdSig, KeyProj, projection_report
from qsm_tpu.models import (AtomicKvSUT, AtomicMultiCasSUT,
                            AtomicMultiRegisterSUT, KvSpec, MultiCasSpec,
                            MultiRegisterSpec, RacyMultiCasSUT,
                            ShardedStaleMultiRegisterSUT, StaleCacheKvSUT)
from qsm_tpu.ops.backend import verify_witness
from qsm_tpu.ops.pcomp import (NotDecomposableError, PComp, split_gain,
                               split_history, stitch_witness)
from qsm_tpu.utils.corpus import build_corpus

# a corpus with real per-cell races: 2 cells concentrate conflicting ops
FAMILIES = (
    (MultiRegisterSpec(n_cells=2, n_values=4),
     (AtomicMultiRegisterSUT, ShardedStaleMultiRegisterSUT)),
    (MultiCasSpec(n_cells=2, n_values=4),
     (AtomicMultiCasSUT, RacyMultiCasSUT)),
    (KvSpec(n_keys=2, n_values=4),
     (AtomicKvSUT, StaleCacheKvSUT)),
)


def _corpus(spec, suts, n=24, pids=8, ops=28, seed_base=0):
    return build_corpus(spec, suts, n=n, n_pids=pids, max_ops=ops,
                        seed_base=seed_base, seed_prefix=f"pc_{spec.name}")


# ---------------------------------------------------------------------------
# compile-time projection validation
# ---------------------------------------------------------------------------

def test_projection_report_clean_for_declared_families():
    for spec, _ in FAMILIES:
        assert projection_report(spec) == [], spec.name


def test_projection_report_refuses_undeclared_spec():
    from qsm_tpu.models import CasSpec

    report = projection_report(CasSpec())
    assert report and "no per-key projection" in report[0]


def test_non_total_partition_refused_everywhere():
    """The refusal path: a spec whose partition_key is not total must
    never decompose — not in PComp, not in the planner, not silently."""
    from qsm_tpu.analysis.fixtures import NonTotalPartitionKvSpec

    spec = NonTotalPartitionKvSpec()
    report = projection_report(spec)
    assert report and "not total" in report[0]
    with pytest.raises(NotDecomposableError, match="decomposable"):
        PComp(spec)


def test_unfaithful_projection_refused():
    from qsm_tpu.analysis.fixtures import UnfaithfulProjectionKvSpec

    assert projection_report(UnfaithfulProjectionKvSpec())
    with pytest.raises(NotDecomposableError):
        PComp(UnfaithfulProjectionKvSpec())


def test_sanctioned_projection_twin_stays_clean():
    from qsm_tpu.analysis.fixtures import SanctionedProjectionKvSpec
    from qsm_tpu.analysis.spec_passes import check_projection

    assert check_projection(SanctionedProjectionKvSpec(), "twin") == []


def test_lint_pass_flags_seeded_projection_bugs():
    from qsm_tpu.analysis.fixtures import (NonTotalPartitionKvSpec,
                                           UnfaithfulProjectionKvSpec)
    from qsm_tpu.analysis.spec_passes import check_projection

    for cls in (NonTotalPartitionKvSpec, UnfaithfulProjectionKvSpec):
        findings = check_projection(cls(), f"fixture:{cls.__name__}")
        assert findings, cls.__name__
        assert all(f.rule_id == "QSM-SPEC-PCOMP" for f in findings)


def test_faithfulness_catches_resp_domain_mismatch():
    """A projected command with a different response domain would let a
    pending completion replay out-of-domain — the validator pins it."""

    class BadRespKv(KvSpec):
        name = "bad_resp_kv"

        def __init__(self):
            super().__init__(n_keys=2, n_values=4)
            get, put = self.CMDS
            # GET declares 2 resps while the projected READ has 4
            self.CMDS = (dataclasses.replace(get, n_resps=2), put)

    assert any("response domain" in p for p in projection_report(BadRespKv()))


def test_keyproj_partition_key_override_consistency():
    """A hand-written partition_key that disagrees with the declared
    KeyProj splits one way and projects another — refused."""

    class SkewedKv(KvSpec):
        name = "skewed_kv"

        def partition_key(self, cmd, arg):
            k = super().partition_key(cmd, arg)
            return (k + 1) % self.n_keys if k is not None else None

    assert any("KeyProj derives" in p for p in projection_report(SkewedKv()))


# ---------------------------------------------------------------------------
# decomposed parity across engines and spec families
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("spec,suts", FAMILIES,
                         ids=[s.name for s, _ in FAMILIES])
def test_decomposed_bit_identical_to_host_ladder(spec, suts):
    """Decomposed verdicts == undecomposed host-ladder verdicts, across
    the memo oracle AND the native ladder as inner engines (the ISSUE 9
    parity pin for the second spec family)."""
    from qsm_tpu.resilience.failover import host_fallback

    hists = _corpus(spec, suts)
    direct = WingGongCPU(memo=True).check_histories(spec, hists)
    assert (direct == Verdict.VIOLATION).any(), "sample vacuous: no fails"
    for make_inner in (None, host_fallback):
        pc = PComp(spec) if make_inner is None \
            else PComp(spec, make_inner=make_inner)
        got = pc.check_histories(spec, hists)
        assert (np.asarray(got) == np.asarray(direct)).all(), pc.name


@pytest.mark.parametrize("spec,suts", FAMILIES,
                         ids=[s.name for s, _ in FAMILIES])
def test_stitched_witness_roundtrip(spec, suts):
    """Per family: every decomposed LINEARIZABLE history yields a
    stitched witness verify_witness accepts; violations yield None and
    match the direct oracle's verdict."""
    hists = _corpus(spec, suts, n=16)
    pc = PComp(spec)
    direct = WingGongCPU(memo=True).check_histories(spec, hists)
    n_ok = 0
    for h, want in zip(hists, direct):
        v, w = pc.check_witness(spec, h)
        assert int(v) == int(want)
        if v == Verdict.LINEARIZABLE:
            assert w is not None and verify_witness(spec, h, w)
            n_ok += 1
        else:
            assert w is None
    assert n_ok, "witness sample vacuous"
    st = pc.search_stats()
    assert st.pcomp_subs >= len(hists)
    assert st.pcomp_max_sub > 0


def test_stitched_witness_with_pending_ops():
    """Pending ops: completed-by-the-witness per key or pruned entirely —
    the stitched whole witness must replay either way."""
    from qsm_tpu.core.history import NO_RESP, History, Op
    from qsm_tpu.models.kv import GET, PUT

    spec = KvSpec(n_keys=2, n_values=4)
    big = 1 << 30  # pending sentinel response time
    h = History([
        Op(pid=0, cmd=PUT, arg=spec.put_arg(0, 3), resp=0,
           invoke_time=0, response_time=1),
        # pending PUT on key 1: the per-key witness may complete or
        # prune it — either way the stitched whole witness must replay
        Op(pid=1, cmd=PUT, arg=spec.put_arg(1, 2), resp=NO_RESP,
           invoke_time=2, response_time=big),
        Op(pid=0, cmd=GET, arg=0, resp=3, invoke_time=3,
           response_time=4),
        Op(pid=2, cmd=GET, arg=1, resp=2, invoke_time=5,
           response_time=6),
    ])
    assert h.n_pending == 1
    v, w = PComp(spec).check_witness(spec, h)
    assert v == Verdict.LINEARIZABLE
    # GET(1) == 2 is only explainable by the pending PUT taking effect,
    # so the witness must have completed it
    assert any(j == 1 for j, _ in w)
    assert verify_witness(spec, h, w)
    # and a pruned variant: the pending PUT never observed — witness may
    # omit it and must still replay
    h2 = History(h.ops[:1] + h.ops[1:2]
                 + [Op(pid=2, cmd=GET, arg=1, resp=0, invoke_time=5,
                       response_time=6)])
    v2, w2 = PComp(spec).check_witness(spec, h2)
    assert v2 == Verdict.LINEARIZABLE
    assert verify_witness(spec, h2, w2)


def test_stitch_witness_is_deterministic_and_ordered():
    from qsm_tpu.core.history import sequential_history
    from qsm_tpu.models.kv import GET, PUT

    spec = KvSpec(n_keys=2, n_values=4)
    h = sequential_history([
        (0, PUT, spec.put_arg(0, 3), 0),
        (1, PUT, spec.put_arg(1, 2), 0),
        (0, GET, 0, 3),
        (1, GET, 1, 2),
    ])
    v, w = PComp(spec).check_witness(spec, h)
    assert v == Verdict.LINEARIZABLE
    # a sequential history admits exactly its own order
    assert [j for j, _ in w] == [0, 1, 2, 3]
    assert verify_witness(spec, h, w)


def test_stitch_witness_refuses_cycles():
    from qsm_tpu.core.history import sequential_history

    h = sequential_history([(0, 0, 0, 0), (0, 1, 1, 0)])
    # op 0 precedes op 1 in real time, but a (corrupt) chain claims the
    # reverse — the stitcher must refuse, never emit a false certificate
    with pytest.raises(RuntimeError, match="cycle"):
        stitch_witness(h, [[(1, 0), (0, 0)]])


# ---------------------------------------------------------------------------
# the planner gate
# ---------------------------------------------------------------------------

def test_planner_decomposes_long_kv_with_provenance():
    from qsm_tpu.search.planner import plan_search, profile_corpus

    spec = KvSpec(n_keys=8, n_values=4)
    hists = build_corpus(spec, (AtomicKvSUT, StaleCacheKvSUT), n=6,
                         n_pids=16, max_ops=96, seed_base=3,
                         seed_prefix="plan")
    profile = profile_corpus(hists, spec)
    assert profile.sub_max_ops > 0
    plan = plan_search(spec, profile, platform="cpu")
    assert plan.decompose_keys
    assert any("decompose_keys=on" in w for w in plan.why)


def test_planner_refuses_short_histories_and_stamps_why():
    """Equal buckets = the split only adds lanes: gate off, why says so."""
    from qsm_tpu.search.planner import plan_search, profile_corpus

    spec = KvSpec(n_keys=2, n_values=4)
    hists = build_corpus(spec, (AtomicKvSUT, StaleCacheKvSUT), n=6,
                         n_pids=4, max_ops=10, seed_base=3,
                         seed_prefix="short")
    plan = plan_search(spec, profile_corpus(hists, spec), platform="cpu")
    assert not plan.decompose_keys
    assert any("decompose_keys=off" in w for w in plan.why)


def test_planner_refusal_why_for_invalid_projection():
    from qsm_tpu.analysis.fixtures import NonTotalPartitionKvSpec
    from qsm_tpu.search.planner import plan_search, profile_corpus

    spec = NonTotalPartitionKvSpec()
    hists = build_corpus(spec, (AtomicKvSUT, StaleCacheKvSUT), n=4,
                         n_pids=8, max_ops=48, seed_base=3,
                         seed_prefix="nt")
    plan = plan_search(spec, profile_corpus(hists, spec), platform="cpu")
    assert not plan.decompose_keys
    assert any("decompose_keys=off (refused" in w for w in plan.why)


def test_build_backend_wraps_pcomp_outermost_with_parity():
    from qsm_tpu.search.planner import (build_backend, plan_search,
                                        profile_corpus)

    spec = KvSpec(n_keys=4, n_values=4)
    hists = build_corpus(spec, (AtomicKvSUT, StaleCacheKvSUT), n=8,
                         n_pids=8, max_ops=48, seed_base=11,
                         seed_prefix="bb")
    plan = plan_search(spec, profile_corpus(hists, spec), platform="cpu")
    assert plan.decompose_keys
    backend = build_backend(spec, plan, budget=100_000)
    assert isinstance(backend, PComp)
    direct = WingGongCPU(memo=True).check_histories(spec, hists)
    got = np.asarray(backend.check_histories(spec, hists))
    und = got == int(Verdict.BUDGET_EXCEEDED)
    # the device kernel may defer under budget; resolved like the
    # property layer does, verdicts must be bit-identical
    assert (np.where(und, direct, got) == direct).all()


def test_split_gain_gate():
    spec = KvSpec(n_keys=8, n_values=4)
    hists = build_corpus(spec, (AtomicKvSUT,), n=1, n_pids=16,
                         max_ops=96, seed_base=1, seed_prefix="g")
    assert split_gain(spec, hists[0])
    short = build_corpus(spec, (AtomicKvSUT,), n=1, n_pids=2,
                         max_ops=6, seed_base=1, seed_prefix="g2")
    assert not split_gain(spec, short[0])


# ---------------------------------------------------------------------------
# pcomp_* accounting
# ---------------------------------------------------------------------------

def test_pcomp_counters_ride_stats_and_timings():
    spec = KvSpec(n_keys=4, n_values=4)
    hists = _corpus(spec, (AtomicKvSUT, StaleCacheKvSUT), n=8, pids=8,
                    ops=24)
    pc = PComp(spec)
    pc.check_histories(spec, hists)
    st = pc.search_stats()
    assert st.pcomp_split > 0
    assert st.pcomp_subs >= st.pcomp_split
    assert st.pcomp_max_sub > 0
    compact = st.to_compact()
    assert compact["pcs"] == st.pcomp_split
    assert compact["pcn"] == st.pcomp_subs
    assert compact["pcm"] == st.pcomp_max_sub
    t = st.to_timings()
    assert t["pcomp_subs"] == float(st.pcomp_subs)
    assert "pcomp_recombine_ms" in t


def test_stats_absorb_max_merges_pcomp_max_sub():
    from qsm_tpu.search.stats import SearchStats, stats_delta

    a = SearchStats(pcomp_split=1, pcomp_subs=4, pcomp_max_sub=9)
    b = SearchStats(pcomp_split=2, pcomp_subs=3, pcomp_max_sub=17)
    a.absorb(b)
    assert (a.pcomp_split, a.pcomp_subs, a.pcomp_max_sub) == (3, 7, 17)
    # delta keeps `after`'s maximum (a max has no per-run difference)
    d = stats_delta(SearchStats(pcomp_subs=10, pcomp_max_sub=33),
                    SearchStats(pcomp_subs=4, pcomp_max_sub=33))
    assert d.pcomp_subs == 6 and d.pcomp_max_sub == 33


def test_property_run_carries_pcomp_timings():
    from qsm_tpu import PropertyConfig, prop_concurrent

    spec = KvSpec(n_keys=4, n_values=4)
    cfg = PropertyConfig(n_trials=8, n_pids=8, max_ops=24, seed=5)
    res = prop_concurrent(spec, AtomicKvSUT(spec), cfg,
                          backend=PComp(spec), oracle=WingGongCPU())
    assert res.ok, res.counterexample
    assert res.timings.get("pcomp_subs", 0) > 0


def test_declarative_kv_matches_legacy_split():
    """The KeyProj-derived split must produce exactly the sub-histories
    the old hand-written partition_key/project_op produced."""
    spec = KvSpec(n_keys=4, n_values=4)
    hists = _corpus(spec, (AtomicKvSUT, StaleCacheKvSUT), n=6, pids=8,
                    ops=24)
    for h in hists:
        subs = split_history(spec, h)
        for key, sub in subs.items():
            for op in sub.ops:
                assert 0 <= key < spec.n_keys
                # projected ops are register ops: READ arg 0 / WRITE v
                assert op.cmd in (0, 1)
                if op.cmd == 0:
                    assert op.arg == 0
                else:
                    assert 0 <= op.arg < spec.n_values
