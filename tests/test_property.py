"""End-to-end slice (M4): correct impl passes, racy impls fail and shrink to
minimal counterexamples — the reference's correct-vs-racy regression asset
(SURVEY.md §4, BASELINE.json:7)."""

import pytest

from qsm_tpu import (PropertyConfig, Verdict, WingGongCPU, check_one,
                     prop_concurrent, replay)
from qsm_tpu.models.register import (AtomicRegisterSUT, RacyCachedRegisterSUT,
                                     ReplicatedRegisterSUT, RegisterSpec)

SPEC = RegisterSpec(n_values=5)
CFG = PropertyConfig(n_trials=60, n_pids=2, max_ops=12, seed=1234)


def test_atomic_register_passes():
    res = prop_concurrent(SPEC, AtomicRegisterSUT(), CFG)
    assert res.ok, res.counterexample


def test_racy_cached_register_fails_and_shrinks():
    res = prop_concurrent(SPEC, RacyCachedRegisterSUT(), CFG)
    assert not res.ok, "racy cached register was never caught"
    cx = res.counterexample
    # minimal counterexample needs at most ~3 ops (read-cache, write, read)
    assert len(cx.program) <= 4, cx.program
    # the shrunk history must itself be a violation
    assert check_one(WingGongCPU(), SPEC, cx.history) == Verdict.VIOLATION


def test_replicated_register_fails():
    cfg = PropertyConfig(n_trials=300, n_pids=2, max_ops=12, seed=7)
    res = prop_concurrent(SPEC, ReplicatedRegisterSUT(), cfg)
    assert not res.ok, "divergent replicas were never caught"
    assert check_one(WingGongCPU(), SPEC, res.counterexample.history) \
        == Verdict.VIOLATION


def test_replay_reproduces_counterexample_trial():
    res = prop_concurrent(SPEC, RacyCachedRegisterSUT(), CFG)
    assert not res.ok
    h = replay(SPEC, RacyCachedRegisterSUT(), res.counterexample.trial_seed,
               CFG)
    v = check_one(WingGongCPU(), SPEC, h)
    assert v == Verdict.VIOLATION


def test_multi_schedule_detection_power():
    """k seeded schedules per program multiply race exposure: the racy
    register must be caught within 100 trials for every seed 0..2 (round-1
    weakness: one schedule per program needed 155 trials on some seeds)."""
    for seed in range(3):
        cfg = PropertyConfig(n_trials=100, n_pids=2, max_ops=12, seed=seed)
        res = prop_concurrent(SPEC, RacyCachedRegisterSUT(), cfg)
        assert not res.ok, f"seed {seed}: violation not found"
    assert res.schedules_run >= res.trials_run
    assert 0 < res.distinct_histories <= res.schedules_run
    assert 0 < res.schedule_diversity <= 1


def test_schedule_seed_replay_roundtrip():
    """A counterexample found on schedule j>0 replays bit-identically from
    its '#j'-suffixed seed key."""
    cfg = PropertyConfig(n_trials=100, n_pids=2, max_ops=12, seed=2,
                         schedules_per_program=4)
    res = prop_concurrent(SPEC, RacyCachedRegisterSUT(), cfg)
    assert not res.ok
    cx = res.counterexample
    h = replay(SPEC, RacyCachedRegisterSUT(), cx.trial_seed, cfg)
    fields = lambda hh: [(o.pid, o.cmd, o.arg, o.resp, o.invoke_time,
                          o.response_time) for o in hh.ops]
    # the replayed trial history contains the violation pre-shrink; at
    # minimum it must reproduce deterministically and be a violation
    h2 = replay(SPEC, RacyCachedRegisterSUT(), cx.trial_seed, cfg)
    assert fields(h) == fields(h2)
    assert check_one(WingGongCPU(), SPEC, h) == Verdict.VIOLATION
