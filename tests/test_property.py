"""End-to-end slice (M4): correct impl passes, racy impls fail and shrink to
minimal counterexamples — the reference's correct-vs-racy regression asset
(SURVEY.md §4, BASELINE.json:7)."""

import pytest

from qsm_tpu import (PropertyConfig, Verdict, WingGongCPU, check_one,
                     prop_concurrent, replay)
from qsm_tpu.models.register import (AtomicRegisterSUT, RacyCachedRegisterSUT,
                                     ReplicatedRegisterSUT, RegisterSpec)

SPEC = RegisterSpec(n_values=5)
CFG = PropertyConfig(n_trials=60, n_pids=2, max_ops=12, seed=1234)


def test_atomic_register_passes():
    res = prop_concurrent(SPEC, AtomicRegisterSUT(), CFG)
    assert res.ok, res.counterexample


def test_racy_cached_register_fails_and_shrinks():
    res = prop_concurrent(SPEC, RacyCachedRegisterSUT(), CFG)
    assert not res.ok, "racy cached register was never caught"
    cx = res.counterexample
    # minimal counterexample needs at most ~3 ops (read-cache, write, read)
    assert len(cx.program) <= 4, cx.program
    # the shrunk history must itself be a violation
    assert check_one(WingGongCPU(), SPEC, cx.history) == Verdict.VIOLATION


def test_replicated_register_fails():
    cfg = PropertyConfig(n_trials=300, n_pids=2, max_ops=12, seed=7)
    res = prop_concurrent(SPEC, ReplicatedRegisterSUT(), cfg)
    assert not res.ok, "divergent replicas were never caught"
    assert check_one(WingGongCPU(), SPEC, res.counterexample.history) \
        == Verdict.VIOLATION


def test_replay_reproduces_counterexample_trial():
    res = prop_concurrent(SPEC, RacyCachedRegisterSUT(), CFG)
    assert not res.ok
    h = replay(SPEC, RacyCachedRegisterSUT(), res.counterexample.trial_seed,
               CFG)
    v = check_one(WingGongCPU(), SPEC, h)
    assert v == Verdict.VIOLATION
