"""End-to-end slice (M4): correct impl passes, racy impls fail and shrink to
minimal counterexamples — the reference's correct-vs-racy regression asset
(SURVEY.md §4, BASELINE.json:7)."""

import pytest

from qsm_tpu import (PropertyConfig, Verdict, WingGongCPU, check_one,
                     prop_concurrent, replay)
from qsm_tpu.models.register import (AtomicRegisterSUT, RacyCachedRegisterSUT,
                                     ReplicatedRegisterSUT, RegisterSpec)

SPEC = RegisterSpec(n_values=5)
CFG = PropertyConfig(n_trials=60, n_pids=2, max_ops=12, seed=1234)


def test_atomic_register_passes():
    res = prop_concurrent(SPEC, AtomicRegisterSUT(), CFG)
    assert res.ok, res.counterexample


def test_racy_cached_register_fails_and_shrinks():
    res = prop_concurrent(SPEC, RacyCachedRegisterSUT(), CFG)
    assert not res.ok, "racy cached register was never caught"
    cx = res.counterexample
    # minimal counterexample needs at most ~3 ops (read-cache, write, read)
    assert len(cx.program) <= 4, cx.program
    # the shrunk history must itself be a violation
    assert check_one(WingGongCPU(), SPEC, cx.history) == Verdict.VIOLATION


def test_replicated_register_fails():
    cfg = PropertyConfig(n_trials=300, n_pids=2, max_ops=12, seed=7)
    res = prop_concurrent(SPEC, ReplicatedRegisterSUT(), cfg)
    assert not res.ok, "divergent replicas were never caught"
    assert check_one(WingGongCPU(), SPEC, res.counterexample.history) \
        == Verdict.VIOLATION


def test_replay_reproduces_counterexample_trial():
    res = prop_concurrent(SPEC, RacyCachedRegisterSUT(), CFG)
    assert not res.ok
    h = replay(SPEC, RacyCachedRegisterSUT(), res.counterexample.trial_seed,
               CFG)
    v = check_one(WingGongCPU(), SPEC, h)
    assert v == Verdict.VIOLATION


def test_multi_schedule_detection_power():
    """k seeded schedules per program multiply race exposure: the racy
    register must be caught within 100 trials for every seed 0..2 (round-1
    weakness: one schedule per program needed 155 trials on some seeds)."""
    for seed in range(3):
        cfg = PropertyConfig(n_trials=100, n_pids=2, max_ops=12, seed=seed)
        res = prop_concurrent(SPEC, RacyCachedRegisterSUT(), cfg)
        assert not res.ok, f"seed {seed}: violation not found"
    assert res.schedules_run >= res.trials_run
    assert 0 < res.distinct_histories <= res.schedules_run
    assert 0 < res.schedule_diversity <= 1


def test_schedule_seed_replay_roundtrip():
    """A counterexample found on schedule j>0 replays bit-identically from
    its '#j'-suffixed seed key."""
    cfg = PropertyConfig(n_trials=100, n_pids=2, max_ops=12, seed=2,
                         schedules_per_program=4)
    res = prop_concurrent(SPEC, RacyCachedRegisterSUT(), cfg)
    assert not res.ok
    cx = res.counterexample
    h = replay(SPEC, RacyCachedRegisterSUT(), cx.trial_seed, cfg)
    fields = lambda hh: [(o.pid, o.cmd, o.arg, o.resp, o.invoke_time,
                          o.response_time) for o in hh.ops]
    # the replayed trial history contains the violation pre-shrink; at
    # minimum it must reproduce deterministically and be a violation
    h2 = replay(SPEC, RacyCachedRegisterSUT(), cx.trial_seed, cfg)
    assert fields(h) == fields(h2)
    assert check_one(WingGongCPU(), SPEC, h) == Verdict.VIOLATION


def test_trial_batch_grouping_preserves_semantics():
    """trial_batch groups G trials into one backend batch for device
    efficiency (VERDICT round 2 #8); the verdict, counterexample trial,
    seed, and shrunk program must be identical to the ungrouped run."""
    import dataclasses

    from qsm_tpu.models import CasSpec, RacyCasSUT

    spec = CasSpec()
    base = PropertyConfig(n_trials=40, n_pids=4, max_ops=16, seed=9)
    plain = prop_concurrent(spec, RacyCasSUT(spec), base)
    grouped = prop_concurrent(
        spec, RacyCasSUT(spec),
        dataclasses.replace(base, trial_batch=16))
    assert not plain.ok and not grouped.ok
    assert grouped.counterexample.trial == plain.counterexample.trial
    assert grouped.counterexample.trial_seed == plain.counterexample.trial_seed
    assert (grouped.counterexample.history.fingerprint()
            == plain.counterexample.history.fingerprint())
    # and a passing run stays passing with identical trial count
    from qsm_tpu.models import AtomicCasSUT

    ok_plain = prop_concurrent(
        spec, AtomicCasSUT(spec),
        dataclasses.replace(base, n_trials=20))
    ok_grouped = prop_concurrent(
        spec, AtomicCasSUT(spec),
        dataclasses.replace(base, n_trials=20, trial_batch=8))
    assert ok_plain.ok and ok_grouped.ok
    assert ok_grouped.trials_run == ok_plain.trials_run
    assert ok_grouped.histories_checked == ok_plain.histories_checked
    assert ok_grouped.timings.get("check", 0) > 0


def test_trial_batch_ramp_bounds_wasted_work():
    """An early violation under a wide trial_batch must not pay for a
    full-width group of generate/execute work (the measured regression:
    BENCH_E2E_r04 hybrid/racy 48.9 h/s at trial_batch=64 vs 75.5 at 1 —
    VERDICT.md round 4, "Next round" #7).  The group size ramps
    1,2,4,…,64, so the grouped run checks at most ~2× the ungrouped
    run's histories while producing the identical counterexample."""
    import dataclasses

    from qsm_tpu.models import CasSpec, RacyCasSUT

    spec = CasSpec()
    base = PropertyConfig(n_trials=200, n_pids=4, max_ops=16, seed=9)
    plain = prop_concurrent(spec, RacyCasSUT(spec), base)
    grouped = prop_concurrent(
        spec, RacyCasSUT(spec),
        dataclasses.replace(base, trial_batch=64))
    assert not plain.ok and not grouped.ok
    assert grouped.counterexample.trial == plain.counterexample.trial
    assert (grouped.counterexample.trial_seed
            == plain.counterexample.trial_seed)
    # ramp bound: wasted trial-phase work < trials already run, so the
    # grouped total can at most double the ungrouped total
    assert grouped.histories_checked <= 2 * plain.histories_checked


def test_default_oracle_is_native_when_available():
    from qsm_tpu.core.property import _default_oracle
    from qsm_tpu.models import CasSpec
    from qsm_tpu.native import CppOracle, native_available

    oracle = _default_oracle(CasSpec())
    if native_available():
        assert isinstance(oracle, CppOracle)
    else:
        assert isinstance(oracle, WingGongCPU)
