"""CAS register (config #3, BASELINE.json:9): correct impl passes, the
non-atomic read-compare-write impl loses updates and fails."""

from qsm_tpu import (PropertyConfig, Verdict, WingGongCPU, check_one,
                     generate_program, prop_concurrent, run_concurrent)
from qsm_tpu.models.cas import CAS, AtomicCasSUT, CasSpec, RacyCasSUT
from qsm_tpu.ops.jax_kernel import JaxTPU

SPEC = CasSpec(n_values=5)
CFG = PropertyConfig(n_trials=80, n_pids=8, max_ops=32, seed=5)


def test_atomic_cas_passes():
    res = prop_concurrent(SPEC, AtomicCasSUT(SPEC), CFG)
    assert res.ok, res.counterexample


def test_racy_cas_fails_and_shrinks():
    res = prop_concurrent(SPEC, RacyCasSUT(SPEC), CFG)
    assert not res.ok, "lost updates were never caught"
    cx = res.counterexample
    assert check_one(WingGongCPU(), SPEC, cx.history) == Verdict.VIOLATION
    # the minimal counterexample must still contain a CAS
    assert any(op.cmd == CAS for op in cx.program.ops), cx.program


def test_cas_backend_parity():
    from conftest import assert_backend_parity

    hists = []
    for seed in range(30):
        prog = generate_program(SPEC, seed=seed, n_pids=8, max_ops=24)
        for sut in (AtomicCasSUT(SPEC), RacyCasSUT(SPEC)):
            hists.append(run_concurrent(sut, prog, seed=f"c{seed}"))
    assert_backend_parity(SPEC, hists, JaxTPU(SPEC))
