"""Real-socket transport variant (SURVEY.md §5 comm backend row: the
reference's ``network-transport-tcp`` role).  The determinism contract is
transport-independent: histories over loopback TCP must be bit-identical
to in-memory ones, faults and all, because the scheduler owns every
ordering decision and the transport only carries bytes."""

import numpy as np

from qsm_tpu import WingGongCPU, generate_program, run_concurrent
from qsm_tpu.models import CasSpec, AtomicCasSUT, RacyCasSUT
from qsm_tpu.sched.scheduler import FaultPlan
from qsm_tpu.sched.transport import (InMemoryTransport, TcpLoopbackTransport,
                                     make_transport)


def _run(sut_cls, seed, transport=None, faults=None):
    spec = CasSpec()
    prog = generate_program(spec, seed=seed, n_pids=4, max_ops=16)
    return run_concurrent(sut_cls(spec), prog, seed=f"t{seed}",
                          faults=faults, transport=transport)


def test_tcp_history_bit_identical_to_memory():
    for seed in range(12):
        sut = AtomicCasSUT if seed % 2 else RacyCasSUT
        h_mem = _run(sut, seed)
        h_tcp = _run(sut, seed, transport="tcp")
        assert h_mem.fingerprint() == h_tcp.fingerprint(), seed


def test_tcp_deterministic_replay():
    a = _run(RacyCasSUT, 3, transport="tcp")
    b = _run(RacyCasSUT, 3, transport="tcp")
    assert a.fingerprint() == b.fingerprint()


def test_tcp_with_faults_matches_memory():
    faults = FaultPlan(p_drop=0.15, p_duplicate=0.1, p_delay=0.1)
    for seed in range(8):
        h_mem = _run(AtomicCasSUT, seed, faults=faults)
        h_tcp = _run(AtomicCasSUT, seed, transport="tcp",
                     faults=FaultPlan(p_drop=0.15, p_duplicate=0.1,
                                      p_delay=0.1))
        assert h_mem.fingerprint() == h_tcp.fingerprint(), seed


def test_tcp_frames_actually_traverse_sockets():
    spec = CasSpec()
    prog = generate_program(spec, seed=5, n_pids=4, max_ops=16)
    t = TcpLoopbackTransport()
    h = run_concurrent(RacyCasSUT(spec), prog, seed="frames", transport=t)
    assert t.frames > 0          # bytes really crossed the OS socket layer
    assert len(h.ops) > 0
    # a caller-passed INSTANCE stays the caller's (connection reuse across
    # runs is the point); only string-spec transports are run-owned
    assert t._conns
    h2 = run_concurrent(RacyCasSUT(spec), prog, seed="frames", transport=t)
    assert h2.fingerprint() == h.fingerprint()  # reuse, same determinism
    t.close()
    assert not t._conns


def test_tcp_large_payload_roundtrip_no_deadlock():
    """Frames larger than the loopback socket buffers must pump through
    the select-interleaved round-trip instead of deadlocking the single
    scheduler thread on sendall."""
    from qsm_tpu.sched.scheduler import Message

    t = TcpLoopbackTransport()
    try:
        big = b"x" * (8 << 20)  # 8 MB >> any default socket buffer
        msg = Message(src="a", dst="b", payload=big, uid=1)
        up = t.uplink(msg)
        assert up.payload == big
        down = t.downlink(Message(src="a", dst="b", payload=big, uid=2))
        assert down.payload == big
    finally:
        t.close()


def test_property_layer_over_tcp_finds_race():
    import dataclasses

    from qsm_tpu.core.property import PropertyConfig, prop_concurrent

    spec = CasSpec()
    cfg = PropertyConfig(n_trials=40, n_pids=4, max_ops=16, seed=9,
                         transport="tcp")
    res = prop_concurrent(spec, RacyCasSUT(spec), cfg)
    assert not res.ok
    # identical counterexample to the in-memory run
    res_mem = prop_concurrent(
        spec, RacyCasSUT(spec),
        dataclasses.replace(cfg, transport="memory"))
    assert (res.counterexample.history.fingerprint()
            == res_mem.counterexample.history.fingerprint())


def test_make_transport_validates():
    assert isinstance(make_transport("memory"), InMemoryTransport)
    t = make_transport("tcp")
    assert isinstance(t, TcpLoopbackTransport)
    t.close()
    try:
        make_transport("udp")
    except ValueError:
        pass
    else:
        raise AssertionError("unknown transport accepted")


def test_verdicts_over_tcp():
    spec = CasSpec()
    hists = [_run(RacyCasSUT, s, transport="tcp") for s in range(8)]
    v = WingGongCPU(memo=True).check_histories(spec, hists)
    assert len(np.unique(v)) >= 1  # decided without error
