"""The search-efficiency plane (qsm_tpu/search): verdict invariance and
the iterations-per-history regression gate.

Two contracts, both from the package docstring:

* NOTHING in qsm_tpu/search may change a verdict — ordering, memo-slot
  policy, planner schedules, and decomposition change iteration/node
  counts only.  Pinned here bit-identically across every engine
  (oracle, native, XLA kernel, Pallas kernel, hybrid, segdc) on the
  8-family registry corpora.
* The planner's CPU policy must beat the hand-tuned round-3..5 driver by
  ≥10× iters-per-history on the CAS-32 bench corpus (the acceptance gate
  ISSUE 2 is judged on; tools/bench_search.py commits the full artifact).
"""

import dataclasses
import json

import numpy as np
import pytest

from qsm_tpu import Verdict, WingGongCPU, verify_witness
from qsm_tpu.models import AtomicCasSUT, CasSpec, RacyCasSUT
from qsm_tpu.models.registry import MODELS
from qsm_tpu.ops.jax_kernel import JaxTPU
from qsm_tpu.search import (SearchStats, collect_search_stats,
                            ordering_table, permute_history, plan_search,
                            profile_corpus)
from qsm_tpu.search.planner import build_backend
from qsm_tpu.utils.corpus import build_corpus

SPEC = CasSpec()


def _cas_corpus(n=24, n_pids=4, max_ops=16, seed_base=0):
    return build_corpus(SPEC, (AtomicCasSUT, RacyCasSUT), n=n,
                        n_pids=n_pids, max_ops=max_ops,
                        seed_base=seed_base, seed_prefix="search")


def _decided_equal(ref, got):
    """Bit-identical where both engines decided (BUDGET_EXCEEDED lanes are
    budget policy, not search policy — the honest-deferral contract)."""
    ref, got = np.asarray(ref), np.asarray(got)
    both = (ref != int(Verdict.BUDGET_EXCEEDED)) & \
           (got != int(Verdict.BUDGET_EXCEEDED))
    mism = [(i, int(ref[i]), int(got[i]))
            for i in np.nonzero(both & (ref != got))[0]]
    assert not mism, f"verdict drift under search policy: {mism[:10]}"


# ---------------------------------------------------------------------------
# ordering: selectivity semantics + permutation invariants
# ---------------------------------------------------------------------------

def test_cas_selectivity_ranks_constrained_ops_first():
    """CAS semantics, straight from the table: a write's postcondition
    holds everywhere (rank 1.0 — tried last), a read of one specific
    value holds in 1/n_values states (tried first)."""
    from qsm_tpu.models.cas import CAS as CAS_OP, READ, WRITE

    table = ordering_table(SPEC)
    assert table is not None
    n = SPEC.n_values
    for v in range(n):
        assert table.rank(WRITE, v, 0) == pytest.approx(1.0)
        assert table.rank(READ, 0, v) == pytest.approx(1 / n)
        # cas(old=v, new=0): succeeds only in state v, fails in the rest
        arg = SPEC.cas_arg(v, 0)
        assert table.rank(CAS_OP, arg, 1) == pytest.approx(1 / n)
        assert table.rank(CAS_OP, arg, 0) == pytest.approx((n - 1) / n)
    # out-of-domain responses are maximally constrained (rank 0: surface
    # the contradiction at depth 1)
    assert table.rank(READ, 0, 99) == 0.0


def test_permutation_is_deterministic_and_precedence_preserving():
    table = ordering_table(SPEC)
    for h in _cas_corpus(n=6):
        p1, p2 = table.permutation(h), table.permutation(h)
        assert (p1 == p2).all()
        assert sorted(p1) == list(range(len(h.ops)))
        ph = permute_history(h, p1)
        # timestamps ride along: the precedence partial order (as a set
        # of op-identity pairs) is untouched by array order
        ref = {(h.ops[a].invoke_time, h.ops[b].invoke_time)
               for a in range(len(h.ops)) for b in range(len(h.ops))
               if h.ops[a].response_time < h.ops[b].invoke_time}
        got = {(ph.ops[a].invoke_time, ph.ops[b].invoke_time)
               for a in range(len(ph.ops)) for b in range(len(ph.ops))
               if ph.ops[a].response_time < ph.ops[b].invoke_time}
        assert ref == got


def test_ordering_parity_every_registry_family():
    """Try-order cannot change a verdict — the DFS explores the same
    tree, differently.  All 8 families, memoised oracle, ordering on vs
    off, bit-identical (including families with no scalar domain, where
    ordering is a declared no-op)."""
    for name, entry in MODELS.items():
        spec = entry.make_spec()
        hists = build_corpus(
            spec, (entry.impls["atomic"], entry.impls["racy"]), n=8,
            n_pids=min(entry.default_pids, 4),
            max_ops=min(entry.default_ops, 16), seed_prefix="ordpar")
        base = WingGongCPU(memo=True)
        ordered = WingGongCPU(memo=True, ordering=True)
        ref = base.check_histories(spec, hists)
        got = ordered.check_histories(spec, hists)
        assert list(ref) == list(got), f"{name}: ordering changed verdicts"
        st = ordered.search_stats()
        assert st.histories >= len(hists)


def test_ordered_witnesses_still_verify():
    """Witness indices are mapped back through the permutation (the
    kernel's chosen stack indexes the PERMUTED array): every
    LINEARIZABLE witness must replay search-free against the ORIGINAL
    history."""
    dev = JaxTPU(SPEC, ordering=True)
    n_lin = 0
    for h in _cas_corpus(n=8, max_ops=12):
        v, w = dev.check_witness(SPEC, h)
        if v == Verdict.LINEARIZABLE and h.n_pending == 0:
            assert w is not None and verify_witness(SPEC, h, w), w
            n_lin += 1
    assert n_lin > 0, "witness sample vacuous"


# ---------------------------------------------------------------------------
# planner: policy shape + engine-by-engine verdict invariance
# ---------------------------------------------------------------------------

def test_plan_search_policies():
    corpus = _cas_corpus(n=8)
    profile = profile_corpus(corpus)
    assert profile.max_ops <= 16 and profile.n == 8
    cpu = plan_search(SPEC, profile, platform="cpu")
    assert cpu.name.startswith("cpu") and cpu.ordering
    # no crash region on CPU: every bucket gets the full-size memo table
    assert len(set(cpu.slots_for_batch.values())) == 1
    tpu = plan_search(SPEC, profile, platform="tpu")
    # the verified (batch × slots) safe region stands exactly as measured
    assert tpu.slots_for_batch == JaxTPU.MAX_SLOTS_FOR_BATCH
    assert tpu.chunk_schedule[0] <= 256  # early compaction via small chunk
    # first chunk always covers the corpus depth (a shorter one decides
    # nothing); the why trail is part of the artifact contract
    assert cpu.chunk_schedule[0] >= profile.max_ops
    assert any("ordering=on" in w for w in cpu.why)


def test_engine_parity_plan_on_off():
    """Decided verdicts are bit-identical with the search plane on and
    off, engine by engine: XLA kernel (hand / planned / ordered), Pallas
    kernel, hybrid, planned segdc composition — all against the memoised
    oracle reference."""
    corpus = _cas_corpus(n=16, max_ops=16)
    ref = WingGongCPU(memo=True).check_histories(SPEC, corpus)
    assert (np.asarray(ref) == int(Verdict.VIOLATION)).any(), "vacuous"

    profile = profile_corpus(corpus)
    plan = plan_search(SPEC, profile, platform="cpu")
    kernel_only = dataclasses.replace(plan, ordering=False, decompose=False)

    engines = {
        "hand": JaxTPU(SPEC),
        "planned_kernel": JaxTPU(SPEC, plan=kernel_only),
        "ordered": JaxTPU(SPEC, ordering=True),
        "planned_full": build_backend(SPEC, plan),
    }
    from qsm_tpu.ops.hybrid import HybridDevice
    engines["hybrid_planned"] = HybridDevice(SPEC, plan=kernel_only)
    from qsm_tpu.ops.pallas_kernel import PallasTPU
    engines["pallas_ordered"] = PallasTPU(SPEC, budget=4_000, mid_budget=0,
                                          rescue_budget=0, ordering=True)
    from qsm_tpu.native import CppOracle, native_available
    if native_available():
        engines["cpp"] = CppOracle(SPEC)
    for name, engine in engines.items():
        got = engine.check_histories(SPEC, corpus)
        _decided_equal(ref, got)
        st = collect_search_stats(engine)
        assert st is not None, f"{name} exposes no SearchStats"
        assert st.histories > 0, f"{name} stats empty"


# ---------------------------------------------------------------------------
# the regression gate: ≥10× iters-per-history on CAS-32
# ---------------------------------------------------------------------------

def test_iters_per_history_regression_cas32():
    """The acceptance pin: the planned checker needs ≥10× fewer lockstep
    iterations per history than the hand-tuned driver on the CAS-32
    bench corpus slice, at unchanged verdicts, with the host oracle's
    nodes/history reported side-by-side (the vs_best_host decomposition).
    The committed full-corpus artifact is BENCH_SEARCH_r06.json."""
    corpus = build_corpus(SPEC, (AtomicCasSUT, RacyCasSUT), n=128, n_pids=8,
                          max_ops=32, seed_base=1000, seed_prefix="bench")
    memo = WingGongCPU(memo=True)
    ref = memo.check_histories(SPEC, corpus)

    hand = JaxTPU(SPEC)
    _decided_equal(ref, hand.check_histories(SPEC, corpus))

    plan = plan_search(SPEC, profile_corpus(corpus), platform="cpu")
    planned = build_backend(SPEC, plan)
    _decided_equal(ref, planned.check_histories(SPEC, corpus))

    iph_hand = hand.search_stats().iters_per_history
    st = planned.search_stats()
    iph_planned = st.iters_per_history
    nph_memo = memo.search_stats().nodes_per_history
    assert iph_hand > 0 and iph_planned > 0
    ratio = iph_hand / iph_planned
    assert ratio >= 10.0, (
        f"iters/history regression: hand={iph_hand:.0f} "
        f"planned={iph_planned:.0f} ratio={ratio:.1f}x "
        f"(memo oracle denominator: {nph_memo:.0f} nodes/history)")
    # the composition reports both sides honestly: device iterations AND
    # the host nodes decomposition spent on middles/tails
    assert st.nodes_explored > 0 and st.segments_total > 0


# ---------------------------------------------------------------------------
# stats plumbing: record semantics, property layer, CLI
# ---------------------------------------------------------------------------

def test_search_stats_absorb_and_projections():
    a = SearchStats(engine="dev", histories=4, lockstep_iters=400,
                    memo_prunes=3)
    b = SearchStats(engine="tail", histories=2, nodes_explored=50,
                    ordering=True, plan="cpu-fine-v1")
    a.absorb(b)
    assert a.histories == 4  # wrappers count inputs once themselves
    assert a.nodes_explored == 50 and a.ordering and a.plan == "cpu-fine-v1"
    assert a.iters_per_history == pytest.approx(100.0)
    compact = a.to_compact()
    assert compact["iph"] == 100.0 and compact["ord"] == 1
    t = a.to_timings()
    assert all(isinstance(v, float) for v in t.values())
    assert t["search_memo_prunes"] == 3.0
    # delta over cumulative counters (per-run projection)
    from qsm_tpu.search.stats import stats_delta

    later = SearchStats(engine="dev", histories=10, lockstep_iters=1000,
                        memo_prunes=7, nodes_explored=50)
    d = stats_delta(later, a)
    assert (d.histories, d.lockstep_iters, d.memo_prunes) == (6, 600, 4)
    assert stats_delta(later, None) is later
    assert stats_delta(None, a) is None


def test_collect_search_stats_unwraps_combinators():
    class Wrapper:
        def __init__(self, inner):
            self.inner = inner
    memo = WingGongCPU(memo=True)
    memo.check_histories(SPEC, _cas_corpus(n=2))
    st = collect_search_stats(Wrapper(memo))
    assert st is not None and "wrapper" in st.engine
    assert collect_search_stats(object()) is None


def test_property_result_carries_search_timings():
    from qsm_tpu import PropertyConfig, prop_concurrent

    backend = WingGongCPU(memo=True)
    cfg = PropertyConfig(n_trials=20, n_pids=3, max_ops=10, seed=5)
    res = prop_concurrent(SPEC, RacyCasSUT(SPEC), cfg, backend=backend)
    assert "search_nodes_per_history" in res.timings
    assert res.timings["search_histories"] > 0
    # timings are PER-RUN even on a reused backend whose instance
    # counters are cumulative (stats_delta in prop_concurrent)
    res2 = prop_concurrent(SPEC, RacyCasSUT(SPEC), cfg, backend=backend)
    assert res2.timings["search_histories"] == res.timings["search_histories"]


def test_stats_cli_emits_one_json_document(capsys):
    from qsm_tpu.utils.cli import main

    assert main(["stats", "--model", "cas", "--backend", "cpu",
                 "--corpus", "8"]) == 0
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert doc["search_stats"]["histories"] >= 8
    assert doc["plan_for_corpus"]["name"].startswith("cpu")
    assert "pending_fraction" in doc["profile"]
