"""Generation-plane pins (ISSUE 17): determinism, construction-time
soundness, profile grammar, pool/loop discipline, checkpoint rails.

The contracts under test are the ones the fuzzer's value rests on:

* **determinism per path** — a (seed, GenProfile) pair reproduces a
  corpus bit-for-bit on the pure-Python table, and the jax table is
  self-deterministic (gen/core.py docstring: the two tables are NOT
  byte-identical to each other — different PRNG families);
* **linearizable by construction** — with ``p_adverse=0`` every
  generated history carries its own witness (completion order), so the
  memo oracle must call the whole batch LINEARIZABLE.  This is the
  soundness floor that makes a VIOLATION a signal, not noise;
* **profile grammar** — ``mutate`` moves exactly one knob (credit
  assignment stays legible), ``to_dict``/``from_dict`` round-trips,
  ``weights`` pads/floors so no command starves;
* **bounded campaign state** — ``SeedPool`` never exceeds its cap and
  keeps the best entry; the loop's kept-flips log is a tail window
  (the QSM-GEN-UNBOUNDED discipline, analysis/gen_passes.py);
* **checkpoint rails** — save/load round-trips pool + ``gen_*``
  counters via ``atomic_write_json``, and refuses a checkpoint written
  for a different spec.
"""

from __future__ import annotations

import random

import pytest

from qsm_tpu.gen import GenProfile, SeedPool, SteeringLoop, generate_batch
from qsm_tpu.gen.steer import _FLIP_KEEP, PoolSeed
from qsm_tpu.models.registry import MODELS
from qsm_tpu.ops.backend import Verdict
from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
from qsm_tpu.sched.runner import PENDING_T


def _fingerprint(histories):
    return tuple(tuple((o.pid, o.cmd, o.arg, o.resp, o.invoke_time,
                        o.response_time) for o in h.ops)
                 for h in histories)


# -- determinism -------------------------------------------------------

def test_py_path_is_bit_deterministic():
    spec = MODELS["rangeset"].make_spec()
    profile = GenProfile(n_pids=4, n_ops=24, key_skew=1.0,
                         p_pending=0.1, p_adverse=0.05)
    a = generate_batch(spec, profile, seed=7, n=8, path="py")
    b = generate_batch(spec, profile, seed=7, n=8, path="py")
    assert _fingerprint(a) == _fingerprint(b)
    # a different seed is a different corpus (the table actually feeds
    # the assembly; a constant stream would also pass the pin above)
    c = generate_batch(spec, profile, seed=8, n=8, path="py")
    assert _fingerprint(a) != _fingerprint(c)


def test_jax_path_is_self_deterministic():
    spec = MODELS["register"].make_spec()
    profile = GenProfile(n_pids=2, n_ops=12)
    a = generate_batch(spec, profile, seed=3, n=4, path="jax")
    b = generate_batch(spec, profile, seed=3, n=4, path="jax")
    assert _fingerprint(a) == _fingerprint(b)


def test_generated_histories_are_well_formed():
    spec = MODELS["semaphore"].make_spec()
    profile = GenProfile(n_pids=4, n_ops=24, overlap=0.8,
                         p_pending=0.2)
    for h in generate_batch(spec, profile, seed=5, n=8, path="py"):
        assert len(h.ops) <= profile.n_ops
        times = [o.invoke_time for o in h.ops]
        assert times == sorted(times)
        for o in h.ops:
            assert 0 <= o.cmd < spec.n_cmds
            assert 0 <= o.arg < spec.CMDS[o.cmd].n_args
            if o.response_time == PENDING_T:
                assert o.resp == -1
            else:
                assert 0 <= o.resp < spec.CMDS[o.cmd].n_resps


# -- construction-time soundness ---------------------------------------

@pytest.mark.parametrize("family", ["register", "rangeset", "semaphore"])
def test_zero_adverse_corpus_is_linearizable_by_construction(family):
    spec = MODELS[family].make_spec()
    profile = GenProfile(n_pids=4, n_ops=16, p_adverse=0.0,
                         p_pending=0.0)
    hists = generate_batch(spec, profile, seed=11, n=8, path="py")
    oracle = WingGongCPU(memo=True)
    verdicts = oracle.check_histories(spec, hists)
    assert all(int(v) == int(Verdict.LINEARIZABLE) for v in verdicts)


# -- profile grammar ---------------------------------------------------

def test_profile_round_trips_through_dict():
    p = GenProfile(op_mix=(1.0, 0.5, 2.0), key_skew=1.5, n_pids=6,
                   n_ops=32, overlap=0.7, p_pending=0.1,
                   p_adverse=0.02)
    assert GenProfile.from_dict(p.to_dict()) == p
    # defaults fill absent keys (checkpoint forward-compat)
    assert GenProfile.from_dict({}) == GenProfile()


def test_mutate_moves_exactly_one_knob():
    p = GenProfile(op_mix=(1.0, 1.0), key_skew=1.0, n_pids=4,
                   n_ops=24, overlap=0.5, p_pending=0.1,
                   p_adverse=0.05)
    for s in range(40):
        q = p.mutate(random.Random(s))
        diffs = [k for k, v in p.to_dict().items()
                 if q.to_dict()[k] != v]
        assert len(diffs) == 1, (s, diffs)


def test_mutate_respects_domain_bounds():
    p = GenProfile()
    rng = random.Random(0)
    for _ in range(300):
        p = p.mutate(rng)
        assert 2 <= p.n_pids <= 16
        assert 4 <= p.n_ops <= 128
        assert 0.0 <= p.key_skew <= 4.0
        assert 0.0 <= p.p_pending <= 0.3
        assert 0.0 <= p.p_adverse <= 0.5
        assert 0.05 <= p.overlap <= 0.95


def test_weights_pad_floor_and_normalize():
    p = GenProfile(op_mix=(0.0, 4.0))
    w = p.weights(3)
    assert len(w) == 3
    assert abs(sum(w) - 1.0) < 1e-9
    # the zero weight is floored, the missing third command padded —
    # no command is ever starved to exactly zero probability
    assert all(x > 0.0 for x in w)
    assert w[1] == max(w)


# -- bounded campaign state --------------------------------------------

def test_seed_pool_holds_its_cap_and_keeps_the_best():
    pool = SeedPool(cap=4)
    for i in range(50):
        pool.add(PoolSeed(profile=GenProfile(), seed=i, score=float(i)))
    assert len(pool) == 4
    assert pool.best().score == 49.0
    assert all(s.score >= 46.0 for s in pool._seeds)
    with pytest.raises(ValueError):
        SeedPool(cap=0)


def test_loop_counters_and_flip_tail_window():
    spec = MODELS["register"].make_spec()
    # ambient-violation profile: the tail window must engage
    profile = GenProfile(n_pids=2, n_ops=8, p_adverse=0.9)
    loop = SteeringLoop(spec, WingGongCPU(memo=True), profile=profile,
                        batch=32, seed=1, path="py")
    reports = loop.run(4)
    assert len(reports) == 4
    assert loop.stats.gen_feedback_rounds == 4
    assert loop.stats.gen_mutations == 4
    assert loop.stats.gen_seqs == 4 * 32
    assert loop.stats.gen_flips == sum(r["flips"] for r in reports)
    # far more flips happened than the window keeps
    assert loop.stats.gen_flips > _FLIP_KEEP
    assert len(loop.flip_histories) <= _FLIP_KEEP


# -- checkpoint rails --------------------------------------------------

def test_checkpoint_round_trip_and_spec_mismatch(tmp_path):
    spec = MODELS["rangeset"].make_spec()
    loop = SteeringLoop(spec, WingGongCPU(memo=True),
                        profile=GenProfile(n_pids=2, n_ops=8),
                        batch=4, seed=2, path="py")
    loop.run(2)
    ckpt = str(tmp_path / "steer.json")
    loop.save(ckpt)

    fresh = SteeringLoop(spec, WingGongCPU(memo=True),
                         profile=GenProfile(n_pids=2, n_ops=8),
                         batch=4, seed=2, path="py")
    assert fresh.load(ckpt)
    assert fresh.pool.to_dict() == loop.pool.to_dict()
    assert fresh.stats.gen_seqs == loop.stats.gen_seqs
    assert fresh.stats.gen_flips == loop.stats.gen_flips
    assert fresh.stats.gen_feedback_rounds == 2
    assert fresh._next_seed == loop._next_seed

    other = SteeringLoop(MODELS["register"].make_spec(),
                         WingGongCPU(memo=True), batch=4, path="py")
    with pytest.raises(ValueError, match="checkpoint is for spec"):
        other.load(ckpt)
    assert not fresh.load(str(tmp_path / "absent.json"))
