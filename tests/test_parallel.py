"""Mesh batch-sharding: the same kernel, sharded over the 8-device CPU mesh
(SURVEY.md §5 comm backend — batch-axis DP via NamedSharding; the driver's
dryrun_multichip exercises the same path).  The helpers now live in
``qsm_tpu.mesh`` (ISSUE 19); ``qsm_tpu.parallel`` stays as a deprecation
re-export and is pinned as such at the bottom.  Full mesh-substrate
coverage (parity across shapes, planner buckets, batcher targets) is
tests/test_mesh.py."""

import numpy as np
import pytest

from qsm_tpu import generate_program, run_concurrent
from qsm_tpu.mesh import batch_sharding, make_mesh
from qsm_tpu.models import AtomicCasSUT, CasSpec, RacyCasSUT
from qsm_tpu.ops.jax_kernel import JaxTPU


def _corpus(spec, n):
    hists = []
    for seed in range(n):
        prog = generate_program(spec, seed=seed, n_pids=4, max_ops=16)
        sut = (AtomicCasSUT if seed % 2 == 0 else RacyCasSUT)(spec)
        hists.append(run_concurrent(sut, prog, seed=f"m{seed}"))
    return hists


def test_sharded_backend_matches_unsharded():
    spec = CasSpec()
    hists = _corpus(spec, 32)
    mesh = make_mesh(8)
    plain = JaxTPU(spec)
    sharded = JaxTPU(spec, sharding=batch_sharding(mesh))
    a = plain.check_histories(spec, hists)
    b = sharded.check_histories(spec, hists)
    assert (a == b).all(), list(zip(a.tolist(), b.tolist()))


@pytest.mark.slow
def test_sharded_compaction_parity():
    """Device-side lane compaction under a mesh: a corpus big enough to
    retire lanes across batch buckets must compact on-device (the jitted
    gather runs on sharded carries) and keep verdict parity with the
    unsharded driver."""
    spec = CasSpec()
    hists = _corpus(spec, 80)
    mesh = make_mesh(8)
    # small chunks retire the easy lanes over several rounds, forcing a
    # batch-bucket shrink (and so the on-device gather) mid-run
    sharded = JaxTPU(spec, sharding=batch_sharding(mesh))
    sharded.CHUNK_SCHEDULE = (16, 64)
    b = sharded.check_histories(spec, hists)
    assert sharded.compactions > 0, "corpus must exercise compaction"
    plain = JaxTPU(spec)
    plain.CHUNK_SCHEDULE = (16, 64)
    a = plain.check_histories(spec, hists)
    assert (a == b).all()


def test_sharded_inputs_actually_span_devices():
    import jax

    spec = CasSpec()
    mesh = make_mesh(8)
    sharding = batch_sharding(mesh)
    # place a batch-bucket-sized array and confirm it spans all 8 devices
    arr = jax.device_put(np.zeros((64, 12), np.int32), sharding)
    assert len({d for d in arr.sharding.device_set}) == 8


def test_make_mesh_subset():
    mesh = make_mesh(4)
    assert mesh.devices.shape == (4,)
    assert mesh.axis_names == ("batch",)


def test_make_mesh_2d_and_hierarchical_batch_sharding():
    import jax

    from qsm_tpu.mesh import make_mesh_2d

    mesh = make_mesh_2d(2, 4)
    assert mesh.devices.shape == (2, 4)
    assert mesh.axis_names == ("host", "batch")
    sharding = batch_sharding(mesh)  # dim 0 over BOTH axes
    arr = jax.device_put(np.zeros((64, 12), np.int32), sharding)
    assert len(arr.sharding.device_set) == 8
    # each device holds a 64/8 slice of the batch
    assert arr.addressable_shards[0].data.shape == (8, 12)


def test_init_distributed_noop_without_coordinator(monkeypatch):
    from qsm_tpu.mesh import init_distributed

    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert init_distributed() is False  # single-host: no-op by design


def test_parallel_package_is_a_deprecation_reexport():
    """``qsm_tpu.parallel`` survives ONLY as a thin re-export of
    ``qsm_tpu.mesh`` (ISSUE 19 satellite: no second copy of mesh logic)
    — the names must be the SAME objects, and the package must say it is
    deprecated."""
    import qsm_tpu.mesh as mesh
    import qsm_tpu.parallel as parallel

    for name in ("batch_sharding", "init_distributed", "make_mesh",
                 "make_mesh_2d", "replicated_sharding"):
        assert getattr(parallel, name) is getattr(mesh, name), name
    assert "DEPRECATED" in (parallel.__doc__ or "")
    # the old implementation module is gone, not shadowed
    with pytest.raises(ImportError):
        import qsm_tpu.parallel.mesh  # noqa: F401
