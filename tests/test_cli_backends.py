"""CLI backend construction — every ``--backend`` choice builds the
composition it names (VERDICT.md round 2, "What's weak" #6: the tpu CLI
paths had no coverage; the probe is mocked healthy so the wiring is
exercised without a chip — construction never touches jax devices, the
backends are lazy)."""

import pytest

from qsm_tpu.models import CasSpec, QueueSpec
from qsm_tpu.utils import cli as cli_mod
from qsm_tpu.utils.cli import _BACKENDS, _make_backend
from qsm_tpu.utils.device import Probe


@pytest.fixture
def healthy_probe(monkeypatch):
    # this test process imported jax pinned to the CPU platform (conftest),
    # which _ensure_device_reachable rightly refuses before it ever probes;
    # bypass the gate here — its probe handling is exercised separately by
    # test_device_gate_follows_probe below
    monkeypatch.setattr(cli_mod, "_ensure_device_reachable",
                        lambda timeout_s=45.0: None)


def test_device_gate_follows_probe(monkeypatch):
    """With the cpu-pin checks out of the way (jax 'unimported', env
    clear), _ensure_device_reachable's outcome is exactly the probe's."""
    import sys as _sys

    import qsm_tpu.utils.device as device

    monkeypatch.delitem(_sys.modules, "jax", raising=False)
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setattr(
        device, "probe_default_backend",
        lambda timeout_s=45.0: Probe(True, "tpu", "tpu 1 TPU v5 lite0"))
    cli_mod._ensure_device_reachable()  # healthy: no raise
    monkeypatch.setattr(
        device, "probe_default_backend",
        lambda timeout_s=45.0: Probe(False, "none", "wedged (test)"))
    with pytest.raises(SystemExit, match="no accelerator"):
        cli_mod._ensure_device_reachable()


def test_fuzz_device_backend_is_probe_gated():
    """`fuzz --backends device` must refuse on a cpu-pinned process / a
    wedged tunnel exactly like `--backend tpu` — constructing JaxTPU
    bare would hang the first in-process jax.devices() forever
    (regression: the pre-guard CLI wedged a soak run)."""
    from qsm_tpu.utils.cli import main

    # this test process IS cpu-pinned (conftest), which the gate refuses
    with pytest.raises(SystemExit, match="pinned to the CPU platform"):
        main(["fuzz", "--specs", "1", "--histories", "2",
              "--backends", "device"])


def test_every_backend_choice_constructs(healthy_probe):
    from qsm_tpu.native import CppOracle
    from qsm_tpu.ops.hybrid import HybridDevice
    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.ops.pallas_kernel import PallasTPU
    from qsm_tpu.ops.pcomp import PComp
    from qsm_tpu.ops.router import AutoDevice
    from qsm_tpu.ops.rootsplit import RootSplit
    from qsm_tpu.ops.segdc import SegDC
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU

    from qsm_tpu.models import KvSpec

    # pcomp variants need a partition-key spec (KV); the rest take any
    want = {
        "cpu": (WingGongCPU, QueueSpec),
        "cpp": (CppOracle, QueueSpec),
        "tpu": (JaxTPU, QueueSpec),
        "pcomp": (PComp, KvSpec),
        "pcomp-cpp": (PComp, KvSpec),
        "pcomp-tpu": (PComp, KvSpec),
        "segdc": (SegDC, QueueSpec),
        "segdc-cpp": (SegDC, QueueSpec),
        "segdc-tpu": (SegDC, QueueSpec),
        "rootsplit": (RootSplit, QueueSpec),
        "rootsplit-tpu": (RootSplit, QueueSpec),
        # auto = fastest exact host checker (native here: toolchain baked)
        "auto": (CppOracle, QueueSpec),
        "auto-tpu": (AutoDevice, QueueSpec),
        "hybrid-tpu": (HybridDevice, QueueSpec),
        # pallas covers scalar-table specs only — constructed on CAS
        "pallas-tpu": (PallasTPU, CasSpec),
    }
    assert set(want) == set(_BACKENDS)
    for name, (ty, mk_spec) in want.items():
        b = _make_backend(name, mk_spec())
        assert isinstance(b, ty), name

    # the composites really wire the inner they name
    b = _make_backend("segdc-tpu", CasSpec())
    assert isinstance(b.inner, JaxTPU)
    assert b.device_final  # final segments batch on the device
    b = _make_backend("segdc-cpp", CasSpec())
    assert isinstance(b.inner, CppOracle)
    b = _make_backend("pcomp-tpu", KvSpec())
    assert isinstance(b.inner, JaxTPU)
    b = _make_backend("rootsplit-tpu", CasSpec())
    assert isinstance(b.inner, JaxTPU)
    assert not b.eager  # the shipped default is hard-tail escalation
    b = _make_backend("auto-tpu", CasSpec())
    assert isinstance(b.plain, JaxTPU)  # router over the device kernel
    b = _make_backend("auto-tpu", KvSpec())
    assert b.pcomp is not None  # partitionable specs decompose per key
    b = _make_backend("hybrid-tpu", CasSpec())
    assert isinstance(b.device, JaxTPU)  # device majority + host tail
    assert isinstance(b.tail, CppOracle)


def test_unknown_backend_refused():
    with pytest.raises(SystemExit):
        _make_backend("gpu", CasSpec())


def test_pcomp_refuses_non_decomposable_spec():
    from qsm_tpu.ops.pcomp import PComp

    with pytest.raises(ValueError, match="decomposable"):
        PComp(QueueSpec())


def test_bench_unroll_flag(capsys):
    """--unroll is accepted and applied to any kernel the backend wraps
    (no-op for host backends); auto stays the default."""
    from qsm_tpu.utils.cli import main

    assert main(["bench", "--model", "cas", "--backend", "cpu",
                 "--corpus", "8", "--unroll", "4"]) == 0
    out = capsys.readouterr().out
    assert '"histories": 8' in out
