"""Bitmask set (extra model family): the atomic impl passes, the
check-then-act add races and fails; verdict parity across the Python
oracle, the native C++ table kernel, and the device kernel's step-table
path (the scalar-state spec rides all three fast paths at once)."""

import numpy as np

from qsm_tpu import (PropertyConfig, Verdict, WingGongCPU, check_one,
                     generate_program, prop_concurrent, run_concurrent)
from qsm_tpu.core.spec import compile_step_table
from qsm_tpu.models.set import (ADD, AtomicSetSUT, RacyCheckThenActSetSUT,
                                SetSpec)
from qsm_tpu.native import CppOracle
from qsm_tpu.ops.jax_kernel import JaxTPU

SPEC = SetSpec(n_keys=4)
CFG = PropertyConfig(n_trials=80, n_pids=4, max_ops=24, seed=11)


def test_step_table_matches_step_jax():
    """Exhaustive py/jax step agreement over the full scalar domain."""
    import jax.numpy as jnp

    trans, ok = compile_step_table(SPEC, 1 << SPEC.n_keys)
    for s in range(1 << SPEC.n_keys):
        for c, sig in enumerate(SPEC.CMDS):
            for a in range(sig.n_args):
                for r in range(sig.n_resps):
                    ns, good = SPEC.step_jax(
                        jnp.asarray([s], jnp.int32), jnp.int32(c),
                        jnp.int32(a), jnp.int32(r))
                    assert int(ns[0]) == trans[s, c, a, r], (s, c, a, r)
                    assert bool(good) == ok[s, c, a, r], (s, c, a, r)


def test_atomic_set_passes():
    res = prop_concurrent(SPEC, AtomicSetSUT(SPEC), CFG)
    assert res.ok, res.counterexample


def test_racy_set_fails_and_shrinks():
    res = prop_concurrent(SPEC, RacyCheckThenActSetSUT(SPEC), CFG)
    assert not res.ok, "double-insert TOCTOU was never caught"
    cx = res.counterexample
    assert check_one(WingGongCPU(), SPEC, cx.history) == Verdict.VIOLATION
    # the minimal counterexample must still contain an ADD
    assert any(op.cmd == ADD for op in cx.program.ops), cx.program


def test_set_backend_parity():
    from conftest import assert_backend_parity

    hists = []
    for seed in range(30):
        prog = generate_program(SPEC, seed=seed, n_pids=4, max_ops=20)
        for sut in (AtomicSetSUT(SPEC), RacyCheckThenActSetSUT(SPEC)):
            hists.append(run_concurrent(sut, prog, seed=f"s{seed}"))
    cpu = assert_backend_parity(SPEC, hists, JaxTPU(SPEC))

    cpp = CppOracle(SPEC)
    got = cpp.check_histories(SPEC, hists)
    np.testing.assert_array_equal(got, cpu)
    assert cpp.native_histories == len(hists)  # no silent fallback
