"""Deterministic scheduler tests: replay contract, interleaving diversity,
fault hooks (SURVEY.md §4 'seeded determinism tests for the scheduler')."""

from qsm_tpu import (FaultPlan, Program, Recv, Scheduler, Send,
                     generate_program, run_concurrent)
from qsm_tpu.core.generator import ProgOp
from qsm_tpu.models.register import (READ, WRITE, AtomicRegisterSUT,
                                     RacyCachedRegisterSUT,
                                     ReplicatedRegisterSUT, RegisterSpec)

SPEC = RegisterSpec(n_values=5)


def _hist_key(h):
    return tuple((o.pid, o.cmd, o.arg, o.resp, o.invoke_time, o.response_time)
                 for o in h.ops)


def test_same_seed_identical_history():
    prog = generate_program(SPEC, seed=3, n_pids=2, max_ops=10)
    for sut_cls in (AtomicRegisterSUT, RacyCachedRegisterSUT,
                    ReplicatedRegisterSUT):
        a = run_concurrent(sut_cls(), prog, seed="s1")
        b = run_concurrent(sut_cls(), prog, seed="s1")
        assert _hist_key(a) == _hist_key(b), sut_cls.__name__


def test_different_seeds_explore_interleavings():
    # Two concurrent writers + readers: delivery order varies with seed.
    prog = Program((ProgOp(0, WRITE, 1), ProgOp(1, WRITE, 2),
                    ProgOp(0, READ, 0), ProgOp(1, READ, 0)), n_pids=2)
    seen = {_hist_key(run_concurrent(ReplicatedRegisterSUT(), prog,
                                     seed=f"seed{i}"))
            for i in range(40)}
    assert len(seen) > 1, "scheduler never varied the interleaving"


def test_all_ops_complete_without_faults():
    prog = generate_program(SPEC, seed=11, n_pids=3, max_ops=12)
    h = run_concurrent(AtomicRegisterSUT(), prog, seed="x")
    assert len(h) == len(prog)
    assert h.n_pending == 0


def test_intervals_well_formed():
    prog = generate_program(SPEC, seed=5, n_pids=3, max_ops=12)
    h = run_concurrent(AtomicRegisterSUT(), prog, seed="y")
    for o in h.ops:
        assert o.invoke_time < o.response_time
    # per-pid program order must be preserved in invocation order
    per_pid = {}
    for o in h.ops:
        per_pid.setdefault(o.pid, []).append((o.cmd, o.arg))
    expected = {}
    for op in prog.ops:
        expected.setdefault(op.pid, []).append((op.cmd, op.arg))
    assert per_pid == expected


def test_message_drop_leaves_pending_ops():
    prog = Program((ProgOp(0, WRITE, 1), ProgOp(1, READ, 0)), n_pids=2)
    faults = FaultPlan(p_drop=1.0)  # every message dropped
    h = run_concurrent(AtomicRegisterSUT(), prog, seed="z", faults=faults)
    assert h.n_pending == len(h) == 2


def test_crash_injection_kills_client():
    prog = Program((ProgOp(0, WRITE, 1), ProgOp(0, READ, 0),
                    ProgOp(1, READ, 0)), n_pids=2)
    faults = FaultPlan(crash_at={"client:0": 1})
    h = run_concurrent(AtomicRegisterSUT(), prog, seed="c", faults=faults)
    assert any(o.is_pending for o in h.ops if o.pid == 0) or \
        len([o for o in h.ops if o.pid == 0]) < 2
