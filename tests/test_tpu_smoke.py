"""Opt-in real-chip smoke tests (``pytest -m tpu``).

Round-1 verdict: zero real-chip test coverage meant kernel regressions could
only be caught by the (expensive) benchmark.  These tests give golden-parity
coverage on the actual TPU at a fraction of the cost — and SKIP cleanly,
never hang, when the chip tunnel is wedged (the bounded subprocess probe is
the only thing that ever touches the backend from here; conftest.py pins
this process to the CPU platform, so the chip work runs in a subprocess).

Deselected by default via ``addopts = "-m 'not tpu'"`` in pyproject.toml.
"""

import os
import subprocess
import sys

import pytest

from qsm_tpu.utils.device import probe_default_backend

PROBE_TIMEOUT_S = float(os.environ.get("QSM_TPU_PROBE_TIMEOUT", 60))
# first compile on the chip is slow (~20-40s); give the payload headroom
PAYLOAD_TIMEOUT_S = float(os.environ.get("QSM_TPU_SMOKE_TIMEOUT", 600))

pytestmark = pytest.mark.tpu


def test_golden_parity_and_batch_on_chip():
    # probe at RUN time, not import time: a deselected run (the default)
    # must not pay the probe timeout during collection
    probe = probe_default_backend(timeout_s=PROBE_TIMEOUT_S)
    if not probe.is_device:
        pytest.skip(f"no reachable TPU chip: {probe.detail}")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    payload = os.path.join(os.path.dirname(__file__),
                           "_tpu_smoke_payload.py")
    r = subprocess.run(
        [sys.executable, payload], capture_output=True, text=True,
        timeout=PAYLOAD_TIMEOUT_S, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(payload))))
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "TPU_SMOKE_OK" in r.stdout, r.stdout
