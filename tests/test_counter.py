"""Ticket dispenser (config #2, BASELINE.json:8): correct impl passes,
read-then-increment racy impl hands out duplicate tickets and fails."""

from qsm_tpu import (PropertyConfig, Verdict, WingGongCPU, check_one,
                     generate_program, prop_concurrent, run_concurrent)
from qsm_tpu.models.counter import AtomicTicketSUT, RacyTicketSUT, TicketSpec
from qsm_tpu.ops.jax_kernel import JaxTPU

SPEC = TicketSpec(n_tickets=25)
CFG = PropertyConfig(n_trials=60, n_pids=4, max_ops=24, seed=99)


def test_atomic_ticket_passes():
    res = prop_concurrent(SPEC, AtomicTicketSUT(), CFG)
    assert res.ok, res.counterexample


def test_racy_ticket_fails_and_shrinks():
    res = prop_concurrent(SPEC, RacyTicketSUT(), CFG)
    assert not res.ok, "duplicate tickets were never caught"
    cx = res.counterexample
    # minimal counterexample: two overlapping TAKEs
    assert len(cx.program) <= 3, cx.program
    assert check_one(WingGongCPU(), SPEC, cx.history) == Verdict.VIOLATION


def test_ticket_backend_parity():
    from conftest import assert_backend_parity

    hists = []
    for seed in range(40):
        prog = generate_program(SPEC, seed=seed, n_pids=4, max_ops=20)
        for sut_cls in (AtomicTicketSUT, RacyTicketSUT):
            hists.append(run_concurrent(sut_cls(), prog, seed=f"t{seed}"))
    assert_backend_parity(SPEC, hists, JaxTPU(SPEC))


def test_ticket_resp_beyond_domain_parity():
    """A buggy SUT can hand out tickets beyond n_tickets; the oracle accepts
    resp == state with no cap, so the kernel's step table must cover those
    states too (round-2 review: bounding by n_tickets+1 was unsound — the
    sound bound is n_ops+1).  27 sequential TAKEs with resps 0..26 against
    n_tickets=25 must be LINEARIZABLE on both backends."""
    from qsm_tpu.core.history import sequential_history

    h = sequential_history([(0, 0, 0, i) for i in range(27)])
    cpu = check_one(WingGongCPU(), SPEC, h)
    assert cpu == Verdict.LINEARIZABLE
    dev = JaxTPU(SPEC).check_histories(SPEC, [h])[0]
    assert dev == int(cpu)


def test_out_of_domain_args_defer_to_oracle():
    """Step-table specs defer histories with out-of-domain ARGS to the
    oracle (BUDGET_EXCEEDED) instead of risking a silent table/oracle
    divergence (round-2 review)."""
    from qsm_tpu.core.history import sequential_history
    from qsm_tpu.models.register import RegisterSpec

    spec = RegisterSpec(n_values=5)
    # WRITE(7) is outside n_args=5; oracle happily linearizes it
    h = sequential_history([(0, 1, 7, 0), (0, 0, 0, 7)])
    assert check_one(WingGongCPU(), spec, h) == Verdict.LINEARIZABLE
    backend = JaxTPU(spec)
    assert backend.check_histories(spec, [h])[0] == int(
        Verdict.BUDGET_EXCEEDED)
    assert backend.deferred_out_of_domain == 1
