"""Ticket dispenser (config #2, BASELINE.json:8): correct impl passes,
read-then-increment racy impl hands out duplicate tickets and fails."""

from qsm_tpu import (PropertyConfig, Verdict, WingGongCPU, check_one,
                     generate_program, prop_concurrent, run_concurrent)
from qsm_tpu.models.counter import AtomicTicketSUT, RacyTicketSUT, TicketSpec
from qsm_tpu.ops.jax_kernel import JaxTPU

SPEC = TicketSpec(n_tickets=25)
CFG = PropertyConfig(n_trials=60, n_pids=4, max_ops=24, seed=99)


def test_atomic_ticket_passes():
    res = prop_concurrent(SPEC, AtomicTicketSUT(), CFG)
    assert res.ok, res.counterexample


def test_racy_ticket_fails_and_shrinks():
    res = prop_concurrent(SPEC, RacyTicketSUT(), CFG)
    assert not res.ok, "duplicate tickets were never caught"
    cx = res.counterexample
    # minimal counterexample: two overlapping TAKEs
    assert len(cx.program) <= 3, cx.program
    assert check_one(WingGongCPU(), SPEC, cx.history) == Verdict.VIOLATION


def test_ticket_backend_parity():
    from conftest import assert_backend_parity

    hists = []
    for seed in range(40):
        prog = generate_program(SPEC, seed=seed, n_pids=4, max_ops=20)
        for sut_cls in (AtomicTicketSUT, RacyTicketSUT):
            hists.append(run_concurrent(sut_cls(), prog, seed=f"t{seed}"))
    assert_backend_parity(SPEC, hists, JaxTPU(SPEC))
