"""Utils: CLI surface, counterexample printing, regression roundtrip,
schedule coverage (SURVEY.md §5 auxiliary subsystems)."""

import io
import json

from qsm_tpu import PropertyConfig, prop_concurrent
from qsm_tpu.core.generator import generate_program
from qsm_tpu.models.register import RacyCachedRegisterSUT, RegisterSpec
from qsm_tpu.models.registry import MODELS, make
from qsm_tpu.utils import (JsonlLogger, format_counterexample,
                           load_regression, save_regression,
                           schedule_coverage)
from qsm_tpu.utils.cli import main as cli_main

SPEC = RegisterSpec()
CFG = PropertyConfig(n_trials=60, n_pids=2, max_ops=12, seed=1234)


def _failing_result():
    res = prop_concurrent(SPEC, RacyCachedRegisterSUT(), CFG)
    assert not res.ok
    return res


def test_registry_covers_all_five_configs():
    # the five milestone configs (BASELINE.json:7-11) + extra families
    assert {"register", "ticket", "cas", "queue", "kv"} <= set(MODELS)
    assert set(MODELS) == {"register", "ticket", "cas", "queue", "kv",
                           "set", "stack", "failover",
                           "multireg", "multicas",
                           # generation-plane families (ISSUE 17)
                           "rangeset", "semaphore", "txn"}
    for name, entry in MODELS.items():
        spec, sut = make(name, "racy")
        assert hasattr(sut, "perform")
        assert "atomic" in entry.impls


def test_cli_list(capsys):
    from qsm_tpu.native import native_available

    assert native_available()  # ensure the .so exists (compiles once);
    # `list` itself must NOT compile — it reports the compile-free status
    assert cli_main(["list"]) == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert set(out["models"]) == set(MODELS)
    assert out["models"]["cas"]["impls"] == ["atomic", "racy"]
    assert "rootsplit-tpu" in out["backends"]
    assert out["native"] in ("loaded", "built")
    assert out["native_available"] is True  # toolchain is baked in


def test_format_counterexample_mentions_every_op():
    res = _failing_result()
    text = format_counterexample(SPEC, res.counterexample)
    assert str(res.counterexample.trial) in text
    assert text.count("pid ") == len(res.counterexample.history.ops)
    assert "[" in text  # interval bars rendered


def test_regression_roundtrip(tmp_path):
    res = _failing_result()
    path = str(tmp_path / "reg.json")
    save_regression(path, "register", "racy", SPEC, CFG, res.counterexample)
    model, impl, seed_key, prog, hist, faults, spec_kwargs = \
        load_regression(path)
    assert (model, impl) == ("register", "racy")
    assert faults is None
    assert spec_kwargs == SPEC.spec_kwargs() and spec_kwargs  # non-empty
    assert seed_key == res.counterexample.trial_seed
    assert prog == res.counterexample.program
    assert [(o.pid, o.resp) for o in hist.ops] == \
        [(o.pid, o.resp) for o in res.counterexample.history.ops]


def test_jsonl_logger():
    buf = io.StringIO()
    log = JsonlLogger(stream=buf)
    log.emit("trial", trial=3, ok=True)
    rec = json.loads(buf.getvalue())
    assert rec["event"] == "trial" and rec["trial"] == 3 and rec["ok"]


def test_schedule_coverage_deterministic_per_seed():
    prog = generate_program(SPEC, seed=3, n_pids=2, max_ops=8)
    s1 = schedule_coverage(lambda: RacyCachedRegisterSUT(), prog,
                           seeds=range(30))
    s2 = schedule_coverage(lambda: RacyCachedRegisterSUT(), prog,
                           seeds=range(30))
    assert s1 == s2  # same seeds → same stats (determinism contract)
    assert s1.distinct_schedules > 1  # seeds actually vary the interleaving
    # same seed twice adds nothing
    s3 = schedule_coverage(lambda: RacyCachedRegisterSUT(), prog,
                           seeds=[0, 0])
    assert s3.distinct_schedules == 1


def test_cli_run_and_replay(tmp_path, capsys):
    reg = str(tmp_path / "cx.json")
    rc = cli_main(["run", "--model", "register", "--impl", "racy",
                   "--trials", "200", "--seed", "1",
                   "--save-regression", reg])
    assert rc == 1
    out = capsys.readouterr().out
    assert "FAIL" in out and "replay:" in out
    rc = cli_main(["replay", "--regression", reg])
    assert rc == 1  # violation reproduces
    out = capsys.readouterr().out
    assert "bit-identically: True" in out and "VIOLATION" in out


def test_cli_run_atomic_ok(capsys):
    rc = cli_main(["run", "--model", "ticket", "--impl", "atomic",
                   "--trials", "20"])
    assert rc == 0
    assert "OK" in capsys.readouterr().out


def test_regression_nondefault_spec_replays_same_spec(tmp_path):
    """A regression captured against a non-default spec must rebuild THAT
    spec on load, not registry defaults (ADVICE.md round 1)."""
    spec = RegisterSpec(n_values=9)
    res = prop_concurrent(spec, RacyCachedRegisterSUT(), CFG)
    assert not res.ok
    path = str(tmp_path / "reg9.json")
    save_regression(path, "register", "racy", spec, CFG, res.counterexample)
    model, impl, _, _, _, _, spec_kwargs = load_regression(path)
    spec2, _ = make(model, impl, spec_kwargs)
    assert spec2.n_values == 9
