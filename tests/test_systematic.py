"""Systematic schedule exploration (sched/systematic.py): scripted
delivery choices replay deterministically, lexicographic backtracking
visits every leaf exactly once, the racy TOCTOU is FOUND exhaustively
(no sampling luck), and the atomic impl earns the `verified` certainty
claim."""

import json

from qsm_tpu.core.generator import ProgOp, Program
from qsm_tpu.models.register import (AtomicRegisterSUT,
                                     RacyCachedRegisterSUT, RegisterSpec)
from qsm_tpu.models.set import ADD, AtomicSetSUT, RacyCheckThenActSetSUT, SetSpec
from qsm_tpu.sched.runner import run_concurrent
from qsm_tpu.sched.scheduler import FaultPlan
from qsm_tpu.sched.systematic import _next_prefix, explore_program

import pytest

# heaviest parametrized suite: full lane only (README "Tests", pyproject `slow` marker)
pytestmark = pytest.mark.slow

# the crisp 2-pid TOCTOU program: both pids add the same key
SET_SPEC = SetSpec(n_keys=2)
SET_PROG = Program(ops=(ProgOp(0, ADD, 0), ProgOp(1, ADD, 0)), n_pids=2)


def test_next_prefix_enumerates_constant_tree_exactly_once():
    """DFS over a synthetic 2x2 choice tree: 4 leaves, each once."""
    leaves = []
    prefix = []
    while prefix is not None:
        full = (prefix + [0, 0])[:2]
        leaves.append(tuple(full))
        prefix = _next_prefix(prefix, [2, 2])
    assert leaves == [(0, 0), (0, 1), (1, 0), (1, 1)]


def test_scripted_choices_are_deterministic():
    h1 = run_concurrent(RacyCheckThenActSetSUT(SET_SPEC), SET_PROG,
                        seed=0, choices=[1, 0, 1])
    h2 = run_concurrent(RacyCheckThenActSetSUT(SET_SPEC), SET_PROG,
                        seed=99, choices=[1, 0, 1])  # seed must not matter
    assert h1.fingerprint() == h2.fingerprint()
    # that scripts actually STEER schedules is proven by the exploration
    # tests below (distinct_histories >= 2 over the same program)


def test_explore_finds_toctou_with_certainty():
    res = explore_program(lambda: RacyCheckThenActSetSUT(SET_SPEC),
                          SET_PROG, SET_SPEC)
    assert res.exhausted, "tiny program must fully enumerate"
    assert res.violations > 0, "exhaustive exploration missed the race"
    assert not res.ok and not res.verified
    assert res.violating is not None
    # the violating history is the double-insert: both adds returned 1
    assert [o.resp for o in res.violating.ops] == [1, 1]


def test_explore_verifies_atomic_impl():
    res = explore_program(lambda: AtomicSetSUT(SET_SPEC), SET_PROG,
                          SET_SPEC)
    assert res.exhausted and res.violations == 0 and res.undecided == 0
    assert res.verified and res.ok
    assert res.schedules_run >= res.distinct_histories >= 2


def test_explore_register_program():
    spec = RegisterSpec(n_values=3)
    # pid0 writes 1; pid1 reads twice — the stale-cache race needs the
    # SECOND read (served from cache, no round trip) to land real-time
    # after the write completed while the first read's response carried 0
    prog = Program(ops=(ProgOp(0, 1, 1), ProgOp(1, 0, 0),
                        ProgOp(1, 0, 0)), n_pids=2)
    racy = explore_program(lambda: RacyCachedRegisterSUT(), prog, spec)
    atomic = explore_program(lambda: AtomicRegisterSUT(), prog, spec)
    assert atomic.verified
    assert racy.exhausted
    # the cached-read impl serves a stale value under SOME interleaving
    assert racy.violations > 0


def test_explore_truncation_reported():
    res = explore_program(lambda: AtomicSetSUT(SET_SPEC), SET_PROG,
                          SET_SPEC, max_schedules=2)
    assert res.schedules_run == 2
    assert not res.exhausted and not res.verified


def test_explore_refuses_probabilistic_faults():
    with pytest.raises(ValueError, match="PROBABILISTIC"):
        explore_program(lambda: AtomicSetSUT(SET_SPEC), SET_PROG,
                        SET_SPEC, faults=FaultPlan(p_drop=0.5))


# ---------------------------------------------------------------------------
# Deterministic fault plans compose with exhaustive exploration: crash
# schedules fire on delivery counts, so scripted replay explores them
# exactly — the `verified` claim extends to fault tolerance.
# ---------------------------------------------------------------------------

def _failover_prog():
    from qsm_tpu.core.generator import ProgOp, Program
    from qsm_tpu.models.failover import READ, WRITE

    # the bug shape: a write acked on the doomed primary, observations
    # before and after the failover
    return Program((ProgOp(0, WRITE, 1), ProgOp(0, READ, 0),
                    ProgOp(1, READ, 0), ProgOp(1, READ, 0)), n_pids=2)


def test_explore_verifies_sync_failover_under_crash():
    """The sync-replication failover earns `verified` under EVERY
    interleaving of the crash schedule — certainty where round 3 had
    6,000 random trials."""
    from qsm_tpu.models.registry import make

    spec, _ = make("failover", "atomic")
    res = explore_program(lambda: make("failover", "atomic")[1],
                          _failover_prog(), spec,
                          faults=FaultPlan(crash_at={"primary": 2}),
                          max_schedules=30_000)
    assert res.exhausted and res.verified


def test_explore_convicts_async_failover_under_crash():
    """The async impl's lost-acked-write bug is FOUND exhaustively, and
    the violating schedule replays bit-identically under the same crash
    plan (the finding is a proof, not a sample)."""
    from qsm_tpu.models.registry import make
    from qsm_tpu.sched.systematic import parse_schedule_key

    spec, _ = make("failover", "racy")
    plan = FaultPlan(crash_at={"primary": 2})
    res = explore_program(lambda: make("failover", "racy")[1],
                          _failover_prog(), spec, faults=plan,
                          max_schedules=30_000)
    assert res.exhausted and res.violations > 0
    assert res.violating is not None
    h = run_concurrent(make("failover", "racy")[1], _failover_prog(),
                       seed=res.violating.seed, faults=plan,
                       choices=parse_schedule_key(res.violating.seed))
    assert h.fingerprint() == res.violating.fingerprint()


def test_explore_crash_cli_roundtrip(tmp_path, capsys):
    """explore --crash-at finds the async failover bug exhaustively, the
    regression file carries the crash plan, and replay reproduces the
    history bit for bit under it."""
    from qsm_tpu.utils.cli import main

    path = str(tmp_path / "crash_cx.json")
    # seed 9's generated program is the known write-then-read bug shape
    rc = main(["explore", "--model", "failover", "--impl", "racy",
               "--pids", "2", "--ops", "4", "--seed", "9",
               "--crash-at", "primary:2", "--max-schedules", "30000",
               "--save-regression", path])
    out = capsys.readouterr().out.strip().splitlines()[0]
    res = json.loads(out)
    assert res["exhausted"] and res["violations"] > 0
    assert rc == 1
    rc = main(["replay", "--regression", path])
    printed = capsys.readouterr().out
    assert rc == 1
    assert "history reproduced bit-identically: True" in printed


def test_crash_sweep_cli(capsys):
    """One command certifies an impl over the whole crash-point range:
    the sync failover earns all_verified, the async is convicted with a
    per-point violation count and replayable schedules."""
    from qsm_tpu.utils.cli import main

    rc = main(["explore", "--model", "failover", "--impl", "atomic",
               "--pids", "2", "--ops", "4", "--seed", "9",
               "--crash-sweep", "primary:1-3", "--max-schedules", "30000"])
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    assert rc == 0
    assert lines[-1]["all_verified"] is True
    assert len(lines) == 4  # 3 crash points + summary

    rc = main(["explore", "--model", "failover", "--impl", "racy",
               "--pids", "2", "--ops", "4", "--seed", "9",
               "--crash-sweep", "primary:2-2", "--max-schedules", "30000"])
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    assert rc == 1
    assert lines[0]["violations"] > 0 and lines[0]["exhausted"]
    assert lines[0]["violating_schedule"].startswith("explore:")


def test_crash_sweep_cli_rejects_bad_combos():
    from qsm_tpu.utils.cli import main

    with pytest.raises(SystemExit, match="crash-sweep"):
        main(["explore", "--model", "failover", "--crash-sweep",
              "primary:1-3", "--crash-at", "backup:2"])
    with pytest.raises(SystemExit, match="NAME:LO-HI"):
        main(["explore", "--model", "failover", "--crash-sweep",
              "primary"])
    # an inverted range would certify over ZERO explored executions
    with pytest.raises(SystemExit, match="empty"):
        main(["explore", "--model", "failover", "--crash-sweep",
              "primary:5-3"])
    # a typo'd process name would certify the fault-FREE system
    # (Scheduler.crash silently ignores unknown names)
    with pytest.raises(SystemExit, match="no process named"):
        main(["explore", "--model", "failover", "--crash-sweep",
              "pirmary:1-3"])


def test_crash_sweep_inconclusive_exits_2(capsys):
    """A truncated sweep must NOT exit 0: no violation found, but the
    certification claim was not earned either (mirrors run's exit-2
    convention for undecided outcomes)."""
    from qsm_tpu.utils.cli import main

    rc = main(["explore", "--model", "failover", "--impl", "atomic",
               "--pids", "2", "--ops", "4", "--seed", "9",
               "--crash-sweep", "primary:2-2", "--max-schedules", "50"])
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    assert rc == 2
    assert lines[0]["exhausted"] is False
    assert lines[-1]["all_verified"] is False


def test_crash_sweep_composes_with_partition(capsys):
    """--partition is EXTENDED into every sweep point's plan, not
    silently dropped (the certified system must be the one the user
    described)."""
    from qsm_tpu.utils.cli import main

    # partitioning the backup away makes replication impossible: the
    # sync impl's writes wedge to pending instead of acking un-durably,
    # so the sweep still verifies — but over the PARTITIONED system
    # (distinct trees from the unpartitioned sweep prove the plan took)
    rc = main(["explore", "--model", "failover", "--impl", "atomic",
               "--pids", "2", "--ops", "4", "--seed", "9",
               "--crash-sweep", "primary:1-2", "--partition", "backup",
               "--max-schedules", "30000"])
    part = [json.loads(x) for x in
            capsys.readouterr().out.strip().splitlines()]
    assert rc in (0, 2)
    main(["explore", "--model", "failover", "--impl", "atomic",
          "--pids", "2", "--ops", "4", "--seed", "9",
          "--crash-sweep", "primary:1-2", "--max-schedules", "30000"])
    plain = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    assert [p["schedules_run"] for p in part[:-1]] != \
        [p["schedules_run"] for p in plain[:-1]]


def test_explore_cli_refuses_probabilistic_faults():
    from qsm_tpu.utils.cli import main

    with pytest.raises(SystemExit, match="DETERMINISTIC"):
        main(["explore", "--model", "set", "--impl", "racy",
              "--pids", "2", "--ops", "4", "--p-drop", "0.2"])


def test_every_family_has_a_convicted_certified_pair():
    """Sampled evidence (docs/evidence/explore_families_r04.jsonl): for
    EVERY model family there is a program whose racy impl is convicted
    by exhaustive exploration while the atomic impl is verified on the
    SAME program.  Pin two sub-second pairs; the committed evidence file
    carries the rest, and failover's pair lives in the crash-sweep tests
    above.  max_ops=5 here matches the evidence GENERATION config (the
    file's "ops" field records the resulting program LENGTH, 4)."""
    from qsm_tpu.core.generator import generate_program
    from qsm_tpu.models.registry import make

    for family, seed, pids, ops in (("register", 11, 2, 5),
                                    ("queue", 0, 2, 5)):
        spec, _ = make(family, "racy")
        prog = generate_program(spec, seed=seed, n_pids=pids, max_ops=ops)
        racy = explore_program(lambda: make(family, "racy")[1], prog,
                               spec, max_schedules=60_000)
        assert racy.exhausted and racy.violations > 0, family
        atomic = explore_program(lambda: make(family, "atomic")[1], prog,
                                 spec, max_schedules=60_000)
        assert atomic.verified, family


def test_prune_preserves_history_sets_under_crash_plan():
    """Pruning soundness extends to fault plans: the delivery count joins
    the state identity (pending crash points fire on it), and pruned vs
    unpruned walks must still agree."""
    from qsm_tpu.core.generator import ProgOp, Program
    from qsm_tpu.models.failover import READ, WRITE
    from qsm_tpu.models.registry import make
    from qsm_tpu.sched.systematic import _enumerate

    prog = Program((ProgOp(0, WRITE, 1), ProgOp(1, READ, 0)), n_pids=2)
    plan = FaultPlan(crash_at={"primary": 2})
    factory = lambda: make("failover", "racy")[1]  # noqa: E731
    up_h, _, up_exh, _p0 = _enumerate(factory, prog, 50_000, 100_000,
                                      prune=False, faults=plan)
    pr_h, pr_n, pr_exh, _p1 = _enumerate(factory, prog, 50_000, 100_000,
                                         prune=True, faults=plan)
    assert up_exh and pr_exh
    assert ({h.fingerprint() for h in up_h}
            == {h.fingerprint() for h in pr_h})


def test_shrink_explored_minimizes_to_the_double_add():
    """Start from a padded 4-op program; exploration shrink must strip
    the noise down to the 2-op double-add core (or smaller-equivalent),
    still violating under SOME schedule."""
    from qsm_tpu.sched.systematic import shrink_explored

    noisy = Program(ops=(ProgOp(0, ADD, 0), ProgOp(0, 2, 1),
                         ProgOp(1, ADD, 0), ProgOp(1, 2, 1)), n_pids=2)
    prog, res, steps = shrink_explored(
        lambda: RacyCheckThenActSetSUT(SET_SPEC), noisy, SET_SPEC)
    assert res.violations > 0
    assert steps > 0 and len(prog) == 2, (len(prog), steps)
    assert all(op.cmd == ADD and op.arg == 0 for op in prog.ops)


def test_shrink_explored_passing_program_untouched():
    from qsm_tpu.sched.systematic import shrink_explored

    prog, res, steps = shrink_explored(
        lambda: AtomicSetSUT(SET_SPEC), SET_PROG, SET_SPEC)
    assert steps == 0 and res.violations == 0 and prog is SET_PROG


def test_explore_regression_roundtrip(tmp_path, capsys):
    """explore --save-regression persists the violating schedule script;
    replay --regression re-runs it and reproduces the history bit for
    bit (the checkpoint story extends to exploration findings)."""
    from qsm_tpu.utils.cli import main

    path = str(tmp_path / "explored.json")
    rc = main(["explore", "--model", "set", "--impl", "racy",
               "--pids", "3", "--ops", "6", "--seed", "25",
               "--max-schedules", "3000", "--save-regression", path])
    assert rc == 1, "seed 25 is the known violating program"
    out = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert out["violating_schedule"].startswith("explore:")

    rc = main(["replay", "--regression", path])
    printed = capsys.readouterr().out
    assert rc == 1  # replay exits by verdict; a violation is rc 1
    assert "history reproduced bit-identically: True" in printed


def test_explore_many_matches_per_program():
    """Batching the union of trees must not change any per-program
    result — same enumeration, same verdicts, one backend call."""
    from qsm_tpu.core.generator import generate_program
    from qsm_tpu.sched.systematic import explore_many, explore_program

    progs = [generate_program(SET_SPEC, seed=s, n_pids=2, max_ops=5)
             for s in range(6)] + [SET_PROG]
    factory = lambda: RacyCheckThenActSetSUT(SET_SPEC)  # noqa: E731
    batched = explore_many(factory, progs, SET_SPEC, max_schedules=500)
    assert len(batched) == len(progs)
    for prog, got in zip(progs, batched):
        solo = explore_program(factory, prog, SET_SPEC, max_schedules=500)
        assert (got.schedules_run, got.distinct_histories, got.exhausted,
                got.violations, got.undecided) == (
            solo.schedules_run, solo.distinct_histories, solo.exhausted,
            solo.violations, solo.undecided)
    assert batched[-1].violations > 0  # the crafted double-add is in


def test_explore_many_cli(capsys):
    from qsm_tpu.utils.cli import main

    rc = main(["explore", "--model", "set", "--impl", "atomic",
               "--pids", "2", "--ops", "4", "--seed", "0",
               "--programs", "5"])
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    assert rc == 0
    assert len(lines) == 6  # 5 programs + summary
    assert lines[-1]["programs"] == 5
    assert lines[-1]["all_verified"] is True  # atomic, all trees tiny

    with pytest.raises(SystemExit, match="sweep"):
        main(["explore", "--model", "set", "--programs", "3", "--shrink"])


def test_coverage_exact_cli(capsys):
    """--exact grounds sampled coverage against the enumerated tree."""
    from qsm_tpu.utils.cli import main

    rc = main(["coverage", "--model", "set", "--impl", "racy",
               "--pids", "2", "--ops", "4", "--runs", "30", "--exact"])
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 0
    assert out["exact"]["exhausted"] is True
    assert out["exact"]["distinct_histories"] >= out["distinct_histories"]
    assert 0 < out["sampled_history_coverage"] <= 1.0


def test_explore_cli(capsys):
    from qsm_tpu.utils.cli import main

    rc = main(["explore", "--model", "set", "--impl", "racy",
               "--pids", "2", "--ops", "4", "--seed", "1"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert out["schedules_run"] >= 1
    assert out["exhausted"] in (True, False)
    assert rc in (0, 1)


# ---------------------------------------------------------------------------
# State-fingerprint pruning (VERDICT.md round 3, "Next round" #7): pruning
# must change COST only, never the answer — identical distinct-history
# sets and verdict counts wherever the unpruned walk also finishes.
# ---------------------------------------------------------------------------

def _history_set(sut_factory, prog, spec, prune, max_schedules=20_000):
    from qsm_tpu.sched.systematic import _enumerate

    hists, schedules, exhausted, _pruned = _enumerate(
        sut_factory, prog, max_schedules, 100_000, prune=prune)
    assert exhausted, "parity check needs both walks to finish"
    return {h.fingerprint() for h in hists}, schedules


def test_prune_preserves_history_sets_across_families():
    """Pruned and unpruned enumeration produce the SAME distinct-history
    set on every model family probed (the soundness contract: identical
    scheduler state ⇒ identical subtree, so skips drop only duplicates).
    """
    from qsm_tpu.core.generator import generate_program
    from qsm_tpu.models.cas import AtomicCasSUT, CasSpec
    from qsm_tpu.models.counter import RacyTicketSUT, TicketSpec
    from qsm_tpu.models.register import ReplicatedRegisterSUT

    cases = []
    reg_spec = RegisterSpec(n_values=3)
    cases.append((lambda: ReplicatedRegisterSUT(), reg_spec,
                  generate_program(reg_spec, seed=3, n_pids=2, max_ops=4)))
    cases.append((lambda: RacyCachedRegisterSUT(), reg_spec,
                  generate_program(reg_spec, seed=1, n_pids=3, max_ops=4)))
    cases.append((lambda: RacyCheckThenActSetSUT(SET_SPEC), SET_SPEC,
                  generate_program(SET_SPEC, seed=10, n_pids=3, max_ops=5)))
    cas_spec = CasSpec(n_values=3)
    cases.append((lambda: AtomicCasSUT(cas_spec), cas_spec,
                  generate_program(cas_spec, seed=2, n_pids=2, max_ops=4)))
    ctr_spec = TicketSpec()
    cases.append((lambda: RacyTicketSUT(), ctr_spec,
                  generate_program(ctr_spec, seed=4, n_pids=2, max_ops=4)))
    for factory, spec, prog in cases:
        plain, n_plain = _history_set(factory, prog, spec, prune=False)
        pruned, n_pruned = _history_set(factory, prog, spec, prune=True)
        assert pruned == plain, spec.name
        assert n_pruned <= n_plain, spec.name


def test_prune_still_finds_the_violation():
    racy = explore_program(lambda: RacyCheckThenActSetSUT(SET_SPEC),
                           SET_PROG, SET_SPEC, prune=True)
    assert racy.exhausted and racy.violations > 0
    plain = explore_program(lambda: RacyCheckThenActSetSUT(SET_SPEC),
                            SET_PROG, SET_SPEC, prune=False)
    assert plain.violations == racy.violations
    assert plain.distinct_histories == racy.distinct_histories


def test_prune_exhausts_the_round3_truncation_case():
    """The round-3 EXPERIMENTS shape (set/racy, 3 pids × 5 ops, seed 5):
    unpruned truncates at 10k schedules with the tree unfinished; pruned
    must EXHAUST it in under 1k — and surface the histories the
    truncation was hiding."""
    from qsm_tpu.core.generator import generate_program

    spec = SetSpec()
    prog = generate_program(spec, seed=5, n_pids=3, max_ops=5)
    res = explore_program(lambda: RacyCheckThenActSetSUT(spec), prog, spec,
                          prune=True, max_schedules=1_000)
    assert res.exhausted, "pruned walk must finish the round-3 case"
    assert res.schedules_run < 1_000
    # the observability counter must actually count (it is the only
    # direct witness that the prune fired on this tree)
    assert 0 < res.pruned_schedules < res.schedules_run
    # the unpruned walk truncated at 10k with only 35 distinct histories;
    # the exhausted pruned walk finds the full set (more than 35)
    assert res.distinct_histories > 35
