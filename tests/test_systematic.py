"""Systematic schedule exploration (sched/systematic.py): scripted
delivery choices replay deterministically, lexicographic backtracking
visits every leaf exactly once, the racy TOCTOU is FOUND exhaustively
(no sampling luck), and the atomic impl earns the `verified` certainty
claim."""

import json

from qsm_tpu.core.generator import ProgOp, Program
from qsm_tpu.models.register import (AtomicRegisterSUT,
                                     RacyCachedRegisterSUT, RegisterSpec)
from qsm_tpu.models.set import ADD, AtomicSetSUT, RacyCheckThenActSetSUT, SetSpec
from qsm_tpu.sched.runner import run_concurrent
from qsm_tpu.sched.scheduler import FaultPlan
from qsm_tpu.sched.systematic import _next_prefix, explore_program

import pytest

# the crisp 2-pid TOCTOU program: both pids add the same key
SET_SPEC = SetSpec(n_keys=2)
SET_PROG = Program(ops=(ProgOp(0, ADD, 0), ProgOp(1, ADD, 0)), n_pids=2)


def test_next_prefix_enumerates_constant_tree_exactly_once():
    """DFS over a synthetic 2x2 choice tree: 4 leaves, each once."""
    leaves = []
    prefix = []
    while prefix is not None:
        full = (prefix + [0, 0])[:2]
        leaves.append(tuple(full))
        prefix = _next_prefix(prefix, [2, 2])
    assert leaves == [(0, 0), (0, 1), (1, 0), (1, 1)]


def test_scripted_choices_are_deterministic():
    h1 = run_concurrent(RacyCheckThenActSetSUT(SET_SPEC), SET_PROG,
                        seed=0, choices=[1, 0, 1])
    h2 = run_concurrent(RacyCheckThenActSetSUT(SET_SPEC), SET_PROG,
                        seed=99, choices=[1, 0, 1])  # seed must not matter
    assert h1.fingerprint() == h2.fingerprint()
    # that scripts actually STEER schedules is proven by the exploration
    # tests below (distinct_histories >= 2 over the same program)


def test_explore_finds_toctou_with_certainty():
    res = explore_program(lambda: RacyCheckThenActSetSUT(SET_SPEC),
                          SET_PROG, SET_SPEC)
    assert res.exhausted, "tiny program must fully enumerate"
    assert res.violations > 0, "exhaustive exploration missed the race"
    assert not res.ok and not res.verified
    assert res.violating is not None
    # the violating history is the double-insert: both adds returned 1
    assert [o.resp for o in res.violating.ops] == [1, 1]


def test_explore_verifies_atomic_impl():
    res = explore_program(lambda: AtomicSetSUT(SET_SPEC), SET_PROG,
                          SET_SPEC)
    assert res.exhausted and res.violations == 0 and res.undecided == 0
    assert res.verified and res.ok
    assert res.schedules_run >= res.distinct_histories >= 2


def test_explore_register_program():
    spec = RegisterSpec(n_values=3)
    # pid0 writes 1; pid1 reads twice — the stale-cache race needs the
    # SECOND read (served from cache, no round trip) to land real-time
    # after the write completed while the first read's response carried 0
    prog = Program(ops=(ProgOp(0, 1, 1), ProgOp(1, 0, 0),
                        ProgOp(1, 0, 0)), n_pids=2)
    racy = explore_program(lambda: RacyCachedRegisterSUT(), prog, spec)
    atomic = explore_program(lambda: AtomicRegisterSUT(), prog, spec)
    assert atomic.verified
    assert racy.exhausted
    # the cached-read impl serves a stale value under SOME interleaving
    assert racy.violations > 0


def test_explore_truncation_reported():
    res = explore_program(lambda: AtomicSetSUT(SET_SPEC), SET_PROG,
                          SET_SPEC, max_schedules=2)
    assert res.schedules_run == 2
    assert not res.exhausted and not res.verified


def test_explore_refuses_faults():
    with pytest.raises(ValueError, match="fault"):
        explore_program(lambda: AtomicSetSUT(SET_SPEC), SET_PROG,
                        SET_SPEC, faults=FaultPlan(p_drop=0.5))


def test_shrink_explored_minimizes_to_the_double_add():
    """Start from a padded 4-op program; exploration shrink must strip
    the noise down to the 2-op double-add core (or smaller-equivalent),
    still violating under SOME schedule."""
    from qsm_tpu.sched.systematic import shrink_explored

    noisy = Program(ops=(ProgOp(0, ADD, 0), ProgOp(0, 2, 1),
                         ProgOp(1, ADD, 0), ProgOp(1, 2, 1)), n_pids=2)
    prog, res, steps = shrink_explored(
        lambda: RacyCheckThenActSetSUT(SET_SPEC), noisy, SET_SPEC)
    assert res.violations > 0
    assert steps > 0 and len(prog) == 2, (len(prog), steps)
    assert all(op.cmd == ADD and op.arg == 0 for op in prog.ops)


def test_shrink_explored_passing_program_untouched():
    from qsm_tpu.sched.systematic import shrink_explored

    prog, res, steps = shrink_explored(
        lambda: AtomicSetSUT(SET_SPEC), SET_PROG, SET_SPEC)
    assert steps == 0 and res.violations == 0 and prog is SET_PROG


def test_explore_regression_roundtrip(tmp_path, capsys):
    """explore --save-regression persists the violating schedule script;
    replay --regression re-runs it and reproduces the history bit for
    bit (the checkpoint story extends to exploration findings)."""
    from qsm_tpu.utils.cli import main

    path = str(tmp_path / "explored.json")
    rc = main(["explore", "--model", "set", "--impl", "racy",
               "--pids", "3", "--ops", "6", "--seed", "25",
               "--max-schedules", "3000", "--save-regression", path])
    assert rc == 1, "seed 25 is the known violating program"
    out = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert out["violating_schedule"].startswith("explore:")

    rc = main(["replay", "--regression", path])
    printed = capsys.readouterr().out
    assert rc == 1  # replay exits by verdict; a violation is rc 1
    assert "history reproduced bit-identically: True" in printed


def test_explore_many_matches_per_program():
    """Batching the union of trees must not change any per-program
    result — same enumeration, same verdicts, one backend call."""
    from qsm_tpu.core.generator import generate_program
    from qsm_tpu.sched.systematic import explore_many, explore_program

    progs = [generate_program(SET_SPEC, seed=s, n_pids=2, max_ops=5)
             for s in range(6)] + [SET_PROG]
    factory = lambda: RacyCheckThenActSetSUT(SET_SPEC)  # noqa: E731
    batched = explore_many(factory, progs, SET_SPEC, max_schedules=500)
    assert len(batched) == len(progs)
    for prog, got in zip(progs, batched):
        solo = explore_program(factory, prog, SET_SPEC, max_schedules=500)
        assert (got.schedules_run, got.distinct_histories, got.exhausted,
                got.violations, got.undecided) == (
            solo.schedules_run, solo.distinct_histories, solo.exhausted,
            solo.violations, solo.undecided)
    assert batched[-1].violations > 0  # the crafted double-add is in


def test_explore_many_cli(capsys):
    from qsm_tpu.utils.cli import main

    rc = main(["explore", "--model", "set", "--impl", "atomic",
               "--pids", "2", "--ops", "4", "--seed", "0",
               "--programs", "5"])
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    assert rc == 0
    assert len(lines) == 6  # 5 programs + summary
    assert lines[-1]["programs"] == 5
    assert lines[-1]["all_verified"] is True  # atomic, all trees tiny

    with pytest.raises(SystemExit, match="sweep"):
        main(["explore", "--model", "set", "--programs", "3", "--shrink"])


def test_coverage_exact_cli(capsys):
    """--exact grounds sampled coverage against the enumerated tree."""
    from qsm_tpu.utils.cli import main

    rc = main(["coverage", "--model", "set", "--impl", "racy",
               "--pids", "2", "--ops", "4", "--runs", "30", "--exact"])
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 0
    assert out["exact"]["exhausted"] is True
    assert out["exact"]["distinct_histories"] >= out["distinct_histories"]
    assert 0 < out["sampled_history_coverage"] <= 1.0


def test_explore_cli(capsys):
    from qsm_tpu.utils.cli import main

    rc = main(["explore", "--model", "set", "--impl", "racy",
               "--pids", "2", "--ops", "4", "--seed", "1"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert out["schedules_run"] >= 1
    assert out["exhausted"] in (True, False)
    assert rc in (0, 1)
