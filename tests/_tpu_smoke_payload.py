"""Real-chip smoke payload — run BY tests/test_tpu_smoke.py in a subprocess.

Runs on whatever backend the default environment provides (the axon TPU
plugin); tests/conftest.py forces the parent test process onto the CPU
platform, so chip work must happen out-of-process.  Prints one line
``TPU_SMOKE_OK {...}`` on success; any assertion/exception makes the
subprocess exit nonzero and the parent test fail with the captured output.
"""

import json
import sys

sys.path.insert(0, ".")  # repo root (subprocess cwd)

import numpy as np  # noqa: E402

import jax  # noqa: E402

device = jax.devices()[0]
assert device.platform != "cpu", f"payload ran on {device} — not a chip"

from qsm_tpu.core.history import sequential_history  # noqa: E402
from qsm_tpu.models import AtomicCasSUT, CasSpec, RacyCasSUT  # noqa: E402
from qsm_tpu.ops.jax_kernel import JaxTPU  # noqa: E402
from qsm_tpu.ops.wing_gong_cpu import WingGongCPU  # noqa: E402
from qsm_tpu.utils.corpus import build_corpus  # noqa: E402

spec = CasSpec()
backend = JaxTPU(spec)
oracle = WingGongCPU(memo=True)

# 1. golden pair: one linearizable, one violating (the kernel must
#    discriminate, not just run)
golden = [
    sequential_history([(0, 1, 1, 0), (1, 0, 0, 1)]),  # write 1; read 1: ok
    sequential_history([(0, 1, 1, 0), (1, 0, 0, 0)]),  # write 1; read 0: bad
]
gv = backend.check_histories(spec, golden)
assert gv.tolist() == [1, 0], f"golden verdicts wrong: {gv.tolist()}"

# 2. one real 256-history batch (8 pids x 32 ops, the bench shape) with
#    full parity against the host oracle
corpus = build_corpus(spec, (AtomicCasSUT, RacyCasSUT), n=256, n_pids=8,
                      max_ops=32, seed_base=4242, seed_prefix="smoke")
dev = backend.check_histories(spec, corpus)
cpu = oracle.check_histories(spec, corpus)
decided = (dev != 2) & (cpu != 2)
wrong = int(np.sum(dev[decided] != cpu[decided]))
assert wrong == 0, f"{wrong} verdict mismatches on the smoke corpus"

print("TPU_SMOKE_OK " + json.dumps({
    "device": str(device),
    "batch": len(corpus),
    "undecided_device": int(np.sum(dev == 2)),
    "rescued": backend.rescued,
}))
