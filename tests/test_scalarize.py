"""Scalarization (ops/scalarize.py): packing is a bijection on bounded
states, the shadow applies exactly where the packed domain is small, and
JaxTPU defers out-of-bounds start states instead of mispacking them."""

import numpy as np
import pytest

from qsm_tpu import Verdict, WingGongCPU, generate_program, run_concurrent
from qsm_tpu.models.kv import KvSpec
from qsm_tpu.models.queue import (AtomicQueueSUT, QueueSpec,
                                  RacyTwoPhaseQueueSUT)
from qsm_tpu.models.register import RegisterSpec
from qsm_tpu.ops.jax_kernel import JaxTPU
from qsm_tpu.ops.scalarize import Scalarized, scalar_shadow


def test_pack_unpack_roundtrip_exhaustive():
    import itertools

    spec = QueueSpec(capacity=2, n_values=3)
    sh = Scalarized(spec)
    seen = set()
    for state in itertools.product(range(3), range(3), range(3)):
        if state[0] > spec.capacity:
            continue
        packed = sh.pack(list(state))
        assert 0 <= packed < sh.n_packed
        assert sh.unpack(packed) == list(state)
        assert packed not in seen  # injective
        seen.add(packed)


def test_step_py_matches_inner_through_packing():
    import random

    spec = QueueSpec()
    sh = Scalarized(spec)
    rng = random.Random(5)
    state = list(spec.initial_state())
    for _ in range(200):
        cmd = rng.randrange(len(spec.CMDS))
        arg = rng.randrange(spec.CMDS[cmd].n_args)
        resp = rng.randrange(spec.CMDS[cmd].n_resps)
        want_vec, want_ok = spec.step_py(state, cmd, arg, resp)
        got_packed, got_ok = sh.step_py([sh.pack(state)], cmd, arg, resp)
        assert got_ok == want_ok
        assert sh.unpack(got_packed[0]) == [int(v) for v in want_vec]
        state = [int(v) for v in want_vec]


def test_shadow_applicability():
    assert scalar_shadow(RegisterSpec()) is None       # already scalar
    assert scalar_shadow(QueueSpec()) is not None      # 1,280 states
    assert scalar_shadow(KvSpec(n_keys=4)) is not None  # 256 states
    assert scalar_shadow(KvSpec(n_keys=16)) is None    # 4^16: too big
    assert Scalarized(QueueSpec()).n_packed == 5 * 4 ** 4


def test_jax_tpu_uses_shadow_and_stays_exact():
    spec = QueueSpec()
    b = JaxTPU(spec, budget=2_000, mid_budget=10_000,
               rescue_budget=100_000)
    assert b._shadow is not None and b._uses_table
    hists = []
    for seed in range(10):
        prog = generate_program(spec, seed=seed, n_pids=4, max_ops=16)
        for sut in (AtomicQueueSUT(spec), RacyTwoPhaseQueueSUT(spec)):
            hists.append(run_concurrent(sut, prog, seed=f"sc{seed}"))
    want = WingGongCPU(memo=True).check_histories(spec, hists)
    got = b.check_histories(spec, hists)
    decided = got != int(Verdict.BUDGET_EXCEEDED)
    np.testing.assert_array_equal(got[decided], np.asarray(want)[decided])
    assert decided.all(), "capped budgets should decide this small corpus"


def test_out_of_bounds_start_state_deferred_not_mispacked():
    spec = QueueSpec()
    b = JaxTPU(spec)
    prog = generate_program(spec, seed=3, n_pids=2, max_ops=8)
    h = run_concurrent(AtomicQueueSUT(spec), prog, seed="oob")
    bad = np.asarray([99] + [0] * spec.capacity, np.int32)  # length 99
    v = b.check_histories(spec, [h], init_states=[bad])
    assert int(v[0]) == int(Verdict.BUDGET_EXCEEDED)  # honest deferral
    assert b.deferred_out_of_domain == 1


def test_bad_bounds_declaration_refused():
    class Broken(QueueSpec):
        def state_elem_bounds(self):
            return [2]  # wrong arity vs STATE_DIM

    with pytest.raises(ValueError, match="one exclusive"):
        Scalarized(Broken())
    with pytest.raises(ValueError, match="outside declared bound"):
        Scalarized(QueueSpec()).pack([99, 0, 0, 0, 0])
