"""Bounded LIFO stack (extra vector-state model family): the atomic impl
passes, the two-phase pop duplicates the top and fails; verdict parity
across the Python oracle, the native C++ step kernel (wg.cpp kind 3),
and the device kernel's vector-state path."""

import pytest

import numpy as np

from qsm_tpu import (PropertyConfig, Verdict, WingGongCPU, check_one,
                     generate_program, prop_concurrent, run_concurrent)
from qsm_tpu.models.stack import (POP, AtomicStackSUT, RacyTwoPhaseStackSUT,
                                  StackSpec)
from qsm_tpu.native import CppOracle
from qsm_tpu.ops.jax_kernel import JaxTPU

SPEC = StackSpec(capacity=4, n_values=4)
CFG = PropertyConfig(n_trials=80, n_pids=8, max_ops=32, seed=13)


def test_step_py_matches_step_jax_random_walk():
    """py/jax step agreement along seeded random walks (the state space is
    too big to sweep exhaustively — same strategy as the queue tests)."""
    import random

    import jax.numpy as jnp

    rng = random.Random(42)
    for _ in range(40):
        state = [0] * SPEC.STATE_DIM
        for _ in range(25):
            cmd = rng.randrange(len(SPEC.CMDS))
            arg = rng.randrange(SPEC.CMDS[cmd].n_args)
            resp = rng.randrange(SPEC.CMDS[cmd].n_resps)
            ns_py, ok_py = SPEC.step_py(state, cmd, arg, resp)
            ns_jx, ok_jx = SPEC.step_jax(
                jnp.asarray(state, jnp.int32), jnp.int32(cmd),
                jnp.int32(arg), jnp.int32(resp))
            assert list(map(int, ns_jx)) == list(map(int, ns_py))
            assert bool(ok_jx) == bool(ok_py)
            state = list(map(int, ns_py))


def test_atomic_stack_passes():
    res = prop_concurrent(SPEC, AtomicStackSUT(SPEC), CFG)
    assert res.ok, res.counterexample


def test_racy_stack_fails_and_shrinks():
    res = prop_concurrent(SPEC, RacyTwoPhaseStackSUT(SPEC), CFG)
    assert not res.ok, "duplicate pop was never caught"
    cx = res.counterexample
    assert check_one(WingGongCPU(), SPEC, cx.history) == Verdict.VIOLATION
    # the minimal counterexample must still contain a POP
    assert any(op.cmd == POP for op in cx.program.ops), cx.program


@pytest.mark.slow
def test_stack_backend_parity():
    from conftest import assert_backend_parity

    hists = []
    for seed in range(24):
        prog = generate_program(SPEC, seed=seed, n_pids=8, max_ops=28)
        for sut in (AtomicStackSUT(SPEC), RacyTwoPhaseStackSUT(SPEC)):
            hists.append(run_concurrent(sut, prog, seed=f"k{seed}"))
    cpu = assert_backend_parity(SPEC, hists, JaxTPU(SPEC))

    cpp = CppOracle(SPEC)
    got = cpp.check_histories(SPEC, hists)
    np.testing.assert_array_equal(got, cpu)
    assert cpp.native_histories == len(hists)  # the kind-3 kernel ran
